"""Native (C++) image loader: decode fidelity vs the tf.data pipeline,
augmentation determinism, sharding, tail handling, error counting."""

import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def loader_lib():
    from edl_tpu.data.native_loader import ensure_loader_lib
    try:
        return ensure_loader_lib()
    except Exception as e:  # no toolchain -> skip, don't error
        pytest.skip("native loader unavailable: %r" % e)


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    """A small class-per-subdirectory JPEG tree with varied sizes."""
    from PIL import Image
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(7)
    sizes = [(40, 40), (64, 48), (48, 64), (96, 96)]
    for c in range(3):
        d = root / ("class_%d" % c)
        d.mkdir()
        for i in range(8):
            w, h = sizes[(c + i) % len(sizes)]
            arr = rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
            Image.fromarray(arr).save(str(d / ("img%02d.jpg" % i)),
                                      quality=92)
    return str(root)


def test_eval_matches_tf_pipeline(loader_lib, image_tree):
    """Same JPEGs, eval mode: the native decode+resize+normalize must
    agree with the tf.data pipeline (both sit on libjpeg; bilinear
    half-pixel resize on both sides) to small numeric tolerance."""
    from edl_tpu.data.input_pipeline import image_folder_pipeline
    from edl_tpu.data.native_loader import native_image_folder_pipeline

    tf_batches = list(image_folder_pipeline(
        image_tree, 8, image_size=32, train=False))
    nat_batches = list(native_image_folder_pipeline(
        image_tree, 8, image_size=32, train=False))
    assert len(tf_batches) == len(nat_batches)
    for tb, nb in zip(tf_batches, nat_batches):
        np.testing.assert_array_equal(tb["label"], nb["label"])
        assert tb["image"].shape == nb["image"].shape
        diff = np.abs(tb["image"] - nb["image"]).mean()
        assert diff < 0.05, diff  # normalized units (std ~58 raw)


def test_train_deterministic_and_augmenting(loader_lib, image_tree):
    from edl_tpu.data.native_loader import native_image_folder_pipeline

    a = list(native_image_folder_pipeline(image_tree, 8, image_size=32,
                                          train=True, epoch_seed=5))
    b = list(native_image_folder_pipeline(image_tree, 8, image_size=32,
                                          train=True, epoch_seed=5))
    c = list(native_image_folder_pipeline(image_tree, 8, image_size=32,
                                          train=True, epoch_seed=6))
    # train drops the ragged tail: 24 files -> 3 full batches
    assert len(a) == 3 and all(x["image"].shape == (8, 32, 32, 3)
                               for x in a)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["image"], y["image"])
        np.testing.assert_array_equal(x["label"], y["label"])
    # a different epoch seed reshuffles
    assert any(not np.array_equal(x["label"], z["label"])
               for x, z in zip(a, c))


def test_sharding_partitions_files(loader_lib, image_tree):
    from edl_tpu.data.native_loader import native_image_folder_pipeline

    whole = [b["label"] for b in native_image_folder_pipeline(
        image_tree, 4, image_size=16, train=False)]
    s0 = [b["label"] for b in native_image_folder_pipeline(
        image_tree, 4, image_size=16, train=False, shard_index=0,
        shard_count=2)]
    s1 = [b["label"] for b in native_image_folder_pipeline(
        image_tree, 4, image_size=16, train=False, shard_index=1,
        shard_count=2)]
    n_whole = sum(len(x) for x in whole)
    assert sum(len(x) for x in s0) + sum(len(x) for x in s1) == n_whole
    assert sorted(np.concatenate(s0 + s1)) == sorted(
        np.concatenate(whole))


def test_eval_tail_batch(loader_lib, image_tree):
    from edl_tpu.data.native_loader import native_image_folder_pipeline

    batches = list(native_image_folder_pipeline(
        image_tree, 5, image_size=16, train=False))
    rows = [len(b["label"]) for b in batches]
    assert sum(rows) == 24 and rows[-1] == 24 % 5


def test_decode_error_zero_fills_and_counts(loader_lib, tmp_path):
    from edl_tpu.data.native_loader import NativeImageLoader

    from PIL import Image
    good = tmp_path / "ok.jpg"
    Image.fromarray(np.full((20, 20, 3), 128, np.uint8)).save(str(good))
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"not a jpeg at all")
    loader = NativeImageLoader([(str(good), 0), (str(bad), 1)], 2,
                               image_size=16, train=False, seed=0)
    batch = next(loader)
    assert loader.decode_errors == 1
    # the bad row is zero-filled, the good one is not
    assert np.abs(batch["image"][1]).sum() == 0
    assert np.abs(batch["image"][0]).sum() > 0
    loader.close()


def test_rejects_non_jpeg(loader_lib, tmp_path):
    from edl_tpu.data.native_loader import NativeImageLoader

    with pytest.raises(ValueError):
        NativeImageLoader([(str(tmp_path / "x.png"), 0)], 1)


@pytest.mark.integration
def test_resnet_example_trains_with_native_loader(loader_lib, tmp_path):
    """The --loader native path end-to-end: real JPEGs -> C++ decode ->
    ElasticTrainer steps -> benchmark-log JSON."""
    import json
    import subprocess
    import sys

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_examples_and_resize import _make_real_dataset

    data = _make_real_dataset(str(tmp_path / "train"), classes=2,
                              per_class=16, size=40)
    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(2)
    proc = subprocess.run(
        [sys.executable, "-u",
         os.path.join(REPO, "examples/resnet/train.py"),
         "--depth", "18", "--epochs", "1", "--steps_per_epoch", "3",
         "--total_batch_size", "8", "--image_size", "32",
         "--data_dir", data, "--loader", "native"],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads([l for l in proc.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert out["steps"] == 3 and out["model"] == "ResNet18_vd"
