"""GPT decoder family: causality, KV-cache exactness, generation,
TP sharding equivalence, ring-attention parity, and learning."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models import gpt
from edl_tpu.parallel.sharding import shard_params
from edl_tpu.runtime import mesh as mesh_mod
from edl_tpu.runtime.trainer import ElasticTrainer


def _tiny(**kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("d_model", 32)
    kw.setdefault("num_heads", 2)
    kw.setdefault("mlp_dim", 64)
    kw.setdefault("vocab_size", 64)
    kw.setdefault("max_len", 64)
    kw.setdefault("dtype", jnp.float32)
    return gpt.Gpt(**kw)


def test_gpt_is_causal():
    """Changing future tokens must not change past logits."""
    model = _tiny()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (2, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))["params"]
    base = model.apply({"params": params}, jnp.asarray(ids))
    mutated = ids.copy()
    mutated[:, 10:] = (mutated[:, 10:] + 7) % 64
    out = model.apply({"params": params}, jnp.asarray(mutated))
    np.testing.assert_allclose(np.asarray(out[:, :10]),
                               np.asarray(base[:, :10]),
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(np.asarray(out[:, 10:]),
                           np.asarray(base[:, 10:]), atol=1e-3)


def test_gpt_decode_cache_matches_full_forward():
    """Stepwise KV-cache logits must equal the full-sequence forward at
    every position (the standard cache-correctness obligation)."""
    model = _tiny()
    rng = np.random.RandomState(1)
    ids = jnp.asarray(rng.randint(0, 64, (2, 12)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    full = model.apply({"params": params}, ids)

    cache = gpt.init_cache(model, params, 2)
    got = []
    for t in range(12):
        logits, muts = model.apply(
            {"params": params, "cache": cache}, ids[:, t:t + 1],
            decode=True, decode_index=jnp.int32(t), mutable=["cache"])
        cache = muts["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(got, axis=1), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_gpt_learns_and_generates_pattern():
    """Train on arithmetic-mod sequences, then generate greedily from a
    short prompt: the continuation must follow the learned pattern."""
    model, params, loss_fn = gpt.create_model_and_loss(
        model=_tiny(num_layers=2, d_model=64, num_heads=4, mlp_dim=128))
    tx = optax.adam(3e-3)
    from edl_tpu.runtime.trainer import make_train_state, make_train_step
    state = make_train_state(params, tx)
    step = jax.jit(make_train_step(loss_fn, tx))
    rng = jax.random.PRNGKey(0)
    losses = []
    for i in range(150):
        batch = gpt.synthetic_lm_batch(32, seq_len=24, vocab_size=64,
                                       seed=i)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        state, loss = step(state, batch, rng)
        losses.append(float(loss))
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])

    # prompt = first 6 tokens of a held-out sequence (start 5, step 3)
    seq = (5 + 3 * np.arange(20)) % 64
    prompt = jnp.asarray(seq[None, :6].astype(np.int32))
    out = gpt.generate(model, state["params"], prompt, max_new_tokens=8)
    got = np.asarray(out)[0, 6:14]
    want = seq[6:14]
    # the pattern is learned statistically; most continuations must match
    assert (got == want).mean() >= 0.75, (got, want)


def test_gpt_generate_respects_prompt_and_shapes():
    model = _tiny()
    ids = jnp.asarray(np.arange(8, dtype=np.int32)[None] % 64)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out = gpt.generate(model, params, ids, max_new_tokens=5,
                       temperature=1.0, rng=jax.random.PRNGKey(3))
    assert out.shape == (1, 13)
    np.testing.assert_array_equal(np.asarray(out)[:, :8], np.asarray(ids))
    with pytest.raises(ValueError):
        gpt.generate(model, params, ids, max_new_tokens=1000)
    # max_new_tokens=0 returns the prompt unchanged
    out0 = gpt.generate(model, params, ids, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(ids))
    out1 = gpt.generate(model, params, ids, max_new_tokens=1)
    assert out1.shape == (1, 9)


def test_gpt_tp_sharded_matches_replicated():
    model = _tiny()
    dummy = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 16)),
                      jnp.int32)

    def loss_fn(p):
        logits = model.apply({"params": p}, ids)
        tgt = jax.nn.one_hot(ids[:, 1:], 64)
        return optax.softmax_cross_entropy(logits[:, :-1], tgt).mean()

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)
    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    sharded, shardings = shard_params(params, mesh,
                                      gpt.gpt_partition_rules())
    qkv = sharded["block_0"]["attention"]["query"]["kernel"]
    assert qkv.sharding.spec == P(None, "tp", None)
    tp_loss, tp_grads = jax.jit(
        jax.value_and_grad(loss_fn),
        out_shardings=(NamedSharding(mesh, P()), shardings))(sharded)
    np.testing.assert_allclose(float(tp_loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(tp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gpt_ring_attention_matches_dense():
    mesh = mesh_mod.make_mesh(dp=2, sp=4)
    kw = dict(num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
              vocab_size=64, max_len=64, dtype=jnp.float32)
    m_dense = gpt.Gpt(**kw)
    m_ring = gpt.Gpt(use_ring=True, mesh=mesh, **kw)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 32)),
                      jnp.int32)
    params = m_dense.init(jax.random.PRNGKey(0), ids)["params"]
    out_d = m_dense.apply({"params": params}, ids)
    out_r = m_ring.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


def test_gpt_pipeline_matches_sequential_grads():
    """dp x pp causal LM through the 1F1B engine == the unpipelined
    composite (loss and grads)."""
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp, dp = 2, 2
    mesh = mesh_mod.make_mesh(dp=dp, pp=pp,
                              devices=jax.devices()[:dp * pp])
    params, encode, stage, decode, seq_loss = gpt.create_gpt_pipeline(
        pp, num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
        vocab_size=64, max_len=64, seq_len=16, dtype=jnp.float32)
    rng = np.random.RandomState(5)
    ids = jnp.asarray(rng.randint(0, 64, (8, 16)).astype(np.int32))

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, ids, ids)
    got_loss, got_g = jax.jit(lambda p, x, y: pipeline_value_and_grad(
        p, x, y, encode_fn=encode, stage_fn=stage, decode_fn=decode,
        mesh=mesh, num_micro=2))(params, ids, ids)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want_g)
    flat_g = dict(jax.tree_util.tree_flatten_with_path(got_g)[0])
    for path, w in flat_w:
        np.testing.assert_allclose(
            np.asarray(flat_g[path]), np.asarray(w), rtol=5e-4,
            atol=1e-5, err_msg=jax.tree_util.keystr(path))


def test_gpt_pipeline_composes_with_sequence_parallelism():
    """sp x pp causal LM: seq-sharded activations inside the pipeline
    (causal in-shard ring attention, shard-offset positions, globally
    sliced next-token targets across the shard boundary) — loss and
    grads must match the dense sequential model."""
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp, sp, dp = 2, 2, 2
    mesh = mesh_mod.make_mesh(dp=dp, pp=pp, sp=sp)
    params, encode, stage, decode, seq_loss = gpt.create_gpt_pipeline(
        pp, num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
        vocab_size=64, max_len=64, seq_len=16, dtype=jnp.float32,
        seq_parallel_axis="sp")
    rng = np.random.RandomState(6)
    ids = jnp.asarray(rng.randint(0, 64, (8, 16)).astype(np.int32))

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, ids, ids)
    got_loss, got_g = jax.jit(lambda p, x, y: pipeline_value_and_grad(
        p, x, y, encode_fn=encode, stage_fn=stage, decode_fn=decode,
        mesh=mesh, num_micro=2, seq_axes=("sp",)))(params, ids, ids)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want_g)
    flat_g = dict(jax.tree_util.tree_flatten_with_path(got_g)[0])
    for path, w in flat_w:
        np.testing.assert_allclose(
            np.asarray(flat_g[path]), np.asarray(w), rtol=5e-4,
            atol=1e-5, err_msg=jax.tree_util.keystr(path))


def test_gpt_trains_under_elastic_trainer(tmp_path):
    model, params, loss_fn = gpt.create_model_and_loss(
        model=_tiny(num_layers=2))
    trainer = ElasticTrainer(loss_fn, params, optax.adam(1e-3),
                             total_batch_size=16,
                             checkpoint_dir=str(tmp_path / "ckpt"))
    losses = []
    for i in range(10):
        batch = gpt.synthetic_lm_batch(16, seq_len=16, vocab_size=64,
                                       seed=i % 2)
        losses.append(float(trainer.train_step(batch)))
    assert losses[-1] < losses[0]


def test_filter_logits_top_k_and_top_p():
    from edl_tpu.models.gpt import _filter_logits

    logits = jnp.log(jnp.asarray([[0.5, 0.25, 0.15, 0.10]]))
    # top_k=2: only the two largest survive
    f = _filter_logits(logits, top_k=2)
    assert np.isfinite(np.asarray(f[0, :2])).all()
    assert np.isinf(np.asarray(f[0, 2:])).all()
    # top_p=0.6: 0.5 alone has preceding mass 0 < 0.6; adding 0.25 has
    # preceding mass 0.5 < 0.6 -> kept; 0.15 preceded by 0.75 -> cut
    f = _filter_logits(logits, top_p=0.6)
    assert np.isfinite(np.asarray(f[0, :2])).all()
    assert np.isinf(np.asarray(f[0, 2:])).all()
    # top_p tiny: always keeps at least the argmax
    f = _filter_logits(logits, top_p=1e-6)
    assert np.isfinite(float(f[0, 0]))
    assert np.isinf(np.asarray(f[0, 1:])).all()
    # unsorted input: mask follows VALUES, not positions
    shuffled = logits[:, ::-1]
    f = _filter_logits(shuffled, top_k=1)
    assert np.isfinite(float(f[0, -1])) and np.isinf(f[0, 0])


def test_generate_topk_sampling_stays_in_pattern():
    """top_k=1 sampling at temperature>0 must equal greedy decoding."""
    import jax

    from edl_tpu.models import gpt

    model = gpt.gpt_tiny(vocab_size=32, max_len=32)
    ids = jnp.asarray(gpt.synthetic_lm_batch(2, seq_len=8,
                                             vocab_size=32)["input_ids"])
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    greedy = gpt.generate(model, params, ids, max_new_tokens=6)
    top1 = gpt.generate(model, params, ids, max_new_tokens=6,
                        temperature=0.7, top_k=1,
                        rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(top1))
