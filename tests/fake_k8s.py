"""A fake kubernetes API surface for operator reconcile tests.

Implements exactly the calls Operator makes, with the same read-side attr
shapes the real client exposes (snake_case object attrs) and dict bodies
on the write side (which the real client also accepts)."""

import copy


class FakeApiException(Exception):
    def __init__(self, status):
        super().__init__("status %d" % status)
        self.status = status


class _View(object):
    """Attr view over a StatefulSet manifest dict, shaped like the real
    client's V1StatefulSet (spec.replicas, spec.template.spec.containers,
    status.ready_replicas)."""

    class _C(object):
        def __init__(self, c):
            self.name = c["name"]
            self.image = c["image"]
            self.command = list(c["command"])

    def __init__(self, body, ready):
        tpl = body["spec"]["template"]["spec"]
        containers = [self._C(c) for c in tpl["containers"]]
        self.spec = type("S", (), {})()
        self.spec.replicas = body["spec"]["replicas"]
        self.spec.template = type("T", (), {})()
        self.spec.template.spec = type("TS", (), {})()
        self.spec.template.spec.containers = containers
        self.status = type("St", (), {})()
        self.status.ready_replicas = ready
        self.metadata = type("M", (), {})()
        self.metadata.name = body["metadata"]["name"]
        self.metadata.owner_references = body["metadata"].get(
            "ownerReferences", [])


class FakeAppsV1Api(object):
    def __init__(self):
        self.sets = {}    # name -> manifest dict
        self.ready = {}   # name -> ready replica count
        self.creates = []
        self.patches = []

    def read_namespaced_stateful_set(self, name, ns):
        if name not in self.sets:
            raise FakeApiException(404)
        return _View(self.sets[name], self.ready.get(name, 0))

    def create_namespaced_stateful_set(self, ns, body):
        name = body["metadata"]["name"]
        if name in self.sets:
            raise FakeApiException(409)
        self.sets[name] = copy.deepcopy(body)
        self.creates.append(name)

    def patch_namespaced_stateful_set(self, name, ns, body):
        if name not in self.sets:
            raise FakeApiException(404)
        self.sets[name] = copy.deepcopy(body)
        self.patches.append(name)

    # test helper: simulate pods becoming ready
    def set_ready(self, name, n):
        self.ready[name] = n


class FakeCustomObjectsApi(object):
    def __init__(self, jobs=()):
        self.jobs = {j["metadata"]["name"]: copy.deepcopy(j) for j in jobs}
        self.status_patches = []

    def list_namespaced_custom_object(self, group, version, ns, plural):
        return {"items": [copy.deepcopy(j) for _, j in
                          sorted(self.jobs.items())]}

    def patch_namespaced_custom_object_status(self, group, version, ns,
                                              plural, name, body):
        if name not in self.jobs:
            raise FakeApiException(404)
        self.jobs[name].setdefault("status", {}).update(body["status"])
        self.status_patches.append((name, copy.deepcopy(body["status"])))
