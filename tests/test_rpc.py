"""RPC substrate tests: framing, dispatch, error envelopes, reconnect."""

import threading

import pytest

from edl_tpu.rpc import framing
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors


def test_framing_roundtrip():
    obj = {"a": 1, "b": [1, 2, 3], "c": b"\x00\xff", "d": "héllo"}
    frame = framing.pack_frame(obj)
    assert frame[:4] == framing.MAGIC

    class FakeSock(object):
        def __init__(self, data):
            self._data = data

        def recv(self, n):
            chunk, self._data = self._data[:n], self._data[n:]
            return chunk

    assert framing.read_frame(FakeSock(frame)) == obj
    with pytest.raises(framing.FramingError, match="bad magic"):
        framing.read_frame(FakeSock(b"XXXX" + frame[4:]))


def test_rpc_call_and_errors():
    server = RpcServer(host="127.0.0.1")
    server.register("add", lambda a, b: a + b)

    def boom():
        raise errors.NotFoundError("nothing here")

    server.register("boom", boom)
    server.start()
    try:
        client = RpcClient(server.endpoint)
        assert client.call("add", 2, 3) == 5
        assert client.call("add", a=10, b=20) == 30
        with pytest.raises(errors.NotFoundError, match="nothing here"):
            client.call("boom")
        with pytest.raises(errors.RpcError, match="no such method"):
            client.call("missing")
        client.close()
    finally:
        server.stop()


def test_rpc_concurrent_clients():
    server = RpcServer(host="127.0.0.1")
    server.register("echo", lambda x: x)
    server.start()
    results = {}

    def worker(i):
        c = RpcClient(server.endpoint)
        for _ in range(20):
            results[i] = c.call("echo", i)
        c.close()

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i for i in range(8)}
    finally:
        server.stop()


def test_rpc_reconnect_after_server_restart():
    server = RpcServer(host="127.0.0.1")
    server.register("ping", lambda: "pong")
    server.start()
    port = server.port
    client = RpcClient(server.endpoint)
    assert client.call("ping") == "pong"
    server.stop()
    client.close()  # existing handler threads outlive stop(); force reconnect
    with pytest.raises(errors.ConnectError):
        client.call("ping")
    server2 = RpcServer(host="127.0.0.1", port=port)
    server2.register("ping", lambda: "pong2")
    server2.start()
    try:
        assert client.call("ping") == "pong2"
    finally:
        server2.stop()
