"""RPC substrate tests: framing, dispatch, error envelopes, reconnect."""

import threading

import pytest

from edl_tpu.rpc import framing
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors


def test_framing_roundtrip():
    obj = {"a": 1, "b": [1, 2, 3], "c": b"\x00\xff", "d": "héllo"}
    frame = framing.pack_frame(obj)
    assert frame[:4] == framing.MAGIC

    class FakeSock(object):
        def __init__(self, data):
            self._data = data

        def recv(self, n):
            chunk, self._data = self._data[:n], self._data[n:]
            return chunk

    assert framing.read_frame(FakeSock(frame)) == obj
    with pytest.raises(framing.FramingError, match="bad magic"):
        framing.read_frame(FakeSock(b"XXXX" + frame[4:]))


def test_rpc_call_and_errors():
    server = RpcServer(host="127.0.0.1")
    server.register("add", lambda a, b: a + b)

    def boom():
        raise errors.NotFoundError("nothing here")

    server.register("boom", boom)
    server.start()
    try:
        client = RpcClient(server.endpoint)
        assert client.call("add", 2, 3) == 5
        assert client.call("add", a=10, b=20) == 30
        with pytest.raises(errors.NotFoundError, match="nothing here"):
            client.call("boom")
        with pytest.raises(errors.RpcError, match="no such method"):
            client.call("missing")
        client.close()
    finally:
        server.stop()


def test_rpc_concurrent_clients():
    server = RpcServer(host="127.0.0.1")
    server.register("echo", lambda x: x)
    server.start()
    results = {}

    def worker(i):
        c = RpcClient(server.endpoint)
        for _ in range(20):
            results[i] = c.call("echo", i)
        c.close()

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == {i: i for i in range(8)}
    finally:
        server.stop()


def test_rpc_reconnect_after_server_restart():
    server = RpcServer(host="127.0.0.1")
    server.register("ping", lambda: "pong")
    server.start()
    port = server.port
    client = RpcClient(server.endpoint)
    assert client.call("ping") == "pong"
    server.stop()
    client.close()  # existing handler threads outlive stop(); force reconnect
    with pytest.raises(errors.ConnectError):
        client.call("ping")
    server2 = RpcServer(host="127.0.0.1", port=port)
    server2.register("ping", lambda: "pong2")
    server2.start()
    try:
        assert client.call("ping") == "pong2"
    finally:
        server2.stop()


def _socketpair():
    import socket

    a, b = socket.socketpair()
    return a, b


def test_tensor_frame_roundtrip_zero_copy_path():
    """v2 tensor frames: ndarrays anywhere in the pytree ride as
    out-of-band raw segments (no tobytes/msgpack-bin copies) and come
    back as owned, WRITABLE arrays; array-free payloads stay v1 so
    pre-v2 peers (the C++ store pins v1's magic) never see v2."""
    import numpy as np

    a, b = _socketpair()
    try:
        obj = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
               "nested": [{"y": np.array(7, dtype=np.int64)},
                          "text", 3.5],
               "plain": [1, 2]}
        t = threading.Thread(
            target=lambda: framing.write_frame(a, obj))
        t.start()
        out = framing.read_frame(b)
        t.join()
        np.testing.assert_array_equal(out["x"], obj["x"])
        assert out["x"].flags.writeable and out["x"].flags.owndata
        np.testing.assert_array_equal(out["nested"][0]["y"], 7)
        assert out["nested"][1:] == ["text", 3.5]
        assert out["plain"] == [1, 2]

        # array-free stays v1 on the wire
        t = threading.Thread(
            target=lambda: framing.write_frame(a, {"k": 1}))
        t.start()
        hdr = framing.recv_exact(b, 8)
        assert hdr[:4] == framing.MAGIC
        body = framing.recv_exact(
            b, framing._HEADER.unpack(hdr)[1])
        t.join()
        import msgpack
        assert msgpack.unpackb(body, raw=False) == {"k": 1}
    finally:
        a.close()
        b.close()


def test_tensor_frame_rejects_meta_mismatch():
    """A v2 frame whose meta lies about payload sizes must be refused
    before any allocation-sized-by-attacker recv happens."""
    import numpy as np

    a, b = _socketpair()
    try:
        meta = framing._pack_body(
            {"tree": {framing._ND_REF: 0, "dtype": "<f4",
                      "shape": [4]},
             "lens": [999]})  # 4 floats != 999 bytes
        a.sendall(framing._HEADER.pack(framing.MAGIC_V2, len(meta))
                  + meta)
        with pytest.raises(framing.FramingError, match="mismatch"):
            framing.read_frame(b)
    finally:
        a.close()
        b.close()


def test_tensor_frame_rejects_object_dtype():
    """A v2 meta claiming dtype "O" must be refused before any array is
    allocated: recv_into() on an object array would write attacker
    bytes straight into PyObject pointer slots."""
    import numpy as np

    a, b = _socketpair()
    try:
        # itemsize of "O" is 8, so 4 x 8 = 32 passes the size check —
        # only the POD-dtype gate stands between the wire and memory
        meta = framing._pack_body(
            {"tree": {framing._ND_REF: 0, "dtype": "O", "shape": [4]},
             "lens": [4 * np.dtype("O").itemsize]})
        a.sendall(framing._HEADER.pack(framing.MAGIC_V2, len(meta))
                  + meta)
        with pytest.raises(framing.FramingError, match="non-POD"):
            framing.read_frame(b)
    finally:
        a.close()
        b.close()


def test_tensor_frame_recv_failure_surfaces_as_framing_error(monkeypatch):
    """A non-OSError failure inside the v2 allocation/recv loop leaves
    unread payload bytes on the socket: it must surface as FramingError
    (the close-the-socket class) so the connection is never reused
    desynced."""
    import numpy as np

    a, b = _socketpair()
    try:
        meta = framing._pack_body(
            {"tree": {framing._ND_REF: 0, "dtype": "<f4", "shape": [4]},
             "lens": [16]})
        a.sendall(framing._HEADER.pack(framing.MAGIC_V2, len(meta))
                  + meta + b"\x00" * 16)
        monkeypatch.setattr(
            np, "empty",
            lambda *a_, **k: (_ for _ in ()).throw(
                ValueError("allocator hiccup")))
        with pytest.raises(framing.FramingError, match="recv failed"):
            framing.read_frame(b)
    finally:
        a.close()
        b.close()


def test_disable_tensor_frames_env_is_read_per_call(monkeypatch):
    """EDL_TPU_DISABLE_TENSOR_FRAMES is consulted on every write_frame
    (like the UDS knob), so a long-lived process can be flipped to the
    v1 wire form — and back — without a restart."""
    import msgpack
    import numpy as np

    from edl_tpu.rpc.ndarray import decode_tree

    a, b = _socketpair()
    try:
        obj = {"x": np.arange(4, dtype=np.float32)}
        monkeypatch.setenv("EDL_TPU_DISABLE_TENSOR_FRAMES", "1")
        t = threading.Thread(target=lambda: framing.write_frame(a, obj))
        t.start()
        hdr = framing.recv_exact(b, 8)
        assert hdr[:4] == framing.MAGIC  # v1 on the wire, post-import
        body = framing.recv_exact(b, framing._HEADER.unpack(hdr)[1])
        t.join()
        out = decode_tree(msgpack.unpackb(body, raw=False))
        np.testing.assert_array_equal(out["x"], obj["x"])

        monkeypatch.delenv("EDL_TPU_DISABLE_TENSOR_FRAMES")
        # same process, knob cleared: v2 frames resume immediately
        t = threading.Thread(target=lambda: framing.write_frame(a, obj))
        t.start()
        out = framing.read_frame(b)
        t.join()
        np.testing.assert_array_equal(out["x"], obj["x"])
    finally:
        a.close()
        b.close()


def test_rpc_call_carries_raw_ndarrays():
    """End to end through RpcServer/RpcClient: raw numpy in, raw numpy
    out (the distill feed path's transport after the r5 v2 upgrade)."""
    import numpy as np

    server = RpcServer(host="127.0.0.1")
    server.register("double", lambda batch: {
        k: np.asarray(v) * 2 for k, v in batch.items()})
    server.start()
    try:
        client = RpcClient(server.endpoint)
        x = np.random.rand(8, 16).astype(np.float32)
        out = client.call("double", {"x": x})
        np.testing.assert_allclose(out["x"], x * 2, rtol=1e-6)
        client.close()
    finally:
        server.stop()


def test_tensor_frame_edges():
    """v2 hardening: reserved-key rejection, datetime64 via i8 views,
    wide pytrees past Linux IOV_MAX, and malformed meta surfacing as
    FramingError (the only exception the RPC client treats as
    close-the-socket)."""
    import numpy as np

    a, b = _socketpair()
    try:
        # reserved sentinel inside an array-carrying payload: refused
        # at the sender before any byte hits the wire
        with pytest.raises(framing.FramingError, match="reserved"):
            framing.write_frame(
                a, {"x": np.zeros(4), "cfg": {framing._ND_REF: 0}})

        # datetime64 has no buffer protocol: i8-view transport
        obj = {"t": np.array(["2026-07-31", "2026-01-01"],
                             dtype="datetime64[D]")}
        t = threading.Thread(target=lambda: framing.write_frame(a, obj))
        t.start()
        out = framing.read_frame(b)
        t.join()
        np.testing.assert_array_equal(out["t"], obj["t"])

        # one segment per array: >IOV_MAX arrays must chunk, not fail
        wide = {"a%d" % i: np.full((2,), i, np.int32)
                for i in range(1100)}
        t = threading.Thread(target=lambda: framing.write_frame(a, wide))
        t.start()
        out = framing.read_frame(b)
        t.join()
        assert len(out) == 1100
        np.testing.assert_array_equal(out["a1099"], [1099, 1099])

        # malformed meta (missing keys) -> FramingError, not KeyError
        meta = framing._pack_body({"not_tree": 1})
        a.sendall(framing._HEADER.pack(framing.MAGIC_V2, len(meta))
                  + meta)
        with pytest.raises(framing.FramingError, match="malformed"):
            framing.read_frame(b)
    finally:
        a.close()
        b.close()


def test_tensor_frame_fuzz_roundtrip():
    """Property pin for the v2 transport: 30 random nested pytrees
    (mixed dtypes incl. bool/f16/i8/c64, 0-d and empty arrays, deep
    nesting, non-contiguous slices) must round-trip exactly through a
    real socket."""
    import numpy as np

    rng = np.random.RandomState(7)
    dtypes = [np.float32, np.float16, np.int8, np.int32, np.bool_,
              np.complex64, np.float64]

    def rand_tree(depth):
        kind = rng.randint(0, 6 if depth < 3 else 4)
        if kind == 0:
            shape = tuple(rng.randint(0, 5)
                          for _ in range(rng.randint(0, 4)))
            dt = dtypes[rng.randint(len(dtypes))]
            arr = np.asarray(rng.rand(*shape) * 100).astype(dt)
            if arr.ndim >= 2 and arr.shape[0] >= 3:
                arr = arr[::2]  # genuinely non-contiguous view
            return arr
        if kind == 1:
            return rng.randint(-1000, 1000)
        if kind == 2:
            return "s%d" % rng.randint(100)
        if kind == 3:
            return None
        if kind == 4:
            return [rand_tree(depth + 1)
                    for _ in range(rng.randint(0, 4))]
        return {"k%d" % i: rand_tree(depth + 1)
                for i in range(rng.randint(0, 4))}

    def assert_same(a, b, path=""):
        if isinstance(a, np.ndarray):
            np.testing.assert_array_equal(a, b, err_msg=path)
            assert a.dtype == b.dtype, (path, a.dtype, b.dtype)
        elif isinstance(a, dict):
            assert set(a) == set(b), path
            for k in a:
                assert_same(a[k], b[k], path + "/" + k)
        elif isinstance(a, (list, tuple)):
            assert len(a) == len(b), path
            for i, (x, y) in enumerate(zip(a, b)):
                assert_same(x, y, "%s[%d]" % (path, i))
        else:
            assert a == b, (path, a, b)

    base = np.arange(24, dtype=np.float32).reshape(6, 4)
    trials = [{"t": rand_tree(0)} for _ in range(30)]
    # deterministic coverage the seed can't opt out of: genuinely
    # non-contiguous views (strided + transposed) and empty arrays
    # (both deadlocked the transport before their guards existed)
    trials.append({"strided": base[::2], "transposed": base.T,
                   "empty": np.empty((0, 3), np.float32),
                   "scalar": np.float64(3.25)})

    a, b = _socketpair()
    try:
        for trial, tree in enumerate(trials):
            t = threading.Thread(
                target=lambda tr=tree: framing.write_frame(a, tr))
            t.start()
            out = framing.read_frame(b)
            t.join()
            assert_same(tree, out, "trial%d" % trial)
    finally:
        a.close()
        b.close()


def test_uds_fast_path_and_fallback(monkeypatch):
    """Same-host RPC auto-rides the AF_UNIX listener (r5: 1381 vs 997
    MB/s on tensor frames); the path is uid-checked, disable-able, and
    every failure falls back to TCP silently."""
    import os

    import numpy as np

    from edl_tpu.rpc.server import uds_path_for_port

    server = RpcServer(host="127.0.0.1")
    server.register("echo", lambda x: x)
    server.start()
    try:
        path = uds_path_for_port(server.port)
        assert os.path.exists(path)
        assert oct(os.stat(path).st_mode & 0o777) == "0o600"

        client = RpcClient(server.endpoint)
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_array_equal(client.call("echo", {"x": x})["x"],
                                      x)
        assert client.transport == "uds"
        client.close()

        monkeypatch.setenv("EDL_TPU_DISABLE_UDS", "1")
        client = RpcClient(server.endpoint)
        assert client.call("echo", 7) == 7
        assert client.transport == "tcp"
        client.close()
        monkeypatch.delenv("EDL_TPU_DISABLE_UDS")
    finally:
        server.stop()
    # stop() unlinks the socket file
    assert not os.path.exists(path)

    # stale socket file (dead server) -> silent TCP fallback
    server2 = RpcServer(host="127.0.0.1")
    server2.register("ping", lambda: "pong")
    server2.start()
    try:
        stale = uds_path_for_port(server2.port)
        # simulate a server that died before unlinking: remove the live
        # listener file and plant a dead one
        server2._uds_server.shutdown()
        server2._uds_server.server_close()
        server2._uds_server = None
        # the file may or may not remain after server_close; ensure a
        # stale one exists
        import socket as _s
        if os.path.exists(stale):
            os.unlink(stale)
        dead = _s.socket(_s.AF_UNIX)
        dead.bind(stale)
        dead.close()  # bound then closed: connect() will fail
        client = RpcClient(server2.endpoint)
        assert client.call("ping") == "pong"
        assert client.transport == "tcp"
        client.close()
        os.unlink(stale)
    finally:
        server2.stop()


def test_uds_identity_two_servers_sharing_a_port_number():
    """Regression: the UDS path is keyed by PORT NUMBER only, so two
    servers bound to different loopback addresses with the same port
    number collide on it. The first owner keeps the socket (flock
    sidecar); a client dialing the OTHER server must detect the
    identity mismatch on the UDS probe and fall back to TCP — never
    silently talk to the wrong process."""
    import os

    from edl_tpu.rpc.server import uds_path_for_port

    a = RpcServer(host="127.0.0.2")
    a.register("who", lambda: "A")
    a.start()
    b = None
    try:
        path = uds_path_for_port(a.port)
        assert os.path.exists(path) and os.path.exists(path + ".lock")

        # same port number, different loopback address: B must see the
        # held flock, leave A's socket alone, and serve TCP-only
        b = RpcServer(host="127.0.0.1", port=a.port)
        b.register("who", lambda: "B")
        b.start()
        assert b._uds_server is None
        assert os.path.exists(path)  # A's listener survived B's start

        # dialing B rides the shared UDS path into A's listener; the
        # identity probe unmasks it and the call goes out over TCP
        cb = RpcClient(b.endpoint)
        assert cb.call("who") == "B"
        assert cb.transport == "tcp"
        cb.close()

        # dialing A at 127.0.0.2 is not a "this machine" address for
        # the client's fast path: plain TCP, and it still reaches A
        ca = RpcClient(a.endpoint)
        assert ca.call("who") == "A"
        assert ca.transport == "tcp"
        ca.close()

        # positive control: once B is gone, a loopback dial of the same
        # port number rides A's UDS listener iff the identity matches —
        # it doesn't (A is bound to 127.0.0.2), so this must stay TCP
        # even with no competing server
        b.stop()
        cb2 = RpcClient("127.0.0.1:%d" % a.port)
        with pytest.raises(errors.EdlError):
            cb2.call("who")  # nobody serves TCP 127.0.0.1:P anymore
        assert cb2.transport != "uds"  # never rode A's socket
        cb2.close()
        b = None
    finally:
        if b is not None:
            b.stop()
        a.stop()
    # socket unlinked on stop; the lock sidecar deliberately is NOT
    # (unlinking it would resurrect the probe/unlink/bind race)
    assert not os.path.exists(path)
    assert os.path.exists(path + ".lock")


def test_uds_identity_probe_rejects_garbage(monkeypatch):
    """A listener that answers the identity probe with junk (or not at
    all) is treated as a mismatch: silent TCP fallback."""
    import os
    import socket as _s
    import threading

    from edl_tpu.rpc.server import uds_path_for_port

    server = RpcServer(host="127.0.0.1")
    server.register("ping", lambda: "pong")
    server.start()
    try:
        path = uds_path_for_port(server.port)
        # replace the real UDS listener with one that answers nothing
        server._uds_server.shutdown()
        server._uds_server.server_close()
        server._uds_server = None
        if os.path.exists(path):
            os.unlink(path)
        rogue = _s.socket(_s.AF_UNIX)
        rogue.bind(path)
        os.chmod(path, 0o600)
        rogue.listen(1)

        def _accept_and_stall():
            try:
                conn, _ = rogue.accept()
                conn.recv(4096)   # swallow the probe, answer nothing
                conn.close()
            except OSError:
                pass

        t = threading.Thread(target=_accept_and_stall, daemon=True)
        t.start()
        client = RpcClient(server.endpoint)
        assert client.call("ping") == "pong"
        assert client.transport == "tcp"
        client.close()
        rogue.close()
        t.join(timeout=5)
        os.unlink(path)
    finally:
        server.stop()
