"""The minimum end-to-end slice (SURVEY.md §7.3): fit_a_line under the
launcher — barrier → train → per-epoch checkpoint → forced resize →
resume-from-checkpoint → completion. Real launcher + trainer processes, CPU
devices, real multi-process jax.distributed when world > 1."""

import json
import os
import subprocess
import sys
import time

import pytest

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import status
from edl_tpu.controller.status import Status

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "examples", "fit_a_line", "train.py")


def _spawn(store_endpoint, job_id, nodes_range, tmp_path, name,
           script_args=()):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep children off the TPU plugin
    env.update({
        "PYTHONPATH": REPO,
        "EDL_TPU_POD_IP": "127.0.0.1",
        "EDL_TPU_TTL": "3",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    log = open(str(tmp_path / ("%s.log" % name)), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
         "--job_id", job_id, "--store_endpoints", store_endpoint,
         "--nodes_range", nodes_range,
         "--checkpoint_path", str(tmp_path / "ckpt"),
         "--log_dir", str(tmp_path / ("%s_logs" % name)),
         SCRIPT] + list(script_args),
        env=env, stdout=log, stderr=subprocess.STDOUT,
        preexec_fn=os.setsid)
    log.close()
    return proc


def _logs(tmp_path):
    out = []
    for root, _, files in os.walk(str(tmp_path)):
        for f in files:
            if f.endswith(".log") or f.startswith("workerlog"):
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    out.append("== %s ==\n%s" % (
                        p, fh.read().decode("utf-8", "replace")))
    return "\n".join(out)


@pytest.mark.integration
def test_fit_a_line_single_pod(store, tmp_path):
    coord = store.client(root="fal1")
    p = _spawn(store.endpoint, "fal1", "1:1", tmp_path, "pod1",
               ("--epochs", "3", "--steps_per_epoch", "10"))
    try:
        assert p.wait(timeout=180) == 0, _logs(tmp_path)
        assert status.load_job_status(coord) == Status.SUCCEED
        log = (tmp_path / "pod1_logs" / "workerlog.0").read_text()
        result = json.loads([l for l in log.splitlines()
                             if l.startswith("{")][-1])
        assert result["steps"] == 30
        assert result["final_loss"] < 0.05, log
        # per-epoch checkpoints committed
        ckpts = [d for d in os.listdir(str(tmp_path / "ckpt"))
                 if d.startswith("v_")]
        assert len(ckpts) == 3, ckpts
    finally:
        p.kill()


@pytest.mark.integration
def test_fit_a_line_elastic_resize_resume(store, tmp_path):
    """1 pod trains slowly; pod2 joins (resize to world=2, multi-process
    jax.distributed); trainers restart and RESUME from the checkpoint
    instead of starting over."""
    coord = store.client(root="fal2")
    slow = ("--epochs", "4", "--steps_per_epoch", "10", "--step_sleep",
            "0.25")
    p1 = _spawn(store.endpoint, "fal2", "1:2", tmp_path, "pod1", slow)
    p2 = None
    try:
        # wait for pod1's first checkpoint (epoch 0 done)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            d = tmp_path / "ckpt"
            if d.exists() and any(n.startswith("v_") for n in
                                  os.listdir(str(d))):
                break
            time.sleep(0.5)
        else:
            raise AssertionError("no checkpoint appeared\n" + _logs(tmp_path))

        p2 = _spawn(store.endpoint, "fal2", "1:2", tmp_path, "pod2", slow)
        assert p1.wait(timeout=300) == 0, _logs(tmp_path)
        assert p2.wait(timeout=300) == 0, _logs(tmp_path)
        assert status.load_job_status(coord) == Status.SUCCEED

        log1 = (tmp_path / "pod1_logs" / "workerlog.0").read_text()
        # the restarted trainer resumed from a non-zero epoch
        resumes = [l for l in log1.splitlines() if "resumed=True" in l]
        assert resumes, log1
        assert any("world=2" in l for l in resumes), log1
        result = json.loads([l for l in log1.splitlines()
                             if l.startswith("{")][-1])
        assert result["world"] == 2
        assert result["final_loss"] < 0.05
    finally:
        p1.kill()
        if p2 is not None:
            p2.kill()
