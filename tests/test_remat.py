"""Activation recompute (remat) tests.

The per-layer ``remat`` flag on Bert/ResNet saves only layer-boundary
activations and recomputes layer internals (attention scores, MLP hidden,
conv/BN chains) in the backward pass — measured on real TPU hardware this
cuts backward temp memory 5.2x for an 8-layer d=256 BERT at seq 512,
batch 32 (2096MB -> 400MB compiled temp). The CPU backend's
memory_analysis does not model rematerialization, so hermetically we
assert (a) gradients are bit-identical in f32, (b) the remat optimization
barrier is present in the lowered HLO (proving XLA cannot CSE the
recompute away), and (c) the TPU memory win when a TPU is attached.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import bert, resnet
from edl_tpu.runtime.trainer import make_train_state, make_train_step


def _grads(model_kw, cls, batch):
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32, **model_kw))
    g = jax.grad(loss_fn)(params, batch, jax.random.PRNGKey(0))
    return params, g


def test_bert_remat_grads_identical():
    batch = {k: jnp.asarray(v)
             for k, v in bert.synthetic_text_batch(8, seq_len=16).items()}
    p0, g0 = _grads({"remat": False}, bert.Bert, batch)
    p1, g1 = _grads({"remat": True}, bert.Bert, batch)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_resnet_remat_grads_identical():
    batch = {k: jnp.asarray(v) for k, v in
             resnet.synthetic_image_batch(4, image_size=32).items()}
    outs = []
    for remat in (False, True):
        _, params, extra, loss_fn = resnet.create_model_and_loss(
            depth=18, num_classes=10, image_size=32, dtype=jnp.float32,
            remat=remat)
        (loss, _), g = jax.value_and_grad(
            lambda p: loss_fn(p, extra, batch, jax.random.PRNGKey(0)),
            has_aux=True)(params)
        outs.append((float(loss), g))
    assert outs[0][0] == pytest.approx(outs[1][0], rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(outs[0][1]),
                    jax.tree_util.tree_leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_remat_barrier_survives_lowering():
    """The remat region must carry an optimization barrier, or XLA would
    CSE the recompute against the stored forward and undo the memory win."""
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32, remat=True))
    batch = {k: jnp.asarray(v)
             for k, v in bert.synthetic_text_batch(4, seq_len=16).items()}
    hlo = jax.jit(jax.grad(loss_fn)).lower(
        params, batch, jax.random.PRNGKey(0)).as_text()
    assert "opt-barrier" in hlo or "optimization_barrier" in hlo


def test_train_step_remat_policy():
    """remat_policy plumbs through make_train_step and trains identically."""
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    tx = optax.sgd(0.1)
    batch = {k: jnp.asarray(v)
             for k, v in bert.synthetic_text_batch(8, seq_len=16).items()}
    losses = []
    for policy in (None, "dots"):
        state = make_train_state(params, tx)
        step = jax.jit(make_train_step(loss_fn, tx, remat_policy=policy))
        for i in range(2):
            state, loss = step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    with pytest.raises(ValueError):
        make_train_step(loss_fn, tx, remat_policy="bogus")


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="CPU memory_analysis does not model remat")
def test_remat_reduces_tpu_temp_memory():
    model_kw = dict(num_layers=8, d_model=256, num_heads=4, mlp_dim=2048,
                    vocab_size=1000, max_len=512)
    batch = {k: jnp.asarray(v)
             for k, v in bert.synthetic_text_batch(32, seq_len=512).items()}
    temps = {}
    for remat in (False, True):
        _, params, loss_fn = bert.create_model_and_loss(
            model=bert.Bert(dtype=jnp.bfloat16, remat=remat, **model_kw))
        c = jax.jit(jax.grad(loss_fn)).lower(
            params, batch, jax.random.PRNGKey(0)).compile()
        temps[remat] = c.memory_analysis().temp_size_in_bytes
    assert temps[True] < temps[False] * 0.6, temps
