"""Tier-1 wiring for tools/check_no_ad_hoc_instrumentation.py: a NEW
stopwatch-plus-print pair in one function fails the build — record a
registry histogram (edl_tpu.obs.metrics) or a timeline span
(edl_tpu.utils.timeline) so the sample lands on the fleet snapshot."""

import ast
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_no_ad_hoc_instrumentation.py")


def test_no_new_ad_hoc_instrumentation():
    out = subprocess.run([sys.executable, TOOL], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def _finder(src):
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import check_no_ad_hoc_instrumentation as lint
    finally:
        sys.path.pop(0)
    f = lint._Finder("x.py")
    f.visit(ast.parse(src))
    return f.hits


def test_lint_actually_detects_stopwatch_print():
    """The lint must not be a rubber stamp: it flags the timed-then-
    printed combination in both the attribute and the from-import
    spelling, via print and via sys.stderr.write."""
    hits = _finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    print('took', time.monotonic() - t0)\n")
    assert hits == [("x.py", "f", 4)]
    hits = _finder(
        "import sys\n"
        "from time import perf_counter as pc\n"
        "def g():\n"
        "    t0 = pc()\n"
        "    sys.stderr.write('%f\\n' % (pc() - t0))\n")
    assert hits == [("x.py", "g", 5)]


def test_lint_ignores_benign_timing():
    """Timing into a variable/stats dict (no console write) and printing
    without a stopwatch are both fine — separately or in sibling
    functions."""
    assert _finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    return time.monotonic() - t0\n"
        "def g():\n"
        "    print('hello')\n") == []


def _pair_finder(src, relpath="edl_tpu/runtime/x.py"):
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import check_no_ad_hoc_instrumentation as lint
    finally:
        sys.path.pop(0)
    f = lint._Finder(relpath)
    f.visit(ast.parse(src))
    return f.pair_hits


def test_pair_rule_flags_unledgered_stopwatch_delta():
    """A raw t0 = perf_counter() … x - t0 pair whose delta lands in a
    plain variable (or a log line) is a ledger bypass in runtime/."""
    hits = _pair_finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    work()\n"
        "    elapsed = time.perf_counter() - t0\n"
        "    logger.info('took %.1fs', elapsed)\n")
    assert hits == [("edl_tpu/runtime/x.py", "f", 5)]


def test_pair_rule_out_of_scope_outside_runtime():
    """The pair rule applies to edl_tpu/runtime/ only — the same code
    elsewhere passes (the ledger invariant lives in runtime)."""
    src = ("import time\n"
           "def f():\n"
           "    t0 = time.monotonic()\n"
           "    d = time.monotonic() - t0\n"
           "    return d\n")
    assert _pair_finder(src) != []
    assert _pair_finder(src, relpath="edl_tpu/data/x.py") == []


def test_pair_rule_passes_deadline_math():
    """deadline = monotonic() + x is a BinOp assignment, never tracked,
    so deadline - monotonic() and remaining-time checks pass."""
    assert _pair_finder(
        "import time\n"
        "def f(timeout):\n"
        "    deadline = time.monotonic() + timeout\n"
        "    while time.monotonic() < deadline:\n"
        "        remaining = deadline - time.monotonic()\n"
        "        wait(remaining)\n") == []


def test_pair_rule_passes_sanctioned_sinks():
    """A delta consumed directly inside .observe()/.inc()/.set()/
    .time_ms() already lands in the registry — not a bypass."""
    assert _pair_finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    work()\n"
        "    _STEP_MS.observe(1000.0 * (time.perf_counter() - t0))\n"
        "    _RETRIES.inc(time.perf_counter() - t0)\n") == []


def test_pair_rule_tracking_is_per_function():
    """A stopwatch variable from one function must not taint a Sub in a
    sibling function that reuses the name."""
    assert _pair_finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.perf_counter()\n"
        "    use(t0)\n"
        "def g(t0, t1):\n"
        "    return t1 - t0\n") == []
