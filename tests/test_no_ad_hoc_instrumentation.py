"""Tier-1 wiring for tools/check_no_ad_hoc_instrumentation.py: a NEW
stopwatch-plus-print pair in one function fails the build — record a
registry histogram (edl_tpu.obs.metrics) or a timeline span
(edl_tpu.utils.timeline) so the sample lands on the fleet snapshot."""

import ast
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_no_ad_hoc_instrumentation.py")


def test_no_new_ad_hoc_instrumentation():
    out = subprocess.run([sys.executable, TOOL], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def _finder(src):
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import check_no_ad_hoc_instrumentation as lint
    finally:
        sys.path.pop(0)
    f = lint._Finder("x.py")
    f.visit(ast.parse(src))
    return f.hits


def test_lint_actually_detects_stopwatch_print():
    """The lint must not be a rubber stamp: it flags the timed-then-
    printed combination in both the attribute and the from-import
    spelling, via print and via sys.stderr.write."""
    hits = _finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    print('took', time.monotonic() - t0)\n")
    assert hits == [("x.py", "f", 4)]
    hits = _finder(
        "import sys\n"
        "from time import perf_counter as pc\n"
        "def g():\n"
        "    t0 = pc()\n"
        "    sys.stderr.write('%f\\n' % (pc() - t0))\n")
    assert hits == [("x.py", "g", 5)]


def test_lint_ignores_benign_timing():
    """Timing into a variable/stats dict (no console write) and printing
    without a stopwatch are both fine — separately or in sibling
    functions."""
    assert _finder(
        "import time\n"
        "def f():\n"
        "    t0 = time.monotonic()\n"
        "    return time.monotonic() - t0\n"
        "def g():\n"
        "    print('hello')\n") == []
