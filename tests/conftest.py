"""Test harness config.

Tests never touch real TPU hardware: JAX is forced onto CPU with 8 virtual
devices so multi-chip sharding (dp/tp/sp meshes) is exercised hermetically —
the TPU analogue of the reference's "many actors against a local etcd" test
strategy (SURVEY.md §4).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from edl_tpu.utils.cpu_mesh import force_cpu_env  # noqa: E402

# must run before jax backends initialize; also scrubs the TPU plugin's
# sitecustomize trigger so children spawned by integration tests stay on CPU
force_cpu_env(os.environ, 8)

import jax  # noqa: E402

# the axon TPU plugin's sitecustomize sets jax_platforms="axon,cpu" at
# interpreter start, overriding $JAX_PLATFORMS — force CPU back for tests
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from edl_tpu.coordination.embedded import (  # noqa: E402
    EmbeddedStore, set_global_endpoints)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cpu_subprocess_env(n_devices=2, **extra):
    """Environment for example/worker SUBPROCESSES on a hermetic
    n-device CPU platform: the force_cpu_env scrub recipe (the one true
    source — tests must not hand-roll JAX_PLATFORMS/XLA_FLAGS/axon
    scrubbing) plus PYTHONPATH, with ``extra`` vars merged on top."""
    env = force_cpu_env(dict(os.environ), n_devices)
    env["PYTHONPATH"] = REPO
    env.update(extra)
    return env


@pytest.fixture()
def store():
    """A fresh in-process coordination store per test."""
    with EmbeddedStore() as s:
        set_global_endpoints(s.endpoint)
        yield s


@pytest.fixture(params=["py", "native"])
def coord(request):
    """A CoordClient on an isolated root namespace, parametrized over both
    store backends: the Python StoreServer and the C++ edl_tpu_store binary
    (identical wire protocol)."""
    if request.param == "py":
        with EmbeddedStore() as s:
            set_global_endpoints(s.endpoint)
            client = s.client(root="test_job")
            yield client
            client.clean_root()
    else:
        from edl_tpu.coordination.client import CoordClient
        from edl_tpu.coordination.native import (NativeStoreServer,
                                                 ensure_binary)
        try:
            ensure_binary()
        except Exception as e:  # no C++ toolchain → skip, don't error
            pytest.skip("native store unavailable: %r" % e)
        with NativeStoreServer() as s:
            set_global_endpoints(s.endpoint)
            yield CoordClient([s.endpoint], root="test_job")
