"""Cluster generator + barrier protocol tests — many actors in one process
against the embedded store (reference parity: test_cluster_generator.py,
test_leader_pod.py shapes)."""

import os
import threading
import time

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, status
from edl_tpu.controller.barrier import PodServer, barrier_wait
from edl_tpu.controller.cluster_generator import Generator
from edl_tpu.controller.env import JobEnv
from edl_tpu.controller.leader import LeaderElector, get_leader_id
from edl_tpu.controller.pod import Pod
from edl_tpu.controller.resource_pods import ResourceRegister


def _pod():
    os.environ["EDL_TPU_POD_IP"] = "127.0.0.1"
    args = type("A", (), dict(
        job_id="test_job", store_endpoints="x", nodes_range="1:4",
        nproc_per_node=1, pod_ip="127.0.0.1", checkpoint_path=None,
        log_dir=None, log_level=None))()
    return Pod.from_env(JobEnv(args))


def _wait(pred, timeout=15.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within %ss" % timeout)


def test_generator_initial_scale_out_and_shrink(coord):
    pod_a, pod_b, pod_c = _pod(), _pod(), _pod()
    reg_a = ResourceRegister(coord, pod_a)
    reg_b = ResourceRegister(coord, pod_b)
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    gen = Generator(coord, pod_a.id, min_nodes=2, max_nodes=3,
                    below_min_grace=1.0).start()
    try:
        c1 = _wait(lambda: cluster_mod.load_from_store(coord))
        assert len(c1.pods) == 2
        assert c1.pods[0].id == pod_a.id  # leader first
        assert [p.rank for p in c1.pods] == [0, 1]

        # scale out: pod_c joins
        reg_c = ResourceRegister(coord, pod_c)
        c2 = _wait(lambda: (lambda c: c if c and len(c.pods) == 3 else None)(
            cluster_mod.load_from_store(coord)))
        assert c2.stage != c1.stage
        assert pod_c.id in c2.pod_ids()

        # shrink: pod_c dies (lease revoked)
        reg_c.stop()
        c3 = _wait(lambda: (lambda c: c if c and len(c.pods) == 2 else None)(
            cluster_mod.load_from_store(coord)))
        assert c3.stage != c2.stage
        assert pod_c.id not in c3.pod_ids()

        # below min: pod_b dies → job FAILED (after the below-min grace)
        reg_b.stop()
        _wait(lambda: status.load_job_status(coord) == status.Status.FAILED)
    finally:
        gen.stop()
        reg_a.stop()


def test_generator_below_min_blip_is_not_fatal(coord):
    """A mass lease lapse (store failover / every launcher's heartbeat
    starved at once) drops live pods below min for up to a TTL, but the
    launchers are alive and re-register (register.py self-heals). The
    generator must ride out a below-min state shorter than its grace
    instead of instantly declaring the job FAILED."""
    pod_a, pod_b = _pod(), _pod()
    reg_a = ResourceRegister(coord, pod_a)
    reg_b = ResourceRegister(coord, pod_b)
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    gen = Generator(coord, pod_a.id, min_nodes=2, max_nodes=2,
                    below_min_grace=8.0).start()
    reg_b2 = None
    try:
        c1 = _wait(lambda: (lambda c: c if c and len(c.pods) == 2
                            else None)(cluster_mod.load_from_store(coord)))
        # the blip: pod_b's registration vanishes...
        reg_b.stop()
        time.sleep(2.0)  # several generator periods inside the grace
        assert status.load_job_status(coord) != status.Status.FAILED
        # ...and self-heals within the grace: the cluster rides through
        # UNCHANGED (no churn, no stage change, no failure)
        reg_b2 = ResourceRegister(coord, pod_b)
        time.sleep(3.0)  # well past the original grace expiry
        c2 = cluster_mod.load_from_store(coord)
        assert c2 is not None and len(c2.pods) == 2
        assert c2.stage == c1.stage, "blip churned the cluster"
        assert pod_b.id in c2.pod_ids()
        assert status.load_job_status(coord) != status.Status.FAILED
    finally:
        gen.stop()
        reg_a.stop()
        if reg_b2 is not None:
            reg_b2.stop()


def test_generator_commit_requires_leadership(coord):
    pod_a = _pod()
    reg = ResourceRegister(coord, pod_a)
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "someone_else")
    gen = Generator(coord, pod_a.id, min_nodes=1, max_nodes=2).start()
    try:
        time.sleep(3)
        assert cluster_mod.load_from_store(coord) is None
    finally:
        gen.stop()
        reg.stop()


def test_leader_elector_failover(coord):
    events = []
    e1 = LeaderElector(coord, "pod_1",
                       on_elected=lambda: events.append("e1+"),
                       on_lost=lambda: events.append("e1-")).start()
    _wait(lambda: e1.is_leader())
    assert get_leader_id(coord) == "pod_1"
    e2 = LeaderElector(coord, "pod_2",
                       on_elected=lambda: events.append("e2+")).start()
    time.sleep(1.0)
    assert not e2.is_leader()
    e1.stop()
    _wait(lambda: e2.is_leader(), timeout=20)
    assert get_leader_id(coord) == "pod_2"
    e2.stop()
    assert events[0] == "e1+" and "e2+" in events


def test_leader_stop_does_not_evict_successor(coord):
    """stop() on a stale leader must not delete a successor's key: the
    delete is guarded on the key still holding OUR pod id (ADVICE r1)."""
    e1 = LeaderElector(coord, "pod_1").start()
    _wait(lambda: e1.is_leader())
    # simulate a silent lease expiry + successor seize while e1 still
    # believes it leads (e.g. a process pause longer than the TTL)
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "pod_2")
    assert e1.is_leader()
    e1.stop()
    assert get_leader_id(coord) == "pod_2"  # successor untouched


def test_barrier_all_pods_get_cluster(coord):
    pod_a, pod_b = _pod(), _pod()
    regs = [ResourceRegister(coord, pod_a)]
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    server = PodServer(coord, pod_a).start()
    # re-register pod_a now that its barrier port is known
    regs[0].stop()
    regs = [ResourceRegister(coord, pod_a), ResourceRegister(coord, pod_b)]
    gen = Generator(coord, pod_a.id, min_nodes=2, max_nodes=2).start()
    results = {}

    def arrive(pod):
        results[pod.id] = barrier_wait(coord, pod.id, timeout=30)

    try:
        threads = [threading.Thread(target=arrive, args=(p,))
                   for p in (pod_a, pod_b)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=40)
        assert set(results) == {pod_a.id, pod_b.id}
        stages = {c.stage for c in results.values()}
        assert len(stages) == 1
        assert all(len(c.pods) == 2 for c in results.values())
    finally:
        gen.stop()
        server.stop()
        for r in regs:
            r.stop()


def test_generator_failover_guard_holds_membership(coord):
    """While the promoted standby's failover guard key exists, a pod
    whose registration vanished (lease nuked by the failover, launcher
    alive and about to re-register) must be KEPT in the cluster;
    explicit FAILED still evicts; once the guard expires/clears, a
    still-missing pod is genuinely gone."""
    from edl_tpu.coordination.standby import FAILOVER_GUARD_KEY

    pod_a, pod_b = _pod(), _pod()
    reg_a = ResourceRegister(coord, pod_a)
    reg_b = ResourceRegister(coord, pod_b)
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    gen = Generator(coord, pod_a.id, min_nodes=1, max_nodes=2,
                    below_min_grace=1.0).start()
    try:
        c1 = _wait(lambda: (lambda c: c if c and len(c.pods) == 2
                            else None)(cluster_mod.load_from_store(coord)))
        # the failover: guard planted, pod_b's registration vanishes
        coord.put(FAILOVER_GUARD_KEY, "promoted_by=test")
        reg_b.stop()
        time.sleep(2.0)
        c2 = cluster_mod.load_from_store(coord)
        assert c2.stage == c1.stage and len(c2.pods) == 2, \
            "guarded membership churned"
        # settle window ends with pod_b still missing: now it IS gone
        coord.delete(FAILOVER_GUARD_KEY)
        c3 = _wait(lambda: (lambda c: c if c and len(c.pods) == 1
                            else None)(cluster_mod.load_from_store(coord)))
        assert pod_b.id not in c3.pod_ids()
    finally:
        gen.stop()
        reg_a.stop()


# -- health-advisory eviction ordering (PR: fleet health verdicts) ---------


class _NullCoord(object):
    """Just enough store surface for a direct _next_cluster call."""

    def get_key(self, key):
        return None

    def get_service(self, service):
        return []


def _victim_gen(victims, leader_id):
    # room for a 5th pod to join, but topology caps the cluster at 4:
    # admitting the joiner forces a one-pod drop, which is where the
    # eviction-order hook bites
    return Generator(_NullCoord(), leader_id, min_nodes=1, max_nodes=5,
                     topology_valid=lambda n: n <= 4,
                     preferred_victims=lambda: victims)


def _cluster_of(pods):
    c = cluster_mod.Cluster()
    c.pods = list(pods)
    return c


def test_scale_in_evicts_flagged_straggler_over_tail_default():
    """A joiner over capacity forces a one-pod drop; with a health
    verdict naming pod c, the eviction lands on c and the newcomer is
    admitted (default order would have dropped the newcomer)."""
    a, b, c, d, e = (_pod() for _ in range(5))
    gen = _victim_gen([c.id], a.id)
    resources = {p.id: p for p in (a, b, c, d, e)}
    new = gen._next_cluster(_cluster_of([a, b, c, d]), resources, {})
    assert new is not None
    assert set(p.id for p in new.pods) == {a.id, b.id, d.id, e.id}


def test_scale_in_takes_worst_ranked_victim_first():
    """Victims are ranked worst-first by the monitor; a single-pod drop
    must consume rank 0, not whichever victim happens to sit later."""
    a, b, c, d, e = (_pod() for _ in range(5))
    gen = _victim_gen([c.id, b.id], a.id)  # c is ranked worse than b
    resources = {p.id: p for p in (a, b, c, d, e)}
    new = gen._next_cluster(_cluster_of([a, b, c, d]), resources, {})
    ids = set(p.id for p in new.pods)
    assert c.id not in ids and b.id in ids


def test_scale_in_never_evicts_the_leader():
    """The hook is advisory: flagging the generator's own pod must not
    decapitate the job."""
    a, b, c, d, e = (_pod() for _ in range(5))
    gen = _victim_gen([a.id], a.id)
    resources = {p.id: p for p in (a, b, c, d, e)}
    new = gen._next_cluster(_cluster_of([a, b, c, d]), resources, {})
    ids = set(p.id for p in new.pods)
    assert a.id in ids and e.id not in ids  # default tail-drop instead


def test_scale_in_victim_hook_fails_open():
    a, b, c, d, e = (_pod() for _ in range(5))

    def boom():
        raise RuntimeError("monitor not ready")

    gen = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5,
                    topology_valid=lambda n: n <= 4,
                    preferred_victims=boom)
    resources = {p.id: p for p in (a, b, c, d, e)}
    new = gen._next_cluster(_cluster_of([a, b, c, d]), resources, {})
    assert set(p.id for p in new.pods) == {a.id, b.id, c.id, d.id}


def test_generator_loop_scale_in_prefers_flagged_straggler(coord):
    """End to end against the store: an even-sizes-only topology forces
    a 4->2 shrink when one pod dies; the health-flagged pod is evicted
    instead of the tail default."""
    pods = [_pod() for _ in range(4)]
    regs = [ResourceRegister(coord, p) for p in pods]
    leader = pods[0]
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, leader.id)
    gen = Generator(coord, leader.id, min_nodes=2, max_nodes=4,
                    topology_valid=lambda n: n % 2 == 0,
                    below_min_grace=1.0,
                    preferred_victims=lambda: [pods[1].id]).start()
    try:
        _wait(lambda: (lambda c: c and len(c.pods) == 4)(
            cluster_mod.load_from_store(coord)))
        regs[3].stop()  # pod 3 dies; 3 is topology-invalid -> shrink to 2
        c2 = _wait(lambda: (lambda c: c if c and len(c.pods) == 2
                            else None)(cluster_mod.load_from_store(coord)))
        assert set(c2.pod_ids()) == {pods[0].id, pods[2].id}, \
            "flagged straggler survived the shrink"
    finally:
        gen.stop()
        for i, r in enumerate(regs):
            if i != 3:
                r.stop()
