"""Liveft launch supervisor e2e: two real supervisor processes against a
real store; a scale signal (np 2→1 + host loss) must RESTART the
surviving trainer with a fresh rank assignment; trainer exit 0 completes
the job (reference flow: edl/liveft/launch.py:24-59)."""

import os
import signal
import subprocess
import sys
import time

import pytest

TRAINER = """\
import os, sys, time
log, done = sys.argv[1], sys.argv[2]
with open(log, "a") as f:
    f.write("%s rank=%s np=%s\\n" % (os.environ["EDL_TPU_LIVEFT_HOST"],
                                     os.environ["EDL_TPU_LIVEFT_RANK"],
                                     os.environ["EDL_TPU_LIVEFT_NP"]))
    f.flush()
while not os.path.exists(done):
    time.sleep(0.1)
sys.exit(0)
"""


def _read_lines(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _wait_for(pred, timeout=40, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError("timed out waiting for %s" % what)


def test_liveft_launch_scale_restart(store, tmp_path):
    trainer_py = tmp_path / "trainer.py"
    trainer_py.write_text(TRAINER)
    log = str(tmp_path / "ranks.log")
    done = str(tmp_path / "done")

    def supervisor(host):
        return subprocess.Popen(
            [sys.executable, "-m", "edl_tpu.liveft.launch",
             "--store_endpoints", store.endpoint, "--job_id", "lf_job",
             "--host", host, "--np", "2", "--ttl", "3",
             "--", sys.executable, str(trainer_py), log, done],
            env=dict(os.environ, PYTHONPATH=os.getcwd()),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    sup_a, sup_b = supervisor("node_a"), supervisor("node_b")
    try:
        # both trainers come up with distinct ranks in a 2-world
        _wait_for(lambda: len([ln for ln in _read_lines(log)
                               if "np=2" in ln]) >= 2,
                  what="both trainers started at np=2")
        first = [ln for ln in _read_lines(log) if "np=2" in ln]
        assert {ln.split()[1] for ln in first} == {"rank=0", "rank=1"}

        # scale signal: np -> 1, and node_b disappears (supervisor killed;
        # its lease expires after the ttl)
        from edl_tpu.coordination.client import CoordClient
        from edl_tpu.liveft.elastic import NP_KEY, SERVICE_CONF
        coord = CoordClient([store.endpoint], root="lf_job")
        sup_b.send_signal(signal.SIGTERM)
        sup_b.wait(timeout=20)
        coord.set_server_permanent(SERVICE_CONF, NP_KEY, "1")

        # the survivor must respawn its trainer as rank 0 of a 1-world
        _wait_for(lambda: any("node_a rank=0 np=1" == ln
                              for ln in _read_lines(log)),
                  what="node_a restarted as rank 0 of np=1")

        # trainer completion (exit 0) completes the supervisor with rc 0
        with open(done, "w") as f:
            f.write("x")
        assert sup_a.wait(timeout=30) == 0
    finally:
        for p in (sup_a, sup_b):
            if p.poll() is None:
                p.kill()
                p.wait()


def test_liveft_exit_on_restart_mode(store, tmp_path):
    """Reference behavior: --exit-on-restart exits 101 on the scale event
    so an external supervisor (k8s) can restart the pod."""
    trainer_py = tmp_path / "trainer.py"
    trainer_py.write_text(TRAINER)
    log = str(tmp_path / "ranks.log")
    done = str(tmp_path / "done")

    sup = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.liveft.launch",
         "--store_endpoints", store.endpoint, "--job_id", "lf_job2",
         "--host", "solo", "--np", "1", "--ttl", "3", "--exit-on-restart",
         "--", sys.executable, str(trainer_py), log, done],
        env=dict(os.environ, PYTHONPATH=os.getcwd()),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        _wait_for(lambda: len(_read_lines(log)) >= 1,
                  what="trainer started")
        # trainer asks for a restart by exiting 101 — simulate via np bump
        # (a membership-level scale event): np 1 -> ... back to 1 won't
        # trigger; instead kill the trainer with exit 101 through the done
        # protocol is exit 0, so use the np key with a second registrant.
        from edl_tpu.coordination.client import CoordClient
        from edl_tpu.liveft.elastic import (ELASTIC_EXIT_CODE, NP_KEY,
                                            SERVICE_CONF, SERVICE_NODES)
        coord = CoordClient([store.endpoint], root="lf_job2")
        # a second host joins and np goes to 2 → RESTART verdict
        lease = coord.set_server_with_lease(SERVICE_NODES, "joiner",
                                            "t", 30)
        coord.set_server_permanent(SERVICE_CONF, NP_KEY, "2")
        assert sup.wait(timeout=30) == ELASTIC_EXIT_CODE
        coord.lease_revoke(lease)
    finally:
        if sup.poll() is None:
            sup.kill()
            sup.wait()
