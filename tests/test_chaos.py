"""Chaos suite: deterministic fault injection (edl_tpu.robustness.faults)
driven through the real control plane, plus unit coverage for the unified
retry / deadline / circuit-breaker policy layer.

Every scenario arms a seeded FaultPlane, runs a real multi-actor drill
(liveft rendezvous, barrier, store failover, distill reads) and asserts
BOTH that the faults actually fired (``Fault.fired`` counters / the
plane's log) and that the system converged within its deadline — a chaos
test that cannot prove its faults fired is indistinguishable from a
green run with the chaos plane disabled.

Store-level fault points only exist in the Python store, so these tests
build their own EmbeddedStore rather than using the parametrized
``coord`` fixture (the native C++ backend has no hooks).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from edl_tpu.controller import constants
from edl_tpu.controller.barrier import PodServer, barrier_wait
from edl_tpu.controller.cluster_generator import Generator
from edl_tpu.controller.env import JobEnv
from edl_tpu.controller.pod import Pod
from edl_tpu.controller.resource_pods import ResourceRegister
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.embedded import EmbeddedStore
from edl_tpu.coordination.server import StoreServer
from edl_tpu.coordination.standby import StandbyServer, WitnessServer
from edl_tpu.distill.distill_reader import DistillReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.liveft.elastic import ElasticManager
from edl_tpu.robustness import faults, policy
from edl_tpu.robustness.faults import (FaultPlane, FaultSpecError,
                                       plane_from_spec)
from edl_tpu.robustness.policy import CircuitBreaker, Deadline, RetryPolicy
from edl_tpu.utils import errors

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SEED = 20240805


@pytest.fixture()
def plane():
    """A fresh installed FaultPlane; ALWAYS uninstalled on teardown (the
    plane is process-global — leaking one would chaos every later test)."""
    p = FaultPlane(seed=SEED).install()
    yield p
    p.uninstall()
    assert faults.PLANE is None


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return False


def _pod():
    os.environ["EDL_TPU_POD_IP"] = "127.0.0.1"
    args = type("A", (), dict(
        job_id="chaos_job", store_endpoints="x", nodes_range="1:4",
        nproc_per_node=1, pod_ip="127.0.0.1", checkpoint_path=None,
        log_dir=None, log_level=None))()
    return Pod.from_env(JobEnv(args))


# ---------------------------------------------------------------------------
# the plane itself: gate, determinism, spec grammar, env activation
# ---------------------------------------------------------------------------


def test_plane_disabled_by_default():
    assert faults.PLANE is None


def test_same_seed_same_schedule():
    """The determinism contract: equal seeds driven through equal match
    sequences produce equal fault schedules, regardless of how many
    other faults are armed."""
    def drive(seed, extra_fault=False):
        p = FaultPlane(seed=seed)
        p.inject("x.point", "drop", prob=0.3)
        if extra_fault:
            # a second fault at the same point must not perturb the
            # first one's stream (per-fault RNG, not a shared plane RNG)
            p.inject("x.point", "delay", seconds=0.0, prob=0.5)
        for i in range(200):
            p.fire("x.point", idx=i)
        drop = p._faults["x.point"][0]
        return [e for e in p.log if e == ("x.point", "drop")], drop.fired

    assert drive(7) == drive(7)
    assert drive(7) == drive(7, extra_fault=True)
    assert drive(7) != drive(8)


def test_fault_filters_and_scheduling():
    p = FaultPlane(seed=1)
    f = p.inject("pt", "drop", method="barrier", after=2, times=2)
    for _ in range(3):
        assert p.fire("pt", method="store_put") is None  # filtered out
    hits = [p.fire("pt", method="barrier") for _ in range(6)]
    # after=2 skips the first two matches; times=2 caps firings
    assert [h is not None for h in hits] == [False, False, True, True,
                                             False, False]
    assert f.fired == 2 and f.matched == 6


def test_error_kind_raises_typed_errors():
    p = FaultPlane()
    p.inject("pt", "error_once", error="LeaseExpiredError")
    with pytest.raises(errors.LeaseExpiredError):
        p.fire("pt")
    assert p.fire("pt") is None  # error_once defaults to times=1


def test_fault_spec_grammar():
    p = plane_from_spec("seed=7;rpc.server.request:drop(method=barrier,"
                        "times=2);store.lease.refresh:delay(seconds=0.01)")
    assert p.seed == 7
    f = p._faults["rpc.server.request"][0]
    assert f.kind == "drop" and f.times == 2
    assert f.filters == {"method": "barrier"}
    d = p._faults["store.lease.refresh"][0]
    assert d.params["seconds"] == 0.01
    assert faults.PLANE is None  # parsing must not install


@pytest.mark.parametrize("bad", ["", "   ", "nonsense", "p:frobnicate",
                                 "p:drop(x", "p:drop(times)"])
def test_fault_spec_malformed_fails_loudly(bad):
    with pytest.raises(FaultSpecError):
        plane_from_spec(bad)


def test_env_spec_activates_plane_in_subprocess():
    """EDL_TPU_FAULT_SPEC places a whole process under chaos at import —
    the mechanism integration tests use on their worker subprocesses."""
    code = ("from edl_tpu.robustness import faults; "
            "assert faults.PLANE is not None; "
            "f = faults.PLANE._faults['rpc.frame.write'][0]; "
            "assert f.kind == 'drop' and f.times == 1; "
            "print(faults.PLANE.seed)")
    env = dict(os.environ, PYTHONPATH=REPO,
               EDL_TPU_FAULT_SPEC="seed=9;rpc.frame.write:drop(times=1)")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert out.stdout.strip() == "9"


# ---------------------------------------------------------------------------
# policy layer units
# ---------------------------------------------------------------------------


def test_deadline_budget_cap_union():
    d = Deadline(10.0)
    assert 0 < d.remaining() <= 10.0
    assert d.remaining(cap=0.5) == 0.5
    assert not d.expired()
    tight = Deadline(0.0)
    assert tight.expired()
    with pytest.raises(errors.DeadlineExceededError):
        tight.check("op")
    assert not tight.sleep(1.0)  # no budget: no sleep, returns False
    assert d.union(tight) is tight  # budget intersection = the earlier
    assert policy.FOREVER.remaining() is None
    assert policy.FOREVER.remaining(cap=3.0) == 3.0
    assert policy.FOREVER.union(d) is d
    # DeadlineExceededError stays catchable as the pre-existing timeout
    assert issubclass(errors.DeadlineExceededError, errors.TimeoutError_)


def test_retry_policy_jitter_is_seeded_and_capped():
    mk = lambda: RetryPolicy(base_delay=0.1, max_delay=5.0,  # noqa: E731
                             multiplier=2.0, jitter=0.5, seed=3)
    a = [mk().delay(i) for i in range(1, 10)]
    b = [mk().delay(i) for i in range(1, 10)]
    assert a == b  # same seed, same jitter stream
    assert all(d <= 5.0 * 1.5 for d in a)  # max_delay * (1 + jitter)
    assert all(d >= 0.1 * 0.5 for d in a)  # base_delay * (1 - jitter)


def test_retry_call_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise errors.ConnectError("boom")
        return "ok"

    p = RetryPolicy(base_delay=0.01, max_delay=0.02, seed=1)
    assert p.call(flaky, deadline=Deadline(10.0)) == "ok"
    assert len(calls) == 3


def test_retry_call_deadline_exhaustion_raises_deadline_error():
    p = RetryPolicy(base_delay=0.05, max_delay=0.05, seed=1)

    def always():
        raise errors.ConnectError("down")

    t0 = time.monotonic()
    with pytest.raises(errors.DeadlineExceededError) as ei:
        p.call(always, deadline=Deadline(0.3))
    assert time.monotonic() - t0 < 5.0  # the budget bounded the loop
    assert isinstance(ei.value.__cause__, errors.ConnectError)


def test_retry_call_max_attempts_and_give_up():
    n = [0]

    def always():
        n[0] += 1
        raise errors.ConnectError("x")

    p = RetryPolicy(max_attempts=3, base_delay=0.01, seed=1)
    with pytest.raises(errors.ConnectError):
        p.call(always)
    assert n[0] == 3

    def stopper():
        raise errors.StopError("halt")

    with pytest.raises(errors.StopError):
        p.call(stopper)  # give_up_on short-circuits, no retries


def test_circuit_breaker_state_machine():
    t = [0.0]
    cb = CircuitBreaker(failure_threshold=2, reset_timeout=5.0,
                        half_open_max=1, clock=lambda: t[0])
    assert cb.allow("ep") and cb.state("ep") == cb.CLOSED
    cb.record_failure("ep")
    assert cb.allow("ep")  # one failure below threshold: still closed
    cb.record_failure("ep")
    assert cb.state("ep") == cb.OPEN and not cb.allow("ep")
    t[0] += 5.1  # reset window elapses -> half-open
    assert cb.allow("ep") and cb.state("ep") == cb.HALF_OPEN
    assert not cb.allow("ep")  # half_open_max=1: second probe denied
    cb.record_failure("ep")  # probe failed -> re-open, clock restarts
    assert cb.state("ep") == cb.OPEN and not cb.allow("ep")
    t[0] += 5.1
    assert cb.allow("ep")
    cb.record_success("ep")  # probe succeeded -> closed
    assert cb.state("ep") == cb.CLOSED and cb.allow("ep")


def test_circuit_breaker_prune_bounds_state():
    cb = CircuitBreaker()
    for i in range(100):
        cb.record_failure("ghost-%d" % i)
    cb.prune(["live-1", "ghost-7"])
    assert set(cb.keys()) == {"ghost-7"}  # live-1 never had state


def test_distill_breaker_state_is_pruned_to_live_teachers():
    """Regression for the unbounded ``_recent_failures`` map the breaker
    replaced: teacher endpoint churn must not grow reader state."""
    dr = DistillReader(ins=["img"], predicts=["p"], teacher_backoff=60)
    live = ["127.0.0.1:7001", "127.0.0.1:7002"]
    dr.set_fixed_teacher(live)
    for i in range(50):
        dr._breaker.record_failure("10.9.9.%d:1" % i)  # churned-away eps
    for ep in live:
        dr._breaker.record_failure(ep)  # open: _sync_workers won't dial
    dr._sync_workers()
    assert set(dr._breaker.keys()) == set(live)
    assert dr._workers == {}  # open circuits gated the dials
    dr.stop()


# ---------------------------------------------------------------------------
# scenario A: lease expiry during a liveft rendezvous
# ---------------------------------------------------------------------------


def test_chaos_liveft_lease_expiry_mid_wait(plane):
    """One manager's lease refreshes are dropped and its re-registration
    attempts error; its lease genuinely expires mid-run (the expiry
    sweep fires), membership visibly shrinks, and both managers still
    converge back to full strength within their deadlines."""
    with EmbeddedStore() as s:
        coord_a = s.client(root="chaos_liveft")
        coord_b = s.client(root="chaos_liveft")
        m1 = ElasticManager(coord_a, "h1:8470", 2, ttl=1.5).start()
        m2 = ElasticManager(coord_b, "h2:8470", 2, ttl=1.5).start()
        try:
            both = ["h1:8470", "h2:8470"]
            assert m1.wait(timeout=30) == both
            assert m2.wait(timeout=30) == both

            # pick the victim whose lease id can't substring-match the
            # survivor's (filters are substring matches)
            l1, l2 = str(m1._lease), str(m2._lease)
            victim = m1 if l1 not in l2 else m2
            drop = plane.inject("store.lease.refresh", "drop",
                                lease_id=str(victim._lease), times=50)
            grant_err = plane.inject("store.lease.grant", "error",
                                     error="RpcError", times=3)
            expired = plane.inject("store.lease.expire", "delay",
                                   seconds=0.0)  # observer: logs expiries

            # the victim's key must actually vanish: both watchers see
            # membership fall below the agreed set
            assert _wait(lambda: m1._hosts_changed.is_set()
                         or m2._hosts_changed.is_set(), timeout=20), \
                "lease never expired / watchers never saw the shrink"
            assert expired.fired >= 1
            assert drop.fired >= 1 and grant_err.fired >= 1

            # ...and the plane converges back to full strength
            assert m1.wait(timeout=30) == both
            assert m2.wait(timeout=30) == both
        finally:
            m1.stop()
            m2.stop()


# ---------------------------------------------------------------------------
# scenario B: barrier frames dropped during the resize rendezvous
# ---------------------------------------------------------------------------


def test_chaos_barrier_converges_through_dropped_frames(plane):
    """Barrier requests are dropped (server never answers) and errored at
    the dispatch layer; the jittered retry cadence still gets every pod
    the same cluster within the barrier deadline."""
    with EmbeddedStore() as s:
        coord = s.client(root="chaos_barrier")
        pod_a, pod_b = _pod(), _pod()
        reg_a = ResourceRegister(coord, pod_a)
        coord.set_server_permanent(constants.SERVICE_LEADER,
                                   constants.LEADER_SERVER, pod_a.id)
        server = PodServer(coord, pod_a).start()
        # re-register pod_a now that its barrier port is known
        reg_a.stop()
        regs = [ResourceRegister(coord, pod_a),
                ResourceRegister(coord, pod_b)]
        gen = Generator(coord, pod_a.id, min_nodes=2, max_nodes=2).start()

        # one silent drop (client eats a full socket timeout) + two
        # dispatch-layer errors (fast retries); method filter keeps the
        # store's own RPC server out of blast radius
        drop = plane.inject("rpc.server.request", "drop",
                            method="barrier", times=1)
        err = plane.inject("rpc.server.request", "error",
                           method="barrier", error="BarrierError", times=2)
        results = {}

        def arrive(pod):
            results[pod.id] = barrier_wait(coord, pod.id, timeout=60)

        try:
            threads = [threading.Thread(target=arrive, args=(p,))
                       for p in (pod_a, pod_b)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=70)
            assert set(results) == {pod_a.id, pod_b.id}, \
                "a pod never cleared the barrier under chaos"
            assert len({c.stage for c in results.values()}) == 1
            assert all(len(c.pods) == 2 for c in results.values())
            assert drop.fired == 1 and err.fired == 2
        finally:
            gen.stop()
            server.stop()
            for r in regs:
                r.stop()


# ---------------------------------------------------------------------------
# scenario C: store leader failover under client load
# ---------------------------------------------------------------------------


def test_chaos_store_failover_under_load(plane):
    """A writer streams permanent puts through a [primary, standby]
    client while the primary is killed; the standby promotes; every
    single write is acked exactly once in order and the final state is
    the last write — no lost acks, no error surfaced to the writer."""
    primary = StoreServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=1.0,
                       sync_poll=0.25).start()
    client = CoordClient([primary.endpoint, sb.endpoint],
                         root="chaos_ha", failover_grace=30.0)
    # chaos garnish on the data path: jittered per-call delays
    plane.inject("rpc.client.call", "delay", method="store_put",
                 seconds=0.005, times=20)

    n_writes = 120
    acked, write_errors = [], []

    def writer():
        for i in range(n_writes):
            try:
                client.set_server_permanent("seq", "k", str(i))
            except errors.EdlError as e:
                write_errors.append(e)
                return
            acked.append(i)
            time.sleep(0.01)

    t = threading.Thread(target=writer, name="chaos-writer", daemon=True)
    try:
        assert _wait(sb.synced.is_set)
        t.start()
        assert _wait(lambda: len(acked) >= 10)
        primary.stop()  # the outage, mid-stream
        assert _wait(lambda: sb.promoted, timeout=30)
        t.join(timeout=90)
        assert not t.is_alive(), "writer wedged across the failover"
        assert write_errors == []
        assert acked == list(range(n_writes))
        assert client.get_value("seq", "k") == str(n_writes - 1)
    finally:
        if t.ident is not None:
            t.join(timeout=1)
        sb.stop()


# ---------------------------------------------------------------------------
# scenario D: teacher endpoint flap during distill reads
# ---------------------------------------------------------------------------


def test_chaos_teacher_flap_during_distill_reads(plane):
    """Mid-epoch, predict calls error (workers retire, the breaker
    opens) and discovery briefly reports zero teachers (all workers torn
    down, in-flight tasks requeued); the epoch still yields every batch
    in order with correct values."""
    def echo(feed):
        return {"soft_label": feed["img"] * 2.0}

    teachers = [TeacherServer(echo, {"img": ([2], "<f4")},
                              {"soft_label": ([2], "<f4")},
                              max_batch=16, host="127.0.0.1").start()
                for _ in range(2)]

    def gen():
        for i in range(20):
            yield (np.full((4, 2), i, np.float32),)

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=4, teacher_backoff=0.5)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([t.endpoint for t in teachers])
    predict_err = plane.inject("rpc.client.call", "error",
                               method="predict", error="ConnectError",
                               times=2)
    flap = plane.inject("distill.discovery", "drop", after=1, times=2)
    try:
        seen = []
        for batch in dr():
            img, soft = batch
            assert np.allclose(soft, img * 2.0)
            seen.append(int(img[0, 0]))
        assert seen == list(range(20))
        assert predict_err.fired == 2, "predict faults never fired"
        assert flap.fired >= 1, "discovery flap never fired"
    finally:
        dr.stop()
        for t in teachers:
            t.stop()


# ---------------------------------------------------------------------------
# satellite: witness-probe failover under injected RPC timeouts
# ---------------------------------------------------------------------------


def test_chaos_witness_probe_timeouts_fail_safe_then_promote(plane):
    """Injected timeouts on the witness probe path: with zero witness
    answers the standby must NOT promote (no evidence = fail safe); once
    the probes recover and the witness corroborates the dead primary,
    promotion proceeds and the sync loop has survived the fault storm."""
    primary = StoreServer(host="127.0.0.1").start()
    witness = WitnessServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=0.5,
                       sync_poll=0.25,
                       witness_endpoints=[witness.endpoint]).start()
    # two probe attempts = one full corroboration pass (retry policy
    # allows max_attempts=2): the first pass sees only timeouts
    probe_err = plane.inject("standby.witness.probe", "error",
                             error="TimeoutError_", times=2)
    try:
        assert _wait(sb.synced.is_set)
        primary.stop()
        assert _wait(lambda: sb.promoted, timeout=60), \
            "standby never promoted after probe faults cleared"
        assert probe_err.fired == 2
        # the denied pass really happened before the promoting one
        assert plane.log.count(("standby.witness.probe", "error")) == 2
    finally:
        sb.stop()
        witness.stop()


# ---------------------------------------------------------------------------
# satellite: rpc.frame.* faults against a pipelined connection
# ---------------------------------------------------------------------------


def test_chaos_frame_corrupt_fails_all_pipelined_inflight(plane):
    """A corrupted frame on a pipelined connection desyncs the whole
    stream: every call in flight fails with ConnectError (no silent
    loss, no misparse) and the next call dials a clean connection."""
    from edl_tpu.rpc.client import RpcClient
    from edl_tpu.rpc.server import RpcServer

    gate = threading.Event()
    srv = RpcServer(host="127.0.0.1", port=0)
    srv.register("echo", lambda x: x)
    srv.register("gated", lambda x: (gate.wait(10), x)[1])
    srv.start()
    c = RpcClient("127.0.0.1:%d" % srv.port, timeout=10)
    try:
        assert c.call("echo", 0) == 0  # connection warmed, fault unarmed
        # unlimited while armed: the point is process-global, so a
        # stray writer from another component must not eat the only
        # firing before our request goes out
        corrupt = plane.inject("rpc.frame.write", "corrupt")
        futs = [c.call_async("gated", i) for i in range(4)]
        gate.set()
        # the armed write replaced request 0 with a garbage magic: the
        # server kills the stream, so EVERY in-flight future fails
        for fut in futs:
            with pytest.raises(errors.ConnectError):
                fut.result(timeout=10)
        assert corrupt.fired >= 1
        plane.clear("rpc.frame.write")
        assert c.call("echo", "recovered") == "recovered"  # fresh dial
    finally:
        c.close()
        srv.stop()
        gate.set()


def test_chaos_frame_faults_during_pipelined_distill(plane):
    """rpc.frame.write corruption under a pipelined DistillReader with
    an adaptive-batching teacher: in-flight tasks are requeued, the
    epoch still delivers every batch exactly once, in order."""
    def fn(feed):
        return {"soft_label": feed["img"] * 2.0}

    teacher = TeacherServer(fn, {"img": ([2], "<f4")},
                            {"soft_label": ([2], "<f4")},
                            max_batch=16, host="127.0.0.1").start()

    def gen():
        for i in range(20):
            yield np.full((4, 2), i, np.float32),

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=8, pipeline_depth=4,
                       teacher_backoff=0.5, predict_timeout=10)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([teacher.endpoint])
    # arm only after the reader's discovery/get_feed_fetch calls by
    # matching nothing until the data plane is live would be racy —
    # instead allow the first few frames through with after=
    corrupt = plane.inject("rpc.frame.write", "corrupt", after=4,
                           times=2)
    try:
        seen = []
        for img, soft in dr():
            np.testing.assert_allclose(soft, img * 2.0)
            seen.append(int(img[0, 0]))
        assert seen == list(range(20))  # exactly once, in order
        assert corrupt.fired == 2, "frame faults never fired"
    finally:
        dr.stop()
        teacher.stop()


def _peer_plane_fixture(tmp_path, root):
    """(store, coord, cm, srv, tree, target, shardings) for the peer
    restore chaos drills: one committed stream checkpoint, one live
    peer serving the same version."""
    import jax
    from jax.sharding import SingleDeviceSharding

    from edl_tpu.runtime.checkpoint import CheckpointManager
    from edl_tpu.runtime.state_server import StateServer, snapshot_entries

    store = StoreServer(host="127.0.0.1", port=0).start()
    coord = CoordClient([store.endpoint], root=root)
    rng = np.random.RandomState(11)
    tree = {"w": rng.randn(32, 8).astype(np.float32),
            "b": rng.randn(8).astype(np.float32),
            "step": np.int32(5)}
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(5, tree, meta={"state": {"epoch": 2}}).result(60.0)
    srv = StateServer(rank=0, host="127.0.0.1")
    entries, dtypes = snapshot_entries(tree)
    srv.publish(5, entries, dtypes, meta={"state": {"epoch": 2}})
    srv.advertise(coord)
    sh = SingleDeviceSharding(jax.devices("cpu")[0])
    target = {k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype)
              for k, v in tree.items()}
    shardings = {k: sh for k in tree}
    return store, coord, cm, srv, tree, target, shardings


def _assert_tree_bytes_equal(got, tree):
    for k, v in tree.items():
        ga = np.asarray(got[k])
        assert ga.dtype == v.dtype and ga.tobytes() == v.tobytes(), k


def test_chaos_peer_connect_partition_wholesale_fs_fallback(plane,
                                                            tmp_path):
    """Every peer unreachable at dial time: the peer plane raises
    PeerRestoreError and the caller's wholesale shared-FS restore
    yields bit-identical state (the trainer's outermost ladder rung)."""
    from edl_tpu.runtime.state_server import PeerRestorer

    store, coord, cm, srv, tree, target, shardings = \
        _peer_plane_fixture(tmp_path, "chaos_peer_conn")
    cut = plane.inject("peer_restore.connect", "partition")
    try:
        with pytest.raises(errors.PeerRestoreError):
            PeerRestorer(coord, cm).restore_placed(5, target, shardings)
        assert cut.fired >= 1, "connect fault never fired"
        v, got, meta = cm.restore_placed(5, target, shardings)
        assert v == 5 and meta == {"state": {"epoch": 2}}
        _assert_tree_bytes_equal(got, tree)
    finally:
        srv.stop()
        cm.close()
        store.stop()


def test_chaos_peer_death_mid_fetch_per_span_fs_fill(plane, tmp_path):
    """Peer dies mid-fetch (every range read errors after a healthy
    manifest): the failed spans are re-filled per-key from the shared
    FS and the result is bit-identical to a pure FS restore."""
    from edl_tpu.runtime.state_server import PeerRestorer

    store, coord, cm, srv, tree, target, shardings = \
        _peer_plane_fixture(tmp_path, "chaos_peer_read")
    die = plane.inject("peer_restore.read", "error",
                       error="ConnectError")
    try:
        v, got, meta, stats = PeerRestorer(coord, cm).restore_placed(
            5, target, shardings)
        assert die.fired >= 1, "read fault never fired"
        assert v == 5 and stats["source"] == "peer+fs"
        assert set(stats["fs_keys"]) == set(tree)
        _assert_tree_bytes_equal(got, tree)
        _, fs_got, _ = cm.restore_placed(5, target, shardings)
        _assert_tree_bytes_equal(fs_got, tree)
    finally:
        srv.stop()
        cm.close()
        store.stop()


def test_chaos_peer_read_error_once_partial_then_peer(plane, tmp_path):
    """A single faulted read: only that key falls back to FS, the rest
    still comes off the peer, and the assembled state is unchanged."""
    from edl_tpu.runtime.state_server import PeerRestorer

    store, coord, cm, srv, tree, target, shardings = \
        _peer_plane_fixture(tmp_path, "chaos_peer_once")
    once = plane.inject("peer_restore.read", "error_once")
    try:
        v, got, meta, stats = PeerRestorer(coord, cm).restore_placed(
            5, target, shardings)
        assert once.fired == 1
        assert stats["source"] == "peer+fs"
        assert len(stats["fs_keys"]) == 1
        _assert_tree_bytes_equal(got, tree)
    finally:
        srv.stop()
        cm.close()
        store.stop()


# ---------------------------------------------------------------------------
# data plane: pipelined fetch under fetch faults (exact lost-batch
# accounting) and assignment faults (retry-absorbed, zero loss)
# ---------------------------------------------------------------------------


def _data_files(tmp_path, n_files, lines_per_file):
    paths = []
    for i in range(n_files):
        p = tmp_path / ("part-%02d.txt" % i)
        p.write_text("".join("file%d_rec%d\n" % (i, j)
                             for j in range(lines_per_file)))
        paths.append(str(p))
    return paths


def test_chaos_data_fetch_faults_exact_lost_accounting(plane, tmp_path):
    """data.fetch drill: the first 3 remote fetches fail (deterministic
    times=3). Those exact batches are logged lost — no duplicates, no
    wedge — the epoch still converges to END, and the completion pass
    behind the data checkpoint recovers exactly the lost records."""
    from edl_tpu.data.reader import ElasticReader
    from edl_tpu.data.splitter import TxtFileSplitter
    from edl_tpu.runtime.state import State

    paths = _data_files(tmp_path, 4, 20)  # 80 records, 10 batches
    total = ["file%d_rec%d" % (f, j) for f in range(4) for j in range(20)]
    fault = plane.inject("data.fetch", "error", times=3)
    state = State()

    prod = ElasticReader("prod", TxtFileSplitter(), batch_size=8,
                         file_list=paths, is_leader=True)
    cons = ElasticReader("cons", TxtFileSplitter(), batch_size=8,
                         produce=False, leader_endpoint=prod.endpoint)
    got_batches, got = [], []
    try:
        for batch in cons:
            ElasticReader.mark_consumed(state, batch)
            got_batches.append(batch)
            got.extend(batch["records"])
        lost = cons.stats()["lost"]
        stats = prod._leader.call("ds_stats")
    finally:
        cons.stop()
        prod.stop()

    assert fault.fired == 3                       # the chaos happened
    assert sorted(lost) == sorted(set(lost)) and len(lost) == 3
    assert len(got) == len(set(got))              # nothing duplicated
    # EXACT accounting: every assignment the leader handed out was
    # delivered or logged lost
    assert stats["consumed"] == len(got_batches) + len(lost)

    plane.clear()  # the completion pass runs chaos-free
    state2 = State().from_json(state.to_json())
    rest = []
    sweeper = ElasticReader("sweep", TxtFileSplitter(), batch_size=8,
                            file_list=paths, is_leader=True,
                            skip_record=state2.data_checkpoint.is_processed)
    try:
        for batch in sweeper:
            rest.extend(batch["records"])
    finally:
        sweeper.stop()
    assert sorted(got + rest) == sorted(total)    # exactly once overall
    assert not set(got) & set(rest)
    # the sweep is EXACTLY the lost batches: 20 lines at batch_size 8
    # split 8/8/4, so a file's _b2 tail holds 4 records
    assert len(rest) == sum(4 if b.endswith("_b2") else 8 for b in lost)


def test_chaos_data_assign_fault_absorbed_by_retry(plane, tmp_path):
    """data.assign drill: a one-shot assignment failure is absorbed by
    the fetch pipeline's RetryPolicy — the epoch completes with ZERO
    loss and the consumer never sees the error."""
    from edl_tpu.data.reader import ElasticReader
    from edl_tpu.data.splitter import TxtFileSplitter

    paths = _data_files(tmp_path, 2, 16)  # 32 records
    fault = plane.inject("data.assign", "error_once")

    prod = ElasticReader("prod", TxtFileSplitter(), batch_size=8,
                         file_list=paths, is_leader=True)
    cons = ElasticReader("cons", TxtFileSplitter(), batch_size=8,
                         produce=False, leader_endpoint=prod.endpoint)
    try:
        got = []
        for batch in cons:
            got.extend(batch["records"])
        assert fault.fired == 1
        assert cons.stats()["lost"] == []
        assert sorted(got) == sorted(
            "file%d_rec%d" % (f, j) for f in range(2) for j in range(16))
    finally:
        cons.stop()
        prod.stop()
