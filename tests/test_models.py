"""Model family tests: BERT (incl. TP sharding equivalence and ring
attention), DeepFM, BOW with distillation loss."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.models import bert, bow, deepfm
from edl_tpu.parallel.sharding import shard_params
from edl_tpu.runtime import mesh as mesh_mod
from edl_tpu.runtime.trainer import ElasticTrainer


def test_bert_tiny_forward_and_learn(tmp_path):
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    trainer = ElasticTrainer(loss_fn, params, optax.adam(1e-3),
                             total_batch_size=16,
                             checkpoint_dir=str(tmp_path / "ckpt"))
    losses = []
    for i in range(12):
        batch = bert.synthetic_text_batch(16, seq_len=32, seed=i % 2)
        losses.append(float(trainer.train_step(batch)))
    assert losses[-1] < losses[0]


def test_bert_tp_sharded_matches_replicated():
    """The same BERT step, params TP-sharded via partition rules, must give
    the same loss as the replicated run (XLA inserts the collectives)."""
    model = bert.bert_tiny(dtype=jnp.float32)
    dummy = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), dummy)["params"]
    batch = bert.synthetic_text_batch(8, seq_len=16, seed=0)

    def loss_fn(p):
        logits = model.apply({"params": p},
                             jnp.asarray(batch["input_ids"]))
        one_hot = jax.nn.one_hot(jnp.asarray(batch["label"]), 2)
        return optax.softmax_cross_entropy(logits, one_hot).mean()

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)

    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    sharded_params, shardings = shard_params(params, mesh,
                                             bert.bert_partition_rules())
    # verify something actually got TP-sharded
    qkv = sharded_params["layer_0"]["attention"]["query"]["kernel"]
    assert qkv.sharding.spec == P(None, "tp", None)
    tp_loss, tp_grads = jax.jit(
        jax.value_and_grad(loss_fn),
        out_shardings=(NamedSharding(mesh, P()), shardings))(sharded_params)
    np.testing.assert_allclose(float(tp_loss), float(ref_loss), rtol=1e-5)
    ref_flat = jax.tree_util.tree_leaves(ref_grads)
    tp_flat = jax.tree_util.tree_leaves(tp_grads)
    for a, b in zip(ref_flat, tp_flat):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_bert_ring_attention_matches_dense():
    mesh = mesh_mod.make_mesh(dp=2, sp=4)
    kw = dict(num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
              vocab_size=100, max_len=64, dtype=jnp.float32)
    m_dense = bert.Bert(use_ring=False, **kw)
    m_ring = bert.Bert(use_ring=True, mesh=mesh, **kw)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 100, (4, 32)),
                      jnp.int32)
    params = m_dense.init(jax.random.PRNGKey(0), ids)["params"]
    out_d = m_dense.apply({"params": params}, ids)
    out_r = m_ring.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


def test_deepfm_learns_ctr(tmp_path):
    model, params, loss_fn = deepfm.create_model_and_loss(
        field_vocab_sizes=(50,) * 6)
    trainer = ElasticTrainer(loss_fn, params, optax.adam(1e-2),
                             total_batch_size=64)
    losses = []
    for i in range(25):
        batch = deepfm.synthetic_ctr_batch(64, (50,) * 6, seed=i % 5)
        losses.append(float(trainer.train_step(batch)))
    assert losses[-1] < losses[0] * 0.9


def test_bow_distill_loss_uses_soft_labels():
    model, params, loss_fn = bow.create_model_and_loss(
        vocab_size=100, distill_weight=0.5)
    rng = np.random.RandomState(0)
    batch = {
        "input_ids": rng.randint(0, 100, (8, 12)).astype(np.int32),
        "label": rng.randint(0, 2, (8,)).astype(np.int32),
    }
    hard_only = float(loss_fn(params, batch, None))
    batch["soft_label"] = rng.randn(8, 2).astype(np.float32)
    mixed = float(loss_fn(params, batch, None))
    assert mixed != pytest.approx(hard_only)

    # the distill objective trains
    tx = optax.adam(5e-3)
    opt = tx.init(params)
    losses = []
    step = jax.jit(lambda p, o, b: _sgd(p, o, b, loss_fn, tx))
    for i in range(20):
        params, opt, l = step(params, opt, batch)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def _sgd(p, o, b, loss_fn, tx):
    l, g = jax.value_and_grad(loss_fn)(p, b, None)
    up, o = tx.update(g, o, p)
    import optax as _o
    return _o.apply_updates(p, up), o, l
