"""Runtime tests: mesh axes, LR schedules, State adjust hooks, and the
ElasticTrainer end-to-end on the 8-device CPU mesh (data-parallel sharding
with XLA-inserted gradient reduction)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.runtime import lr_schedules, mesh as mesh_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
from edl_tpu.runtime import state as state_mod
from edl_tpu.runtime.trainer import ElasticTrainer


def test_mesh_axes_and_sizes():
    assert jax.device_count() == 8
    m = mesh_mod.make_mesh()
    assert m.shape[mesh_mod.DATA_AXIS] == 8
    m2 = mesh_mod.make_mesh(tp=2)
    assert m2.shape[mesh_mod.DATA_AXIS] == 4
    assert m2.shape[mesh_mod.MODEL_AXIS] == 2
    m3 = mesh_mod.make_mesh(tp=2, sp=2)
    assert m3.shape[mesh_mod.DATA_AXIS] == 2
    with pytest.raises(ValueError):
        mesh_mod.make_mesh(tp=3)


def test_topology_valid():
    assert [n for n in range(1, 10)
            if mesh_mod.topology_valid_power_of_two(n)] == [1, 2, 4, 8]
    assert mesh_mod.largest_valid_world(7) == 4


def test_lr_schedules():
    s = lr_schedules.piecewise_decay(0.1, [100, 200])
    assert float(s(0)) == pytest.approx(0.1)
    assert float(s(150)) == pytest.approx(0.01)
    assert float(s(250)) == pytest.approx(0.001)
    w = lr_schedules.linear_warmup(s, warmup_steps=10)
    assert float(w(0)) == pytest.approx(0.0)
    assert float(w(5)) == pytest.approx(0.05)
    assert float(w(50)) == pytest.approx(0.1)
    c = lr_schedules.cosine_decay(1.0, 100)
    assert float(c(0)) == pytest.approx(1.0)
    assert float(c(100)) == pytest.approx(0.0, abs=1e-6)
    assert lr_schedules.scale_lr_for_batch(0.1, 1024) == pytest.approx(0.4)


def test_multi_step_matches_sequential_steps():
    """make_multi_step(K) in one dispatch == K make_train_step calls
    with the same per-step rng folding."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import (make_multi_step, make_train_state,
                                         make_train_step)

    params = linear.init_params(feature_dim=4)
    loss_fn = linear.loss_fn
    tx = optax.sgd(0.1)
    K = 3
    rng = jax.random.PRNGKey(7)
    rs = np.random.RandomState(0)
    batches = {
        "x": rs.randn(K, 8, 4).astype(np.float32),
        "y": rs.randn(K, 8).astype(np.float32),
    }

    base = jax.jit(make_train_step(loss_fn, tx))
    want = make_train_state(params, tx)
    want_losses = []
    for i in range(K):
        b = {k: v[i] for k, v in batches.items()}
        want, loss = base(want, b, jax.random.fold_in(rng, want["step"]))
        want_losses.append(float(loss))

    multi = jax.jit(make_multi_step(loss_fn, tx, steps_per_call=K))
    got, losses = multi(make_train_state(params, tx), batches, rng)

    assert int(got["step"]) == K
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(want_losses), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(got["params"]),
                    jax.tree_util.tree_leaves(want["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_state_roundtrip_and_adjust(coord):
    st = state_mod.State(total_batch_size=256)
    st.begin_epoch(0, world_size=8)
    st.end_epoch(step_num=100, avg_step_time=0.01)
    st.data_checkpoint.file_list = ["a.txt"]
    st.data_checkpoint.mark_processed("a.txt", 0, 49)
    st.data_checkpoint.mark_processed("a.txt", 50, 99)
    assert st.data_checkpoint.processed["a.txt"] == [[0, 99]]
    assert st.data_checkpoint.is_processed("a.txt", 75)

    calls = []
    st.register_adjust_function(
        lambda s, w: calls.append((s.total_batch_size, w)))
    st.adjust(4)
    assert calls == [(256, 4)]

    state_mod.save_to_store(coord, st)
    loaded = state_mod.load_from_store(coord)
    assert loaded.total_batch_size == 256
    assert loaded.epochs["0"]["step_num"] == 100
    assert loaded.data_checkpoint.is_processed("a.txt", 10)


def _linreg_trainer(tmp_path, total_batch=64, **kw):
    w_true = np.arange(1, 5, dtype=np.float32)

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    params = {"w": jnp.zeros(4), "b": jnp.zeros(())}
    trainer = ElasticTrainer(
        loss_fn, params, optax.sgd(0.1), total_batch_size=total_batch,
        checkpoint_dir=str(tmp_path / "ckpt"), **kw)

    def make_batch(seed):
        rng = np.random.RandomState(seed)
        x = rng.randn(total_batch, 4).astype(np.float32)
        y = x @ w_true + 0.01 * rng.randn(total_batch).astype(np.float32)
        return {"x": x, "y": y}

    return trainer, make_batch, w_true


def test_elastic_trainer_learns_and_resumes(tmp_path):
    trainer, make_batch, w_true = _linreg_trainer(tmp_path)
    trainer.begin_epoch(0)
    first = float(trainer.train_step(make_batch(0)))
    for i in range(1, 30):
        loss = float(trainer.train_step(make_batch(i)))
    assert loss < first * 0.05
    assert trainer.global_step == 30
    trainer.end_epoch(save=True)  # writes checkpoint v30

    np.testing.assert_allclose(
        np.asarray(trainer.train_state["params"]["w"]), w_true, atol=0.2)

    # a fresh trainer (simulating a post-resize restart) resumes at step 30
    trainer2, make_batch2, _ = _linreg_trainer(tmp_path)
    assert trainer2.resume()
    assert trainer2.global_step == 30
    assert trainer2.state.epoch_no == 0
    loss2 = float(trainer2.train_step(make_batch2(99)))
    assert loss2 < first * 0.05


def test_preemption_saves_emergency_checkpoint(tmp_path):
    """SIGTERM mid-training: the next step boundary writes a checkpoint
    at the CURRENT step and raises PreemptedError; a restarted trainer
    resumes from it with zero lost steps (the grace window that
    train_process.terminate_trainers's SIGTERM->SIGKILL kill provides)."""
    import os
    import signal

    from edl_tpu.utils.errors import PreemptedError

    try:
        trainer, make_batch, _ = _linreg_trainer(tmp_path)
        trainer.install_preemption_handler()
        trainer.begin_epoch(0)
        for i in range(5):
            trainer.train_step(make_batch(i))
        assert not trainer.preempted
        os.kill(os.getpid(), signal.SIGTERM)  # launcher / k8s preemption
        with pytest.raises(PreemptedError):
            trainer.train_step(make_batch(5))
        assert trainer.preempted

        # the emergency checkpoint carries the step that completed (6),
        # not the last epoch-end save (there was none)
        trainer2, make_batch2, _ = _linreg_trainer(tmp_path)
        assert trainer2.resume()
        assert trainer2.global_step == 6
        # a mid-epoch save must re-run the interrupted epoch, not skip
        # its remaining data
        assert trainer2.state.next_epoch() == 0
        trainer2.train_step(make_batch2(6))
        assert trainer2.global_step == 7
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)


def test_fit_loop_trains_resumes_and_preempts(tmp_path):
    """ElasticTrainer.fit(): the one-call loop trains to convergence,
    a second fit() resumes from the checkpoints it wrote, and a
    preemption mid-loop raises PreemptedError (code=None) after the
    emergency save."""
    import signal

    from edl_tpu.utils.errors import PreemptedError

    trainer, make_batch, w_true = _linreg_trainer(tmp_path)
    out = trainer.fit(2, lambda e: (make_batch(e * 100 + i)
                                    for i in range(15)))
    assert out["steps"] == 30 and not out["resumed"]
    assert out["final_loss"] < 0.05
    np.testing.assert_allclose(
        np.asarray(trainer.train_state["params"]["w"]), w_true, atol=0.2)

    trainer2, make_batch2, _ = _linreg_trainer(tmp_path)
    out2 = trainer2.fit(3, lambda e: (make_batch2(e * 100 + i)
                                      for i in range(15)))
    assert out2["resumed"] and out2["steps"] == 45

    try:
        trainer3, make_batch3, _ = _linreg_trainer(tmp_path)

        def batches(epoch):
            for i in range(15):
                if i == 4:
                    os.kill(os.getpid(), signal.SIGTERM)
                yield make_batch3(epoch * 100 + i)

        with pytest.raises(PreemptedError):
            trainer3.fit(9, batches, preemption_exit_code=None)
        # the emergency checkpoint carries the preempted step, beyond
        # the resumed 45 but before epoch 3's end at 60
        trainer4, _, _ = _linreg_trainer(tmp_path)
        assert trainer4.resume()
        assert 45 < trainer4.global_step < 60
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)


def test_fit_reports_neartheend_after_running(tmp_path):
    """Status order on the LAST epoch: begin_epoch reports RUNNING, so
    fit() must publish NEARTHEEND after it — the scale-out-stopping
    verdict would otherwise be clobbered for the entire final epoch."""
    from edl_tpu.controller.train_status import TrainStatus

    trainer, make_batch, _ = _linreg_trainer(tmp_path)
    calls = []
    trainer.report_status = calls.append
    trainer.fit(2, lambda e: (make_batch(e * 10 + i) for i in range(3)))
    assert calls[-1] == TrainStatus.SUCCEED
    near = calls.index(TrainStatus.NEARTHEEND)
    assert calls[near - 1] == TrainStatus.RUNNING
    # and nothing overwrites NEARTHEEND before the SUCCEED
    assert calls[near + 1:] == [TrainStatus.SUCCEED]


def test_elastic_trainer_runs_the_pipeline_engine(tmp_path):
    """Elastic pipeline-parallel training end to end: the 1F1B engine as
    ElasticTrainer's step_fn — train on dp x pp, checkpoint (sharded
    write keeps "stages" pp-laid-out), resume in a fresh trainer via the
    placed restore, and keep training with the loss still improving."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from edl_tpu.models.bert import create_bert_pipeline
    from edl_tpu.parallel.pipeline import make_pipeline_train_step

    pp = 4
    mesh = mesh_mod.make_mesh(dp=2, pp=pp)
    repl = NamedSharding(mesh, P())
    stage_sh = NamedSharding(mesh, P("pp"))

    def build():
        pparams, enc, stg, dec, _ = create_bert_pipeline(
            pp, num_layers=4, d_model=32, num_heads=2, mlp_dim=64,
            vocab_size=50, max_len=64, seq_len=16, dtype=jnp.float32)
        shardings = {
            "encode": jax.tree_util.tree_map(lambda _: repl,
                                             pparams["encode"]),
            "stages": jax.tree_util.tree_map(lambda _: stage_sh,
                                             pparams["stages"]),
            "decode": jax.tree_util.tree_map(lambda _: repl,
                                             pparams["decode"]),
        }
        tx = optax.adam(3e-3)
        step = make_pipeline_train_step(
            tx, encode_fn=enc, stage_fn=stg, decode_fn=dec, mesh=mesh,
            num_micro=4)
        return ElasticTrainer(
            None, pparams, tx, total_batch_size=16,
            checkpoint_dir=str(tmp_path / "ckpt"), mesh=mesh,
            param_shardings=shardings, step_fn=step)

    rng = np.random.RandomState(3)

    def batch(i):
        return {"input_ids": rng.randint(0, 50, (16, 16))
                .astype(np.int32),
                "label": rng.randint(0, 2, (16,)).astype(np.int32)}

    tr = build()
    first = float(tr.train_step(batch(0)))
    for i in range(1, 8):
        loss = float(tr.train_step(batch(i)))
    tr.begin_epoch(0)
    tr.end_epoch(save=True)
    qkv = tr.train_state["params"]["stages"]["layer_0"]["attention"][
        "query"]["kernel"]
    assert "pp" in str(qkv.sharding.spec)

    tr2 = build()
    assert tr2.resume()
    assert tr2.global_step == 8
    qkv2 = tr2.train_state["params"]["stages"]["layer_0"]["attention"][
        "query"]["kernel"]
    assert "pp" in str(qkv2.sharding.spec)  # layout survived the restore
    for i in range(8, 24):
        loss = float(tr2.train_step(batch(i)))
    assert loss < first, (loss, first)


def test_elastic_trainer_runs_interleaved_pipeline(tmp_path):
    """num_chunks routes the elastic step_fn through the interleaved
    (circular) engine: train on dp x pp with V=2 virtual stages,
    checkpoint, resume, layouts intact."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from edl_tpu.parallel.pipeline import (device_major_stage_params,
                                           make_pipeline_train_step)

    pp, V = 4, 2
    mesh = mesh_mod.make_mesh(dp=2, pp=pp)
    repl = NamedSharding(mesh, P())
    stage_sh = NamedSharding(mesh, P("pp"))
    S, d = pp * V, 8
    rng = np.random.RandomState(11)

    def encode(p, xb):
        return jnp.tanh(xb @ p["w"])

    def stage(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def decode(p, act, labels):
        logits = act @ p["w"]
        oh = jax.nn.one_hot(labels, 2)
        return -(jax.nn.log_softmax(logits) * oh).sum(-1).mean()

    def build():
        pparams = {
            "encode": {"w": jnp.asarray(
                rng.randn(3, d).astype(np.float32) * 0.3)},
            "stages": device_major_stage_params(
                {"w": jnp.asarray(np.stack(
                    [np.eye(d) * 0.9 for _ in range(S)])
                    .astype(np.float32)),
                 "b": jnp.zeros((S, d), jnp.float32)}, pp, V),
            "decode": {"w": jnp.asarray(
                rng.randn(d, 2).astype(np.float32) * 0.3)},
        }
        shardings = {
            "encode": {"w": repl},
            "stages": jax.tree_util.tree_map(lambda _: stage_sh,
                                             pparams["stages"]),
            "decode": {"w": repl},
        }
        tx = optax.adam(5e-3)
        step = make_pipeline_train_step(
            tx, encode_fn=encode, stage_fn=stage, decode_fn=decode,
            mesh=mesh, num_micro=4, num_chunks=V, x_key="x")
        return ElasticTrainer(
            None, pparams, tx, total_batch_size=16,
            checkpoint_dir=str(tmp_path / "ckpt"), mesh=mesh,
            param_shardings=shardings, step_fn=step)

    data = np.random.RandomState(4)

    def batch(i):
        x = data.randn(16, 3).astype(np.float32)
        return {"x": x, "label": (x.sum(1) > 0).astype(np.int32)}

    tr = build()
    first = float(tr.train_step(batch(0)))
    for i in range(1, 6):
        tr.train_step(batch(i))
    tr.begin_epoch(0)
    tr.end_epoch(save=True)

    tr2 = build()
    assert tr2.resume() and tr2.global_step == 6
    assert "pp" in str(
        tr2.train_state["params"]["stages"]["w"].sharding.spec)
    loss = None
    for i in range(6, 40):
        loss = float(tr2.train_step(batch(i)))
    assert loss < first, (loss, first)


def test_coordinated_stop_protocol(coord):
    """CoordinatedStop: a flagged rank's request makes the rank-0 watcher
    publish stop_at = leader_step + margin, and every rank's watcher
    observes the same value (the aligned-boundary guarantee)."""
    import time

    from edl_tpu.runtime.preemption import CoordinatedStop

    c0 = CoordinatedStop(coord, 0, stage="stg1", margin=4,
                         poll_interval=0.05,
                         current_step=lambda: 10).start()
    c1 = CoordinatedStop(coord, 1, stage="stg1",
                         poll_interval=0.05).start()
    try:
        time.sleep(0.2)
        assert c0.stop_at is None and c1.stop_at is None
        c1.request(12)  # rank 1 got SIGTERM at its step 12
        deadline = time.time() + 10
        while time.time() < deadline and (c0.stop_at is None
                                          or c1.stop_at is None):
            time.sleep(0.05)
        # max(leader step 10, requester step 12) + margin 4
        assert c0.stop_at == 16 and c1.stop_at == 16
        # a different stage (a restarted incarnation) sees nothing
        c2 = CoordinatedStop(coord, 1, stage="stg2", poll_interval=0.05)
        assert c2._read_stop_at() is None
    finally:
        c0.stop()
        c1.stop()


def test_coordinated_stop_staleness_defenses(coord):
    """A restarted incarnation must never act on its predecessor's keys:
    stop_at and request values at or below min_step are rejected, a
    stale stop_at is overwritten (put-if-absent would block on it), and
    requests are clamped above min_step so live ones always survive the
    leader's filter."""
    import time

    from edl_tpu.runtime.preemption import CoordinatedStop

    # predecessor's leftovers: stop_at=30 and a request at step 25
    coord.set_server_not_exists("preempt:stgX", "stop_at", "30", ttl=60)
    coord.set_server_not_exists("preempt:stgX", "req_1", "25", ttl=60)

    # the resumed job's baseline is step 30 — everything above is stale
    c0 = CoordinatedStop(coord, 0, stage="stgX", margin=4,
                         poll_interval=0.05, current_step=lambda: 31,
                         min_step=30).start()
    c1 = CoordinatedStop(coord, 1, stage="stgX", poll_interval=0.05,
                         min_step=30).start()
    try:
        time.sleep(0.4)
        # stale stop_at/req observed but rejected; no new stop published
        assert c0.stop_at is None and c1.stop_at is None

        # a LIVE preemption now: the request clamps above min_step and
        # the leader overwrites the stale stop_at
        c1.request(5)  # a silly-low step still publishes min_step + 1
        deadline = time.time() + 10
        while time.time() < deadline and (c0.stop_at is None
                                          or c1.stop_at is None):
            time.sleep(0.05)
        # max(leader 31, clamped request 31) + margin 4
        assert c0.stop_at == 35 and c1.stop_at == 35
    finally:
        c0.stop()
        c1.stop()


def test_coordinated_stop_covers_ahead_nonrequester(coord):
    """A non-requesting rank whose step counter runs AHEAD of both the
    leader and the requester publishes step heartbeats, so the leader's
    stop_at still lands ahead of it (advisor r3: stop_at was
    max(leader, requesters) only)."""
    import time

    from edl_tpu.runtime.preemption import CoordinatedStop

    c0 = CoordinatedStop(coord, 0, stage="stgA", margin=4,
                         poll_interval=0.05,
                         current_step=lambda: 10).start()
    c1 = CoordinatedStop(coord, 1, stage="stgA", poll_interval=0.05,
                         current_step=lambda: 12).start()
    # rank 2 is far ahead and never receives a signal
    c2 = CoordinatedStop(coord, 2, stage="stgA", poll_interval=0.05,
                         current_step=lambda: 40,
                         heartbeat_interval=0.05).start()
    try:
        # let rank 2's heartbeat land before the preemption fires
        deadline = time.time() + 5
        while time.time() < deadline and \
                coord.get_value("preempt:stgA", "step_2") is None:
            time.sleep(0.02)
        c1.request(12)
        deadline = time.time() + 10
        while time.time() < deadline and (c0.stop_at is None
                                          or c2.stop_at is None):
            time.sleep(0.05)
        # stop must clear rank 2's counter (40), not just max(10,12)
        assert c0.stop_at is not None and c0.stop_at > 40
        assert c2.stop_at == c0.stop_at
    finally:
        c0.stop()
        c1.stop()
        c2.stop()


def test_coordinated_stop_margin_capped_by_grace_budget(coord):
    """With multi-second steps the stop lead is capped so
    lead*step_time fits the SIGTERM->SIGKILL grace window instead of
    scheduling the save past the kill (advisor r3)."""
    import time

    from edl_tpu.runtime.preemption import CoordinatedStop

    # 5 s/step, 8 s grace budget -> lead = max(1, int(8/5)) = 1 step,
    # despite margin=4
    c0 = CoordinatedStop(coord, 0, stage="stgB", margin=4,
                         poll_interval=0.05, current_step=lambda: 100,
                         step_time=lambda: 5.0,
                         grace_budget=8.0).start()
    try:
        c0.request(100)
        deadline = time.time() + 10
        while time.time() < deadline and c0.stop_at is None:
            time.sleep(0.05)
        assert c0.stop_at == 101, c0.stop_at
    finally:
        c0.stop()


def test_coordinated_stop_lead_tracks_step_rate_not_heartbeat(coord):
    """VERDICT r4 weak #5: the stop lead must track the watcher's
    observation latency (a few polls / step_time), NOT a blanket
    worst-case heartbeat-staleness term — heartbeat beats are instead
    projected per-rank by their OBSERVED age. At 10ms steps with a 5s
    heartbeat the old model published stop_at >= 500 steps out; the new
    model stays within a few dozen (fresh beats, fresh req)."""
    import time

    from edl_tpu.runtime.preemption import CoordinatedStop

    t0 = time.monotonic()

    def stepper(base):
        # ranks genuinely advance at 10ms/step, like a real fast loop
        return lambda: base + int((time.monotonic() - t0) / 0.01)

    kw = dict(poll_interval=0.05, step_time=lambda: 0.01,
              heartbeat_interval=0.05, grace_budget=8.0)
    c0 = CoordinatedStop(coord, 0, stage="stgR", margin=4,
                         current_step=stepper(100), **kw).start()
    c1 = CoordinatedStop(coord, 1, stage="stgR",
                         current_step=stepper(102), **kw).start()
    try:
        time.sleep(0.4)  # warm the leader's heartbeat history
        c1.request(c1._current_step())
        deadline = time.time() + 10
        while time.time() < deadline and (c0.stop_at is None
                                          or c1.stop_at is None):
            time.sleep(0.02)
        assert c0.stop_at is not None
        now_step = stepper(102)()
        # ahead of every rank (the correctness bar)...
        assert c0.stop_at > now_step - 5, (c0.stop_at, now_step)
        # ...but NOT padded by hb_interval-as-steps: the old model's
        # floor here was ~(4*0.05+5s worst-case)/0.01 ≈ 520 steps of
        # lead; fresh beats + per-rank projection keep it tight
        assert c0.stop_at < now_step + 150, (c0.stop_at, now_step)
    finally:
        c0.stop()
        c1.stop()


def test_launcher_clears_only_stale_preempt_keys(coord):
    """Respawn-in-place retires preempt keys at or below the resumed
    step (advisor r3: stale stop_at re-preempts the respawn) but must
    NOT touch a live in-flight preemption's keys (code review r4: a
    blanket delete would split the agreed stop step mid-protocol)."""
    import types

    from edl_tpu.controller.launcher import Launcher
    from edl_tpu.runtime import state as state_mod

    st = state_mod.State()
    st.global_step = 50
    state_mod.save_to_store(coord, st)
    # stale leftovers (<= resumed step 50) and live keys (ahead of it)
    coord.set_server_with_lease("preempt:stg9", "stop_at", "48", ttl=60)
    coord.set_server_with_lease("preempt:stg9", "req_1", "47", ttl=60)
    coord.set_server_with_lease("preempt:stg9", "req_2", "55", ttl=60)
    coord.set_server_with_lease("preempt:stg9", "step_3", "60", ttl=60)

    stub = types.SimpleNamespace(
        _coord=coord, _cluster=types.SimpleNamespace(stage="stg9"))
    Launcher._clear_preempt_keys(stub)
    left = dict(coord.get_service("preempt:stg9"))
    assert "stop_at" not in left and "req_1" not in left
    assert left.get("req_2") == "55" and left.get("step_3") == "60"


def test_locked_make_serializes_concurrent_builds(tmp_path):
    """Two processes running locked_make on the same target do not race
    two compilers onto one output file."""
    import subprocess
    import sys

    native_dir = tmp_path / "native"
    native_dir.mkdir()
    (native_dir / "Makefile").write_text(
        "out.txt:\n"
        "\tsh -c 'echo start >> log.txt; sleep 0.5; echo $$$$ > out.txt;"
        " echo done >> log.txt'\n")
    code = ("import sys; sys.path.insert(0, %r); "
            "from edl_tpu.utils.buildlock import locked_make; "
            "locked_make(%r, 'out.txt')"
            % (REPO, str(native_dir)))
    procs = [subprocess.Popen([sys.executable, "-c", code])
             for _ in range(2)]
    for p in procs:
        assert p.wait(timeout=60) == 0
    # the second holder found the target up to date: exactly one build
    log = (native_dir / "log.txt").read_text().splitlines()
    assert log == ["start", "done"], log
    assert (native_dir / "out.txt").exists()


def test_resume_preserves_adjust_hooks_and_extra_state(tmp_path):
    trainer, make_batch, _ = _linreg_trainer(tmp_path)
    trainer.begin_epoch(0)
    trainer.train_step(make_batch(0))
    trainer.end_epoch(save=True)

    # restart WITH a new extra_state the checkpoint doesn't have: core must
    # still restore, extra kept as the fresh initial value
    def make2():
        t2, mb, _ = _linreg_trainer(
            tmp_path, extra_state={"loader_pos": np.int32(123)})
        return t2

    # 64-bit extra leaves are rejected loudly (device_put would truncate)
    with pytest.raises(ValueError, match="64-bit"):
        _linreg_trainer(tmp_path, extra_state={"pos": np.int64(1 << 40)})

    t2 = make2()
    calls = []
    t2.state.register_adjust_function(lambda s, w: calls.append(w))
    assert t2.resume()
    assert t2.global_step == 1
    assert int(t2.extra_state["loader_pos"]) == 123
    # hooks survived the state swap: simulate a world change record
    t2.state.epochs[str(t2.state.epoch_no)]["world_size"] = 4
    t2.state.adjust(t2.world_size)
    assert calls  # registered hook actually fired

    # now save WITH extra and restore again: extra roundtrips
    t2.begin_epoch(1)
    t2.train_step(make_batch(1))
    t2.end_epoch(save=True)
    t3 = make2()
    assert t3.resume()
    assert int(t3.extra_state["loader_pos"]) == 123


def test_elastic_trainer_with_tensor_parallel_params(tmp_path):
    """Elastic stop-resume composes with tensor parallelism: a dp x tp
    trainer with Megatron partition rules keeps params tp-sharded through
    train/save/resume, and the restored trainer continues bit-equal."""
    import jax.numpy as jnp

    from edl_tpu.models import bert
    from edl_tpu.runtime import mesh as mesh_mod

    def make_trainer():
        model, params, loss_fn = bert.create_model_and_loss(
            model=bert.bert_tiny(dtype=jnp.float32))
        mesh = mesh_mod.make_mesh(dp=4, tp=2)
        return ElasticTrainer(
            loss_fn, params, optax.adamw(1e-3), total_batch_size=16,
            checkpoint_dir=str(tmp_path / "ckpt"), mesh=mesh,
            param_shardings=bert.bert_partition_rules())

    trainer = make_trainer()
    qkv = trainer.train_state["params"]["layer_0"]["attention"]["query"][
        "kernel"]
    assert "tp" in str(qkv.sharding.spec), qkv.sharding.spec
    # adam moments inherit the param layout
    mu_qkv = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda x: x, trainer.train_state["opt_state"]))
    assert any("tp" in str(leaf.sharding.spec) for leaf in mu_qkv)

    batch = {k: np.asarray(v) for k, v in
             bert.synthetic_text_batch(16, seq_len=16).items()}
    trainer.begin_epoch(0)
    for i in range(3):
        loss = float(trainer.train_step(batch))
    trainer.end_epoch(save=True)
    # snapshot before the next donating step deletes the buffer
    qkv = trainer.train_state["params"]["layer_0"]["attention"]["query"][
        "kernel"]
    qkv_np = np.asarray(qkv)
    assert "tp" in str(qkv.sharding.spec)

    trainer2 = make_trainer()
    assert trainer2.resume()
    assert trainer2.global_step == 3
    qkv2 = trainer2.train_state["params"]["layer_0"]["attention"]["query"][
        "kernel"]
    assert "tp" in str(qkv2.sharding.spec)
    np.testing.assert_array_equal(qkv_np, np.asarray(qkv2))
    # the restored trainer steps to the same loss as the original would
    l1 = float(trainer.train_step(batch))
    l2 = float(trainer2.train_step(batch))
    assert l1 == pytest.approx(l2, rel=1e-6)
    assert l2 < loss  # still learning


def test_elastic_trainer_on_hybrid_mesh(tmp_path):
    """ElasticTrainer over a multi-slice (dcn x dp) mesh: batches shard
    over BOTH data axes and training matches the flat-dp mesh."""
    from edl_tpu.models import linear
    from edl_tpu.runtime import mesh as mesh_mod

    results = {}
    for name, mesh in (
            ("flat", mesh_mod.make_mesh(dp=8)),
            ("hybrid", mesh_mod.make_hybrid_mesh(dcn_dp=2))):
        trainer = ElasticTrainer(
            linear.loss_fn, linear.init_params(), optax.sgd(0.05),
            total_batch_size=32,
            checkpoint_dir=str(tmp_path / ("ckpt_" + name)), mesh=mesh)
        for i in range(5):
            loss = float(trainer.train_step(
                linear.synthetic_batch(32, seed=i)))
        results[name] = loss
    assert results["flat"] == pytest.approx(results["hybrid"], rel=1e-5)


def test_elastic_trainer_long_context_ring(tmp_path):
    """Elastic long-context training: BERT with ring attention over sp
    inside the jitted elastic step; save/resume keeps working."""
    import jax.numpy as jnp

    from edl_tpu.models import bert
    from edl_tpu.runtime import mesh as mesh_mod

    mesh = mesh_mod.make_mesh(dp=2, sp=4)

    def make_trainer():
        model = bert.Bert(num_layers=2, d_model=32, num_heads=2,
                          mlp_dim=64, vocab_size=100, max_len=64,
                          dtype=jnp.float32, use_ring=True, mesh=mesh)
        _, params, loss_fn = bert.create_model_and_loss(
            model=model, dummy_batch=8, dummy_seq=32)
        return ElasticTrainer(
            loss_fn, params, optax.adamw(1e-3), total_batch_size=8,
            checkpoint_dir=str(tmp_path / "ckpt"), mesh=mesh)

    trainer = make_trainer()
    batch = {k: np.asarray(v) for k, v in
             bert.synthetic_text_batch(8, seq_len=32,
                                       vocab_size=100).items()}
    trainer.begin_epoch(0)
    losses = [float(trainer.train_step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
    trainer.end_epoch(save=True)

    trainer2 = make_trainer()
    assert trainer2.resume()
    assert trainer2.global_step == 4
    l2 = float(trainer2.train_step(batch))
    assert np.isfinite(l2) and l2 < losses[0]


def test_async_save_overlaps_donation(tmp_path):
    """Async save snapshots on device, so continuing to train (which
    donates the original buffers) cannot corrupt the checkpoint."""
    trainer, make_batch, _ = _linreg_trainer(tmp_path, async_save=True)
    for i in range(5):
        trainer.train_step(make_batch(i))
    trainer.begin_epoch(0)
    trainer.end_epoch(save=True)  # async write of step-5 state
    # keep training immediately — donates the buffers save() snapshotted
    for i in range(5, 10):
        trainer.train_step(make_batch(i))
    trainer.wait_for_save()

    trainer2, make_batch2, _ = _linreg_trainer(tmp_path)
    assert trainer2.resume()
    assert trainer2.global_step == 5  # the snapshot, not the later state
    loss = float(trainer2.train_step(make_batch2(50)))
    assert np.isfinite(loss)


def test_trainer_batch_sharded_over_dp(tmp_path):
    trainer, make_batch, _ = _linreg_trainer(tmp_path)
    batch = trainer.shard_batch(make_batch(0))
    x = batch["x"]
    assert len(x.sharding.device_set) == 8
    # each device holds 1/8 of the batch rows
    shard = x.addressable_shards[0]
    assert shard.data.shape == (8, 4)


def test_accum_step_matches_full_batch_gradient():
    """make_accum_step(k): averaged microbatch gradients == the full-batch
    gradient for a mean-reduced loss, so the update is independent of k."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import (make_accum_step, make_train_state,
                                         make_train_step)

    params = linear.init_params(feature_dim=4)
    tx = optax.sgd(0.1)
    rs = np.random.RandomState(1)
    full = {
        "x": rs.randn(16, 4).astype(np.float32),
        "y": rs.randn(16).astype(np.float32),
    }
    rng = jax.random.PRNGKey(3)

    base = jax.jit(make_train_step(linear.loss_fn, tx))
    want, want_loss = base(make_train_state(params, tx), full, rng)

    K = 4
    micro = {k: v.reshape((K, 16 // K) + v.shape[1:])
             for k, v in full.items()}
    accum = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=K))
    got, got_loss = accum(make_train_state(params, tx), micro, rng)

    assert int(got["step"]) == 1  # ONE optimizer update
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(got["params"]),
                    jax.tree_util.tree_leaves(want["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_accum_step_chains_extra_state():
    """has_aux extra state must thread microbatch-to-microbatch (the BN
    running-stats semantics), ending at the LAST microbatch's value."""
    from edl_tpu.runtime.trainer import make_accum_step, make_train_state

    def loss_fn(params, extra, batch, rng):
        loss = ((params["w"] * batch["x"]) ** 2).mean()
        return loss, {"count": extra["count"] + 1,
                      "last": batch["x"].mean()}

    tx = optax.sgd(0.01)
    params = {"w": jnp.ones((4,))}
    state = make_train_state(params, tx,
                             {"count": jnp.zeros((), jnp.int32),
                              "last": jnp.zeros(())})
    K = 3
    batches = {"x": np.arange(K * 2 * 4, dtype=np.float32)
                      .reshape(K, 2, 4)}
    step = jax.jit(make_accum_step(loss_fn, tx, accum_steps=K,
                                   has_aux=True))
    state, _ = step(state, batches, jax.random.PRNGKey(0))
    assert int(state["extra"]["count"]) == K
    np.testing.assert_allclose(float(state["extra"]["last"]),
                               batches["x"][-1].mean(), rtol=1e-6)


def test_elastic_trainer_grad_accum_equivalent(tmp_path):
    """ElasticTrainer(grad_accum=2) produces the same params as
    grad_accum=1 on the same data (deterministic loss), sharded over the
    virtual dp mesh."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import ElasticTrainer

    rs = np.random.RandomState(2)
    batch = {
        "x": rs.randn(16, 4).astype(np.float32),
        "y": rs.randn(16).astype(np.float32),
    }

    params = []
    for k in (1, 2):
        tr = ElasticTrainer(linear.loss_fn, linear.init_params(4),
                            optax.sgd(0.05), total_batch_size=16,
                            checkpoint_dir="", grad_accum=k)
        for i in range(3):
            tr.train_step(batch, rng=jax.random.PRNGKey(i))
        params.append(jax.tree_util.tree_leaves(
            jax.device_get(tr.train_state["params"])))
    for a, b in zip(*params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_overlap_accum_bitwise_identity_unsharded():
    """Overlap with no mesh: no collectives to hide, so make_accum_step
    returns the eager step unchanged — the update is BITWISE identical
    for a fixed seed across accum_steps 1/2/4, by construction."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import make_accum_step, make_train_state

    params = linear.init_params(feature_dim=4)
    tx = optax.sgd(0.1)
    rs = np.random.RandomState(5)
    full = {
        "x": rs.randn(16, 4).astype(np.float32),
        "y": rs.randn(16).astype(np.float32),
    }
    rng = jax.random.PRNGKey(11)
    for K in (1, 2, 4):
        micro = {k: v.reshape((K, 16 // K) + v.shape[1:])
                 for k, v in full.items()}
        off = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=K))
        on = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=K,
                                     overlap_axis="dp", mesh=None))
        got_off, loss_off = off(make_train_state(params, tx), micro, rng)
        got_on, loss_on = on(make_train_state(params, tx), micro, rng)
        assert np.asarray(loss_on).tobytes() == np.asarray(loss_off).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(got_on["params"]),
                        jax.tree_util.tree_leaves(got_off["params"])):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), K


def test_overlap_accum_degrades_on_single_device_mesh():
    """A real 1-device mesh must take the logged no-op path (no
    collectives, no shard_map — the eager step is returned) and match
    the plain accum step bitwise."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import make_accum_step, make_train_state

    mesh1 = mesh_mod.make_mesh(dp=1, devices=jax.devices()[:1])
    params = linear.init_params(feature_dim=4)
    tx = optax.sgd(0.1)
    rs = np.random.RandomState(6)
    micro = {
        "x": rs.randn(2, 8, 4).astype(np.float32),
        "y": rs.randn(2, 8).astype(np.float32),
    }
    rng = jax.random.PRNGKey(0)
    off = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=2))
    on = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=2,
                                 overlap_axis=mesh_mod.DATA_AXIS,
                                 mesh=mesh1))
    got_off, _ = off(make_train_state(params, tx), micro, rng)
    got_on, _ = on(make_train_state(params, tx), micro, rng)
    for a, b in zip(jax.tree_util.tree_leaves(got_on["params"]),
                    jax.tree_util.tree_leaves(got_off["params"])):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_overlap_accum_sharded_matches_eager():
    """Overlap over the real 8-way dp axis (shard_map + delayed pmean)
    must agree with the eager accum step on the same global batch: the
    per-shard sum-then-pmean reassociates the row reduction, so allclose
    rather than bitwise."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import make_accum_step, make_train_state

    mesh = mesh_mod.make_mesh(dp=8)
    params = linear.init_params(feature_dim=4)
    tx = optax.sgd(0.1)
    rs = np.random.RandomState(9)
    K = 2
    micro = {
        "x": rs.randn(K, 16, 4).astype(np.float32),
        "y": rs.randn(K, 16).astype(np.float32),
    }
    rng = jax.random.PRNGKey(4)
    off = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=K))
    on = jax.jit(make_accum_step(linear.loss_fn, tx, accum_steps=K,
                                 overlap_axis=mesh_mod.DATA_AXIS,
                                 mesh=mesh))
    got_off, loss_off = off(make_train_state(params, tx), micro, rng)
    got_on, loss_on = on(make_train_state(params, tx), micro, rng)
    assert int(got_on["step"]) == 1
    np.testing.assert_allclose(float(loss_on), float(loss_off), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(got_on["params"]),
                    jax.tree_util.tree_leaves(got_off["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_overlap_accum_rejects_has_aux():
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import make_accum_step

    with pytest.raises(ValueError, match="has_aux"):
        make_accum_step(linear.loss_fn, optax.sgd(0.1), accum_steps=2,
                        has_aux=True, overlap_axis="dp")


def test_elastic_trainer_dp_overlap_matches_plain(tmp_path):
    """ElasticTrainer(dp_overlap=True, grad_accum=2) trains to the same
    params as the plain accum trainer on the same data, and the invalid
    combinations raise up front."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import ElasticTrainer

    rs = np.random.RandomState(3)
    batch = {
        "x": rs.randn(16, 4).astype(np.float32),
        "y": rs.randn(16).astype(np.float32),
    }
    params = []
    for overlap in (False, True):
        tr = ElasticTrainer(linear.loss_fn, linear.init_params(4),
                            optax.sgd(0.05), total_batch_size=16,
                            checkpoint_dir="", grad_accum=2,
                            dp_overlap=overlap)
        for i in range(3):
            tr.train_step(batch, rng=jax.random.PRNGKey(i))
        params.append(jax.tree_util.tree_leaves(
            jax.device_get(tr.train_state["params"])))
    for a, b in zip(*params):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="has_aux"):
        ElasticTrainer(linear.loss_fn, linear.init_params(4),
                       optax.sgd(0.05), total_batch_size=16,
                       checkpoint_dir="", grad_accum=2, dp_overlap=True,
                       has_aux=True, extra_state={"n": jnp.zeros(())})
    with pytest.raises(ValueError, match="replicated"):
        ElasticTrainer(linear.loss_fn, linear.init_params(4),
                       optax.sgd(0.05), total_batch_size=16,
                       checkpoint_dir="", grad_accum=2, dp_overlap=True,
                       zero1=True)


def test_zero1_spec_composition():
    """zero1_spec shards the first free divisible dim over dp, on top of
    the param's tp layout; falls back to the param spec when nothing
    divides."""
    from jax.sharding import PartitionSpec as P

    from edl_tpu.parallel.sharding import zero1_spec
    from edl_tpu.runtime import mesh as mesh_mod

    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    # replicated 2-D param: dim0 divisible -> ("dp", None)
    assert zero1_spec(P(), (8, 6), mesh) == P("dp", None)
    # tp on dim0 -> dp goes to dim1
    assert zero1_spec(P("tp", None), (2, 8), mesh) == P("tp", "dp")
    # nothing divisible by 4 -> unchanged
    assert zero1_spec(P(), (6, 3), mesh) == P()
    # scalars unchanged
    assert zero1_spec(P(), (), mesh) == P()
    # rank-mismatched leaf (factored optimizer row/col): left alone
    assert zero1_spec(P("tp", None), (8,), mesh) == P("tp", None)
    # tuple axis (hybrid mesh data-replica set): sharded over both
    hybrid = mesh_mod.make_hybrid_mesh(dcn_dp=2, tp=1,
                                       devices=jax.devices()[:8])
    got = zero1_spec(P(), (8, 4), hybrid, axis=("dcn", "dp"))
    assert got == P(("dcn", "dp"), None), got


def test_elastic_trainer_zero1_shards_moments_and_matches(tmp_path):
    """zero1=True: adam moments are dp-sharded (1/dp per-device memory),
    training is numerically equivalent to the replicated optimizer, and
    save/resume round-trips."""
    from edl_tpu.models import linear

    rs = np.random.RandomState(3)
    batch = {
        "x": rs.randn(16, 8).astype(np.float32),
        "y": rs.randn(16).astype(np.float32),
    }

    from jax.sharding import PartitionSpec as P

    losses = {}
    finals = {}
    for z in (False, True):
        tr = ElasticTrainer(linear.loss_fn, linear.init_params(8),
                            optax.adamw(1e-2), total_batch_size=16,
                            checkpoint_dir=str(tmp_path / ("z%d" % z)),
                            zero1=z)
        if z:
            mu_w = tr.train_state["opt_state"][0].mu["w"]
            assert "dp" in str(mu_w.sharding.spec), mu_w.sharding.spec
            n_dp = tr.mesh.shape["dp"]
            shard_rows = mu_w.addressable_shards[0].data.shape[0]
            assert shard_rows == mu_w.shape[0] // n_dp
            # params stay replicated
            assert tr.train_state["params"]["w"].sharding.spec == P()
        ls = [float(tr.train_step(batch, rng=jax.random.PRNGKey(i)))
              for i in range(3)]
        losses[z] = ls
        tr.state.begin_epoch(0, tr.world_size)
        tr.end_epoch(save=True)
        finals[z] = jax.device_get(tr.train_state["params"])
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(finals[True]),
                    jax.tree_util.tree_leaves(finals[False])):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    # resume restores the dp-sharded layout
    tr2 = ElasticTrainer(linear.loss_fn, linear.init_params(8),
                         optax.adamw(1e-2), total_batch_size=16,
                         checkpoint_dir=str(tmp_path / "z1"), zero1=True)
    assert tr2.resume()
    mu_w = tr2.train_state["opt_state"][0].mu["w"]
    assert "dp" in str(mu_w.sharding.spec)


def test_zero1_composes_with_tensor_parallel():
    """zero1 over dp composes with Megatron tp rules: moments carry BOTH
    axes, params keep only tp."""
    import jax.numpy as jnp

    from edl_tpu.models import bert
    from edl_tpu.runtime import mesh as mesh_mod

    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    tr = ElasticTrainer(loss_fn, params, optax.adamw(1e-3),
                        total_batch_size=8, checkpoint_dir="", mesh=mesh,
                        param_shardings=bert.bert_partition_rules(),
                        zero1=True)
    mu = tr.train_state["opt_state"][0].mu
    qkv_mu = mu["layer_0"]["attention"]["query"]["kernel"]
    spec = str(qkv_mu.sharding.spec)
    assert "tp" in spec and "dp" in spec, spec
    qkv = tr.train_state["params"]["layer_0"]["attention"]["query"]["kernel"]
    assert "dp" not in str(qkv.sharding.spec)
    batch = {k: np.asarray(v) for k, v in
             bert.synthetic_text_batch(8, seq_len=16).items()}
    l0 = float(tr.train_step(batch, rng=jax.random.PRNGKey(0)))
    l1 = float(tr.train_step(batch, rng=jax.random.PRNGKey(0)))
    assert np.isfinite([l0, l1]).all() and l1 < l0


def test_zero1_on_hybrid_mesh_uses_full_replica_set(tmp_path):
    """On a multi-slice mesh zero1 shards moments over (dcn, dp) — the
    whole data-replica set — not just dp."""
    from edl_tpu.models import linear
    from edl_tpu.runtime import mesh as mesh_mod

    mesh = mesh_mod.make_hybrid_mesh(dcn_dp=2, devices=jax.devices()[:8])
    tr = ElasticTrainer(linear.loss_fn, linear.init_params(8),
                        optax.adamw(1e-2), total_batch_size=16,
                        checkpoint_dir="", mesh=mesh, zero1=True)
    mu_w = tr.train_state["opt_state"][0].mu["w"]
    spec = str(mu_w.sharding.spec)
    assert "dcn" in spec and "dp" in spec, spec
    rs = np.random.RandomState(4)
    batch = {"x": rs.randn(16, 8).astype(np.float32),
             "y": rs.randn(16).astype(np.float32)}
    l0 = float(tr.train_step(batch, rng=jax.random.PRNGKey(0)))
    l1 = float(tr.train_step(batch, rng=jax.random.PRNGKey(0)))
    assert np.isfinite([l0, l1]).all() and l1 < l0


def test_auto_grad_accum_policy():
    """max_per_device_batch picks the smallest dividing accumulation that
    fits the budget — the per-world-size elastic memory policy."""
    from edl_tpu.models import linear
    from edl_tpu.runtime.trainer import auto_grad_accum

    assert auto_grad_accum(8, 8) == 1
    assert auto_grad_accum(8, 4) == 2
    assert auto_grad_accum(8, 3) == 4   # 8/2=4 > 3; next divisor 4 -> 2
    assert auto_grad_accum(8, 1) == 8
    assert auto_grad_accum(6, 4) == 2   # divisors only: 6/2=3 fits
    with pytest.raises(ValueError):
        auto_grad_accum(8, 0)

    # through the trainer: 8 devices, total 64 -> per-device 8; budget 2
    # -> grad_accum 4 (observable via the microbatch-major reshape)
    tr = ElasticTrainer(linear.loss_fn, linear.init_params(4),
                        optax.sgd(0.05), total_batch_size=64,
                        checkpoint_dir="", max_per_device_batch=2)
    assert tr._grad_accum == 4
    rs = np.random.RandomState(5)
    batch = {"x": rs.randn(64, 4).astype(np.float32),
             "y": rs.randn(64).astype(np.float32)}
    loss = float(tr.train_step(batch))
    assert np.isfinite(loss)


def test_auto_grad_accum_rejects_explicit_conflict():
    from edl_tpu.models import linear

    with pytest.raises(ValueError, match="not\\s+both"):
        ElasticTrainer(linear.loss_fn, linear.init_params(4),
                       optax.sgd(0.05), total_batch_size=64,
                       checkpoint_dir="", grad_accum=2,
                       max_per_device_batch=2)


def test_resize_invariant_training_under_budget(tmp_path):
    """The elastic headline: with a fixed total_batch_size and a
    per-device budget, training at world 8 (accum 1) and at world 2
    (accum 4 chosen automatically) produces the same parameters — a
    resize changes THROUGHPUT, never convergence."""
    from edl_tpu.models import linear
    from edl_tpu.runtime import mesh as mesh_mod

    rs = np.random.RandomState(7)
    batch = {"x": rs.randn(32, 4).astype(np.float32),
             "y": rs.randn(32).astype(np.float32)}

    finals = []
    for n_dev in (8, 2):
        mesh = mesh_mod.make_mesh(dp=n_dev,
                                  devices=jax.devices()[:n_dev])
        tr = ElasticTrainer(linear.loss_fn, linear.init_params(4),
                            optax.sgd(0.05), total_batch_size=32,
                            checkpoint_dir="", mesh=mesh,
                            max_per_device_batch=4)
        # world 8: per-device 4 -> accum 1; world 2: per-device 16 -> 4
        assert tr._grad_accum == (1 if n_dev == 8 else 4)
        for i in range(3):
            tr.train_step(batch, rng=jax.random.PRNGKey(i))
        finals.append(jax.tree_util.tree_leaves(
            jax.device_get(tr.train_state["params"])))
    for a, b in zip(*finals):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_prewarm_resize_aot_executable_cross_process(tmp_path):
    """The restart-latency lever (SURVEY §7): an 8-device trainer
    prewarns the 4-device step as a SERIALIZED AOT EXECUTABLE (the
    persistent compile cache cannot carry it — its key includes the
    platform topology); a FRESH 4-device process loads it at its first
    train_step and skips the compile, and training still converges. A
    2-device control — never prewarmed — must NOT report a hit."""
    import json
    import subprocess
    import sys

    from conftest import cpu_subprocess_env

    cache = tmp_path / "xla_cache"
    cache.mkdir()

    script = r"""
import json
import sys
import jax
import numpy as np
import optax
from edl_tpu.models import linear
from edl_tpu.runtime import trainer as trainer_mod
from edl_tpu.runtime.trainer import ElasticTrainer

hits = []
orig = ElasticTrainer._try_load_prewarmed_step
def spy(self):
    out = orig(self)
    hits.append(out is not None)
    return out
ElasticTrainer._try_load_prewarmed_step = spy

trainer = ElasticTrainer(linear.loss_fn, linear.init_params(),
                         optax.sgd(0.05), total_batch_size=16)
w_true = np.arange(1, 14, dtype=np.float32) / 10
rs = np.random.RandomState(0)
loss = None
for i in range(30):
    x = rs.randn(16, 13).astype(np.float32)
    batch = {"x": x, "y": x @ w_true}
    loss = float(trainer.train_step(batch))
if "--prewarm" in sys.argv:
    done = trainer.prewarm_resize_compiles([4])
    assert done == [4], done
print(json.dumps({"hit": bool(hits and hits[0]), "loss": loss,
                  "devices": jax.device_count()}))
"""

    def run(n_devices, *args):
        env = cpu_subprocess_env(n_devices,
                                 EDL_TPU_COMPILE_CACHE=str(cache))
        r = subprocess.run([sys.executable, "-c", script] + list(args),
                           env=env, capture_output=True, text=True,
                           timeout=240)
        assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
        return json.loads(r.stdout.strip().splitlines()[-1])

    a = run(8, "--prewarm")
    assert not a["hit"]  # nothing to load the first time
    aot = cache / "aot_steps"
    assert aot.is_dir() and list(aot.glob("step_w4_*.pkl"))

    b = run(4)
    assert b["hit"], "4-device restart did not load the AOT step"
    assert b["loss"] < 0.1, b  # the loaded executable really trains

    c = run(2)  # control: never prewarmed -> no hit, still works
    assert not c["hit"]
    assert c["loss"] < 0.1, c


def test_prewarm_targets_respect_grad_accum_batch_axis(tmp_path,
                                                       monkeypatch):
    """Under grad accumulation the example batch is [k, rows/k, ...] —
    the prewarm divisibility check must follow the SHARDED axis (axis 1
    here), not axis 0 (code review r4): with k=2 and 32 rows, world 4
    must be accepted (16 sharded rows % 4 == 0), not rejected because
    2 % 4 != 0."""
    from edl_tpu.models import linear

    # the trainer's cache enablement mutates PROCESS-GLOBAL jax config
    # that monkeypatch cannot undo — snapshot and restore it, or later
    # in-process tests inherit a dead per-test cache dir
    prior_dir = jax.config.jax_compilation_cache_dir
    prior_floor = jax.config.jax_persistent_cache_min_compile_time_secs
    monkeypatch.setenv("EDL_TPU_COMPILE_CACHE", str(tmp_path / "cache"))
    try:
        trainer = ElasticTrainer(linear.loss_fn, linear.init_params(),
                                 optax.sgd(0.01), total_batch_size=32,
                                 grad_accum=2)
        batch = {"x": np.ones((32, 13), np.float32),
                 "y": np.ones((32,), np.float32)}
        trainer.train_step(batch)
        done = trainer.prewarm_resize_compiles([4])
        assert done == [4], done
        aot = tmp_path / "cache" / "aot_steps"
        assert list(aot.glob("step_w4_*.pkl"))
    finally:
        jax.config.update("jax_compilation_cache_dir", prior_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prior_floor)
