"""Distillation accuracy story (the ERNIE→BOW analogue, hermetic):
a BERT teacher trained on plentiful data distills into a BOW student
that only has a small labeled set — the distilled student must beat the
label-only student on held-out data (reference result shape:
example/distill/nlp README, BOW 0.901 → 0.905/0.915 with distill;
BASELINE.md row 6)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import bert, bow

VOCAB = 100
SEQ = 17


def _data(n, seed):
    """Clean-margin count task: label = majority of tokens in the low
    half of the vocab; borderline counts (7..10 of 17) rejected so the
    decision boundary has margin."""
    rng = np.random.RandomState(seed)
    out_ids, out_y = [], []
    while len(out_ids) < n:
        ids = rng.randint(0, VOCAB, (4 * n, SEQ)).astype(np.int32)
        counts = (ids < VOCAB // 2).sum(axis=1)
        keep = (counts <= 6) | (counts >= 11)
        out_ids.append(ids[keep])
        out_y.append((counts[keep] >= 11).astype(np.int32))
    ids = np.concatenate(out_ids)[:n]
    labels = np.concatenate(out_y)[:n]
    return ids, labels


def _train(loss_fn, params, batches, lr=3e-3, steps=None):
    tx = optax.adamw(lr)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, batch):
        loss, g = jax.value_and_grad(loss_fn)(params, batch,
                                              jax.random.PRNGKey(0))
        updates, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    for batch in batches:
        params, opt, loss = step(params, opt, batch)
    return params, float(loss)


def _acc(model, params, ids, labels):
    logits = jax.jit(
        lambda p, i: model.apply({"params": p}, i))(params, ids)
    return float((np.argmax(np.asarray(logits), -1) == labels).mean())


@pytest.mark.integration
def test_distillation_beats_label_only_student():
    # --- teacher: BERT trained on plentiful labeled data ---------------
    t_model, t_params, t_loss = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32, vocab_size=VOCAB))
    ids_big, y_big = _data(4096, seed=1)

    def teacher_batches(steps, bs=64):
        for i in range(steps):
            lo = (i * bs) % (len(ids_big) - bs)
            yield {"input_ids": jnp.asarray(ids_big[lo:lo + bs]),
                   "label": jnp.asarray(y_big[lo:lo + bs])}

    t_params, _ = _train(t_loss, t_params, teacher_batches(220), lr=1e-3)
    ids_test, y_test = _data(512, seed=9)
    t_acc = _acc(t_model, t_params, jnp.asarray(ids_test), y_test)
    assert t_acc > 0.9, t_acc  # the teacher must actually know the task

    @jax.jit
    def teacher_logits(ids):
        return t_model.apply({"params": t_params}, ids)

    # --- students: 16 labeled samples only vs + teacher distillation ---
    ids_small, y_small = _data(16, seed=2)
    ids_unlab, _ = _data(2048, seed=3)

    s_model, s_params0, s_loss_plain = bow.create_model_and_loss(
        vocab_size=VOCAB, distill_weight=0.0)

    def small_batches(steps, bs=16):
        for i in range(steps):
            sel = np.arange(i * bs, (i + 1) * bs) % len(ids_small)
            yield {"input_ids": jnp.asarray(ids_small[sel]),
                   "label": jnp.asarray(y_small[sel])}

    plain_params, _ = _train(s_loss_plain, s_params0, small_batches(300))
    plain_acc = _acc(s_model, plain_params, jnp.asarray(ids_test), y_test)

    _, s_params1, s_loss_distill = bow.create_model_and_loss(
        vocab_size=VOCAB, distill_weight=0.7, temperature=2.0)

    def distill_batches(steps, bs=64):
        for i in range(steps):
            lo = (i * bs) % (len(ids_unlab) - bs)
            chunk = jnp.asarray(ids_unlab[lo:lo + bs])
            soft = teacher_logits(chunk)
            yield {"input_ids": chunk,
                   "label": jnp.argmax(soft, -1),  # teacher pseudo-labels
                   "soft_label": soft}

    dist_params, _ = _train(s_loss_distill, s_params1,
                            distill_batches(300))
    dist_acc = _acc(s_model, dist_params, jnp.asarray(ids_test), y_test)

    # the reference's claim, reproduced: distillation closes the gap the
    # small labeled set leaves open
    assert dist_acc > plain_acc + 0.03, (plain_acc, dist_acc, t_acc)
    assert dist_acc > 0.85, (plain_acc, dist_acc, t_acc)


def test_mnist_distill_example_end_to_end():
    """The minimal single-file distill example (reference
    example/distill/mnist_distill): in-process teacher -> TeacherServer
    -> DistillReader -> student; the 32-unit student must recover the
    256-unit teacher's accuracy through the served soft labels."""
    import json
    import os
    import subprocess
    import sys

    from conftest import cpu_subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "distill", "mnist_distill.py")],
        env=cpu_subprocess_env(1), capture_output=True, text=True,
        timeout=280)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    out = json.loads([l for l in r.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert out["teacher_acc"] > 0.95, out
    assert out["student_acc"] > 0.9, out
    assert out["steps"] == 60
