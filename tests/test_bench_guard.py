"""Regression pins for bench._guarded_timed_loop (the r5 slow-step
guard): the first real-TPU LM bench run found a ~100x-slow steady
state, queued 30 dispatches anyway, and the attempt kill wedged the
tunnel for the rest of the sweep. These tests lock the guard's three
behaviors — healthy untouched, truncated-but-amortized untagged,
pathological tagged / probe-only — against a FAKE clock (dispatches
advance virtual time), so they are exact and immune to host load.
No jax needed: bench.py's top level is import-clean and the guard only
touches time/env.
"""

import pytest

import bench


class FakeClock:
    """Stands in for bench's ``time`` module inside the guard."""

    def __init__(self):
        self.t = 0.0

    def perf_counter(self):
        return self.t


@pytest.fixture()
def clock(monkeypatch):
    fake = FakeClock()
    monkeypatch.setattr(bench, "time", fake)
    monkeypatch.setattr(bench, "_PROC_START", 0.0)
    # plenty of attempt budget remaining by default; the loop budget is
    # then the env knob alone
    monkeypatch.setattr(bench, "ATTEMPT_TIMEOUT_S", 10_000)
    return fake


def _dispatcher(clock, delays):
    """Returns (dispatch, calls): call i advances the fake clock by
    delays[min(i, last)]."""
    calls = []

    def dispatch():
        i = len(calls)
        calls.append(i)
        clock.t += delays[min(i, len(delays) - 1)]
        return i

    return dispatch, calls


def test_healthy_run_untouched(monkeypatch, clock):
    monkeypatch.setenv("BENCH_LOOP_BUDGET", "60")
    dispatch, calls = _dispatcher(clock, [0.05])
    iters, dt, slowstep = bench._guarded_timed_loop(
        dispatch, lambda x: x, 10)
    assert iters == 10
    assert not slowstep
    assert dt == pytest.approx(0.5)
    assert len(calls) == 11  # probe + 10 timed


def test_truncated_but_amortized_is_not_tagged(monkeypatch, clock):
    # the probe pays a one-off cost (tunnel RTT analogue) but steady
    # state is fast: the loop shrinks, the sample stays untagged
    monkeypatch.setenv("BENCH_LOOP_BUDGET", "1.0")
    dispatch, calls = _dispatcher(clock, [0.4, 0.005])
    iters, dt, slowstep = bench._guarded_timed_loop(
        dispatch, lambda x: x, 50)
    assert iters == 2  # int(1.0 / 0.4)
    assert not slowstep  # measured rate would NOT blow the budget
    assert len(calls) == 1 + iters


def test_pathological_rate_is_tagged(monkeypatch, clock):
    monkeypatch.setenv("BENCH_LOOP_BUDGET", "0.5")
    dispatch, calls = _dispatcher(clock, [0.2])
    iters, dt, slowstep = bench._guarded_timed_loop(
        dispatch, lambda x: x, 10)
    assert iters == 2
    assert slowstep  # 0.2s/step * 10 requested >> 0.5s budget
    assert dt == pytest.approx(0.4)
    assert len(calls) == 1 + iters


def test_probe_becomes_the_measurement(monkeypatch, clock):
    # a single dispatch consumes the whole budget: report it, and
    # NEVER queue dispatches a parent kill could land in the middle of
    monkeypatch.setenv("BENCH_LOOP_BUDGET", "0.5")
    dispatch, calls = _dispatcher(clock, [0.6])
    iters, dt, slowstep = bench._guarded_timed_loop(
        dispatch, lambda x: x, 10)
    assert (iters, slowstep) == (1, True)
    assert dt == pytest.approx(0.6)
    assert len(calls) == 1  # the probe and nothing else


def test_ckpt_bench_tiny_cpu_schema(tmp_path):
    """The checkpoint bench must keep working in a tiny CPU config
    under tier-1 and honor its JSON contract (schema ckpt_bench/v1) —
    the guard that keeps the tool from bit-rotting."""
    import json

    from edl_tpu.tools import ckpt_bench

    out = ckpt_bench.run(tree_mb=2, workers=2,
                         directory=str(tmp_path), repeats=1)
    assert out["schema"] == "ckpt_bench/v1"
    assert out["roundtrip_ok"] is True
    assert out["tree_mb"] == pytest.approx(2.0, rel=0.1)
    assert out["sync"]["wall_ms"] > 0 and out["sync"]["mb_s"] > 0
    assert out["async"]["blocked_ms"] > 0
    assert out["async"]["persist_ms"] > 0 and out["async"]["mb_s"] > 0
    assert out["blocked_frac_of_sync"] > 0
    json.dumps(out)  # the whole report is JSON-serializable


def test_remaining_attempt_budget_clips_the_loop(monkeypatch, clock):
    # compile/warmup already burned most of the attempt: the guard must
    # budget against what is LEFT, not the env constant
    monkeypatch.setenv("BENCH_LOOP_BUDGET", "60")
    monkeypatch.setattr(bench, "ATTEMPT_TIMEOUT_S", 10)
    clock.t = 7.6  # pretend compile+warmup spent 7.6s of the attempt
    # after the 0.3s probe: remaining = 10*0.8 - 7.9 = 0.1s < probe
    dispatch, calls = _dispatcher(clock, [0.3])
    iters, dt, slowstep = bench._guarded_timed_loop(
        dispatch, lambda x: x, 10)
    assert (iters, slowstep) == (1, True)
    assert dt == pytest.approx(0.3)
    assert len(calls) == 1


def test_distill_bench_tiny_cpu_schema():
    """The distill data-plane bench must keep working in a tiny CPU
    config under tier-1 and honor its JSON contract (schema
    distill_bench/v1): both modes report throughput + occupancy, the
    two paths return byte-identical predictions, and the whole report
    serializes. No speedup assertion here — CI boxes are too noisy for
    a timing gate; the acceptance run does that offline."""
    import json

    from edl_tpu.tools import distill_bench

    out = distill_bench.run(model="linear", students=2, batches=6,
                            batch_size=4, feed_dim=16, fetch_dim=16,
                            max_batch=8, depth=3)
    assert out["schema"] == "distill_bench/v1"
    assert out["identical_ok"] is True
    for mode in ("serial", "pipelined"):
        assert out[mode]["wall_ms"] > 0
        assert out[mode]["predicts_s"] > 0
        assert out[mode]["goodput_mb_s"] > 0
        assert out[mode]["device_batches"] > 0
        assert 0 < out[mode]["occupancy_pct"] <= 100
    assert out["speedup_predicts_s"] > 0
    json.dumps(out)  # the whole report is JSON-serializable


def test_measure_resize_micro_peer_arc_cpu_schema(capsys):
    """Tier-1 smoke of the peer-restore bench arc: the hermetic micro
    mode (in-process save -> holdout peer -> placed restore) must run
    on CPU and emit a resize_bench/v1 record with the full per-stage
    downtime breakdown. No peer-vs-FS timing gate here — CI boxes are
    too noisy; the acceptance run compares the two arcs offline."""
    import json

    from edl_tpu.tools import measure_resize

    rc = measure_resize.main(["--arcs", "peer_restore_on", "--micro",
                              "--micro_mb", "2", "--platform", "cpu"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "error" not in out
    assert out["schema"] == "resize_bench/v1"
    assert out["metric"] == "resize_downtime_s_peer_restore_on"
    assert out["unit"] == "s" and out["mode"] == "micro"
    assert out["arc"] == "peer_restore_on"
    assert set(out["breakdown"]) == set(measure_resize.BREAKDOWN_STAGES)
    assert out["value"] >= out["breakdown"]["restore_s"] > 0
    assert out["restore"]["source"] == "peer"
    assert out["restore"]["peers"] >= 1
    assert out["restore"]["bytes"] > 0
    assert out["restore"]["version"] == 1
    json.dumps(out)  # round-trips


def test_measure_resize_kill_pod_arc_cpu_schema(capsys):
    """Tier-1 pin of the kill-one-pod arc (diskless fault tolerance,
    resize_bench/v1): the dead pod's state is rebuilt purely from
    partner-held erasure shards — ``fs_reads == 0`` across the whole
    parity window, byte-identical to the FS restore, surviving a
    partner SIGKILLed mid-rebuild — and the chaos-faulted rebuild
    drill degrades to the FS rung losslessly.

    This arc DOES carry a timing gate, unlike its siblings: parity
    restore must beat the FS baseline. It is safe here because both
    windows are measured best-of-3 back-to-back in the same process
    against a loopback fake GCS (the most FS-favorable baseline
    possible — real object stores only widen the gap), and the parity
    side wins every observed run by >=1.5x at this size."""
    import json

    from edl_tpu.tools import measure_resize

    rc = measure_resize.main(["--arcs", "kill_pod", "--micro",
                              "--micro_mb", "16", "--platform", "cpu"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "error" not in out
    assert out["schema"] == "resize_bench/v1"
    assert out["arc"] == "kill_pod" and out["mode"] == "micro"
    assert set(out["breakdown"]) == set(measure_resize.BREAKDOWN_STAGES)
    assert out["shards"] == {"k": 2, "m": 1, "pushed": 3}

    # the diskless guarantee: zero FS reads, byte-identical, decoded
    # through a partner dying mid-rebuild
    restore = out["restore"]
    assert restore["source"] == "parity"
    assert restore["fs_reads"] == 0
    assert restore["byte_identical"] is True
    assert restore["killed_partner"] is True
    assert restore["owners"] == ["victim"]
    assert restore["bytes"] > 0
    assert restore["cold_restore_s"] > 0

    # sub-second and faster than the FS rung it replaces
    assert out["fs_baseline"]["fs_reads"] > 0
    assert 0 < out["breakdown"]["restore_s"] < 1.0
    assert out["breakdown"]["restore_s"] \
        < out["fs_baseline"]["restore_s"]

    # the chaos drill: faulted rebuild -> FS rung, losslessly
    drill = out["fallback_drill"]
    assert drill["fault_fired"] is True
    assert drill["source"] == "fs"
    assert drill["fs_reads"] > 0
    assert drill["byte_identical"] is True
    json.dumps(out)  # round-trips


def test_measure_resize_live_arc_cpu_schema(capsys):
    """Tier-1 smoke of the live in-place resize arc: one worker process
    is resized 8→4→8 through the store 2PC without ever exiting, and
    the emitted resize_bench/v1 record must show the live shape —
    kill_s/barrier_s/restore_s structurally zero, reshard_s carrying
    the pause, the process alive at the end. No live-vs-stop_resume
    timing gate here — CI boxes are too noisy; the acceptance run
    compares the two arcs offline."""
    import json

    from edl_tpu.tools import measure_resize

    rc = measure_resize.main(["--arcs", "live", "--platform", "cpu",
                              "--from_devices", "8", "--timeout", "120"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "error" not in out and "warning" not in out
    assert out["schema"] == "resize_bench/v1"
    assert out["metric"] == "resize_downtime_s_live"
    assert out["arc"] == "live" and out["mode"] == "live"
    assert set(out["breakdown"]) == set(measure_resize.BREAKDOWN_STAGES)
    assert out["breakdown"]["kill_s"] == 0.0
    assert out["breakdown"]["barrier_s"] == 0.0
    assert out["breakdown"]["restore_s"] == 0.0
    assert out["value"] > 0 and out["breakdown"]["reshard_s"] > 0
    assert (out["from_devices"], out["to_devices"]) == (8, 4)
    assert out["process_survived"] is True
    assert out["grow"]["to_devices"] == 8  # same process grew back
    json.dumps(out)  # round-trips
    # time-ledger agreement: the worker's published ledger must
    # attribute the live pause. resize_pause owns the window except
    # the drain (nested ckpt_block) and any first-batch data_wait, so
    # resize_pause alone can't exceed the pause, and with the drain
    # added back it must cover it to within 10% (+50ms clock noise).
    ledger = out["ledger"]
    assert ledger is not None, "worker published no ledger totals"
    pause = out["value"]
    tol = 0.10 * pause + 0.05
    assert ledger["resize_pause"] <= pause + tol
    assert ledger["resize_pause"] + out["drain_s"] >= pause - tol, (
        ledger, out["value"], out["drain_s"])


def test_measure_resize_stop_resume_ledger_agreement(capsys):
    """Stop-resume arc + time-ledger agreement: the respawned trainer's
    restore + resize_pause must account for the in-process portion of
    the downtime (t_first_step - t_resume_start) to within 10%. The
    full bench value additionally counts kill/respawn wall time that
    belongs to no process — invisible to a per-process ledger by
    construction, which is why the record carries pause_in_process_s."""
    import json

    from edl_tpu.tools import measure_resize

    rc = measure_resize.main(["--arcs", "stop_resume", "--platform",
                              "cpu", "--from_devices", "8",
                              "--timeout", "120"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    out = json.loads(lines[0])
    assert "error" not in out
    assert out["schema"] == "resize_bench/v1"
    assert out["arc"] == "stop_resume"
    assert out["value"] >= out["pause_in_process_s"] > 0
    ledger = out["ledger"]
    assert ledger is not None, "worker published no ledger totals"
    pause = out["pause_in_process_s"]
    tol = 0.10 * pause + 0.05
    attributed = ledger["restore"] + ledger["resize_pause"]
    # the only other state that can own part of the window is the
    # first batch's data_wait; 10% bounds it
    assert attributed <= pause + tol, (ledger, out)
    assert attributed + ledger["data_wait"] >= pause - tol, (ledger,
                                                            out)


def test_store_bench_micro_schema():
    """The replicated-store bench must keep working hermetically under
    tier-1 and honor its JSON contract (schema store_bench/v1): the
    3-replica micro arc elects, quorum-acks writes, kills the leader,
    re-elects, and proves zero acknowledged-write loss; the fleet arc
    reports keepalive coalescing. No latency gate — CI boxes are too
    noisy; the acceptance run reads failover downtime offline."""
    import json

    from edl_tpu.tools import store_bench

    out = store_bench.run(writes=40, pods=16,
                          election_timeout=(0.15, 0.3))
    assert out["schema"] == "store_bench/v1"
    assert out["mode"] == "micro"
    rep = out["replication"]
    assert rep["replicas"] == 3
    assert rep["elect_ms"] > 0
    assert rep["writes_acked"] == 40
    assert rep["write_ops_s"] > 0
    assert rep["failover_downtime_ms"] > 0
    assert rep["lost_acked_writes"] == 0
    assert rep["linearizable_ok"] is True
    assert rep["leader_changed"] is True
    assert rep["commit_index"] >= 40
    fleet = out["fleet"]
    assert fleet["pods"] == 16
    assert fleet["refreshes_ok"] == 16
    assert fleet["per_lease_ok"] == 16
    assert fleet["coalesced_ms"] > 0 and fleet["per_lease_ms"] > 0
    assert fleet["coalesce_speedup"] > 0
    json.dumps(out)  # the whole report is JSON-serializable


def test_store_bench_fleet_watch_schema():
    """The fleet-watch arc must keep working hermetically under tier-1
    and honor its store_bench/v1 contract at the acceptance fleet size:
    both paths report propagation p50/p99 and store_rpcs_per_event, the
    relay tree beats the direct fan-out by the O(log N) margin (>=8x
    for RPCs per event AND store writes per obs tick at 2048 pods), the
    relay-kill drill loses zero events and reattaches its watchers. No
    latency gate — CI boxes are too noisy; the acceptance run reads
    propagation p99 offline."""
    import json

    from edl_tpu.tools import store_bench

    out = store_bench.run(pods=2048, watchers=8, watch_events=6,
                          arcs=("fleet_watch",))
    assert out["schema"] == "store_bench/v1"
    assert out["mode"] == "micro"
    fw = out["fleet_watch"]
    assert fw["pods"] == 2048
    assert fw["depth"] >= 2          # the drill needs a mid relay
    assert fw["interior_relays"] >= 1
    for path in ("direct", "relay"):
        assert fw[path]["publish_p50_ms"] is not None
        assert fw[path]["publish_p99_ms"] is not None
        assert fw[path]["publish_p99_ms"] >= fw[path]["publish_p50_ms"]
        assert fw[path]["lost_events"] == 0
        assert fw[path]["store_rpcs_per_event"] > 0
    # the O(log N) claim: one upstream pump per tree vs one poll loop
    # per pod, and one folded obs write vs N flat writes
    assert fw["relay"]["store_rpcs_per_event"] \
        < fw["direct"]["store_rpcs_per_event"]
    assert fw["rpc_reduction_x"] >= 8
    assert fw["obs_reduction_x"] >= 8
    # the relay-kill drill: lossless by since_rev resume, and the
    # orphaned watchers re-adopted a live ancestor
    assert fw["relay"]["kill_events"] > 0
    assert fw["relay"]["reattached_watchers"] >= 1
    json.dumps(out)  # the whole report is JSON-serializable


def test_data_bench_micro_schema():
    """The elastic data-plane bench must keep working in a tiny CPU
    config under tier-1 and honor its JSON contract (schema
    databench/v1): both arcs report throughput / latency / steal /
    idle, the two arcs move byte-identical record streams, and the
    whole report serializes. No speedup assertion here — CI boxes are
    too noisy for a timing gate; the acceptance run does that offline."""
    import json

    from edl_tpu.tools import data_bench

    out = data_bench.run(files=2, rows=96, dim=32, batch_size=16,
                         step_ms=1.0, fetch_ahead=4)
    assert out["schema"] == "databench/v1"
    assert out["identical_ok"] is True
    for arc in ("serial_row", "pipelined_col"):
        assert out[arc]["wall_ms"] > 0
        assert out[arc]["batches"] == 12          # 2 files * 96/16
        assert out[arc]["records"] == 192
        assert out[arc]["records_s"] > 0
        assert out[arc]["fetch_ms_p50"] >= 0
        assert out[arc]["fetch_ms_p99"] >= out[arc]["fetch_ms_p50"]
        assert out[arc]["steal_ratio"] == 1.0     # pure consumer arc
        assert 0 <= out[arc]["consumer_idle_pct"] <= 100
        assert out[arc]["lost"] == 0
        assert out[arc]["pool_dials"] >= 1
    assert out["speedup_records_s"] > 0
    json.dumps(out)  # the whole report is JSON-serializable


def test_obs_bench_micro_schema():
    """The observability-overhead bench must keep working in a tiny CPU
    config under tier-1 and honor its JSON contract (schema
    obs_bench/v1): both arcs run the pipelined data hot loop, the
    primitive microbenchmarks cover every handle op enabled AND
    disabled, and the registry is left enabled afterwards. No overhead
    gate here — CI boxes are too noisy for a timing assertion; the <2%
    acceptance number is measured offline."""
    import json

    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.tools import obs_bench

    out = obs_bench.run(mode="micro", files=2, rows=64, dim=32,
                        batch_size=16, step_ms=0.2)
    assert out["schema"] == "obs_bench/v1"
    for arc in ("on", "off"):
        assert out[arc]["records_s"] > 0
        assert out[arc]["lost"] == 0
    assert out["overhead_pct"] is not None
    prim = out["primitives"]
    for state in ("enabled", "disabled"):
        for op in ("counter_inc_ns", "labeled_inc_ns", "gauge_set_ns",
                   "histogram_observe_ns", "span_noop_ns"):
            assert prim[state][op] > 0
    assert obs_metrics.enabled()  # the bench must restore the switch
    det = out["detectors"]
    assert det["pods"] >= 2 and det["windows"] > 0
    assert det["tick_ms_p50"] > 0
    assert det["tick_ms_max"] >= det["tick_ms_p50"]
    strag = det["straggler"]
    assert strag["clean_false_positives"] == 0
    assert strag["detected_window"] is not None
    # the detection-latency acceptance bound: the injected straggler is
    # flagged within 2 publish windows (virtual clock — not host-noisy)
    assert strag["detection_windows"] <= 2
    json.dumps(out)  # the whole report is JSON-serializable


def test_health_report_schema():
    """health_report/v1 contract: every field the doctor and job_stats
    consume, produced by a real HealthMonitor.evaluate() pass over the
    detector bench's synthetic fleet."""
    import json

    from edl_tpu.obs import events as obs_events
    from edl_tpu.obs import health as obs_health
    from edl_tpu.tools import obs_bench

    monitor = obs_health.HealthMonitor(
        coord=None, pod_id="guard-monitor", interval=10.0,
        events=obs_events.EventLog(), clock=lambda: 1_000_000.0)
    state = {}
    steps = {"pod-%02d" % p: (600.0 if p == 3 else 100.0)
             for p in range(4)}
    report = None
    for w in range(4):
        docs = obs_bench._synth_fleet_docs(4, w, steps, state,
                                           1_000_000.0, 10.0)
        report = monitor.evaluate(docs, now=1_000_000.0 + w * 10.0)
    assert report["schema"] == "health_report/v1"
    assert report["fleet"]["verdict"] == "critical"
    assert report["fleet"]["pods_total"] == 4
    assert report["fleet"]["pods_degraded"] == ["pod-03"]
    assert set(report["pods"]) == set(steps)
    assert report["pods"]["pod-03"]["verdict"] == "critical"
    f = report["findings"][0]
    for field in ("detector", "pod", "severity", "summary", "metric",
                  "value", "baseline", "threshold"):
        assert field in f
    assert isinstance(report["slos"], list)
    assert report["preferred_victims"] == ["pod-03"]
    kinds = [e["kind"] for e in report["events"]]
    assert "health.degraded" in kinds
    json.dumps(report)


def test_doctor_report_schema():
    """doctor_report/v1 contract, including the no-monitor degradation:
    verdict "unknown" with an explanatory summary when no health report
    has ever been published."""
    import json

    from edl_tpu.tools import job_doctor

    doc = job_doctor.diagnose({"job_id": "j", "job_status": None,
                               "health": None, "obs": {}})
    assert doc["schema"] == "doctor_report/v1"
    assert doc["verdict"] == "unknown"
    assert doc["findings"] == []
    assert doc["summary"]
    json.dumps(doc)
    job_doctor.render(doc)  # the human surface renders without a report


def test_obs_bench_ledger_section_schema():
    """obs_bench "ledger" section contract: both arcs timed, per-step
    overhead derived, and the acceptance criterion (<1%) carried in
    the record. No overhead gate here — CI boxes are too noisy; the
    <1% number is measured offline like every other bench figure."""
    import json

    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.tools import obs_bench

    out = obs_bench.bench_ledger(iters=200, work_us=50.0, repeats=2)
    assert out["iters"] == 200 and out["repeats"] == 2
    assert out["enabled_s"] > 0 and out["disabled_s"] > 0
    assert out["overhead_pct"] is not None
    assert out["criterion_pct"] == 1.0
    assert obs_metrics.enabled()  # the bench must restore the switch
    json.dumps(out)


def test_goodput_doc_schema():
    """goodput/v1 contract: every field job_stats --pretty and the
    doctor read, produced by a real GoodputMerger fold."""
    import json

    from edl_tpu.obs import ledger as obs_ledger

    m = obs_ledger.GoodputMerger()
    m.update("pod-00", {"compute": 80.0, "ckpt_block": 15.0,
                        "idle": 5.0})
    m.update("pod-01", {"compute": 95.0, "data_wait": 5.0})
    doc = m.doc(now=1_000_000.0)
    assert doc["schema"] == "goodput/v1"
    fleet = doc["fleet"]
    for field in ("total_s", "goodput_s", "goodput_pct", "badput"):
        assert field in fleet
    assert fleet["badput"] == sorted(fleet["badput"],
                                     key=lambda b: -b["seconds"])
    for b in fleet["badput"]:
        assert set(b) == {"state", "seconds", "share_pct"}
    for pod, cell in doc["pods"].items():
        for field in ("total_s", "goodput_s", "goodput_pct",
                      "top_badput", "states"):
            assert field in cell
    assert set(doc["spread"]) == {"goodput_pct_min", "goodput_pct_max",
                                  "states"}
    json.dumps(doc)


def test_blackbox_doc_schema(tmp_path):
    """blackbox/v1 contract: every field --postmortem renders, produced
    by a real FlightRecorder dump; bounded and JSON-round-trippable."""
    import json

    from edl_tpu.obs import flight as obs_flight

    rec = obs_flight.FlightRecorder("guard-pod", out_dir=str(tmp_path))
    path = rec.dump("trainer_exit", RuntimeError("guard"))
    with open(path) as f:
        box = json.load(f)
    assert box["schema"] == "blackbox/v1"
    for field in ("ts", "pod", "pid", "reason", "exception", "events",
                  "spans", "metrics", "ledger", "threads", "context"):
        assert field in box
    assert len(box["events"]) <= obs_flight.MAX_EVENTS
    assert len(box["spans"]) <= obs_flight.MAX_SPANS
    assert len(box["threads"]) <= obs_flight.MAX_THREAD_DUMP
    json.dumps(box)


def test_obs_bench_autopilot_arc_schema():
    """obs_bench "autopilot" section contract: the policy-engine arc
    rides every monitor tick over the synthetic fleet, the seeded
    straggler draws an evict within 2 windows of detection, the clean
    half of the run produces ZERO actions, and the combined
    evaluate+on_report tick cost is carried for the <2%-of-interval
    criterion (measured offline — no timing gate here)."""
    import json

    from edl_tpu.tools import obs_bench

    out = obs_bench.bench_autopilot(pods=6, windows=12)
    assert out["pods"] == 6 and out["windows"] == 12
    assert out["interval_s"] > 0
    assert out["tick_ms_p50"] > 0
    assert out["tick_ms_max"] >= out["tick_ms_p50"]
    assert out["overhead_pct_of_interval"] >= 0
    strag = out["straggler"]
    for field in ("victim", "injected_window", "detected_window",
                  "action_window", "action_latency_windows"):
        assert field in strag
    assert strag["detected_window"] is not None
    assert strag["action_window"] is not None
    # the acceptance bound: the evict lands within 2 windows of the
    # detection verdict (virtual clock — not host-noisy)
    assert strag["action_latency_windows"] <= 2
    assert out["clean_actions"] == 0   # quiet fleet -> quiet engine
    assert out["actions_total"] >= 1   # the straggler WAS acted on
    json.dumps(out)


def test_action_record_schema():
    """action/v1 contract: every field job_stats/job_doctor render and
    load_actions filters on, produced by a real Autopilot apply pass
    and round-tripped through the store journal."""
    import json

    from edl_tpu.obs import autopilot as obs_autopilot

    class _Store(object):
        def __init__(self):
            self.store = {}

        def set_server_permanent(self, service, server, value):
            self.store[(service, server)] = value

        def get_value(self, service, server):
            return self.store.get((service, server))

        def get_service(self, service):
            return [(srv, v) for (svc, srv), v in self.store.items()
                    if svc == service]

    coord = _Store()
    ap = obs_autopilot.Autopilot(coord, "guard-monitor", mode="on",
                                 evict_fn=lambda pod: True,
                                 clock=lambda: 1_000_000.0)
    report = {"schema": "health_report/v1", "ts": 1_000_000.0,
              "fleet": {"verdict": "critical", "pods_total": 3,
                        "pods_degraded": ["pod-x"]},
              "findings": [{"detector": "straggler", "pod": "pod-x",
                            "severity": "critical", "summary": "slow",
                            "event_ids": [7]}],
              "preferred_victims": ["pod-x"], "goodput": {},
              "events": []}
    ap.on_report(report)
    actions = ap.on_report(report)
    assert len(actions) == 1
    a = actions[0]
    assert a["schema"] == "action/v1"
    for field in ("id", "seq", "ts", "kind", "mode", "actor", "target",
                  "reason", "cause", "outcome", "attempts", "error",
                  "result"):
        assert field in a
    assert a["kind"] in obs_autopilot.ACTION_KINDS
    assert a["mode"] in ("applied", "dry_run")
    assert a["outcome"] in ("applied", "dry_run", "failed")
    cause = a["cause"]
    for field in ("report_ts", "detector", "summary", "evidence_ids"):
        assert field in cause
    assert cause["evidence_ids"] == [7]
    # the stored journal round-trips and filters on the schema tag
    assert [x["id"] for x in obs_autopilot.load_actions(coord)] \
        == [a["id"]]
    json.dumps(a)


def test_serve_bench_micro_schema():
    """Tier-1 pin of the serving-plane bench contract (schema
    serve_bench/v1): the micro mode must force a full
    scale-out -> overload -> shed -> scale-in cycle under seeded chaos
    and prove the serving-plane guarantees — saturation sheds are
    typed OverloadedErrors with retry-after hints (never a timeout
    pile-up), the drain-safe decommission strands zero requests, the
    scaler's dry replay journals the identical action stream, and a
    clean low-load fleet produces zero scaler actions and zero sheds.
    The shed-rate and zero-stranded fields are MANDATORY: a report
    without them is a schema break, not a passing run."""
    import json

    from edl_tpu.tools import serve_bench

    out = serve_bench.run(mode="micro", seed=7)
    assert out["schema"] == "serve_bench/v1"
    assert out["sent"] > 0 and out["ok"] > 0
    assert out["goodput_rps"] > 0

    # overload produced typed sheds, and ONLY typed sheds: no timeout
    # pile-up, no untyped errors at saturation
    assert out["shed"]["total"] > 0
    assert out["shed"]["rate"] > 0
    assert out["shed"]["with_retry_after_hint"] > 0
    assert sum(out["shed"]["by_reason"].values()) == out["shed"]["total"]
    assert out["timeouts"] == 0
    assert out["untyped_errors"] == 0

    # zero stranded requests, by count AND by drain report
    assert out["stranded"] == 0
    assert out["drain"]["zero_stranded"] is True
    assert all(r["drained"] and r["pending_rows"] == 0
               for r in out["drain"]["reports"])

    # the forced cycle really scaled out and back in, and the drain
    # chaos drill fired on the real drain path
    assert out["scaler"]["scale_out"] >= 1
    assert out["scaler"]["scale_in"] >= 1
    assert out["faults_fired"].get("serve.drain", 0) >= 1

    # dry mode journals the IDENTICAL action stream to on mode
    assert out["dry_parity_ok"] is True
    assert out["live_action_stream"] == out["dry_action_stream"]

    # stats RPCs stayed answerable under overload (strict priority)
    assert out["stats_rpc_ms"]["p99"] is not None
    assert out["stats_rpc_ms"]["p99"] < out["latency_ms"]["p99"]

    # a clean fleet at low load: zero sheds, zero scaler actions
    assert out["clean"]["shed_total"] == 0
    assert out["clean"]["scaler_actions"] == 0
    assert out["clean"]["stranded"] == 0

    json.dumps(out)  # the whole report is JSON-serializable


def test_reshard_bench_cpu_schema(capsys):
    """Tier-1 pin of the cross-mesh reshard bench contract (schema
    reshard_bench/v1): every arc must be byte-identical to stop-resume
    and carry a sharding record, and the headline dp->dp x tp arc must
    move strictly fewer bytes than a wholesale restore of the state —
    but not zero (the dp-sharded moment really re-rows). No live-vs-
    stop_resume timing gate — CI boxes are too noisy; the acceptance
    run compares the pause columns offline."""
    import json

    from edl_tpu.tools import reshard_bench

    rc = reshard_bench.main([])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == len(reshard_bench.ARCS)
    by_arc = {}
    for line in lines:
        out = json.loads(line)
        assert "error" not in out, out
        assert out["schema"] == "reshard_bench/v1"
        assert out["byte_identical"] is True
        assert out["saved_record"] is True
        assert out["live_pause_s"] > 0
        assert out["stop_resume_s"] > 0
        assert 0 <= out["bytes_moved"] <= out["bytes_needed"]
        assert out["state_bytes"] > 0
        by_arc[out["arc"]] = out
    assert set(by_arc) == {"dp_to_dp_tp", "tp_change", "pp_resplit"}
    # the headline acceptance gate
    arc = by_arc["dp_to_dp_tp"]
    assert arc["from_mesh"] == {"dp": 4}
    assert arc["to_mesh"] == {"dp": 2, "tp": 2}
    assert 0 < arc["bytes_moved"] < arc["state_bytes"]
    # every arc keeps some state in place — the overlap fast path is
    # doing work (moved strictly under the wholesale volume)
    for out in by_arc.values():
        assert out["bytes_moved"] < out["bytes_needed"]


def test_measure_resize_live_sharded_arc_mesh_records(capsys):
    """The sharded live arc: a dp x tp worker (--mesh dp,tp) is resized
    4->2->4 through the 2PC with the tp axis pinned on the intent; the
    worker must keep tp=2 across both transitions, survive in place,
    and publish its mesh shape in every resize_timing record (the
    from_mesh/mesh pair in the emitted bench record)."""
    import json

    from edl_tpu.tools import measure_resize

    rc = measure_resize.main(["--arcs", "live", "--platform", "cpu",
                              "--from_devices", "4", "--mesh", "dp,tp",
                              "--timeout", "120"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "error" not in out and "warning" not in out, out
    assert out["schema"] == "resize_bench/v1"
    assert out["mode"] == "live"
    assert out["process_survived"] is True
    # the worker started on dp=2 x tp=2 and shrank to dp=1 x tp=2:
    # tp rides the intent, dp absorbs the world change
    assert out["from_mesh"]["dp"] == 2 and out["from_mesh"]["tp"] == 2
    assert out["mesh"]["dp"] == 1 and out["mesh"]["tp"] == 2
    # ...and grew back to the full factorization in the same process
    assert out["grow"]["mesh"]["dp"] == 2
    assert out["grow"]["mesh"]["tp"] == 2
    json.dumps(out)  # round-trips


# -- roofline_gap/v1 (measured-vs-predicted roofline bench) ---------------


def test_roofline_gap_micro_cpu_schema(capsys):
    """Tier-1 pin of the roofline-gap bench contract (schema
    roofline_gap/v1): >= 2 (model, mesh) configs, a measured/predicted
    ratio for EVERY cost-model term (with honest exercised flags), a
    roofline_calib/v1 calibration record, and a gpt tok/s arc for the
    perf_accounting fold. No absolute-ratio gate — CPU interpret ratios
    are astronomically off the v5e prediction by design; the pin is
    presence + finiteness + positivity."""
    import json

    import numpy as np

    from edl_tpu.tools import roofline_gap

    rc = roofline_gap.main(["--micro", "--iters", "1"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(lines) == 1
    doc = json.loads(lines[-1])
    assert doc["schema"] == "roofline_gap/v1"
    assert doc["mode"] == "micro"
    assert set(doc["chip_builtin"]) >= {"bf16_tflops", "hbm_gbps",
                                        "ici_gbps"}
    configs = doc["configs"]
    assert len(configs) >= 2
    assert {c["model"] for c in configs} == {"gpt", "bert"}
    terms = set(roofline_gap.RATIO_TERMS)
    for cfg in configs:
        assert "error" not in cfg, cfg
        assert cfg["world"] >= 2
        assert set(cfg["ratios"]) == terms
        assert set(cfg["exercised"]) == terms
        for term, r in cfg["ratios"].items():
            assert np.isfinite(r) and r > 0, (cfg["name"], term, r)
        # unexercised terms report the neutral ratio, not a fake fit
        for term, on in cfg["exercised"].items():
            if not on and term not in ("compute", "hbm"):
                assert cfg["ratios"][term] == 1.0, (cfg["name"], term)
        assert cfg["measured"]["total_s"] > 0
        assert cfg["predicted"]["total_s"] > 0
        assert cfg["tokens_per_sec_per_chip"] > 0
    # the dp term was actually measured on these meshes
    assert any(c["exercised"]["dp"] for c in configs)
    # the accum-over-dp config swept the overlap schedule
    overlaps = [c["overlap"] for c in configs if c["overlap"]]
    assert overlaps and all(o["off_s"] > 0 and o["on_s"] > 0
                            for o in overlaps)
    calib = doc["calibration"]
    assert calib["schema"] == "roofline_calib/v1"
    assert isinstance(calib["chip"], dict)
    for field, val in calib["chip"].items():
        if field == "name":
            continue
        assert np.isfinite(val) and val > 0, (field, val)
    arc = doc["gpt_arc"]
    assert arc and arc["value"] > 0
    assert arc["unit"] == "tok/s/chip"
    assert arc["platform"] == "cpu"
    json.dumps(doc)  # round-trips


def test_roofline_calib_round_trip_and_fail_open(tmp_path, monkeypatch):
    """costmodel loads a roofline_calib/v1 record via the env var and
    plans against the fitted constants; a missing, corrupt, or
    out-of-sanity-bounds record keeps the builtin CHIP_V5E values
    PER FIELD (fail-open proven, the acceptance bullet)."""
    import json

    from edl_tpu.parallel import costmodel

    prof = costmodel.transformer_profile(n_layers=8, d_model=1024,
                                         n_heads=16, seq_len=512)
    factors = {"dp": 4, "tp": 1, "pp": 1, "ep": 1}

    # no calibration installed: defaults ARE the builtins
    monkeypatch.delenv(costmodel.CALIB_ENV, raising=False)
    assert costmodel.calibrated_chip() == costmodel.CHIP_V5E

    # round trip: fitted constants flow into default-chip scoring
    good = tmp_path / "calib_good.json"
    good.write_text(json.dumps({
        "schema": costmodel.CALIB_SCHEMA,
        "chip": {"name": "v5e+fit", "bf16_tflops": 150.0,
                 "hbm_gbps": 700.0, "ici_gbps": 90.0}}))
    monkeypatch.setenv(costmodel.CALIB_ENV, str(good))
    chip = costmodel.calibrated_chip()
    assert chip["bf16_tflops"] == 150.0
    assert chip["hbm_gbps"] == 700.0
    assert chip["ici_gbps"] == 90.0
    t_cal = costmodel.step_time_s(factors, prof, total_batch=64)
    t_builtin = costmodel.step_time_s(factors, prof, total_batch=64,
                                      chip=costmodel.CHIP_V5E)
    # slower fitted ICI -> a larger dp term under the default chip
    assert t_cal["dp_s"] > t_builtin["dp_s"]

    # corrupt file: whole record dropped, builtins stay
    bad = tmp_path / "calib_corrupt.json"
    bad.write_text("{not json")
    monkeypatch.setenv(costmodel.CALIB_ENV, str(bad))
    assert costmodel.calibrated_chip() == costmodel.CHIP_V5E

    # wrong schema: dropped
    wrong = tmp_path / "calib_wrong_schema.json"
    wrong.write_text(json.dumps({"schema": "nope/v9",
                                 "chip": {"bf16_tflops": 150.0}}))
    monkeypatch.setenv(costmodel.CALIB_ENV, str(wrong))
    assert costmodel.calibrated_chip() == costmodel.CHIP_V5E

    # out-of-bounds field dropped PER FIELD, sane sibling kept (a CPU
    # micro fit must not brick the planner's compute constant)
    partial = tmp_path / "calib_partial.json"
    partial.write_text(json.dumps({
        "schema": costmodel.CALIB_SCHEMA,
        "chip": {"bf16_tflops": 0.001, "hbm_gbps": 700.0,
                 "ici_gbps": float("nan")}}))
    monkeypatch.setenv(costmodel.CALIB_ENV, str(partial))
    chip = costmodel.calibrated_chip()
    assert chip["bf16_tflops"] == costmodel.V5E_BF16_TFLOPS
    assert chip["hbm_gbps"] == 700.0
    assert chip["ici_gbps"] == costmodel.V5E_ICI_GBPS

    # missing path: builtins
    monkeypatch.setenv(costmodel.CALIB_ENV, str(tmp_path / "gone.json"))
    assert costmodel.calibrated_chip() == costmodel.CHIP_V5E


def test_fold_roofline_gap_updates_best(tmp_path):
    """perf_accounting folds a roofline_gap/v1 gpt arc into the
    BENCH_BEST pointer: vs_baseline computed against the 59,157.8
    baseline, source stamped, non-TPU arcs refused — the headline can
    never silently sit at 0.0 again."""
    import json

    from edl_tpu.tools import perf_accounting as pa

    best = tmp_path / "best.json"
    best.write_text(json.dumps({"gpt": {
        "metric": "gpt2s_train_tokens_per_sec_per_chip",
        "value": 59157.8, "unit": "tok/s/chip", "vs_baseline": 0.0,
        "measured": "2026-07-31", "source": "BENCH_SWEEP_r5b.txt"}}))

    def gap(platform, value):
        return {"schema": "roofline_gap/v1",
                "gpt_arc": {"metric": "gpt_train_tokens_per_sec_per_chip",
                            "value": value, "unit": "tok/s/chip",
                            "platform": platform, "config": "gpt2s_dp_all",
                            "measured": "2026-08-08"}}

    # a CPU arc is refused outright (the pointer stays TPU-measured)
    changed, msg = pa.fold_roofline_gap(gap("cpu", 999999.0), str(best))
    assert not changed and "refusing" in msg
    assert json.loads(best.read_text())["gpt"]["value"] == 59157.8

    # a slower TPU arc does not regress the best value, but the stale
    # 0.0 vs_baseline is backfilled
    changed, msg = pa.fold_roofline_gap(gap("tpu", 50000.0), str(best))
    assert changed
    rec = json.loads(best.read_text())["gpt"]
    assert rec["value"] == 59157.8
    assert rec["vs_baseline"] == 1.0
    assert rec["baseline"] == pa.BASELINES["gpt"]

    # a faster TPU arc takes the record and stamps its source
    changed, msg = pa.fold_roofline_gap(gap("tpu", 70989.4), str(best))
    assert changed
    rec = json.loads(best.read_text())["gpt"]
    assert rec["value"] == 70989.4
    assert rec["source"].startswith("roofline_gap/v1 gpt2s_dp_all")
    assert rec["vs_baseline"] == round(70989.4 / 59157.8, 3)

    # idempotent: same arc again changes nothing
    changed, _ = pa.fold_roofline_gap(gap("tpu", 70989.4), str(best))
    assert not changed

    # malformed docs are rejected, not half-applied
    changed, msg = pa.fold_roofline_gap({"schema": "other/v1"}, str(best))
    assert not changed
    changed, msg = pa.fold_roofline_gap({"schema": "roofline_gap/v1",
                                         "gpt_arc": None}, str(best))
    assert not changed


def test_decode_bench_micro_schema():
    """Tier-1 pin of the decode bench contract (schema decode_bench/v1):
    micro mode must prove the serving-decode guarantees end to end —
    continuous batching is token-identical to ``gpt.generate`` (serial,
    batched, and int8 engines) while beating the serial engine >= 1.5x
    under ONE fused step trace; every decode shed reason fires typed
    with zero admitted sequences stranded; slot saturation drives a
    journaled scale-out whose drain also strands nothing; the int8
    teacher passes the logits parity gate at half the weight bytes;
    shared-prefix reuse beats cold prefill >= 1.5x TTFT with identical
    tokens and exact reuse accounting; and chunked prefill bounds the
    storm ITL stall monolithic prefill demonstrably suffers.
    The parity and zero-stranded fields are MANDATORY: a report without
    them is a schema break, not a passing run."""
    import json

    from edl_tpu.serve.admission import DECODE_SHED_REASONS
    from edl_tpu.tools import serve_bench

    out = serve_bench.run_decode(mode="micro", seed=7)
    assert out["schema"] == "decode_bench/v1"

    # token parity: continuous batching NEVER changes the decode
    assert out["parity"]["serial_vs_generate_ok"] is True
    assert out["parity"]["cb_vs_generate_ok"] is True
    assert out["parity"]["int8_tokens_match"] is True

    # batching pays on the same host, under fixed-shape discipline
    assert out["throughput"]["speedup"] >= 1.5
    assert out["throughput"]["cb_tokens_per_s"] > 0
    assert out["compile"]["step_traces"] == 1
    assert out["latency_ms"]["ttft_p50"] > 0
    assert out["latency_ms"]["itl_p50"] > 0

    # every decode-phase shed reason fired, typed; nothing admitted
    # was stranded
    assert out["shed"]["reasons_covered"] == sorted(DECODE_SHED_REASONS)
    assert sum(out["shed"]["by_reason"].values()) >= \
        len(DECODE_SHED_REASONS)
    assert out["shed"]["stranded"] == 0

    # pinned slots forced a journaled scale-out; the fleet drained with
    # zero stranded sequences
    assert out["scale_out"]["engines"] >= 2
    assert out["scale_out"]["scale_out"] >= 1
    assert out["scale_out"]["journaled"] >= 1
    assert out["scale_out"]["zero_stranded"] is True

    # the quantization gate: close logits, genuinely smaller teacher
    assert out["quant"]["int8_logits_rel_err"] < 0.05
    assert out["quant"]["int8_bytes_ratio"] < 0.6

    # shared-prefix reuse: >= 1.5x TTFT at >= 50% overlap, tokens
    # IDENTICAL to cold prefill, and token-exact reuse accounting
    assert out["prefix"]["overlap_frac"] >= 0.5
    assert out["prefix"]["ttft_speedup"] >= 1.5
    assert out["prefix"]["parity_ok"] is True
    assert out["prefix"]["accounting_exact"] is True
    assert out["prefix"]["hits"] >= 1

    # chunked prefill bounds the storm stall monolithic prefill
    # demonstrably suffers, under the same fixed-shape discipline
    assert out["chunked"]["chunked_within_2x"] is True
    assert out["chunked"]["monolithic_exceeds_2x"] is True
    assert out["chunked"]["step_traces"] == 1
    assert out["chunked"]["prefill_traces"] == 0
    assert out["chunked"]["chunk_traces"] <= 2

    json.dumps(out)  # the whole report is JSON-serializable


def test_rec_bench_micro_schema_and_gates():
    """The sharded-embedding bench must keep working in a tiny CPU
    config under tier-1 and honor its JSON contract (schema
    rec_bench/v1). Unlike the other bench pins, this one DOES gate the
    arcs: the dedup+hot-cache arc replaces per-slot RPCs with one
    coalesced gather per owner, so its >=1.5x floor over the naive arc
    has order-of-magnitude headroom (~19x on an idle box) and holds on
    a noisy CI host; overlap must strictly cut embed_wait vs its
    no-overlap twin; and the mid-run reshard must leave the stitched
    table byte-identical to stop-resume."""
    import json

    from edl_tpu.tools import rec_bench

    out = rec_bench.run(mode="micro")
    assert out["schema"] == "rec_bench/v1"
    for arc in ("naive", "dedup", "dedup_cache", "overlap"):
        a = out["arcs"][arc]
        assert a["rows_s"] > 0
        assert a["lookup_ms_p99"] >= a["lookup_ms_p50"] >= 0
        assert a["retries"] == 0  # no chaos in the bench
    assert out["arcs"]["naive"]["unique_key_frac"] == 1.0
    assert out["arcs"]["dedup"]["unique_key_frac"] < 1.0  # zipf head
    cached = out["arcs"]["dedup_cache"]
    assert 0 <= cached["cache_hit_rate"] <= 1
    assert 0 < out["predicted_head_mass"] <= 1
    # the three acceptance gates ride tier-1
    assert out["speedup_dedup_cache_vs_naive"] >= 1.5
    assert out["arcs"]["overlap"]["embed_wait_s"] \
        < out["arcs"]["dedup_cache"]["embed_wait_s"]
    assert out["resize"]["identical_ok"] is True
    assert out["resize"]["members_from"] == 2
    assert out["resize"]["members_to"] == 3
    assert out["gates"] == {"speedup_ok": True, "overlap_ok": True,
                            "identical_ok": True}
    assert out["ok"] is True
    json.dumps(out)  # the whole report is JSON-serializable
