"""Async (snapshot-then-stream) checkpoint engine tests: two-phase
save handles, max_inflight=1 back-pressure, stream-format round-trips
(dense, sharded, placed), crash injection mid-persist (uncommitted →
cleaned → fallback), corrupt-entry CRC fallback, and the
PreemptionGuard drain-on-SIGTERM contract — parametrized over LocalFS
and the fake-GCS GCSFS where the fs shape matters."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.runtime.checkpoint import CheckpointManager
from edl_tpu.runtime.fs import GCSFS, LocalFS


@pytest.fixture(params=["local", "gcs"])
def ckpt_fs(request, tmp_path):
    """(base_path, FileSystem) for each backend."""
    if request.param == "local":
        yield str(tmp_path), LocalFS()
    else:
        from edl_tpu.tools.fake_gcs import FakeGCSServer
        with FakeGCSServer() as srv:
            yield "gs://ckpt-bucket/job1/ckpt", GCSFS(endpoint=srv.endpoint)


class _WrapFS(object):
    """Delegating FileSystem wrapper for fault/latency injection."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _GatedFS(_WrapFS):
    """Entry-file writes block until the gate opens (persist stays
    in-flight for as long as the test needs)."""

    def __init__(self, inner, gate):
        super().__init__(inner)
        self._gate = gate

    def write_chunks(self, path, chunks):
        self._gate.wait(15)
        return self._inner.write_chunks(path, chunks)


class _FlakyFS(_WrapFS):
    """Every stream entry write dies — the writer-pool crash: data
    files fail, so the MANIFEST must never be written."""

    def write_chunks(self, path, chunks):
        raise IOError("injected writer-pool failure: %s" % path)


def _tree(seed):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "dense": {"w": rng.randn(4, 3).astype(np.float32),
                      "b": np.zeros(3, np.float32)},
            "emb": rng.randn(10, 4).astype(np.float32),
        },
        "step": np.int32(seed),
        "bf16": jnp.ones((2, 2), jnp.bfloat16) * seed,
    }


def _assert_trees_equal(a, b):
    assert np.array_equal(np.asarray(a["step"]), np.asarray(b["step"]))
    np.testing.assert_array_equal(a["params"]["dense"]["w"],
                                  b["params"]["dense"]["w"])
    np.testing.assert_array_equal(np.asarray(a["bf16"], np.float32),
                                  np.asarray(b["bf16"], np.float32))
    assert np.asarray(b["bf16"]).dtype == np.asarray(a["bf16"]).dtype


def test_async_save_restore_roundtrip(ckpt_fs):
    base, fs = ckpt_fs
    cm = CheckpointManager(base, keep=3, fs=fs)
    tree = _tree(5)
    handle = cm.save_async(5, tree, meta={"epoch": 1})
    assert handle.version == 5 and handle.blocked_s >= 0.0
    vdir = handle.result(30)
    assert handle.done() and handle.exception() is None
    assert handle.persist_s is not None
    with fs.open(vdir + "/MANIFEST", "r") as f:
        manifest = json.load(f)
    assert manifest["format"] == "stream"
    # per-entry files with per-file crcs, committed manifest-last
    assert manifest["entries"] and all(
        {"file", "crc", "dtype", "shape", "nbytes"} <= set(e)
        for e in manifest["entries"].values())
    version, restored, meta = cm.restore_latest()
    assert version == 5 and meta == {"epoch": 1}
    _assert_trees_equal(tree, restored)
    # structured restore into the original layout
    version, restored, _ = cm.restore(5, target=tree)
    _assert_trees_equal(tree, restored)
    cm.close()


def test_async_backpressure_drains_previous(tmp_path):
    """max_inflight=1: a second save_async must BLOCK until the first
    persist lands (which is what makes host-buffer reuse safe)."""
    gate = threading.Event()
    cm = CheckpointManager(str(tmp_path), fs=_GatedFS(LocalFS(), gate))
    t1 = {"w": np.full(1024, 1.0, np.float32)}
    t2 = {"w": np.full(1024, 2.0, np.float32)}
    h1 = cm.save_async(1, t1)
    out = {}
    t = threading.Thread(
        target=lambda: out.update(h2=cm.save_async(2, t2)))
    t.start()
    time.sleep(0.3)
    assert t.is_alive() and not h1.done()  # drain() is waiting on v1
    gate.set()
    t.join(15)
    assert not t.is_alive()
    assert h1.result(15) and out["h2"].result(15)
    version, restored, _ = cm.restore_latest()
    assert version == 2
    np.testing.assert_array_equal(restored["w"], t2["w"])
    cm.close()


def test_async_snapshot_is_donation_safe(tmp_path):
    """Phase 1 copies into pooled host buffers: mutating (or donating)
    the source arrays after save_async returns must not change what is
    persisted."""
    gate = threading.Event()
    cm = CheckpointManager(str(tmp_path), fs=_GatedFS(LocalFS(), gate))
    src = {"w": np.arange(256, dtype=np.float32)}
    want = src["w"].copy()
    h = cm.save_async(3, src)
    src["w"][:] = -1.0  # "donated"/reused buffer, mid-persist
    gate.set()
    h.result(15)
    _, restored, _ = cm.restore_latest()
    np.testing.assert_array_equal(restored["w"], want)
    cm.close()


def test_async_crash_mid_persist_stays_uncommitted(ckpt_fs):
    """Writer-pool death mid-persist: the version must stay uncommitted
    (no MANIFEST), clean_uncommitted() removes it, restore_latest falls
    back to the previous committed version, and the failure surfaces
    through the handle — never into the training thread."""
    base, fs = ckpt_fs
    good = CheckpointManager(base, keep=3, fs=fs)
    tree1 = _tree(1)
    good.save(1, tree1, meta={"epoch": 0})  # committed baseline (npz)

    bad = CheckpointManager(base, keep=3, fs=_FlakyFS(fs))
    handle = bad.save_async(2, _tree(2), meta={"epoch": 1})
    assert handle.wait(30)
    assert isinstance(handle.exception(), IOError)
    with pytest.raises(IOError, match="injected"):
        handle.result(1)
    # drain() logs the failure instead of raising (trainer exit paths)
    assert bad.drain() is handle
    assert bad.drain() is None  # consumed: a second drain is a no-op
    assert not fs.exists(base + "/v_00000002/MANIFEST")
    assert good.versions() == [1]  # uncommitted => invisible
    good.clean_uncommitted()
    assert not fs.exists(base + "/v_00000002")
    version, restored, _ = good.restore_latest()
    assert version == 1
    _assert_trees_equal(tree1, restored)
    bad.close()
    good.close()


def test_async_corrupt_entry_crc_falls_back(tmp_path):
    """A committed stream version with a corrupted entry file must fail
    its per-file CRC on read and fall back to the older version."""
    cm = CheckpointManager(str(tmp_path), keep=3)
    tree1 = _tree(1)
    cm.save(1, tree1)
    cm.save_async(2, _tree(2)).result(30)
    vdir = tmp_path / "v_00000002"
    victim = sorted(vdir.glob("*.bin"))[0]
    victim.write_bytes(b"\xff" * victim.stat().st_size)
    version, restored, _ = cm.restore_latest()
    assert version == 1
    _assert_trees_equal(tree1, restored)
    cm.close()


def test_preemption_guard_drains_on_sigterm(tmp_path):
    """The SIGTERM contract: the flag-only handler never does I/O, and
    guard.drain() (the trainer's preemption exit hook) lands the
    in-flight async version before the process dies."""
    import os
    import signal

    from edl_tpu.runtime.preemption import PreemptionGuard

    gate = threading.Event()
    cm = CheckpointManager(str(tmp_path), fs=_GatedFS(LocalFS(), gate))
    guard = PreemptionGuard(drain=cm.drain)
    old = signal.getsignal(signal.SIGTERM)
    try:
        guard.install()
        tree = {"w": np.arange(64, dtype=np.float32)}
        h = cm.save_async(7, tree)
        assert not h.done()  # persist is gated, still in flight
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 10
        while not guard.preempted and time.time() < deadline:
            time.sleep(0.01)
        assert guard.preempted
        gate.set()
        guard.drain()
        assert h.done() and h.exception() is None
        assert (tmp_path / "v_00000007" / "MANIFEST").exists()
        version, restored, _ = cm.restore_latest()
        assert version == 7
        np.testing.assert_array_equal(restored["w"], tree["w"])
    finally:
        signal.signal(signal.SIGTERM, old)
        cm.close()


# -- sharded stream -----------------------------------------------------------


def _sharded_tree(seed):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 4).astype(np.float32)
    moments = rng.randn(16, 4).astype(np.float32)
    bf = (rng.randn(8, 2) * seed).astype(np.float32)
    tree = {
        "params": {"w": jax.device_put(
            w, NamedSharding(mesh, P()))},            # replicated
        "opt": {"mu": jax.device_put(
            moments, NamedSharding(mesh, P("dp")))},  # zero1-style shard
        "bf16": jax.device_put(jnp.asarray(bf, jnp.bfloat16),
                               NamedSharding(mesh, P("dp"))),
        "step": np.int32(seed),                       # host leaf
    }
    host = {"params": {"w": w}, "opt": {"mu": moments},
            "bf16": bf, "step": np.int32(seed)}
    return tree, host, mesh


def _struct_target(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       getattr(x, "dtype",
                                               np.asarray(x).dtype)),
        tree)


def test_sharded_async_roundtrip_and_placed(tmp_path):
    from jax.sharding import NamedSharding, PartitionSpec as P

    cm = CheckpointManager(str(tmp_path), keep=3)
    tree, host, mesh = _sharded_tree(4)
    handle = cm.save_sharded_async(4, tree, meta={"epoch": 2})
    vdir = handle.result(30)
    manifest = json.load(open(vdir + "/MANIFEST"))
    assert manifest["sharded"] is True
    assert manifest["format"] == "stream" and manifest["ranks"] == 1
    version, restored, meta = cm.restore_latest(
        target=_struct_target(tree))
    assert version == 4 and meta == {"epoch": 2}
    np.testing.assert_array_equal(restored["params"]["w"],
                                  host["params"]["w"])
    np.testing.assert_array_equal(restored["opt"]["mu"], host["opt"]["mu"])
    np.testing.assert_array_equal(
        np.asarray(restored["bf16"], np.float32),
        np.asarray(jnp.asarray(host["bf16"], jnp.bfloat16), np.float32))
    assert restored["bf16"].dtype == jnp.bfloat16
    # placed restore assembles the sharded jax.Arrays straight from the
    # per-shard stream entries
    shardings = {"params": {"w": NamedSharding(mesh, P())},
                 "opt": {"mu": NamedSharding(mesh, P("dp"))},
                 "bf16": NamedSharding(mesh, P("dp")),
                 "step": NamedSharding(mesh, P())}
    version, placed, meta = cm.restore_placed(4, _struct_target(tree),
                                              shardings)
    assert version == 4 and meta == {"epoch": 2}
    np.testing.assert_array_equal(np.asarray(placed["opt"]["mu"]),
                                  host["opt"]["mu"])
    np.testing.assert_array_equal(np.asarray(placed["params"]["w"]),
                                  host["params"]["w"])
    cm.close()


def test_sharded_async_two_ranks_sentinel_protocol(tmp_path):
    """The STARTED/nonce sentinel protocol survives the move onto
    background persist threads: rank 1 (launched first, nothing to
    wait on but the sentinel) blocks until rank 0's background reset,
    and rank 0 commits a merged stream MANIFEST only after rank 1's
    done marker."""
    cm0 = CheckpointManager(str(tmp_path), keep=3)
    cm1 = CheckpointManager(str(tmp_path), keep=3)
    tree, host, _ = _sharded_tree(9)
    h1 = cm1.save_sharded_async(9, {}, rank=1, nranks=2, timeout=30)
    time.sleep(0.3)  # rank 1's persist is polling for STARTED
    assert not (tmp_path / "v_00000009" / "MANIFEST").exists()
    h0 = cm0.save_sharded_async(9, tree, meta={"k": 1}, rank=0,
                                nranks=2, timeout=30)
    assert h0.result(30) and h1.result(30)
    manifest = json.load(open(str(tmp_path / "v_00000009" / "MANIFEST")))
    assert manifest["ranks"] == 2 and manifest["format"] == "stream"
    # protocol state is retired at commit
    assert not (tmp_path / "v_00000009" / "STARTED").exists()
    version, restored, meta = cm0.restore_latest(
        target=_struct_target(tree))
    assert version == 9 and meta == {"k": 1}
    np.testing.assert_array_equal(restored["opt"]["mu"], host["opt"]["mu"])
    cm0.close()
    cm1.close()
