"""FileSystem.read_range contract on both backends: the primitive the
stream restore's chunk-aligned range reads (and the fake GCS server's
Range handling behind GCSFS) stand on."""

import numpy as np
import pytest

from edl_tpu.runtime.fs import GCSFS, LocalFS


@pytest.fixture(params=["local", "gcs"])
def fs_and_path(request, tmp_path):
    if request.param == "local":
        yield LocalFS(), str(tmp_path / "blob.bin")
    else:
        from edl_tpu.tools.fake_gcs import FakeGCSServer
        with FakeGCSServer() as srv:
            yield GCSFS(endpoint=srv.endpoint), "gs://rb/x/blob.bin"


PAYLOAD = bytes(range(256)) * 4  # 1024 B, position-identifiable


def test_read_range_semantics(fs_and_path):
    fs, path = fs_and_path
    with fs.open(path, "wb") as f:
        f.write(PAYLOAD)
    assert fs.read_range(path, 0, 16) == PAYLOAD[:16]
    assert fs.read_range(path, 100, 256) == PAYLOAD[100:356]
    assert fs.read_range(path, 0, len(PAYLOAD)) == PAYLOAD
    # read past EOF returns the available suffix, not an error
    assert fs.read_range(path, 1000, 500) == PAYLOAD[1000:]
    # at/after EOF -> empty
    assert fs.read_range(path, len(PAYLOAD), 10) == b""
    assert fs.read_range(path, len(PAYLOAD) + 50, 10) == b""
    assert fs.read_range(path, 5, 0) == b""


def test_read_range_missing_file(fs_and_path):
    fs, path = fs_and_path
    with pytest.raises(FileNotFoundError):
        fs.read_range(path, 0, 10)


def test_read_range_large_offsets_round_trip(fs_and_path):
    """Ranges spanning the whole object in chunk-sized hops reassemble
    bit-identically (what _read_entry_rows does)."""
    fs, path = fs_and_path
    blob = np.random.RandomState(3).bytes(10_000)
    with fs.open(path, "wb") as f:
        f.write(blob)
    got = b"".join(fs.read_range(path, off, 999)
                   for off in range(0, 10_000, 999))
    assert got == blob


def test_fake_gcs_parse_range():
    """The emulator's Range parser: full-body fallbacks for malformed
    and suffix forms (GCSFS never sends them), 416 for start >= size."""
    from edl_tpu.tools.fake_gcs import _Handler
    parse = _Handler._parse_range
    assert parse("bytes=0-9", 100) == (0, 9)
    assert parse("bytes=90-199", 100) == (90, 99)  # clamped to EOF
    assert parse("bytes=5-", 100) == (5, 99)
    assert parse(None, 100) is None
    assert parse("bytes=-50", 100) is None      # suffix form: full body
    assert parse("items=0-9", 100) is None      # non-bytes unit
    assert parse("bytes=junk", 100) is None
    assert parse("bytes=100-110", 100) == "unsatisfiable"
