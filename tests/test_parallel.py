"""Parallelism tests on the 8-device CPU mesh: ring attention vs dense,
causal masking, gradients through the ring, and partition-rule matching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.parallel.ring_attention import dense_attention, ring_attention
from edl_tpu.parallel.sharding import match_partition_rules, shard_params
from edl_tpu.runtime import mesh as mesh_mod


def _qkv(b=2, s=32, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [4, 8])
def test_ring_attention_matches_dense(causal, sp):
    mesh = mesh_mod.make_mesh(dp=8 // sp, sp=sp)
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # output stays sequence-sharded
    assert len(got.sharding.device_set) == 8


def test_ring_attention_grads_match_dense():
    mesh = mesh_mod.make_mesh(dp=2, sp=4)
    q, k, v = _qkv(s=16)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_composes_with_tp(causal):
    """sp x tp: heads sharded over tp run independent rings per shard —
    values AND grads must still match dense."""
    mesh = mesh_mod.make_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(b=2, s=16, h=4, d=8, seed=3)
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # the output really is head-sharded over tp (8 distinct devices,
    # per-device shard = full batch/2 x seq/2 x heads/2)
    assert len(got.sharding.device_set) == 8
    assert got.addressable_shards[0].data.shape == (1, 8, 2, 8)

    g_ring = jax.grad(lambda q, k, v: (ring_attention(
        q, k, v, mesh, causal=causal) ** 2).sum(), argnums=(0, 1, 2))(
            q, k, v)
    g_dense = jax.grad(lambda q, k, v: (dense_attention(
        q, k, v, causal=causal) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_head_axis_auto_skips_indivisible():
    """heads=3 does not divide tp=2 → auto must fall back to unsharded
    heads rather than erroring."""
    mesh = mesh_mod.make_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(b=2, s=16, h=3, d=8, seed=4)
    got = ring_attention(q, k, v, mesh)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_long_sequence_sharded_memory():
    """Each device only ever holds its seq shard of q/k/v."""
    mesh = mesh_mod.make_mesh(dp=1, sp=8)
    q, k, v = _qkv(b=1, s=64, h=1, d=4)
    out = ring_attention(q, k, v, mesh, causal=False)
    shard = out.addressable_shards[0]
    assert shard.data.shape == (1, 8, 1, 4)  # 64/8 sequence rows


def test_match_partition_rules():
    params = {
        "encoder": {
            "layer_0": {
                "attn": {"qkv": {"kernel": np.zeros((16, 48)),
                                 "bias": np.zeros(48)},
                         "out": {"kernel": np.zeros((48, 16))}},
                "mlp": {"up": {"kernel": np.zeros((16, 64))},
                        "down": {"kernel": np.zeros((64, 16))}},
            }},
        "embed": {"word": {"embedding": np.zeros((100, 16))}},
        "scalar": np.zeros(()),
    }
    rules = [
        (r"attn/qkv/kernel", P(None, "tp")),
        (r"attn/out/kernel", P("tp", None)),
        (r"mlp/up/kernel", P(None, "tp")),
        (r"mlp/down/kernel", P("tp", None)),
        (r"embedding", P("tp", None)),
    ]
    specs = match_partition_rules(rules, params)
    lyr = specs["encoder"]["layer_0"]
    assert lyr["attn"]["qkv"]["kernel"] == P(None, "tp")
    assert lyr["attn"]["qkv"]["bias"] == P()      # no rule → replicated
    assert lyr["mlp"]["down"]["kernel"] == P("tp", None)
    assert specs["embed"]["word"]["embedding"] == P("tp", None)
    assert specs["scalar"] == P()


def test_shard_params_places_on_mesh():
    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    params = {"w": np.ones((8, 6), np.float32), "b": np.ones(6, np.float32)}
    sharded, shardings = shard_params(params, mesh,
                                      [(r"^w$", P(None, "tp"))])
    assert sharded["w"].sharding.spec == P(None, "tp")
    # tp=2 → each device holds half the columns
    assert sharded["w"].addressable_shards[0].data.shape == (8, 3)
    assert sharded["b"].sharding.spec == P()


def test_match_partition_rules_scalars_never_partitioned():
    """A rule that matches a scalar or size-1 leaf must not shard it —
    and the match still counts, so an all-scalar table is not "dead"."""
    params = {"step": np.zeros(()), "gain": np.ones((1,)),
              "w": np.zeros((4, 4))}
    specs = match_partition_rules([(r".*", P("tp", None))], params)
    assert specs["step"] == P()
    assert specs["gain"] == P()
    assert specs["w"] == P("tp", None)
    # matched only by scalars: still matched, no dead-rule error
    assert match_partition_rules([(r"step", P("dp"))],
                                 {"step": np.zeros(())})["step"] == P()


def test_match_partition_rules_first_match_wins():
    params = {"attn": {"kernel": np.zeros((4, 4))},
              "mlp": {"kernel": np.zeros((4, 4))}}
    rules = [
        (r"attn/kernel", P(None, "tp")),
        (r"kernel", P("tp", None)),       # generic fallback, ordered last
    ]
    specs = match_partition_rules(rules, params)
    assert specs["attn"]["kernel"] == P(None, "tp")  # NOT the fallback
    assert specs["mlp"]["kernel"] == P("tp", None)


def test_match_partition_rules_dead_rule_raises():
    """A rule matching no path is a renamed module silently falling
    back to replicated — it must raise, with the regex named, unless
    explicitly allowed."""
    params = {"mlp": {"kernel": np.zeros((4, 4))}}
    rules = [(r"mlp/kernel", P(None, "tp")),
             (r"attn/qkv/kernel", P("tp", None))]
    with pytest.raises(ValueError, match=r"attn/qkv/kernel"):
        match_partition_rules(rules, params)
    specs = match_partition_rules(rules, params,
                                  allow_unmatched_rules=True)
    assert specs["mlp"]["kernel"] == P(None, "tp")


def test_zero1_spec_mesh_without_dp_axis():
    """A mesh that has NO dp axis at all (hand-built pure-tp Mesh):
    zero1 must degrade to the param layout, never emit a spec naming an
    axis the mesh lacks."""
    from jax.sharding import Mesh

    from edl_tpu.parallel.sharding import zero1_spec

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    assert "dp" not in mesh.shape
    assert zero1_spec(P(), (8, 8), mesh) == P()
    assert zero1_spec(P(None, "tp"), (8, 8), mesh) == P(None, "tp")
    # tuple axis with every member absent: unchanged too
    assert zero1_spec(P(), (8, 8), mesh, axis=("dcn", "dp")) == P()


def test_zero1_spec_size1_dp_axis():
    """make_mesh always carries all five axes; dp=1 must behave exactly
    like an absent dp axis (no P("dp") over a trivial axis)."""
    from edl_tpu.parallel.sharding import zero1_spec

    mesh = mesh_mod.make_mesh(dp=1, tp=2, devices=jax.devices()[:2])
    assert zero1_spec(P(), (8, 8), mesh) == P()
    assert zero1_spec(P(None, "tp"), (8, 8), mesh) == P(None, "tp")
    # partial tuple: dcn absent, dp present and >1 -> only dp composed
    mesh4 = mesh_mod.make_mesh(dp=4, devices=jax.devices()[:4])
    assert zero1_spec(P(), (8, 8), mesh4, axis=("dcn", "dp")) \
        == P("dp", None)


def test_opt_state_shardings_zero1_degenerate_meshes():
    """opt_state_shardings with zero1 enabled on a dp-less/dp=1 mesh:
    every derived spec must be realizable on that mesh (no dp entries),
    and moment leaves keep the param's tp layout."""
    import optax

    from edl_tpu.parallel.sharding import opt_state_shardings
    from edl_tpu.runtime.mesh import replicated

    params = {"w": np.ones((8, 8), np.float32)}
    for kw in ({"dp": 1, "tp": 2}, {"dp": 2, "tp": 1}):
        mesh = mesh_mod.make_mesh(devices=jax.devices()[:2], **kw)
        _, shardings = shard_params(
            params, mesh,
            [(r"^w$", P(None, "tp"))] if kw["tp"] > 1 else [])
        opt_sh = opt_state_shardings(
            optax.sgd(0.1, momentum=0.9), params, shardings,
            replicated(mesh), zero1_mesh=mesh)
        for sh in jax.tree_util.tree_leaves(
                opt_sh, is_leaf=lambda x: isinstance(x, NamedSharding)):
            for entry in sh.spec:
                axes = ((entry,) if isinstance(entry, str)
                        else tuple(entry or ()))
                for a in axes:
                    assert mesh.shape.get(a, 1) > 1, (kw, sh.spec)


def test_spec_transplant_reason():
    """The live-resize computability predicate: None iff every spec
    axis exists on the target and every sharded dim divides."""
    from edl_tpu.parallel.sharding import spec_transplant_reason

    dp_tp = mesh_mod.make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
    assert spec_transplant_reason(P(None, "tp"), (8, 8), dp_tp) is None
    assert spec_transplant_reason(P(), (8, 8), dp_tp) is None
    # indivisible dim
    why = spec_transplant_reason(P("tp"), (7,), dp_tp)
    assert why and "not divisible" in why
    # axis absent from the target mesh entirely
    from jax.sharding import Mesh
    tp_only = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    why = spec_transplant_reason(P("dp"), (8,), tp_only)
    assert why and "absent" in why
    # rank mismatch
    why = spec_transplant_reason(P("dp", None), (8,), dp_tp)
    assert why and "rank" in why
