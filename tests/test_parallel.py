"""Parallelism tests on the 8-device CPU mesh: ring attention vs dense,
causal masking, gradients through the ring, and partition-rule matching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.parallel.ring_attention import dense_attention, ring_attention
from edl_tpu.parallel.sharding import match_partition_rules, shard_params
from edl_tpu.runtime import mesh as mesh_mod


def _qkv(b=2, s=32, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sp", [4, 8])
def test_ring_attention_matches_dense(causal, sp):
    mesh = mesh_mod.make_mesh(dp=8 // sp, sp=sp)
    q, k, v = _qkv()
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # output stays sequence-sharded
    assert len(got.sharding.device_set) == 8


def test_ring_attention_grads_match_dense():
    mesh = mesh_mod.make_mesh(dp=2, sp=4)
    q, k, v = _qkv(s=16)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_composes_with_tp(causal):
    """sp x tp: heads sharded over tp run independent rings per shard —
    values AND grads must still match dense."""
    mesh = mesh_mod.make_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(b=2, s=16, h=4, d=8, seed=3)
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # the output really is head-sharded over tp (8 distinct devices,
    # per-device shard = full batch/2 x seq/2 x heads/2)
    assert len(got.sharding.device_set) == 8
    assert got.addressable_shards[0].data.shape == (1, 8, 2, 8)

    g_ring = jax.grad(lambda q, k, v: (ring_attention(
        q, k, v, mesh, causal=causal) ** 2).sum(), argnums=(0, 1, 2))(
            q, k, v)
    g_dense = jax.grad(lambda q, k, v: (dense_attention(
        q, k, v, causal=causal) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_head_axis_auto_skips_indivisible():
    """heads=3 does not divide tp=2 → auto must fall back to unsharded
    heads rather than erroring."""
    mesh = mesh_mod.make_mesh(dp=2, sp=2, tp=2)
    q, k, v = _qkv(b=2, s=16, h=3, d=8, seed=4)
    got = ring_attention(q, k, v, mesh)
    want = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_long_sequence_sharded_memory():
    """Each device only ever holds its seq shard of q/k/v."""
    mesh = mesh_mod.make_mesh(dp=1, sp=8)
    q, k, v = _qkv(b=1, s=64, h=1, d=4)
    out = ring_attention(q, k, v, mesh, causal=False)
    shard = out.addressable_shards[0]
    assert shard.data.shape == (1, 8, 1, 4)  # 64/8 sequence rows


def test_match_partition_rules():
    params = {
        "encoder": {
            "layer_0": {
                "attn": {"qkv": {"kernel": np.zeros((16, 48)),
                                 "bias": np.zeros(48)},
                         "out": {"kernel": np.zeros((48, 16))}},
                "mlp": {"up": {"kernel": np.zeros((16, 64))},
                        "down": {"kernel": np.zeros((64, 16))}},
            }},
        "embed": {"word": {"embedding": np.zeros((100, 16))}},
        "scalar": np.zeros(()),
    }
    rules = [
        (r"attn/qkv/kernel", P(None, "tp")),
        (r"attn/out/kernel", P("tp", None)),
        (r"mlp/up/kernel", P(None, "tp")),
        (r"mlp/down/kernel", P("tp", None)),
        (r"embedding", P("tp", None)),
    ]
    specs = match_partition_rules(rules, params)
    lyr = specs["encoder"]["layer_0"]
    assert lyr["attn"]["qkv"]["kernel"] == P(None, "tp")
    assert lyr["attn"]["qkv"]["bias"] == P()      # no rule → replicated
    assert lyr["mlp"]["down"]["kernel"] == P("tp", None)
    assert specs["embed"]["word"]["embedding"] == P("tp", None)
    assert specs["scalar"] == P()


def test_shard_params_places_on_mesh():
    mesh = mesh_mod.make_mesh(dp=4, tp=2)
    params = {"w": np.ones((8, 6), np.float32), "b": np.ones(6, np.float32)}
    sharded, shardings = shard_params(params, mesh,
                                      [(r"^w$", P(None, "tp"))])
    assert sharded["w"].sharding.spec == P(None, "tp")
    # tp=2 → each device holds half the columns
    assert sharded["w"].addressable_shards[0].data.shape == (8, 3)
    assert sharded["b"].sharding.spec == P()
