"""Serving-plane tests: admission control + load shedding, typed
overload/feed-spec errors over the wire, drain-safe decommission, the
SLO-driven ServeScaler, and load-aware balancing.

The acceptance properties this file pins down (ISSUE 12):

- saturation produces typed :class:`OverloadedError` sheds with
  retry-after hints, never timeout pile-ups;
- the reader treats a shed as "requeue elsewhere + back off" (breaker,
  no redial) and a bad feed as a poisoned task (surfaced in order,
  never retried);
- drain-safe decommission strands zero requests, with the
  ``serve.drain`` fault point on the real drain path;
- a discovery outage degrades to stale-but-serving with exactly ONE
  ``breaker.open`` per outage and recovery within one probe period;
- the ServeScaler provably never flaps (hysteresis dead band, streaks,
  cooldowns) and journals the identical action stream in dry and on
  modes;
- one server joining a balanced service moves only ~1/N assignments,
  and draining/capacity weights shift load off a teacher.
"""

import json
import threading
import time

import numpy as np
import pytest

from edl_tpu.distill.balance import Service
from edl_tpu.distill.consistent_hash import ConsistentHash
from edl_tpu.distill.discovery_client import DiscoveryClient
from edl_tpu.distill.discovery_server import DiscoveryServer
from edl_tpu.distill.distill_reader import DistillReader, _TeacherConn
from edl_tpu.distill.registry import TeacherRegister, list_teachers
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.obs import events as obs_events
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.robustness.policy import CircuitBreaker
from edl_tpu.rpc.client import RpcClient
from edl_tpu.serve import drain as serve_drain
from edl_tpu.serve.admission import AdmissionController
from edl_tpu.serve.scaler import ServeScaler, load_actions
from edl_tpu.utils import errors


def _echo_teacher(scale, admission=None, fn_sleep=0.0, max_batch=8):
    def fn(feed):
        if fn_sleep:
            time.sleep(fn_sleep)
        return {"soft": feed["img"] * scale}

    return TeacherServer(fn, {"img": ([2], "<f4")},
                         {"soft": ([2], "<f4")}, max_batch=max_batch,
                         host="127.0.0.1", admission=admission).start()


# -- admission control ----------------------------------------------------


def test_admission_cold_server_admits_freely():
    """The queue-wait projection needs a service-time estimate; before
    the first completed batch a cold server must not shed on SLO."""
    ac = AdmissionController(max_queue_rows=100, slo_ms=1.0)
    for _ in range(5):
        ac.admit(10)  # 50 rows x any row_ms would blow a 1ms SLO
    assert ac.stats()["pending_rows"] == 50
    assert ac.stats()["shed_total"] == 0


def test_admission_idle_server_recovers_from_poisoned_estimate():
    """Liveness: a first-batch compile spike must not shed forever.

    The EWMA only updates when admitted work completes, so an SLO shed
    at pending == 0 would freeze a poisoned estimate — no admissions,
    no releases, no recovery. An idle server must always admit, and
    serving at real (fast) speed must heal the projection."""
    ac = AdmissionController(max_queue_rows=100, slo_ms=50.0)
    # batch 1: jit compile — 20s for 8 rows poisons row_ms to 2500
    ac.admit(8)
    ac.release(8, service_s=20.0)
    # the poisoned estimate projects 2500ms >> 50ms for ANY row, but
    # the queue is empty: the next batch must still be admitted
    ac.admit(8)
    # a queued burst behind it IS shed (pending > 0, projection honest)
    with pytest.raises(errors.OverloadedError) as ei:
        ac.admit(8)
    assert "slo" in str(ei.value)
    # batches keep completing at real speed: the EWMA heals until the
    # projection clears and pipelined admits flow again
    ac.release(8, service_s=0.008)  # 1ms/row
    for _ in range(40):
        if ac.stats()["row_ms"] * 16 <= 50.0:
            break
        ac.admit(8)
        ac.release(8, service_s=0.008)
    ac.admit(8)
    ac.admit(8)  # pending 16 rows projects under the SLO: no shed
    assert ac.stats()["pending_rows"] == 16


def test_admission_shed_reasons_and_retry_hints():
    """Every shed reason is a typed OverloadedError carrying a
    retry-after hint that survives the message-only wire format."""
    now = [0.0]
    clock = lambda: now[0]  # noqa: E731

    # draining: the first check — an admitted-elsewhere signal
    ac = AdmissionController(clock=clock)
    ac.set_draining(True)
    with pytest.raises(errors.OverloadedError) as ei:
        ac.admit(1)
    assert "draining" in str(ei.value)
    assert ei.value.retry_after_s is not None

    # queue_full: the bounded admission queue
    ac = AdmissionController(max_queue_rows=4, slo_ms=None, clock=clock)
    ac.admit(4)
    with pytest.raises(errors.OverloadedError) as ei:
        ac.admit(1)
    assert "queue_full" in str(ei.value)

    # rate_limit: empty token bucket; hint == the bucket refill time
    ac = AdmissionController(rate=10.0, burst=4.0, slo_ms=None,
                             clock=clock)
    ac.admit(4)
    with pytest.raises(errors.OverloadedError) as ei:
        ac.admit(2)
    assert "rate_limit" in str(ei.value)
    assert ei.value.retry_after_s == pytest.approx(0.2)
    now[0] += 1.0  # refill
    ac.admit(4)

    # slo: queue-wait projection over the predict-latency SLO
    ac = AdmissionController(max_queue_rows=100, slo_ms=50.0,
                             clock=clock)
    ac.admit(10)
    ac.release(10, service_s=0.1)  # row_ms EWMA = 10ms
    ac.admit(4)                    # projected 40ms <= 50ms
    with pytest.raises(errors.OverloadedError) as ei:
        ac.admit(2)                # projected 60ms > 50ms
    assert "slo" in str(ei.value)
    assert ei.value.retry_after_s == pytest.approx(0.01)

    # deadline: a queued item whose per-request budget elapsed
    admitted_at = ac.admit(1)
    now[0] += 1.0
    assert ac.expired(admitted_at, deadline_ms=500)
    err = ac.shed_expired(1)
    assert isinstance(err, errors.OverloadedError)
    assert "deadline" in str(err)

    stats = ac.stats()
    assert stats["shed"]["slo"] == 1
    assert stats["shed"]["deadline"] == 1
    # a round-tripped error keeps its class AND its hint
    name, detail = errors.serialize_error(err)
    back = errors.deserialize_error(name, detail)
    assert isinstance(back, errors.OverloadedError)


def test_typed_errors_round_trip_wire():
    """Only the message string survives the RPC envelope; the typed
    fields must be recoverable from it on the far side."""
    shed = errors.OverloadedError.shed("slo", retry_after_s=0.25)
    back = errors.deserialize_error(*errors.serialize_error(shed))
    assert isinstance(back, errors.OverloadedError)
    assert back.retry_after_s == pytest.approx(0.25)

    spec = errors.FeedSpecError("missing feeds: ['img']", spec="img",
                                shape=(2,))
    back = errors.deserialize_error(*errors.serialize_error(spec))
    assert isinstance(back, errors.FeedSpecError)
    assert isinstance(back, errors.DataAccessError)
    assert back.spec == "img"
    assert back.shape == "(2,)"


def test_teacher_rejects_bad_feed_with_typed_spec_error():
    """A malformed feed comes back as FeedSpecError naming the
    offending spec — typed across the wire, not a generic RpcError."""
    srv = _echo_teacher(1.0)
    try:
        conn = _TeacherConn(srv.endpoint)
        with pytest.raises(errors.FeedSpecError) as ei:
            conn.predict({"wrong": np.ones((2, 2), np.float32)})
        assert ei.value.spec == "img"
        assert ei.value.shape is not None
        conn.close()
    finally:
        srv.stop()


def test_reader_surfaces_feed_spec_error_not_retried():
    """A permanently bad feed is a poisoned task: the reader surfaces
    it to the consumer in order instead of ping-ponging it between
    teachers forever."""
    srv = _echo_teacher(1.0)

    def gen():
        for i in range(3):
            yield (np.full((2, 2), i, np.float32),)

    dr = DistillReader(ins=["wrong"], predicts=["soft"], max_in_flight=2)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([srv.endpoint])
    try:
        with pytest.raises(errors.DataAccessError) as ei:
            for _ in dr():
                pass
        assert isinstance(ei.value, errors.FeedSpecError)
    finally:
        dr.stop()
        srv.stop()


def test_reader_backs_off_overloaded_teacher():
    """A typed shed requeues the task elsewhere, opens the endpoint's
    breaker, and keeps the healthy pooled client (no redial storm) —
    the epoch still completes with every batch delivered."""
    shed_ac = AdmissionController()
    shed_ac.set_draining(True)  # t1 sheds every predict, typed
    t1 = _echo_teacher(2.0, admission=shed_ac)
    t2 = _echo_teacher(2.0, fn_sleep=0.05)

    def gen():
        for i in range(12):
            yield (np.full((2, 2), i, np.float32),)

    dr = DistillReader(ins=["img"], predicts=["soft"], max_in_flight=4,
                       teacher_backoff=60, pipeline_depth=1)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([t1.endpoint, t2.endpoint])
    retired = []
    orig_retire = dr._pool.retire
    dr._pool.retire = lambda ep: (retired.append(ep), orig_retire(ep))[1]
    try:
        dr._ensure_started()
        dr._sync_workers()  # both workers parked on the task queue
        time.sleep(0.2)
        seen = []
        for img, soft in dr():
            np.testing.assert_allclose(soft, img * 2.0)
            seen.append(int(img[0, 0]))
        assert seen == list(range(12))  # nothing lost to the shed
        # t1 really shed work and its breaker opened for the backoff
        assert shed_ac.stats()["shed"]["draining"] >= 1
        assert dr._breaker.state(t1.endpoint) == CircuitBreaker.OPEN
        # ... but the pooled client was NOT retired: the connection is
        # healthy, backing off must not force a redial
        assert t1.endpoint not in retired
    finally:
        dr.stop()
        t1.stop()
        t2.stop()


def test_predict_deadline_sheds_dead_on_arrival():
    """A queued predict whose per-request deadline elapsed while it
    waited is shed as ``deadline`` instead of burning device time."""

    def slow(feed):
        time.sleep(0.25)
        return {"out": feed["x"]}

    srv = TeacherServer(slow, {"x": ([1], "<f4")}, {"out": ([1], "<f4")},
                        max_batch=1, host="127.0.0.1",
                        admission=AdmissionController()).start()
    cl = RpcClient(srv.endpoint, timeout=30)
    try:
        feed = {"x": np.ones((1, 1), np.float32)}
        f1 = cl.call_async("predict", feed)
        time.sleep(0.1)  # the device thread is now busy with f1
        f2 = cl.call_async("predict", feed, deadline_ms=50)
        assert f1.result(timeout=10)["out"].shape == (1, 1)
        with pytest.raises(errors.OverloadedError) as ei:
            f2.result(timeout=10)
        assert "deadline" in str(ei.value)
    finally:
        cl.close()
        srv.stop()


# -- drain-safe decommission ----------------------------------------------


def test_drain_safe_decommission_zero_stranded():
    """The four-step drain protocol: every in-flight request resolves
    (served or typed shed), the queue is provably empty before the
    exit, and ``serve.drain`` fires on the real drain path."""
    plane = FaultPlane(seed=3)
    fault = plane.inject("serve.drain", "delay", seconds=0.01)
    plane.install()

    def slow(feed):
        time.sleep(0.05)
        return {"out": feed["x"] * 2.0}

    srv = TeacherServer(slow, {"x": ([1], "<f4")}, {"out": ([1], "<f4")},
                        max_batch=2, host="127.0.0.1",
                        admission=AdmissionController()).start()
    cl = RpcClient(srv.endpoint, timeout=30)
    try:
        feed = {"x": np.ones((1, 1), np.float32)}
        futs = [cl.call_async("predict", feed) for _ in range(6)]
        time.sleep(0.02)
        report = serve_drain.decommission(srv, register=None, ttl_s=0.0,
                                          deadline_s=10.0)
        assert report["drained"] is True
        assert report["pending_rows"] == 0
        assert report["queue_depth"] == 0
        assert fault.fired == 1
        served = shed = 0
        for f in futs:
            try:
                out = f.result(timeout=10)
                np.testing.assert_allclose(out["out"], 2.0)
                served += 1
            except errors.OverloadedError as e:
                assert "draining" in str(e)
                shed += 1
        # zero stranded: every future resolved, served or typed shed
        assert served + shed == 6
        assert served >= 1
    finally:
        cl.close()
        srv.stop()
        plane.uninstall()


def test_teacher_kill_mid_predict_zero_lost():
    """Chaos drill: a teacher dies mid-predict (stop() severs live
    connections — SIGKILL semantics). The drain protocol is the
    optimization; the reader's requeue is the delivery backstop, and
    it must lose zero predicts."""
    t1 = _echo_teacher(3.0, admission=AdmissionController())
    t2 = _echo_teacher(3.0, admission=AdmissionController())

    def gen():
        for i in range(24):
            yield (np.full((2, 2), i, np.float32),)

    dr = DistillReader(ins=["img"], predicts=["soft"], max_in_flight=4,
                       teacher_backoff=60)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([t1.endpoint, t2.endpoint])
    killed = False
    seen = []
    try:
        for i, (img, soft) in enumerate(dr()):
            np.testing.assert_allclose(soft, img * 3.0)
            seen.append(int(img[0, 0]))
            if i == 3 and not killed:
                t1.stop()
                killed = True
        assert seen == list(range(24))
    finally:
        dr.stop()
        t2.stop()
        if not killed:
            t1.stop()


def test_registry_drain_stops_advertising(coord):
    """TeacherRegister.drain(): the lease is revoked NOW (no TTL wait)
    and the register loop never re-registers the endpoint."""
    teacher = _echo_teacher(1.0)
    reg = TeacherRegister(coord, "svc_drain", teacher.endpoint,
                          ttl=2).start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline \
                and not list_teachers(coord, "svc_drain"):
            time.sleep(0.1)
        assert list(list_teachers(coord, "svc_drain")) \
            == [teacher.endpoint]
        reg.drain()
        assert reg.draining
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline \
                and list_teachers(coord, "svc_drain"):
            time.sleep(0.05)
        assert list_teachers(coord, "svc_drain") == {}
        # several refresh ticks later: still gone (never re-registers,
        # even though the teacher's port still answers TCP)
        time.sleep(1.5)
        assert list_teachers(coord, "svc_drain") == {}
    finally:
        reg.stop()
        teacher.stop()


def test_discovery_outage_stale_but_serving(coord):
    """Discovery dies mid-stream: clients keep routing on the
    last-known table (zero lost predicts), the outage logs exactly ONE
    closed->open ``breaker.open`` (re-probes are ``reopened``), and a
    server returning at the same endpoint is re-joined within a probe
    period."""
    teacher = _echo_teacher(1.0)
    reg = TeacherRegister(coord, "svc_out", teacher.endpoint,
                          ttl=2).start()
    disc = DiscoveryServer(coord, host="127.0.0.1").start()
    client = None
    disc2 = None
    conn = None
    try:
        client = DiscoveryClient(disc.endpoint, "svc_out",
                                 require_num=1,
                                 heartbeat_interval=0.3).start()
        assert client.wait_for_servers(timeout=20) == [teacher.endpoint]
        disc_ep = disc.endpoint
        port = int(disc_ep.rsplit(":", 1)[1])
        mark = obs_events.emit("test.serve.outage.mark")
        disc.stop()  # the outage

        conn = _TeacherConn(teacher.endpoint)
        opened = []
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            # stale-but-serving: the table is never cleared, and
            # predicts against it keep succeeding through the outage
            assert client.get_servers() == [teacher.endpoint]
            out = conn.predict({"img": np.ones((2, 2), np.float32)})
            np.testing.assert_allclose(out["soft"], 1.0)
            opened = [e for e in obs_events.EVENTS.snapshot(
                          since_id=mark, kinds=("breaker.open",))
                      if e["attrs"].get("key") == disc_ep]
            if len(opened) >= 2:  # the trip + >=1 gated re-probe
                break
            time.sleep(0.2)
        assert len(opened) >= 2
        first = [e for e in opened if not e["attrs"].get("reopened")]
        assert len(first) == 1  # exactly one closed->open per outage

        # recovery: a discovery server returns at the SAME endpoint
        disc2 = DiscoveryServer(coord, host="127.0.0.1",
                                port=port).start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if (client._breaker.state(disc_ep)
                    == CircuitBreaker.CLOSED
                    and client.get_servers() == [teacher.endpoint]):
                break
            time.sleep(0.2)
        assert client._breaker.state(disc_ep) \
            == CircuitBreaker.CLOSED
        assert client.get_servers() == [teacher.endpoint]
    finally:
        if conn is not None:
            conn.close()
        if client is not None:
            client.stop()
        if disc2 is not None:
            disc2.stop()
        reg.stop()
        teacher.stop()


# -- the ServeScaler ------------------------------------------------------


class _FakeCoord(object):
    """The two store calls the scaler journal needs."""

    def __init__(self):
        self.kv = {}

    def get_value(self, service, key):
        return self.kv.get((service, key))

    def set_server_permanent(self, service, key, value):
        self.kv[(service, key)] = value


def _stat(occ, pending=0, shed=0, draining=False):
    return {"occupancy": occ, "pending_rows": pending,
            "queue_frac": 0.0, "projected_wait_ms": 0.0,
            "slo_ms": 100.0, "shed_total": shed, "draining": draining}


def _scaler(coord, mode, calls=None, **kw):
    calls = calls if calls is not None else []
    kw.setdefault("interval", 1.0)
    kw.setdefault("out_streak", 2)
    kw.setdefault("in_streak", 3)
    return ServeScaler(
        coord, "pod-test", mode=mode,
        scale_out_fn=lambda: (calls.append("out"), "ep-new")[1],
        scale_in_fn=lambda ep: (calls.append(ep), True)[1], **kw), calls


def test_scaler_off_mode_is_inert():
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "off")
    for t in range(6):
        assert sc.tick({"t0": _stat(0.99)}, now=float(t)) == []
    assert calls == []
    assert load_actions(coord) == []


def test_scaler_scale_out_streak_and_cooldown():
    """Scale-out needs ``out_streak`` CONSECUTIVE overloaded ticks and
    then waits out its cooldown — two actions across six hot ticks at
    the default 3-interval cooldown, never a burst."""
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "on")
    acts = []
    for t in range(6):
        acts += sc.tick({"t0": _stat(0.95)}, now=float(t))
    assert [a["kind"] for a in acts] == ["scale_out", "scale_out"]
    assert [a["ts"] for a in acts] == [1.0, 4.0]  # streak 2, then
    assert calls == ["out", "out"]                # cooldown + streak
    assert all(a["outcome"] == "applied" for a in acts)
    assert all(a["schema"] == "action/v1" for a in acts)
    assert [a["seq"] for a in acts] == [1, 2]
    assert [a.get("seq") for a in load_actions(coord)] == [1, 2]


def test_scaler_scale_in_drains_least_loaded():
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "on", in_streak=4)
    fleet = {"t0": _stat(0.05), "t1": _stat(0.2, pending=3)}
    acts = []
    for t in range(4):
        acts += sc.tick(fleet, now=float(t))
    assert [a["kind"] for a in acts] == ["scale_in"]
    assert acts[0]["target"] == "t0"  # deterministic: least loaded
    assert calls == ["t0"]


def test_scaler_never_flaps():
    """Opposite signals reset each other's streaks and the dead band
    decays both — an oscillating fleet produces ZERO actions."""
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "on", out_streak=2, in_streak=2)
    hot = {"t0": _stat(0.95), "t1": _stat(0.9)}
    idle = {"t0": _stat(0.05), "t1": _stat(0.1)}
    mid = {"t0": _stat(0.5), "t1": _stat(0.5)}
    acts = []
    for t, stats in enumerate([hot, idle] * 5 + [hot, mid] * 5):
        acts += sc.tick(stats, now=float(t))
    assert acts == []
    assert calls == []


def test_scaler_clean_fleet_zero_actions():
    """A clean single-teacher fleet at low load: no sheds, no burn, no
    headroom to shrink below min — the scaler does nothing."""
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "on", min_teachers=1)
    for t in range(12):
        assert sc.tick({"t0": _stat(0.1)}, now=float(t)) == []
    assert calls == []
    assert load_actions(coord) == []


def test_scaler_burn_severity_triggers_scale_out():
    """The predict_p99 burn-rate evaluator is an overload signal on its
    own: a bad-latency burn scales out even at low occupancy."""
    coord = _FakeCoord()
    sc, calls = _scaler(coord, "on")
    low = {"t0": _stat(0.1)}
    assert sc.tick(low, predict_sample=(0, 0), now=0.0) == []
    assert sc.tick(low, predict_sample=(1000, 500), now=1.0) == []
    acts = sc.tick(low, predict_sample=(2000, 1000), now=2.0)
    assert [a["kind"] for a in acts] == ["scale_out"]
    assert acts[0]["cause"]["burn_severity"] == "critical"
    assert calls == ["out"]


def test_scaler_dry_mode_journals_identical_stream():
    """dry and on modes fed the identical tick stream journal the
    identical (seq, kind, target, decision) action stream; dry applies
    nothing."""
    two_idle = {"t0": _stat(0.05), "t1": _stat(0.1)}
    stream = ([{"t0": _stat(0.95)}] * 2
              + [two_idle] * 4)

    def run(mode):
        coord = _FakeCoord()
        sc, calls = _scaler(coord, mode, in_streak=3,
                            cooldowns={"scale_out": 2.0,
                                       "scale_in": 2.0})
        acts = []
        for t, stats in enumerate(stream):
            acts += sc.tick(stats, now=float(t))
        return sc, calls, acts

    _, on_calls, on_acts = run("on")
    _, dry_calls, dry_acts = run("dry")

    def sig(actions):
        return [(a["seq"], a["kind"], a["target"], a.get("decision"))
                for a in actions]

    assert sig(on_acts) == sig(dry_acts)
    assert [a["kind"] for a in on_acts] == ["scale_out", "scale_in"]
    assert on_calls == ["out", "t0"]
    assert dry_calls == []  # dry NEVER touches the fleet
    assert all(a["mode"] == "dry_run" and a["outcome"] == "dry_run"
               for a in dry_acts)


def test_scaler_seq_anchors_on_stored_journal():
    """A re-elected host's scaler continues the stored sequence instead
    of restarting at 1 — the journal stays totally ordered."""
    coord = _FakeCoord()
    coord.set_server_permanent("serve", "journal", json.dumps(
        [{"schema": "action/v1", "seq": 5, "kind": "scale_out",
          "target": "fleet"}]))
    sc, _ = _scaler(coord, "on")
    acts = []
    for t in range(2):
        acts += sc.tick({"t0": _stat(0.95)}, now=float(t))
    assert [a["seq"] for a in acts] == [6]
    assert [a.get("seq") for a in load_actions(coord)] == [5, 6]


# -- load-aware balancing -------------------------------------------------


def test_balance_single_join_moves_one_nth():
    """Churn-minimal rebalance: one server joining a 12-client/3-server
    service moves EXACTLY clients/new_count = 3 assignments, and every
    move lands in edl_balance_reassignments_total."""
    now = [0.0]
    svc = Service("churn", clock=lambda: now[0])
    svc.set_servers(["s0", "s1", "s2"])
    for i in range(12):
        svc.register_client("c%02d" % i, 1)
    before = svc.stats()
    assert before["fairness"]["reassignments"] == 0  # joins move nothing
    assignments = {cid: eps[0] for cid, eps in before["clients"].items()}
    assert sorted(before["servers"].values()) == [4, 4, 4]

    svc.set_servers(["s0", "s1", "s2", "s3"])
    after = svc.stats()
    moved = [cid for cid, eps in after["clients"].items()
             if eps[0] != assignments[cid]]
    assert len(moved) == 3  # ~1/N: 12 clients / 4 servers
    assert after["fairness"]["reassignments"] == 3
    assert sorted(after["servers"].values()) == [3, 3, 3, 3]


def test_balance_draining_server_sheds_clients():
    """A draining teacher weighs 0: its connection cap collapses and
    clients move off before the discovery TTL even lapses."""
    now = [0.0]
    svc = Service("drainw", clock=lambda: now[0])
    svc.set_servers(["a", "b"])
    for i in range(4):
        svc.register_client("c%d" % i, 1)
    assert sorted(svc.stats()["servers"].values()) == [2, 2]

    svc.set_servers({"a": {}, "b": {"draining": True}})
    stats = svc.stats()
    assert stats["servers"]["b"] == 0
    assert stats["servers"]["a"] == 4
    assert stats["fairness"]["reassignments"] == 2
    # every client still has a teacher (nobody starves during a drain)
    assert all(eps for eps in stats["clients"].values())


def test_balance_capacity_weights_connection_cap():
    """A capacity weight scales a server's connection cap: halving one
    server's weight pushes its overflow to peers with headroom."""
    now = [0.0]
    svc = Service("capw", clock=lambda: now[0])
    svc.set_servers(["a", "b", "c"])
    for i in range(5):
        svc.register_client("c%d" % i, 1)
    # per_server cap 2 -> loads {2, 2, 1} (which server holds 1 is
    # iteration-order dependent; the weighted endpoint below is not)
    assert sorted(svc.stats()["servers"].values()) == [1, 2, 2]

    svc.set_servers({"a": {"capacity": 0.5}, "b": {}, "c": {}})
    stats = svc.stats()
    # a's cap halves to 1; its overflow (if any) moved to the peer
    # that still had weighted headroom — never back onto a
    assert stats["servers"]["a"] == 1
    assert sorted(stats["servers"].values()) == [1, 2, 2]
    # every client still has exactly its entitled one teacher
    assert all(len(eps) == 1 for eps in stats["clients"].values())


def test_weighted_hash_vnode_distribution():
    """Capacity-weighted vnodes: a 2.0-weight node owns ~2x the key
    space, a 0-weight (draining) node owns none."""
    ch = ConsistentHash()
    ch.update(["a", "b", "c"], weights={"a": 2.0, "c": 0.0})
    counts = {"a": 0, "b": 0, "c": 0}
    for i in range(4000):
        node, _ = ch.get_node("key-%d" % i)
        counts[node] += 1
    assert counts["c"] == 0
    assert counts["a"] + counts["b"] == 4000
    ratio = counts["a"] / float(counts["b"])
    assert 1.5 < ratio < 2.7, counts
