"""Zero-downtime live resize: the in-place reshard engine, the 2PC
store protocol, the generator/launcher integration, and the liveft
transition classifier.

The headline contract: a live 8→4→8 resize produces params + optimizer
state BYTE-IDENTICAL to a stop-resume (kill / respawn / restore) over
the same mesh sequence — the live path changes how fast a resize is,
never what it computes. (Neither path is bitwise-comparable to a
never-resized run: any world change reorders the allreduce.) The chaos
drill proves the other half: a fault mid-reshard rolls back to the old
mesh byte-identically and surfaces as LiveResizeError, so the
stop-resume ladder stays the safety net.

Runs on the conftest's 8 virtual CPU devices — single process, pure dp,
replicated state: exactly the live-resize scope.
"""

import json
import threading
import time

import jax
import numpy as np
import optax
import pytest

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants
from edl_tpu.models import linear
from edl_tpu.obs import events as obs_events
from edl_tpu.robustness import faults
from edl_tpu.runtime import live_resize as live_mod
from edl_tpu.runtime.mesh import make_mesh
from edl_tpu.runtime.trainer import ElasticTrainer
from edl_tpu.utils.errors import LiveResizeError

TOTAL_BATCH = 64
BATCHES = [linear.synthetic_batch(TOTAL_BATCH, seed=i) for i in range(8)]


def _trainer(n_devices, ckpt=None, coord=None, **kw):
    return ElasticTrainer(
        linear.loss_fn, linear.init_params(), optax.sgd(0.05),
        total_batch_size=TOTAL_BATCH,
        mesh=make_mesh(devices=jax.devices()[:n_devices]),
        checkpoint_dir=ckpt, coord=coord, **kw)


def _steps(trainer, batches):
    for b in batches:
        trainer.train_step(trainer.local_batch_slice(b))


def _state_bytes(trainer):
    return [np.asarray(x).tobytes()
            for x in jax.tree_util.tree_leaves(trainer.train_state)]


def _world(trainer):
    return len(list(trainer.mesh.devices.flat))


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within %ss" % timeout)


# -- the engine: byte identity, rollback, edges ----------------------------


def test_live_resize_byte_identical_to_stop_resume(tmp_path):
    """The acceptance contract: live 8→4→8 == stop-resume 8→4→8,
    byte for byte, over the same batch schedule."""
    live = _trainer(8)
    _steps(live, BATCHES[:2])
    rec_dn = live.live_resize(4)
    assert rec_dn["mode"] == "live"
    assert (rec_dn["from_devices"], rec_dn["to_devices"]) == (8, 4)
    assert _world(live) == 4
    _steps(live, BATCHES[2:4])
    rec_up = live.live_resize(8)
    assert (rec_up["from_devices"], rec_up["to_devices"]) == (4, 8)
    assert _world(live) == 8
    _steps(live, BATCHES[4:6])

    # the stop-resume chain: three incarnations over the same worlds
    ckpt = str(tmp_path / "ckpt")
    a = _trainer(8, ckpt=ckpt)
    _steps(a, BATCHES[:2])
    a.save()
    b = _trainer(4, ckpt=ckpt)
    assert b.resume()
    _steps(b, BATCHES[2:4])
    b.save()
    c = _trainer(8, ckpt=ckpt)
    assert c.resume()
    _steps(c, BATCHES[4:6])

    assert _state_bytes(live) == _state_bytes(c)


@pytest.mark.parametrize("point", ["resize.live.drain",
                                   "resize.live.reshard"])
def test_live_resize_fault_rolls_back_byte_identical(point):
    """The chaos drill: a fault at either live fault point rolls the
    trainer back to the OLD mesh with state untouched (zero
    divergence), raises LiveResizeError (the nack path), emits the
    fallback event, and the trainer keeps training."""
    tr = _trainer(8)
    _steps(tr, BATCHES[:2])
    before = _state_bytes(tr)
    mark = obs_events.emit("test.live_resize.mark")
    plane = faults.FaultPlane(seed=7)
    plane.inject(point, "error", error="RpcError")
    plane.install()
    try:
        with pytest.raises(LiveResizeError):
            tr.live_resize(4)
    finally:
        plane.uninstall()
    assert (point, "error") in plane.log  # the fault actually fired
    assert _world(tr) == 8
    assert _state_bytes(tr) == before
    kinds = [e["kind"] for e in obs_events.EVENTS.snapshot(since_id=mark)]
    assert "resize.live.fallback" in kinds
    # numerically untouched AND still functional on the old mesh
    _steps(tr, [BATCHES[2]])


def test_live_resize_single_survivor_and_back():
    """The 8→1→8 edge: one device is still a valid dp mesh; the reshard
    is the pure zero-wire fast path (no store, no peers, no FS)."""
    tr = _trainer(8)
    _steps(tr, BATCHES[:1])
    rec = tr.live_resize(1)
    assert _world(tr) == 1
    assert rec["restore_source"] == "local"
    assert rec["restore_peers"] == 0
    _steps(tr, BATCHES[1:2])
    rec_up = tr.live_resize(8)
    assert _world(tr) == 8
    assert rec_up["restore_source"] == "local"
    _steps(tr, BATCHES[2:3])


def test_live_resize_noop_and_scope_rejections():
    tr = _trainer(8)
    _steps(tr, BATCHES[:1])
    assert tr.live_resize(8).get("noop") is True
    before = _state_bytes(tr)
    for bad in (0, len(jax.devices()) + 1, 3):  # range, range, 64 % 3
        with pytest.raises(LiveResizeError):
            tr.live_resize(bad)
    assert _world(tr) == 8
    assert _state_bytes(tr) == before


def test_live_resize_prewarm_hit(tmp_path, monkeypatch):
    """With a compile cache and a prewarmed target world, the live
    swap loads the AOT executable instead of recompiling — the record
    says so, and that is what the doctor's prewarm_miss detector keys
    off."""
    monkeypatch.setenv("EDL_TPU_COMPILE_CACHE", str(tmp_path / "cache"))
    tr = _trainer(8)
    _steps(tr, BATCHES[:1])  # the prewarm needs the batch structure
    assert tr.prewarm_resize_compiles([4], block=True) == [4]
    rec = tr.live_resize(4)
    assert rec["prewarm"] == "hit"
    _steps(tr, BATCHES[1:2])
    # the un-prewarmed grow leg is an honest miss, not "n/a"
    assert tr.live_resize(8)["prewarm"] == "miss"


# -- the store protocol ----------------------------------------------------


def _cluster_key(coord):
    return (coord.service_prefix(constants.SERVICE_CLUSTER)
            + constants.CLUSTER_SERVER)


def test_intent_protocol_roundtrip(coord):
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "gen_a")
    intent = live_mod.make_intent("i1", ["w1", "w2"],
                                  devices={"w1": 4, "w2": 4},
                                  leader="gen_a", cluster_json="{}")
    assert live_mod.publish_prepare(coord, "gen_a", intent)
    assert live_mod.read_intent(coord)["phase"] == live_mod.PREPARE
    # a deposed coordinator's writes are all no-ops
    assert not live_mod.publish_prepare(coord, "gen_b", intent)
    assert not live_mod.commit(coord, "gen_b", intent)
    assert not live_mod.abort(coord, "gen_b", intent)
    # acks are scoped by intent id: a stale ack from a previous resize
    # never satisfies this one
    live_mod.write_ack(coord, "w1", "i1", True, info={"world": 4})
    live_mod.write_ack(coord, "w2", "i0_stale", True)
    assert set(live_mod.read_acks(coord, "i1")) == {"w1"}
    live_mod.write_ack(coord, "w2", "i1", True)
    ok, acks = live_mod.wait_for_acks(coord, intent, timeout=5)
    assert ok and set(acks) == {"w1", "w2"}
    assert acks["w1"]["world"] == 4
    # commit flips the phase AND installs the cluster map in ONE txn
    assert live_mod.commit(coord, "gen_a", intent,
                           extra_puts=[(_cluster_key(coord), "MAP")])
    assert live_mod.read_intent(coord)["phase"] == live_mod.COMMIT
    assert coord.get_value(constants.SERVICE_CLUSTER,
                           constants.CLUSTER_SERVER) == "MAP"


def test_nack_wait_and_abort(coord):
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "gen_a")
    intent = live_mod.make_intent("i2", ["w1", "w2"], leader="gen_a")
    assert live_mod.publish_prepare(coord, "gen_a", intent)
    live_mod.write_ack(coord, "w1", "i2", True)
    live_mod.write_ack(coord, "w2", "i2", False, reason="out of scope")
    ok, acks = live_mod.wait_for_acks(coord, intent, timeout=5)
    assert not ok and set(acks) == {"w1", "w2"}
    assert live_mod.abort(coord, "gen_a", intent, reason="nack w2")
    after = live_mod.read_intent(coord)
    assert after["phase"] == live_mod.ABORT
    assert after["abort_reason"] == "nack w2"
    # a missing ack times out to not-ok too
    intent3 = live_mod.make_intent("i3", ["w1", "ghost"], leader="gen_a")
    assert live_mod.publish_prepare(coord, "gen_a", intent3)
    live_mod.write_ack(coord, "w1", "i3", True)
    ok, acks = live_mod.wait_for_acks(coord, intent3, timeout=0.5)
    assert not ok and set(acks) == {"w1"}


def test_live_resize_watcher(coord):
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "gen_a")
    # a pre-existing intent is picked up at construction, not just via
    # the watch
    i1 = live_mod.make_intent("w_i1", ["me"], devices=4, leader="gen_a")
    assert live_mod.publish_prepare(coord, "gen_a", i1)
    w = live_mod.LiveResizeWatcher(coord, "me")
    try:
        assert _wait(lambda: w.pending())["id"] == "w_i1"
        w.done("w_i1")
        assert w.pending() is None
        # a later intent arrives through the watch; one addressed to
        # someone else never surfaces; an expired one is dropped
        other = live_mod.make_intent("w_other", ["not_me"], leader="gen_a")
        assert live_mod.publish_prepare(coord, "gen_a", other)
        expired = live_mod.make_intent("w_exp", ["me"], leader="gen_a",
                                       deadline_s=-1.0)
        assert live_mod.publish_prepare(coord, "gen_a", expired)
        time.sleep(0.3)
        assert w.pending() is None
        i2 = live_mod.make_intent("w_i2", ["me"], devices=8,
                                  leader="gen_a")
        assert live_mod.publish_prepare(coord, "gen_a", i2)
        assert _wait(lambda: w.pending())["id"] == "w_i2"
        # handled ids never come back, even if the key is re-delivered
        w.done("w_i2")
        assert live_mod.publish_prepare(coord, "gen_a", i2)
        time.sleep(0.3)
        assert w.pending() is None
    finally:
        w.stop()


def test_capability_advertise_and_ready(coord):
    reg = live_mod.advertise_capability(coord, "w1",
                                        info={"devices": 8}, ttl=5)
    assert reg is not None
    try:
        assert _wait(lambda: "w1" in live_mod.ready_participants(coord))
    finally:
        reg.stop()
    _wait(lambda: "w1" not in live_mod.ready_participants(coord))


# -- the generator's two-phase commit --------------------------------------


def _pod():
    import os

    from edl_tpu.controller.env import JobEnv
    from edl_tpu.controller.pod import Pod
    os.environ["EDL_TPU_POD_IP"] = "127.0.0.1"
    args = type("A", (), dict(
        job_id="test_job", store_endpoints="x", nodes_range="1:4",
        nproc_per_node=1, pod_ip="127.0.0.1", checkpoint_path=None,
        log_dir=None, log_level=None))()
    return Pod.from_env(JobEnv(args))


def _cluster(pods):
    c = cluster_mod.Cluster()
    c.pods = list(pods)
    c.assign_ranks()
    return c


def _acker(coord, verdicts, stop):
    """Poll for a prepare intent and ack it like the survivors would."""
    while not stop.is_set():
        intent = live_mod.read_intent(coord)
        if intent and intent.get("phase") == live_mod.PREPARE:
            for who in intent["survivors"]:
                live_mod.write_ack(coord, who, intent["id"],
                                   verdicts.get(who, True),
                                   reason=None if verdicts.get(who, True)
                                   else "drill nack")
            return
        time.sleep(0.05)


def test_generator_live_commit_two_phase(coord):
    from edl_tpu.controller.cluster_generator import Generator
    pod_a, pod_b = _pod(), _pod()
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    gen = Generator(coord, pod_a.id, min_nodes=1, max_nodes=2,
                    live_ack_timeout=5.0)
    new = _cluster([pod_a])  # shrink: pod_b leaves, pod_a survives
    stop = threading.Event()
    t = threading.Thread(target=_acker, args=(coord, {}, stop),
                         daemon=True)
    t.start()
    try:
        assert gen._try_live_commit(new, _cluster_key(coord))
    finally:
        stop.set()
        t.join(timeout=5)
    intent = live_mod.read_intent(coord)
    assert intent["phase"] == live_mod.COMMIT
    assert intent["survivors"] == [pod_a.id]
    assert intent["devices"][pod_a.id] >= 1
    # the cluster map landed in the SAME transaction
    installed = cluster_mod.load_from_store(coord)
    assert installed is not None
    assert installed.pod_ids() == [pod_a.id]


def test_generator_live_nack_aborts_to_stop_resume(coord):
    from edl_tpu.controller.cluster_generator import Generator
    pod_a = _pod()
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, pod_a.id)
    gen = Generator(coord, pod_a.id, min_nodes=1, max_nodes=2,
                    live_ack_timeout=5.0)
    new = _cluster([pod_a])
    stop = threading.Event()
    t = threading.Thread(target=_acker,
                         args=(coord, {pod_a.id: False}, stop),
                         daemon=True)
    t.start()
    try:
        assert gen._try_live_commit(new, _cluster_key(coord)) is False
    finally:
        stop.set()
        t.join(timeout=5)
    intent = live_mod.read_intent(coord)
    assert intent["phase"] == live_mod.ABORT
    assert pod_a.id in intent["abort_reason"]
    # no map installed: the caller falls through to stop-resume commit
    assert cluster_mod.load_from_store(coord) is None


def test_generator_aborts_stale_foreign_intent(coord):
    """Leader loss mid-reshard: the old coordinator published prepare
    and died; the NEW leader's first generation pass aborts the orphan
    so survivors stop draining and stop-resume runs."""
    from edl_tpu.controller.cluster_generator import Generator
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "dead_gen")
    orphan = live_mod.make_intent("orphan", ["w1"], leader="dead_gen")
    assert live_mod.publish_prepare(coord, "dead_gen", orphan)
    # leadership moves
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "gen_b")
    Generator(coord, "gen_b", min_nodes=1,
              max_nodes=2)._abort_stale_intent()
    after = live_mod.read_intent(coord)
    assert after["phase"] == live_mod.ABORT
    assert "dead_gen" in after["abort_reason"]
    # its own fresh prepare is NOT stale — a second pass leaves it alone
    own = live_mod.make_intent("own", ["w1"], leader="gen_b")
    assert live_mod.publish_prepare(coord, "gen_b", own)
    Generator(coord, "gen_b", min_nodes=1,
              max_nodes=2)._abort_stale_intent()
    assert live_mod.read_intent(coord)["phase"] == live_mod.PREPARE


def test_generator_live_eligibility(coord):
    from edl_tpu.controller.cluster_generator import Generator
    pod_a, pod_b, pod_c = _pod(), _pod(), _pod()
    gen = Generator(coord, pod_a.id, min_nodes=1, max_nodes=3)
    current = _cluster([pod_a, pod_b])
    shrink = _cluster([pod_a])
    grow = _cluster([pod_a, pod_b, pod_c])
    # no current cluster yet → cold start is stop-resume
    assert not gen._live_eligible(None, shrink)
    # a joining pod has no process to reshape
    assert not gen._live_eligible(current, grow)
    # survivors-only, but nobody advertises the capability
    assert not gen._live_eligible(current, shrink)
    regs = [live_mod.advertise_capability(coord, p.id)
            for p in (pod_a, pod_b)]
    try:
        assert _wait(lambda: gen._live_eligible(current, shrink))
        assert gen._live_eligible(current, current)
    finally:
        for r in regs:
            r.stop()


# -- the launcher's adoption gate ------------------------------------------


def test_launcher_live_intent_gating(coord):
    from edl_tpu.controller.launcher import Launcher
    pod = _pod()
    launcher = Launcher.__new__(Launcher)
    launcher._coord = coord
    launcher._pod = pod
    launcher._live_done = set()
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, "gen_a")
    assert launcher._live_intent_for_pod() is None  # no intent at all
    intent = live_mod.make_intent("L1", [pod.id], devices={pod.id: 4},
                                  leader="gen_a")
    assert live_mod.publish_prepare(coord, "gen_a", intent)
    assert launcher._live_intent_for_pod() is None  # prepare ≠ commit
    assert live_mod.commit(coord, "gen_a", intent)
    assert launcher._live_intent_for_pod() is None  # no ok ack yet
    live_mod.write_ack(coord, pod.id, "L1", False, reason="drill")
    assert launcher._live_intent_for_pod() is None  # nack ≠ ok
    live_mod.write_ack(coord, pod.id, "L1", True)
    got = launcher._live_intent_for_pod()
    assert got is not None and got["id"] == "L1"
    launcher._live_done.add("L1")
    assert launcher._live_intent_for_pod() is None  # consumed once
    # an intent that excludes this pod is never adopted
    foreign = live_mod.make_intent("L2", ["someone_else"], leader="gen_a")
    assert live_mod.publish_prepare(coord, "gen_a", foreign)
    assert live_mod.commit(coord, "gen_a", foreign)
    assert launcher._live_intent_for_pod() is None


# -- liveft: the transition classifier -------------------------------------


def test_classify_transition():
    from edl_tpu.liveft import elastic as el
    assert el.classify_transition(["a", "b"], ["a"], "a") == el.SHRINK
    assert el.classify_transition(["a"], ["a", "b"], "a") == el.GROW
    # mixed join+leave is conservatively a SHRINK for survivors
    assert el.classify_transition(["a", "b"], ["b", "c"], "b") == el.SHRINK
    assert el.classify_transition(["a", "b"], ["b", "c"],
                                  "a") == el.SELF_EVICTED
    assert el.classify_transition(["a"], ["a"], "a") == el.UNCHANGED
    assert el.classify_transition(None, ["a"], "b") == el.SELF_EVICTED


def _manager(coord, host, np_target, seen):
    from edl_tpu.liveft import elastic as el
    m = el.ElasticManager(
        coord, host, np_target,
        on_transition=lambda k, old, new: seen.append((k, old, new)))
    m._registered.set()  # no threads: drive watch() by hand
    return m


def _register_hosts(coord, hosts):
    from edl_tpu.liveft import elastic as el
    for h in hosts:
        coord.set_server_permanent(el.SERVICE_NODES, h, "1")


def test_elastic_manager_shrink_transition(coord):
    from edl_tpu.liveft import elastic as el
    seen = []
    m = _manager(coord, "h1", 2, seen)
    m._agreed_hosts = ["h1", "h2", "h3"]
    _register_hosts(coord, ["h1", "h2"])
    m._hosts_changed.set()
    assert m.watch(poll=0.01) == el.RESTART
    assert seen == [(el.SHRINK, ["h1", "h2", "h3"], ["h1", "h2"])]


def test_elastic_manager_grow_transition(coord):
    from edl_tpu.liveft import elastic as el
    seen = []
    m = _manager(coord, "h1", 2, seen)
    m._agreed_hosts = ["h1"]
    _register_hosts(coord, ["h1", "h2"])
    m._np = 2
    m._np_changed.set()
    assert m.watch(poll=0.01) == el.RESTART
    assert seen == [(el.GROW, ["h1"], ["h1", "h2"])]


def test_elastic_manager_self_eviction_is_error(coord):
    """The world settled at np WITHOUT us: ERROR, not the old
    HOLD-forever."""
    from edl_tpu.liveft import elastic as el
    seen = []
    m = _manager(coord, "h1", 2, seen)
    m._agreed_hosts = ["h1", "h2"]
    _register_hosts(coord, ["h2", "h3"])
    m._hosts_changed.set()
    assert m.watch(poll=0.01) == el.ERROR
    assert seen == [(el.SELF_EVICTED, ["h1", "h2"], ["h2", "h3"])]


def test_elastic_manager_flap_is_not_a_restart(coord):
    """A watch event that settles back to the agreed membership (lease
    blip, store failover) must neither RESTART nor notify."""
    from edl_tpu.liveft import elastic as el
    seen = []
    m = _manager(coord, "h1", 2, seen)
    m._agreed_hosts = ["h1", "h2"]
    _register_hosts(coord, ["h1", "h2"])
    m._hosts_changed.set()
    assert m.watch(poll=0.01) == el.HOLD
    assert not m._hosts_changed.is_set()  # the flap was consumed
    assert seen == []


# -- the doctor's live-resize detectors ------------------------------------


def test_job_doctor_live_resize_findings():
    from edl_tpu.tools import job_doctor
    events = [
        {"id": 1, "ts": 100.0, "kind": "resize.live.start", "cause": None,
         "attrs": {"from_devices": 8, "to_devices": 4}},
        {"id": 2, "ts": 101.0, "kind": "resize.live.fallback", "cause": 1,
         "attrs": {"reason": "RpcError: fault injected",
                   "from_devices": 8, "to_devices": 4}},
    ]
    obs_doc = {
        "schema": "obs_pub/v1", "events": events,
        "metrics": {"metrics": {"edl_resize_prewarm_misses_total": {
            "series": [{"value": 3.0}]}}},
    }
    report = job_doctor.diagnose({"job_id": "j", "job_status": None,
                                  "health": None,
                                  "obs": {"pod-00": obs_doc}})
    assert report["verdict"] == "unknown"
    assert [f["detector"] for f in report["findings"]] == [
        "live_resize_fallback", "prewarm_miss"]
    fall = report["findings"][0]
    assert fall["pod"] == "pod-00"
    assert "RpcError" in fall["summary"]
    # the chain links the fallback to its start event via the cause id
    assert any("resize.live.start" in step for step in fall["chain"])
    assert any("resize.live.fallback" in step for step in fall["chain"])
    miss = report["findings"][1]
    assert miss["metric"] == "edl_resize_prewarm_misses_total"
    assert "EDL_TPU_COMPILE_CACHE" in miss["summary"]
    assert "doctor-local" in report["summary"]
    json.dumps(report)
    job_doctor.render(report)  # the human surface renders the chains


# -- cross-mesh (model-parallel) transitions -------------------------------


def _tp_trainer(n_devices, mesh_shape=None, ckpt=None, feature_dim=16):
    """A trainer whose w is tp-sharded by rule (replicated while tp=1),
    so the SAME param table rides every factorization of the world."""
    from jax.sharding import PartitionSpec as P
    kw = dict(mesh_shape or {})
    return ElasticTrainer(
        linear.loss_fn, linear.init_params(feature_dim), optax.sgd(0.05),
        total_batch_size=TOTAL_BATCH,
        mesh=make_mesh(devices=jax.devices()[:n_devices], **kw),
        param_shardings=[(r"^w$", P("tp"))],
        checkpoint_dir=ckpt)


def _tp_batches(n=6, feature_dim=16):
    return [linear.synthetic_batch(TOTAL_BATCH, feature_dim=feature_dim,
                                   seed=i) for i in range(n)]


def test_live_resize_dp_to_dp_tp_byte_identical(tmp_path):
    """The tentpole arc at trainer level: a pure-dp world live-reshards
    onto a dp x tp factorization of the SAME device count (the intent's
    mesh_shape), byte-identical to a stop-resume over the same mesh
    sequence, and the record carries both factorizations."""
    batches = _tp_batches()
    live = _tp_trainer(4)
    _steps(live, batches[:2])
    rec = live.live_resize(4, mesh_shape={"dp": 2, "tp": 2})
    assert rec["mode"] == "live"
    assert rec["from_mesh"]["dp"] == 4 and rec["from_mesh"]["tp"] == 1
    assert _world(live) == 4
    assert live.mesh.shape["dp"] == 2 and live.mesh.shape["tp"] == 2
    # w really is tp-sharded on the new mesh
    assert live.train_state["params"]["w"].sharding.spec[0] == "tp"
    _steps(live, batches[2:4])

    ckpt = str(tmp_path / "ckpt")
    a = _tp_trainer(4, ckpt=ckpt)
    _steps(a, batches[:2])
    a.save()
    b = _tp_trainer(4, mesh_shape={"dp": 2, "tp": 2}, ckpt=ckpt)
    assert b.resume()
    _steps(b, batches[2:4])
    assert _state_bytes(live) == _state_bytes(b)

    # and back down to pure dp in the same process
    rec_back = live.live_resize(4, mesh_shape={"dp": 4})
    assert rec_back["mode"] == "live"
    assert live.mesh.shape["tp"] == 1
    _steps(live, batches[4:5])


def test_live_resize_tp_change_with_world_shrink():
    """World 4 -> 2 while keeping tp=2: dp absorbs the change (the
    default when no mesh_shape rides the intent), single process."""
    batches = _tp_batches()
    tr = _tp_trainer(4, mesh_shape={"dp": 2, "tp": 2})
    _steps(tr, batches[:2])
    tr.live_resize(2)  # no mesh_shape: model axes carry over
    assert _world(tr) == 2
    assert tr.mesh.shape["tp"] == 2 and tr.mesh.shape["dp"] == 1
    _steps(tr, batches[2:3])
    tr.live_resize(4, mesh_shape={"dp": 2, "tp": 2})
    assert tr.mesh.shape["dp"] == 2
    _steps(tr, batches[3:4])


def test_live_resize_uncomputable_spans_fallback_names_reason():
    """A target factorization whose spans are NOT computable (w dim 14
    divides tp=2 but not tp=4) must be rejected up front: state
    untouched, LiveResizeError raised, and the fallback event carrying
    scope=True + the exact per-leaf reason — the contract the doctor's
    reshard_fallback detector reads."""
    batches = _tp_batches(feature_dim=14)
    tr = _tp_trainer(4, mesh_shape={"dp": 2, "tp": 2}, feature_dim=14)
    _steps(tr, batches[:1])
    before = _state_bytes(tr)
    mark = obs_events.emit("test.reshard_scope.mark")
    with pytest.raises(LiveResizeError, match="uncomputable target"):
        tr.live_resize(4, mesh_shape={"dp": 1, "tp": 4})
    assert _world(tr) == 4
    assert tr.mesh.shape["tp"] == 2          # untouched factorization
    assert _state_bytes(tr) == before
    falls = [e for e in obs_events.EVENTS.snapshot(since_id=mark)
             if e["kind"] == "resize.live.fallback"]
    assert falls and falls[-1]["attrs"]["scope"] is True
    reason = falls[-1]["attrs"]["reason"]
    assert "uncomputable target spans" in reason
    assert "not divisible" in reason
    _steps(tr, batches[1:2])  # still training on the old mesh


def test_job_doctor_reshard_fallback_finding():
    """scope=True fallbacks get their own detector, ranked apart from
    mid-flight rollbacks, with the _live_scope_check reason verbatim in
    the summary."""
    from edl_tpu.tools import job_doctor
    reason = ("uncomputable target spans: params/w: dim 0 of shape "
              "(14,) not divisible by target tp=4 for spec "
              "PartitionSpec('tp',)")
    events = [
        {"id": 1, "ts": 100.0, "kind": "resize.live.start", "cause": None,
         "attrs": {"from_devices": 4, "to_devices": 4}},
        {"id": 2, "ts": 101.0, "kind": "resize.live.fallback", "cause": 1,
         "attrs": {"reason": reason, "scope": True,
                   "from_devices": 4, "to_devices": 4}},
    ]
    obs_doc = {"schema": "obs_pub/v1", "events": events, "metrics": {}}
    report = job_doctor.diagnose({"job_id": "j", "job_status": None,
                                  "health": None,
                                  "obs": {"pod-00": obs_doc}})
    assert [f["detector"] for f in report["findings"]] == [
        "reshard_fallback"]
    f = report["findings"][0]
    assert f["pod"] == "pod-00"
    assert "uncomputable target spans" in f["summary"]
    assert "not divisible" in f["summary"]   # the EXACT reason, verbatim
    assert any("resize.live.start" in step for step in f["chain"])
    json.dumps(report)
    job_doctor.render(report)
