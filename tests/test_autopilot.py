"""Goodput autopilot: the observe→act loop must be journaled, rate
-limited, provably flap-free, dry-runnable, and chaos-drillable — a
seeded straggler is evicted (that pod exactly, within 2 publish
intervals of detection) and backfilled from standby; a clean fleet
produces zero actions; ``dry`` journals the identical stream while
applying nothing; an injected apply failure is retried without ever
double-applying."""

import json
import time
import types

import pytest

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import constants, status
from edl_tpu.controller.cluster_generator import Generator
from edl_tpu.controller.resource_pods import ResourceRegister
from edl_tpu.data.data_server import BatchCache, DataPlaneServer
from edl_tpu.data.reader import ElasticReader
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.obs import autopilot as obs_autopilot
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import health as obs_health
from edl_tpu.obs import ledger as obs_ledger
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs.publisher import MetricsPublisher
from edl_tpu.robustness import faults
from edl_tpu.rpc.client import RpcClient
from edl_tpu.tools import job_doctor, job_stats
from edl_tpu.utils import errors


class _FleetCoord(object):
    """The store surface the publisher, monitor and autopilot share."""

    def __init__(self):
        self.store = {}
        self.root = "test_job"

    def set_server_permanent(self, service, server, value):
        self.store[(service, server)] = value

    def get_service(self, service):
        return [(server, v) for (s, server), v in sorted(self.store.items())
                if s == service]

    def get_value(self, service, server):
        return self.store.get((service, server))


def _report(victims=(), ts=None, findings=None, goodput=None,
            pods_total=3):
    return {
        "schema": "health_report/v1",
        "ts": 0.0 if ts is None else ts,
        "monitor": "monitor-pod",
        "fleet": {"verdict": "critical" if victims else "ok",
                  "pods_total": pods_total,
                  "pods_degraded": sorted(victims)},
        "pods": {},
        "findings": findings if findings is not None else [
            {"detector": "straggler", "pod": v, "severity": "critical",
             "summary": "%s is slow" % v, "event_ids": [41, 42]}
            for v in victims],
        "slos": [],
        "preferred_victims": list(victims),
        "goodput": goodput or {},
        "events": [],
    }


def _engine(coord=None, clock=None, mode="on", **kw):
    kw.setdefault("interval", 1.0)
    return obs_autopilot.Autopilot(coord or _FleetCoord(), "monitor-pod",
                                   mode=mode,
                                   clock=clock or (lambda: 0.0), **kw)


# -- constants / mode ------------------------------------------------------


def test_service_autopilot_constant_matches_controller():
    """Drift guard: obs is a leaf, the constant is inlined there."""
    assert obs_autopilot.SERVICE_AUTOPILOT == constants.SERVICE_AUTOPILOT


def test_mode_from_env(monkeypatch):
    assert obs_autopilot.mode_from_env("on") == obs_autopilot.MODE_ON
    assert obs_autopilot.mode_from_env("ON ") == obs_autopilot.MODE_ON
    assert obs_autopilot.mode_from_env("1") == obs_autopilot.MODE_ON
    assert obs_autopilot.mode_from_env("dry") == obs_autopilot.MODE_DRY
    assert obs_autopilot.mode_from_env("dry_run") == obs_autopilot.MODE_DRY
    assert obs_autopilot.mode_from_env("off") == obs_autopilot.MODE_OFF
    assert obs_autopilot.mode_from_env("bogus") == obs_autopilot.MODE_OFF
    monkeypatch.delenv(obs_autopilot.ENV_VAR, raising=False)
    assert obs_autopilot.mode_from_env() == obs_autopilot.MODE_OFF
    monkeypatch.setenv(obs_autopilot.ENV_VAR, "dry")
    assert obs_autopilot.mode_from_env() == obs_autopilot.MODE_DRY


def test_off_mode_is_inert():
    coord = _FleetCoord()
    ap = _engine(coord, mode="off")
    for _ in range(5):
        assert ap.on_report(_report(victims=["pod-x", "pod-x"])) == []
    assert ap.actions() == []
    assert obs_autopilot.load_actions(coord) == []
    assert ap.scale_out_allowed() is True


# -- evict policy: hysteresis, rate limits, flap-proofing ------------------


def test_evict_needs_consecutive_streak_then_applies():
    coord = _FleetCoord()
    t = [100.0]
    evicted = []
    ap = _engine(coord, clock=lambda: t[0],
                 evict_fn=lambda pod: evicted.append(pod) or True)
    assert ap.on_report(_report(victims=["pod-c"])) == []  # streak 1
    out = ap.on_report(_report(victims=["pod-c"]))         # streak 2
    assert [a["kind"] for a in out] == ["evict"]
    a = out[0]
    assert a["schema"] == "action/v1"
    assert a["target"] == "pod-c"
    assert a["outcome"] == "applied" and a["mode"] == "applied"
    assert a["attempts"] == 1 and a["error"] is None
    assert evicted == ["pod-c"]
    # cause chain: back to the health evidence ids of the finding
    assert a["cause"]["detector"] == "straggler"
    assert a["cause"]["evidence_ids"] == [41, 42]
    assert a["cause"]["streak"] == 2
    # the journal round-trips through the store
    stored = obs_autopilot.load_actions(coord)
    assert [s["id"] for s in stored] == [a["id"]]


def test_evict_streak_resets_when_victim_changes_or_clears():
    ap = _engine(evict_fn=lambda pod: True)
    assert ap.on_report(_report(victims=["pod-a"])) == []
    assert ap.on_report(_report(victims=["pod-b"])) == []  # reset
    assert ap.on_report(_report()) == []                   # reset
    assert ap.on_report(_report(victims=["pod-b"])) == []  # streak 1
    assert len(ap.on_report(_report(victims=["pod-b"]))) == 1


def test_evict_never_targets_the_engine_host():
    evicted = []
    ap = _engine(evict_fn=lambda pod: evicted.append(pod))
    for _ in range(5):
        assert ap.on_report(_report(victims=["monitor-pod"])) == []
    assert evicted == []


def test_evict_reevict_block_and_cooldown_prevent_flapping():
    """The evict→backfill→re-flag oscillation: after one eviction the
    SAME pod cannot be re-evicted for reevict_block_s even though the
    monitor keeps naming it (the backfilled standby warms up, the EWMA
    re-anchors), and no second evict of ANY pod lands inside the
    per-kind cooldown."""
    t = [0.0]
    evicted = []
    ap = _engine(clock=lambda: t[0], interval=1.0,
                 evict_fn=lambda pod: evicted.append(pod) or True)
    # interval 1.0 -> reevict block 30s, evict cooldown 6s
    ap.on_report(_report(victims=["pod-c"]))
    assert len(ap.on_report(_report(victims=["pod-c"]))) == 1
    for _ in range(20):  # the flap window: report keeps flagging pod-c
        t[0] += 1.0
        assert ap.on_report(_report(victims=["pod-c"])) == []
    assert evicted == ["pod-c"]
    # a DIFFERENT straggler is still actionable once the cooldown ends
    t[0] += 10.0
    ap.on_report(_report(victims=["pod-d"]))
    out = ap.on_report(_report(victims=["pod-d"]))
    assert [a["target"] for a in out] == ["pod-d"]
    # and pod-c itself becomes eligible again only after the block
    t[0] += 40.0
    ap.on_report(_report(victims=["pod-c"]))
    out = ap.on_report(_report(victims=["pod-c"]))
    assert [a["target"] for a in out] == ["pod-c"]
    assert evicted == ["pod-c", "pod-d", "pod-c"]


def test_evict_burst_ring_bounds_actions_per_window():
    t = [0.0]
    ap = _engine(clock=lambda: t[0], evict_streak=1,
                 reevict_block_s=0.0, cooldowns={"evict": 0.0},
                 burst=2, burst_window_s=100.0,
                 evict_fn=lambda pod: True)
    assert len(ap.on_report(_report(victims=["p1"]))) == 1
    t[0] += 1.0
    assert len(ap.on_report(_report(victims=["p2"]))) == 1
    t[0] += 1.0  # third distinct victim inside the window: suppressed
    assert ap.on_report(_report(victims=["p3"])) == []
    t[0] += 200.0  # the window drains
    assert len(ap.on_report(_report(victims=["p4"]))) == 1


# -- dry-run parity --------------------------------------------------------


def test_dry_run_journals_identically_and_applies_nothing():
    t = [0.0]
    seq = ([_report(victims=["pod-c"])] * 3
           + [_report()] * 2
           + [_report(victims=["pod-c"])] * 3)

    def run(mode):
        coord = _FleetCoord()
        applied = []
        ap = _engine(coord, clock=lambda: t[0], mode=mode,
                     evict_fn=lambda pod: applied.append(pod) or True)
        for r in seq:
            ap.on_report(r)
        return coord, ap.actions(), applied

    _, on_actions, on_applied = run("on")
    coord, dry_actions, dry_applied = run("dry")
    # identical action stream: same kinds, targets, sequence numbers
    key = lambda acts: [(a["kind"], a["target"], a["seq"])  # noqa: E731
                        for a in acts]
    assert key(dry_actions) == key(on_actions)
    assert on_applied == ["pod-c"]
    assert dry_applied == []                       # NOTHING applied
    for a in dry_actions:
        assert a["mode"] == "dry_run"
        assert a["outcome"] == "dry_run"
        assert a["attempts"] == 0 and a["result"] is None
    # the dry journal still lands in the store for the tooling
    stored = obs_autopilot.load_actions(coord)
    assert key(stored) == key(on_actions)


def test_dry_run_never_vetoes_scale_out():
    coord = _FleetCoord()
    coord.set_server_permanent("metrics", "pod-x",
                               json.dumps([{"recovery_s": 50.0}]))
    ap = _engine(coord, mode="dry", payback_horizon_s=1.0)
    ap.on_report(_report(goodput={"goodput_pct": 50.0}, pods_total=4))
    ap.on_report(_report(goodput={"goodput_pct": 50.0}, pods_total=4))
    assert ap.scale_out_allowed() is True  # dry applies nothing


# -- the apply step under chaos --------------------------------------------


def test_apply_fault_retried_never_double_applied():
    """autopilot.apply fires INSIDE the retried closure BEFORE the
    actuator: an error_once kills attempt 1 with the actuator untouched,
    the retry succeeds, and the actuator has run exactly once."""
    calls = []
    ap = _engine(evict_fn=lambda pod: calls.append(pod) or True)
    plane = faults.FaultPlane(seed=7)
    plane.inject("autopilot.apply", "error_once", action="evict")
    plane.install()
    try:
        ap.on_report(_report(victims=["pod-c"]))
        out = ap.on_report(_report(victims=["pod-c"]))
    finally:
        plane.uninstall()
    a = out[0]
    assert a["outcome"] == "applied"
    assert a["attempts"] == 2          # failed once, retried once
    assert calls == ["pod-c"]          # applied exactly ONCE
    assert ("autopilot.apply", "error_once") in plane.log


def test_apply_persistent_fault_journals_failed_without_hot_loop():
    calls = []
    t = [0.0]
    ap = _engine(clock=lambda: t[0],
                 evict_fn=lambda pod: calls.append(pod) or True)
    plane = faults.FaultPlane(seed=7)
    plane.inject("autopilot.apply", "error", action="evict")
    plane.install()
    try:
        ap.on_report(_report(victims=["pod-c"]))
        out = ap.on_report(_report(victims=["pod-c"]))
        a = out[0]
        assert a["outcome"] == "failed"
        assert a["attempts"] == 3      # RetryPolicy max_attempts
        assert "ConnectError" in a["error"]
        assert calls == []             # the actuator NEVER ran
        # the reevict block applies on failure too: the next ticks must
        # not hammer the same failing apply
        for _ in range(5):
            t[0] += 1.0
            assert ap.on_report(_report(victims=["pod-c"])) == []
    finally:
        plane.uninstall()


def test_apply_without_actuator_is_a_journaled_failure():
    ap = _engine()  # no evict_fn bound
    ap.on_report(_report(victims=["pod-c"]))
    a = ap.on_report(_report(victims=["pod-c"]))[0]
    assert a["outcome"] == "failed"
    assert "no actuator" in a["error"]


# -- resize trigger/veto gate ----------------------------------------------


def test_resize_payback_model():
    # 10s pause idling 4 pods = 40 compute-seconds; one new pod at 80%
    # goodput repays 0.8 compute-seconds per second -> 50s payback
    assert obs_ledger.resize_payback_s(10.0, 4, 5, 0.8) \
        == pytest.approx(50.0)
    assert obs_ledger.resize_payback_s(10.0, 4, 4, 0.8) == float("inf")
    assert obs_ledger.resize_payback_s(10.0, 5, 4, 0.8) == float("inf")
    assert obs_ledger.resize_payback_s(10.0, 4, 5, 0.0) == float("inf")
    assert obs_ledger.resize_payback_s(-1.0, 4, 5, 0.8) == float("inf")
    assert obs_ledger.resize_payback_s(0.0, 4, 5, 0.8) == 0.0


def test_resize_gate_journals_decision_changes_only():
    coord = _FleetCoord()
    # launcher-journaled resize history: median recovery 20s
    coord.set_server_permanent("metrics", "pod-a",
                               json.dumps([{"recovery_s": 20.0}]))
    t = [0.0]
    ap = _engine(coord, clock=lambda: t[0], payback_horizon_s=600.0)
    # payback = 20*4/gp_frac: 100s at 80% (allow), 800s at 10% (veto)
    good = _report(goodput={"goodput_pct": 80.0}, pods_total=4)
    bad = _report(goodput={"goodput_pct": 10.0}, pods_total=4)
    # the initial position is set silently — a clean fleet journals 0
    assert ap.on_report(good) == []
    assert ap.scale_out_allowed() is True
    t[0] += 100.0
    out = ap.on_report(bad)            # allow -> veto: journaled
    assert [a["kind"] for a in out] == ["resize"]
    assert out[0]["decision"] == "veto"
    assert out[0]["cause"]["payback_s"] == pytest.approx(800.0)
    assert ap.scale_out_allowed() is False
    t[0] += 100.0
    assert ap.on_report(bad) == []     # steady state: no duplicate
    assert ap.scale_out_allowed() is False
    t[0] += 100.0
    out = ap.on_report(good)           # veto -> allow: journaled
    assert out[0]["decision"] == "allow"
    assert ap.scale_out_allowed() is True


def test_resize_gate_fails_open_without_history_or_goodput():
    ap = _engine()  # empty store: no pause projection
    assert ap.on_report(_report(goodput={"goodput_pct": 5.0},
                                pods_total=4)) == []
    assert ap.scale_out_allowed() is True
    coord = _FleetCoord()
    coord.set_server_permanent("metrics", "pod-a",
                               json.dumps([{"recovery_s": 20.0}]))
    ap2 = _engine(coord)
    assert ap2.on_report(_report(pods_total=4)) == []  # no goodput pct
    assert ap2.scale_out_allowed() is True


def test_resize_gate_rate_limited_change_keeps_previous_position():
    coord = _FleetCoord()
    coord.set_server_permanent("metrics", "pod-a",
                               json.dumps([{"recovery_s": 20.0}]))
    ap = _engine(coord, cooldowns={"resize": 1e9}, burst=1,
                 burst_window_s=1e9)
    good = _report(goodput={"goodput_pct": 80.0}, pods_total=4)
    bad = _report(goodput={"goodput_pct": 10.0}, pods_total=4)
    ap.on_report(good)                     # initial: allow (silent)
    ap.on_report(bad)                      # veto journaled (first)
    assert ap.scale_out_allowed() is False
    ap.on_report(good)                     # rate-limited: CANNOT journal
    # a decision the journal cannot record must not act either
    assert ap.scale_out_allowed() is False


# -- knob tuning -----------------------------------------------------------


def _data_wait_report(share_pct):
    return _report(goodput={"goodput_pct": 40.0, "badput": [
        {"state": "data_wait", "seconds": 60.0, "share_pct": share_pct}]})


def test_knobs_double_fetch_ahead_until_ceiling():
    t = [0.0]
    applied = []
    ap = _engine(clock=lambda: t[0], fetch_ahead_base=2,
                 fetch_ahead_max=8,
                 knobs_fn=lambda knobs: applied.append(dict(knobs))
                 or {"pod": knobs})
    out = ap.on_report(_data_wait_report(55.0))
    assert [a["kind"] for a in out] == ["tune_knobs"]
    assert out[0]["knobs"] == {"fetch_ahead": 4}
    t[0] += 100.0
    out = ap.on_report(_data_wait_report(55.0))
    assert out[0]["knobs"] == {"fetch_ahead": 8}
    t[0] += 100.0  # at the ceiling: nothing left to tune
    assert ap.on_report(_data_wait_report(55.0)) == []
    assert applied == [{"fetch_ahead": 4}, {"fetch_ahead": 8}]


def test_knobs_respect_threshold_cooldown_and_dominance():
    t = [0.0]
    ap = _engine(clock=lambda: t[0], knobs_fn=lambda knobs: {})
    assert ap.on_report(_data_wait_report(10.0)) == []  # under threshold
    other = _report(goodput={"badput": [
        {"state": "ckpt_block", "seconds": 90.0, "share_pct": 90.0},
        {"state": "data_wait", "seconds": 50.0, "share_pct": 50.0}]})
    assert ap.on_report(other) == []  # data_wait must RANK FIRST
    assert len(ap.on_report(_data_wait_report(55.0))) == 1
    t[0] += 1.0  # inside the tune_knobs cooldown (12 * interval)
    assert ap.on_report(_data_wait_report(55.0)) == []


def test_knobs_dry_run_advances_the_same_target_ladder():
    t = [0.0]
    ap = _engine(clock=lambda: t[0], mode="dry", fetch_ahead_base=2,
                 fetch_ahead_max=8)
    out = ap.on_report(_data_wait_report(55.0))
    assert out[0]["knobs"] == {"fetch_ahead": 4}
    t[0] += 100.0
    out = ap.on_report(_data_wait_report(55.0))
    assert out[0]["knobs"] == {"fetch_ahead": 8}  # same ladder as on


# -- postmortem filing -----------------------------------------------------


def _box(coord, pod, ts, reason="trainer crash"):
    coord.set_server_permanent(
        "health", "blackbox_%s" % pod,
        json.dumps({"schema": "blackbox/v1", "ts": ts, "pod": pod,
                    "pid": 1, "reason": reason,
                    "exception": {"type": "RuntimeError",
                                  "message": "boom"},
                    "events": [], "spans": [], "metrics": {}}))


def test_postmortem_filed_once_per_crash_loop():
    coord = _FleetCoord()
    t = [1000.0]
    ap = _engine(coord, clock=lambda: t[0], crash_loop_boxes=2,
                 crash_window_s=600.0)
    _box(coord, "pod-a", 990.0)
    assert ap.on_report(_report()) == []  # one box is not a loop
    _box(coord, "pod-b", 995.0)
    out = ap.on_report(_report(victims=["pod-a"]))
    kinds = [a["kind"] for a in out]
    assert "postmortem" in kinds
    a = next(x for x in out if x["kind"] == "postmortem")
    assert a["outcome"] == "applied"
    assert sorted(a["bundle"]["boxes"]) == ["pod-a", "pod-b"]
    assert a["cause"]["detector"] == "crash_loop"
    assert a["cause"]["evidence_ids"] == [41, 42]  # finding evidence
    bundles = obs_autopilot.load_postmortems(coord)
    assert len(bundles) == 1
    bundle = list(bundles.values())[0]
    assert bundle["schema"] == "postmortem/v1"
    assert bundle["findings"][0]["pod"] == "pod-a"
    # the same crash loop is never re-filed, however many ticks pass
    for _ in range(5):
        t[0] += 100.0
        assert all(x["kind"] != "postmortem"
                   for x in ap.on_report(_report()))
    # a NEW box changes the signature: a fresh loop files a fresh bundle
    _box(coord, "pod-c", t[0] - 1.0)
    out = ap.on_report(_report())
    assert [x["kind"] for x in out] == ["postmortem"]
    assert len(obs_autopilot.load_postmortems(coord)) == 2


def test_postmortem_ignores_stale_boxes():
    coord = _FleetCoord()
    _box(coord, "pod-a", 100.0)
    _box(coord, "pod-b", 120.0)
    ap = _engine(coord, clock=lambda: 10000.0, crash_window_s=600.0)
    assert ap.on_report(_report()) == []


# -- failover hold ---------------------------------------------------------


def test_hold_fn_freezes_all_actions_until_released():
    held = [True]
    evicted = []
    ap = _engine(hold_fn=lambda: held[0],
                 evict_fn=lambda pod: evicted.append(pod) or True)
    for _ in range(4):
        assert ap.on_report(_report(victims=["pod-c"])) == []
    assert evicted == []
    held[0] = False  # settle window closed: the streak rebuilds
    ap.on_report(_report(victims=["pod-c"]))
    assert len(ap.on_report(_report(victims=["pod-c"]))) == 1


def test_hold_fn_failure_fails_open():
    def boom():
        raise RuntimeError("witness gone")

    ap = _engine(hold_fn=boom, evict_fn=lambda pod: True)
    ap.on_report(_report(victims=["pod-c"]))
    assert len(ap.on_report(_report(victims=["pod-c"]))) == 1


# -- preferred_victims TTL (the satellite fix) -----------------------------


def _straggler_docs(steps, cum, ts):
    bounds = [10.0, 100.0, 1000.0]
    out = {}
    for pod, step in steps.items():
        st = cum.setdefault(pod, {"sum": 0.0, "count": 0})
        st["sum"] += step * 10
        st["count"] += 10
        out[pod] = {
            "schema": "obs_pub/v1", "key": "obs_" + pod, "ts": ts,
            "metrics": {"schema": "obs_snapshot/v1", "ts": ts, "pid": 1,
                        "series_dropped": 0,
                        "metrics": {"edl_train_step_ms": {
                            "kind": "histogram", "help": "",
                            "labelnames": [], "bounds": bounds,
                            "series": [{"labels": {},
                                        "buckets": [0, 0, 0, 0],
                                        "sum": st["sum"],
                                        "count": st["count"]}]}}},
            "events": []}
    return out


def test_preferred_victims_fail_open_past_report_ttl():
    """Regression: a dead monitor's last verdict must stop biasing
    eviction once it ages past ttl_s — the hook returns [] instead of a
    stale victim list."""
    t = [1000.0]
    monitor = obs_health.HealthMonitor(
        _FleetCoord(), "mon", interval=10, ttl_s=5.0, stale_after=1e9,
        events=obs_events.EventLog(), clock=lambda: t[0])
    cum = {}
    steps = {"w1": 100.0, "w2": 100.0, "w3": 600.0}
    report = None
    for _ in range(4):
        report = monitor.evaluate(_straggler_docs(steps, cum, t[0]))
    assert report["preferred_victims"] == ["w3"]
    assert report["ttl_s"] == 5.0      # reports are TTL-stamped
    assert monitor.preferred_victims() == ["w3"]  # fresh: honored
    t[0] += 100.0                      # the monitor stops ticking
    assert monitor.preferred_victims() == []      # expired: fail open


def test_load_report_fresh_only_expires_on_ttl():
    coord = _FleetCoord()
    doc = {"schema": "health_report/v1", "ts": 1000.0, "ttl_s": 5.0}
    coord.set_server_permanent(obs_health.SERVICE_HEALTH,
                               obs_health.HEALTH_KEY, json.dumps(doc))
    assert obs_health.load_report(coord)["ts"] == 1000.0
    assert obs_health.load_report(coord, fresh_only=True,
                                  now=1003.0) is not None
    assert obs_health.load_report(coord, fresh_only=True,
                                  now=1010.0) is None
    # a pre-TTL doc (no ttl_s) is never expired (render-history path)
    del doc["ttl_s"]
    coord.set_server_permanent(obs_health.SERVICE_HEALTH,
                               obs_health.HEALTH_KEY, json.dumps(doc))
    assert obs_health.load_report(coord, fresh_only=True,
                                  now=1e9) is not None


# -- the generator's directed-eviction actuator ----------------------------


def _pod():
    import os
    os.environ["EDL_TPU_POD_IP"] = "127.0.0.1"
    from edl_tpu.controller.env import JobEnv
    from edl_tpu.controller.pod import Pod
    args = type("A", (), dict(
        job_id="test_job", store_endpoints="x", nodes_range="1:4",
        nproc_per_node=1, pod_ip="127.0.0.1", checkpoint_path=None,
        log_dir=None, log_level=None))()
    return Pod.from_env(JobEnv(args))


class _NullCoord(object):
    def get_key(self, key):
        return None

    def get_service(self, service):
        return []


def _cluster_of(pods):
    c = cluster_mod.Cluster()
    c.pods = list(pods)
    return c


def test_direct_evict_drops_pod_and_blocks_rejoin():
    a, b, c = _pod(), _pod(), _pod()
    gen = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5)
    assert gen.direct_evict(c.id, ttl_s=30.0) is True
    resources = {p.id: p for p in (a, b, c)}  # c still REGISTERED
    new = gen._next_cluster(_cluster_of([a, b, c]), resources, {})
    assert new is not None
    # dropped AND excluded from joinable: no evict->rejoin flap
    assert set(p.id for p in new.pods) == {a.id, b.id}


def test_direct_evict_refuses_the_leader():
    a = _pod()
    gen = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5)
    with pytest.raises(errors.EdlError):
        gen.direct_evict(a.id)


def test_direct_evict_directive_expires():
    a, b, c = _pod(), _pod(), _pod()
    gen = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5)
    gen.direct_evict(c.id, ttl_s=0.01)
    time.sleep(0.05)
    resources = {p.id: p for p in (a, b, c)}
    # expired directive: membership unchanged -> no new cluster at all
    assert gen._next_cluster(_cluster_of([a, b, c]), resources, {}) \
        is None


def test_scale_out_gate_vetoes_and_fails_open():
    a, b = _pod(), _pod()
    gate = [False]
    gen = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5,
                    scale_out_gate=lambda: gate[0])
    resources = {p.id: p for p in (a, b)}  # b is joinable
    assert gen._next_cluster(_cluster_of([a]), resources, {}) is None
    gate[0] = True
    new = gen._next_cluster(_cluster_of([a]), resources, {})
    assert set(p.id for p in new.pods) == {a.id, b.id}

    def boom():
        raise RuntimeError("autopilot gone")

    gen2 = Generator(_NullCoord(), a.id, min_nodes=1, max_nodes=5,
                     scale_out_gate=boom)
    new2 = gen2._next_cluster(_cluster_of([a]), resources, {})
    assert set(p.id for p in new2.pods) == {a.id, b.id}  # fail open


def test_generator_loop_directed_evict_backfills_from_standby(coord):
    """End to end against the store: the autopilot's actuator evicts a
    running pod and the standby (a registered pod over max_nodes)
    backfills through the ordinary scale-out — in the SAME pass, so the
    cluster never dips below min."""
    def _wait(pred, timeout=15.0, interval=0.1):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = pred()
            if v:
                return v
            time.sleep(interval)
        raise AssertionError("condition not met within %ss" % timeout)

    a, b, c, d = (_pod() for _ in range(4))
    regs = [ResourceRegister(coord, p) for p in (a, b, c)]
    coord.set_server_permanent(constants.SERVICE_LEADER,
                               constants.LEADER_SERVER, a.id)
    gen = Generator(coord, a.id, min_nodes=3, max_nodes=3,
                    below_min_grace=8.0).start()
    try:
        c1 = _wait(lambda: (lambda cl: cl if cl and len(cl.pods) == 3
                            else None)(cluster_mod.load_from_store(coord)))
        assert set(c1.pod_ids()) == {a.id, b.id, c.id}
        regs.append(ResourceRegister(coord, d))  # the standby
        time.sleep(1.5)  # at max: the standby stays out
        assert set(cluster_mod.load_from_store(coord).pod_ids()) \
            == {a.id, b.id, c.id}
        gen.direct_evict(b.id)
        c2 = _wait(lambda: (lambda cl: cl if cl
                            and b.id not in cl.pod_ids() else None)(
            cluster_mod.load_from_store(coord)))
        assert set(c2.pod_ids()) == {a.id, c.id, d.id}  # backfilled
        assert status.load_job_status(coord) != status.Status.FAILED
    finally:
        gen.stop()
        for r in regs:
            r.stop()


# -- the knob RPC plane ----------------------------------------------------


def test_set_knobs_rpc_end_to_end():
    seen = []
    server = DataPlaneServer(
        BatchCache(capacity=4), pod_id="p",
        knobs_fn=lambda knobs: seen.append(knobs) or {"fetch_ahead": 8}
    ).start()
    try:
        client = RpcClient(server.endpoint)
        assert client.call("set_knobs", {"fetch_ahead": 8}) \
            == {"fetch_ahead": 8}
        client.close()
        assert seen == [{"fetch_ahead": 8}]
    finally:
        server.stop()


def test_reader_apply_knobs_clamps_and_ignores_unknown():
    ns = types.SimpleNamespace(_fetch_ahead=2)
    assert ElasticReader.apply_knobs(ns, {"fetch_ahead": 999}) \
        == {"fetch_ahead": 64}
    assert ns._fetch_ahead == 64
    assert ElasticReader.apply_knobs(ns, {"fetch_ahead": 0}) \
        == {"fetch_ahead": 1}
    assert ElasticReader.apply_knobs(ns, {"bogus": 3}) == {}
    assert ElasticReader.apply_knobs(ns, "nonsense") == {}
    assert ElasticReader.apply_knobs(ns, {"fetch_ahead": "x"}) == {}


def test_teacher_apply_knobs_clamps_batch_timeout():
    ns = types.SimpleNamespace(_batch_timeout=0.005)
    assert TeacherServer.apply_knobs(ns, {"batch_timeout_ms": 5000}) \
        == {"batch_timeout_ms": 1000.0}
    assert ns._batch_timeout == pytest.approx(1.0)
    assert TeacherServer.apply_knobs(ns, {"batch_timeout_ms": -5}) \
        == {"batch_timeout_ms": 0.0}
    assert TeacherServer.apply_knobs(ns, {"other": 1}) == {}


# -- tooling renders the journal -------------------------------------------


def test_format_autopilot_marks_dry_and_counts_outcomes():
    actions = [
        {"schema": "action/v1", "seq": 1, "kind": "evict",
         "target": "pod-c", "mode": "dry_run", "outcome": "dry_run",
         "cause": {"evidence_ids": [7], "summary": "slow"}},
        {"schema": "action/v1", "seq": 2, "kind": "tune_knobs",
         "target": "data_plane", "mode": "applied", "outcome": "failed",
         "error": "ConnectError('x')", "reason": "data_wait dominates",
         "cause": {}},
    ]
    lines = job_stats.format_autopilot(actions)
    text = "\n".join(lines)
    assert "2 actions: 0 applied, 1 dry-run, 1 failed" in text
    assert "[dry] #1 evidence=[7] -> evict pod-c -> dry_run" in text
    assert "cause: slow" in text
    assert "ConnectError" in text
    assert job_stats.format_autopilot([]) == []
    assert job_stats.format_autopilot(None) == []


# -- the acceptance drill --------------------------------------------------


def _pub(coord, pod, registry, log):
    return MetricsPublisher(coord, pod, interval=999, registry=registry,
                            events=log)


def _autopilot_drill(mode, faulted=True, windows=4, fetches=4,
                     delay_s=0.04):
    """The PR-8 chaos drill with the loop CLOSED: the autopilot rides
    the monitor's on_report hook. Returns
    (coord, autopilot, evicted, flagged_at, acted_at)."""
    coord = _FleetCoord()
    pods = ["pod-a", "pod-b", "pod-c"]
    victim = "pod-c"
    obs_events.EVENTS.clear()
    servers, pubs, hists, clients = {}, {}, {}, {}
    plane = None
    evicted = []
    ap = obs_autopilot.Autopilot(coord, "monitor-pod", mode=mode,
                                 interval=999,
                                 evict_fn=lambda pod:
                                 evicted.append(pod) or True)
    try:
        for p in pods:
            servers[p] = DataPlaneServer(BatchCache(capacity=8),
                                         pod_id=p).start()
            reg = obs_metrics.MetricsRegistry()
            log = (obs_events.EVENTS if p == victim
                   else obs_events.EventLog())
            pubs[p] = _pub(coord, p, reg, log)
            hists[p] = reg.histogram("edl_reader_fetch_ms",
                                     "batch fetch wire ms")
            clients[p] = RpcClient(servers[p].endpoint)

        monitor = obs_health.HealthMonitor(coord, "monitor-pod",
                                           interval=999, stale_after=1e9,
                                           events=obs_events.EventLog(),
                                           on_report=ap.on_report)

        def window(w):
            for p in pods:
                for i in range(fetches):
                    with hists[p].time_ms():
                        clients[p].call("get_batches",
                                        ["w%d-%d" % (w, i)])
                pubs[p].publish_once()
            return monitor.check_once()

        window(0)  # anchor: establishes cumulative baselines
        if faulted:
            plane = faults.FaultPlane(seed=7)
            plane.inject("data.fetch.delay", "delay", seconds=delay_s,
                         pod=victim)
            plane.install()
        flagged_at = acted_at = None
        for w in range(1, windows + 1):
            report = window(w)
            stragglers = {f["pod"] for f in report["findings"]
                          if f["detector"] == "straggler"}
            if stragglers and flagged_at is None:
                flagged_at = w
                assert stragglers == {victim}
            if ap.actions() and acted_at is None:
                acted_at = w
        return coord, ap, evicted, flagged_at, acted_at
    finally:
        if plane is not None:
            plane.uninstall()
        for cl in clients.values():
            cl.close()
        for s in servers.values():
            s.stop()


def test_autopilot_drill_evicts_exactly_the_faulted_pod():
    """The acceptance drill, mode=on: the seeded straggler is evicted —
    that pod exactly, within 2 publish intervals of detection — with a
    full cause chain back to the health evidence, and the doctor/stats
    tooling renders the journal."""
    coord, ap, evicted, flagged_at, acted_at = _autopilot_drill("on")
    assert flagged_at is not None and flagged_at <= 2
    assert acted_at is not None and acted_at - flagged_at <= 1
    assert evicted == ["pod-c"]                    # exactly one, exactly it
    actions = ap.actions()
    assert [a["kind"] for a in actions] == ["evict"]
    a = actions[0]
    assert a["target"] == "pod-c" and a["outcome"] == "applied"
    # cause chain: detector verdict + causal evidence ids from the
    # health report (the fault firings ride the victim's event ring)
    assert a["cause"]["detector"] == "straggler"
    assert a["cause"]["evidence_ids"]
    assert a["cause"]["streak"] >= 2
    # the store journal is the same stream the tooling loads
    stored = obs_autopilot.load_actions(coord)
    assert [s["id"] for s in stored] == [a["id"]]
    doc = job_doctor.diagnose(job_doctor.collect(coord))
    assert [x["kind"] for x in doc["autopilot"]] == ["evict"]
    rendered = job_doctor.render(doc)
    assert "autopilot journal" in rendered
    assert "evict pod-c -> applied" in rendered
    stats = job_stats.collect_job_stats(_StatsCoord(coord))
    pretty = job_stats.format_fleet(stats)
    assert "autopilot journal" in pretty
    json.dumps(doc)  # the machine surface round-trips


class _StatsCoord(object):
    """_FleetCoord plus the extra surface collect_job_stats touches."""

    def __init__(self, inner):
        self._inner = inner
        self.root = inner.root

    def get_service(self, service):
        return self._inner.get_service(service)

    def get_value(self, service, server):
        return self._inner.get_value(service, server)

    def get_key(self, key):
        return None


def test_autopilot_drill_dry_run_journals_but_applies_nothing():
    coord, ap, evicted, flagged_at, acted_at = _autopilot_drill("dry")
    assert flagged_at is not None and acted_at is not None
    assert evicted == []                           # NOTHING applied
    actions = ap.actions()
    assert [(a["kind"], a["target"]) for a in actions] \
        == [("evict", "pod-c")]                    # identical stream
    assert actions[0]["outcome"] == "dry_run"
    assert actions[0]["mode"] == "dry_run"
    # the dry journal is stored and rendered with the [dry] marker
    rendered = job_doctor.render(job_doctor.diagnose(
        job_doctor.collect(coord)))
    assert "[dry]" in rendered


def test_autopilot_drill_clean_fleet_produces_zero_actions():
    coord, ap, evicted, flagged_at, acted_at = _autopilot_drill(
        "on", faulted=False)
    assert flagged_at is None and acted_at is None
    assert evicted == [] and ap.actions() == []
    assert obs_autopilot.load_actions(coord) == []
