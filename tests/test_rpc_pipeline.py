"""Pipelined RPC + teacher adaptive batching.

Covers the distill data-plane concurrency work: out-of-order response
matching by envelope id, whole-connection failure semantics (one dead
socket fails every call in flight), retry/idempotency interaction with
pipelining, strict-peer interop in both directions, and the teacher's
cross-request batch coalescing (occupancy, timeout flush, latency
floor, scatter correctness vs the serial pad-and-lock path).
"""

import socket
import threading
import time

import numpy as np
import pytest

from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.robustness.policy import RetryPolicy
from edl_tpu.rpc import framing
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import FEATURES, RpcServer
from edl_tpu.utils import errors


@pytest.fixture()
def server():
    gate = threading.Event()

    def wait_then(x):
        gate.wait(10)
        return x

    srv = RpcServer(host="127.0.0.1", port=0)
    srv.register("echo", lambda x: x)
    srv.register("sleepy", lambda s, x: (time.sleep(s), x)[1])
    srv.register("gated", wait_then)
    srv.register("boom", lambda: (_ for _ in ()).throw(
        errors.DataAccessError("boom")))
    srv.start()
    srv.gate = gate
    yield srv
    gate.set()
    srv.stop()


def _client(srv, **kw):
    return RpcClient("127.0.0.1:%d" % srv.port, **kw)


# -- pipelined client ------------------------------------------------------


def test_out_of_order_responses(server):
    """A slow request must not block a fast one behind it: the fast
    response arrives (and resolves) while the slow one is still gated
    server-side — response order is completion order, matched by id."""
    c = _client(server)
    try:
        slow = c.call_async("gated", "slow")
        fast = c.call_async("echo", "fast")
        assert fast.result(timeout=5) == "fast"
        assert not slow.done()  # still parked on the gate
        server.gate.set()
        assert slow.result(timeout=5) == "slow"
    finally:
        c.close()


def test_many_async_calls_interleaved(server):
    c = _client(server)
    try:
        futs = [c.call_async("sleepy", 0.01 * (9 - i), i)
                for i in range(10)]
        assert [f.result(timeout=10) for f in futs] == list(range(10))
    finally:
        c.close()


def test_async_error_envelope_is_typed(server):
    c = _client(server)
    try:
        fut = c.call_async("boom")
        with pytest.raises(errors.DataAccessError):
            fut.result(timeout=5)
        # the connection survives a typed error (it's an envelope, not
        # a transport failure)
        assert c.call("echo", 1) == 1
    finally:
        c.close()


def test_inflight_failure_fails_all_pending(server):
    """One torn connection must fail EVERY call in flight on it — a
    byte stream cannot be resynchronized past a lost frame."""
    c = _client(server)
    try:
        futs = [c.call_async("gated", i) for i in range(5)]
        # sever the transport under the client (server keeps running)
        c._conn.sock.shutdown(socket.SHUT_RDWR)
        for fut in futs:
            with pytest.raises(errors.ConnectError):
                fut.result(timeout=5)
        server.gate.set()
        # next call dials a fresh connection
        assert c.call("echo", "back") == "back"
    finally:
        c.close()


def test_result_timeout_kills_connection(server):
    c = _client(server)
    try:
        slow = c.call_async("gated", 1)
        other = c.call_async("echo", 2)
        assert other.result(timeout=5) == 2
        with pytest.raises(errors.ConnectError):
            slow.result(timeout=0.2)  # gate still closed
        server.gate.set()
        assert c.call("echo", 3) == 3  # reconnects
    finally:
        c.close()


def test_retry_idempotent_interaction(server):
    """A request dropped server-side AFTER it hit the wire is only
    retried when the caller marked the call idempotent."""
    plane = FaultPlane(seed=7).install()
    try:
        drop = plane.inject("rpc.server.request", "drop", times=1,
                            method="echo")
        c = _client(server, timeout=0.5,
                    retry=RetryPolicy(max_attempts=3, base_delay=0.05,
                                      jitter=0.0, seed=1))
        try:
            with pytest.raises(errors.ConnectError):
                c.call("echo", 1)  # not idempotent: no resend allowed
            assert drop.fired == 1
        finally:
            c.close()
        drop2 = plane.inject("rpc.server.request", "drop", times=1,
                             method="echo")
        c = _client(server, timeout=0.5,
                    retry=RetryPolicy(max_attempts=3, base_delay=0.05,
                                      jitter=0.0, seed=1))
        try:
            assert c.call("echo", 2, idempotent=True) == 2
            assert drop2.fired == 1  # first send swallowed, retry served
        finally:
            c.close()
    finally:
        plane.uninstall()


def test_retry_before_wire_always_safe(server):
    """A connect-path failure precedes the write, so even a
    non-idempotent call retries."""
    plane = FaultPlane(seed=7).install()
    try:
        cut = plane.inject("rpc.client.connect", "partition", times=1)
        c = _client(server, retry=RetryPolicy(max_attempts=3,
                                              base_delay=0.05,
                                              jitter=0.0, seed=1))
        try:
            assert c.call("echo", 5) == 5
            assert cut.fired == 1
        finally:
            c.close()
    finally:
        plane.uninstall()


def test_features_advertised(server):
    c = _client(server)
    try:
        assert "rpc.pipeline" in c.server_features()
        assert set(FEATURES) <= set(c.server_features())
    finally:
        c.close()


# -- interop with strict (pre-pipelining) peers ----------------------------


def test_pipelined_client_vs_inline_server(server):
    """workers=0 serves every request inline in strict order — the old
    server behavior. call_async must still be correct against it."""
    srv = RpcServer(host="127.0.0.1", port=0, workers=0)
    srv.register("echo", lambda x: x)
    srv.start()
    try:
        c = RpcClient("127.0.0.1:%d" % srv.port)
        try:
            futs = [c.call_async("echo", i) for i in range(8)]
            assert [f.result(timeout=5) for f in futs] == list(range(8))
        finally:
            c.close()
    finally:
        srv.stop()


def test_strict_client_vs_pipelined_server(server):
    """A pre-pipelining peer (no ``pl`` flag, reads exactly one response
    per request) gets strict request-reply ordering from the new
    server: requests without the flag are served inline on the
    connection thread."""
    sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    try:
        for i in range(5):
            framing.write_frame(sock, {"id": i, "method": "echo",
                                       "args": [i], "kwargs": {}})
        for i in range(5):
            resp = framing.read_frame(sock)
            assert resp["id"] == i  # in order, one per request
            assert resp["ok"] and resp["result"] == i
    finally:
        sock.close()


def test_plain_call_is_served_inline(server):
    c = _client(server)
    try:
        assert c.call("echo", "x") == "x"
    finally:
        c.close()


# -- teacher adaptive batching ---------------------------------------------


def _echo_server(max_batch=8, **kw):
    calls = []

    def fn(feed):
        calls.append(int(len(feed["x"])))
        return {"y": feed["x"] * 2.0 + 1.0}

    t = TeacherServer(fn, feed_specs={"x": ([3], "<f4")},
                      fetch_specs={"y": ([3], "<f4")},
                      max_batch=max_batch, host="127.0.0.1", **kw)
    t.start()
    t.calls = calls
    return t


def test_batcher_coalesces_two_clients():
    """Two concurrent single-row requests share one device execution
    when the batch window is open."""
    t = _echo_server(batch_timeout_ms=300)
    try:
        feeds = [np.full((1, 3), float(i), np.float32) for i in range(2)]
        outs = [None, None]

        def one(i):
            c = RpcClient(t.endpoint)
            try:
                outs[i] = c.call("predict", {"x": feeds[i]})
            finally:
                c.close()

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=10)
        for i in range(2):
            np.testing.assert_array_equal(outs[i]["y"],
                                          feeds[i] * 2.0 + 1.0)
        stats = RpcClient(t.endpoint).call("stats")
        assert stats["batches"] == 1  # coalesced, not two executions
        assert stats["rows"] == 2
        assert stats["occupancy"] == pytest.approx(2 / 8)
        assert t.calls == [8]  # one padded max_batch execution
    finally:
        t.stop()


def test_batcher_timeout_flush():
    """A lone short request flushes after batch_timeout_ms, not never."""
    t = _echo_server(batch_timeout_ms=100)
    try:
        c = RpcClient(t.endpoint)
        try:
            x = np.ones((2, 3), np.float32)
            t0 = time.monotonic()
            out = c.call("predict", {"x": x})
            took = time.monotonic() - t0
            np.testing.assert_array_equal(out["y"], x * 2.0 + 1.0)
            assert took < 5.0  # flushed by the timeout, not the 600s bound
        finally:
            c.close()
    finally:
        t.stop()


def test_batcher_single_request_latency_floor():
    """With the default batch_timeout_ms=0 a lone request pays no
    artificial coalescing delay."""
    t = _echo_server(batch_timeout_ms=0)
    try:
        c = RpcClient(t.endpoint)
        try:
            x = np.ones((1, 3), np.float32)
            c.call("predict", {"x": x})  # warm the path
            t0 = time.monotonic()
            for _ in range(5):
                c.call("predict", {"x": x})
            assert (time.monotonic() - t0) / 5 < 0.5
        finally:
            c.close()
    finally:
        t.stop()


def test_batcher_scatter_matches_serial_path():
    """Byte-identical outputs between the adaptive scatter path and the
    serial pad-and-lock path, for every sub-max_batch size."""
    rng = np.random.default_rng(0)
    feeds = [rng.standard_normal((n, 3)).astype(np.float32)
             for n in (1, 3, 8, 5)]
    t_adaptive = _echo_server(batch_timeout_ms=0)
    t_serial = _echo_server(adaptive_batch=False)
    try:
        ca = RpcClient(t_adaptive.endpoint)
        cs = RpcClient(t_serial.endpoint)
        try:
            for x in feeds:
                a = ca.call("predict", {"x": x})["y"]
                s = cs.call("predict", {"x": x})["y"]
                assert a.dtype == s.dtype and a.shape == s.shape
                assert a.tobytes() == s.tobytes()  # byte-identical
        finally:
            ca.close()
            cs.close()
    finally:
        t_adaptive.stop()
        t_serial.stop()


def test_batcher_passthrough_fn_no_buffer_aliasing():
    """A predict fn that returns (a view of) its input must not have its
    result clobbered by the next batch reusing the staging buffer."""
    def fn(feed):
        return {"y": feed["x"]}  # worst case: alias the staging buffer

    t = TeacherServer(fn, feed_specs={"x": ([2], "<f4")},
                      fetch_specs={"y": ([2], "<f4")},
                      max_batch=4, host="127.0.0.1", batch_timeout_ms=0)
    t.start()
    try:
        c = RpcClient(t.endpoint)
        try:
            a = np.full((2, 2), 1.0, np.float32)
            b = np.full((2, 2), 9.0, np.float32)
            out_a = c.call("predict", {"x": a})["y"]
            out_b = c.call("predict", {"x": b})["y"]
            np.testing.assert_array_equal(out_a, a)
            np.testing.assert_array_equal(out_b, b)
        finally:
            c.close()
    finally:
        t.stop()


def test_batcher_error_fails_only_that_group():
    calls = {"n": 0}

    def fn(feed):
        calls["n"] += 1
        if calls["n"] == 1:
            raise errors.DataAccessError("device hiccup")
        return {"y": feed["x"]}

    t = TeacherServer(fn, feed_specs={"x": ([1], "<f4")},
                      fetch_specs={"y": ([1], "<f4")},
                      max_batch=4, host="127.0.0.1", batch_timeout_ms=0)
    t.start()
    try:
        c = RpcClient(t.endpoint)
        try:
            x = np.ones((1, 1), np.float32)
            with pytest.raises(errors.DataAccessError):
                c.call("predict", {"x": x})
            out = c.call("predict", {"x": x})  # server kept serving
            np.testing.assert_array_equal(out["y"], x)
        finally:
            c.close()
    finally:
        t.stop()


def test_batcher_rejects_bad_feeds_before_queueing():
    t = _echo_server()
    try:
        c = RpcClient(t.endpoint)
        try:
            with pytest.raises(errors.DataAccessError):
                c.call("predict", {"x": np.ones((0, 3), np.float32)})
            with pytest.raises(errors.DataAccessError):
                c.call("predict", {"wrong": np.ones((1, 3), np.float32)})
            with pytest.raises(errors.DataAccessError):
                c.call("predict",
                       {"x": np.ones((t._max_batch + 1, 3), np.float32)})
        finally:
            c.close()
    finally:
        t.stop()


def test_teacher_advertises_adaptive_features():
    t = _echo_server()
    try:
        spec = RpcClient(t.endpoint).call("get_feed_fetch")
        assert "rpc.pipeline" in spec["features"]
        assert "adaptive_batch" in spec["features"]
    finally:
        t.stop()
