"""Quorum-replicated coordination store: election, quorum-acked log
replication, linearizable follower reads, snapshot install, client
failover, keepalive coalescing — and the tier-1 chaos drill (leader
killed mid-elastic-resize under store.repl.* faults, zero
acknowledged-write loss).

Election timeouts here are tuned small (0.15-0.3s) so every scenario
converges in a couple of seconds on a loaded CI box.
"""

import json
import os
import threading
import time

import pytest

from edl_tpu.coordination import replica as replica_mod
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.keepalive import KeepaliveHub
from edl_tpu.coordination.replica import (ReplLog, ReplicatedStoreServer,
                                          start_local_replica_set,
                                          wait_for_leader)
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors

ET = (0.15, 0.3)  # election timeout band for every in-test replica set


def _wait(pred, timeout=10.0, interval=0.02):
    gate = threading.Event()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        gate.wait(interval)
    return pred()


@pytest.fixture()
def rset(tmp_path):
    reps = start_local_replica_set(3, data_dir=str(tmp_path),
                                   election_timeout=ET)
    yield reps
    for r in reps:
        try:
            r.stop()
        except Exception:
            pass


def _survivors_logs_match(survivors):
    """Log-matching property over the committed prefix: every survivor
    holds the identical entry sequence up to the common commit index."""
    logs = [r.repl_log_dump() for r in survivors]
    common = min(l["commit"] for l in logs)
    sigs = [[(e["index"], e["term"], e["kind"], e.get("op_id"))
             for e in l["entries"] if e["index"] <= common]
            for l in logs]
    return all(s == sigs[0] for s in sigs[1:]), common


# -- replication log ---------------------------------------------------


def test_repl_log_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "repl.log")
    lg = ReplLog(path)
    ents = [{"index": i, "term": 1, "kind": "put",
             "args": ["k%d" % i, b"v%d" % i, None]} for i in (1, 2, 3)]
    lg.append(ents)
    lg.close()
    # crash mid-write: a torn trailing record on disk
    with open(path, "ab") as f:
        f.write(b'{"op": "ent", "index": 4, "term": 1, "ki')
    lg2 = ReplLog(path)
    assert lg2.last_index == 3
    assert lg2.get(2)["args"][1] == b"v2"
    # the torn bytes were truncated: appending and re-replaying is clean
    lg2.append([{"index": 4, "term": 2, "kind": "noop", "args": []}])
    lg2.close()
    lg3 = ReplLog(path)
    assert lg3.last_index == 4 and lg3.last_term == 2
    lg3.close()


def test_repl_log_truncate_compact_reset(tmp_path):
    path = str(tmp_path / "repl.log")
    lg = ReplLog(path)
    lg.append([{"index": i, "term": 1, "kind": "noop", "args": []}
               for i in range(1, 6)])
    lg.truncate_from(4)                 # conflict resolution
    assert lg.last_index == 3
    lg.compact(2, 1, {"store": {"s": 1}, "dedup": []})
    assert (lg.base_index, lg.last_index) == (2, 3)
    lg.close()
    lg2 = ReplLog(path)                 # compaction survives restart
    assert (lg2.base_index, lg2.last_index) == (2, 3)
    assert lg2.snapshot["store"] == {"s": 1}
    lg2.reset(9, 4, {"store": {"s": 2}, "dedup": []})
    assert (lg2.base_index, lg2.last_index) == (9, 9)
    lg2.close()


# -- election + quorum replication ------------------------------------


def test_election_single_leader_and_quorum_write(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    assert [r.repl_status()["role"] for r in rset].count("leader") == 1
    rev = leader.store_put("/j/a/nodes/x", b"v1")
    assert rev >= 1
    # quorum-committed: every replica converges to the same store state
    assert _wait(lambda: all(
        (r.store.get("/j/a/nodes/x") or {}).get("value") == b"v1"
        for r in rset))


def test_follower_rejects_mutations_with_leader_hint(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    fol = next(r for r in rset if r is not leader)
    rpc = RpcClient(fol.endpoint, timeout=5.0)
    try:
        with pytest.raises(errors.NotLeaderError) as ei:
            rpc.call("store_put", "/j/a/nodes/k", b"v", None)
        assert "leader=%s" % leader.endpoint in str(ei.value)
    finally:
        rpc.close()


def test_linearizable_follower_read(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    fol = next(r for r in rset if r is not leader)
    for i in range(5):
        leader.store_put("/j/lin/nodes/k", b"v%d" % i)
        # read-index: the follower may not serve a stale value for an
        # already-acknowledged write
        got = fol.store_get("/j/lin/nodes/k")
        assert got["value"] == b"v%d" % i


def test_client_redirects_to_leader(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    eps = [r.endpoint for r in rset if r is not leader] + [leader.endpoint]
    c = CoordClient(eps, root="j", failover_grace=10.0)  # followers first
    c.set_server_permanent("svc", "a", b"v1")
    assert c.get_value("svc", "a") == b"v1"


def test_put_if_absent_op_id_applies_exactly_once(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    r1 = leader.store_put_if_absent("/j/e/nodes/l", b"me", None,
                                    op_id="op-xyz")
    # the retry (same idempotency key) must replay the SAME result, not
    # re-execute and observe its own first attempt
    r2 = leader.store_put_if_absent("/j/e/nodes/l", b"me", None,
                                    op_id="op-xyz")
    assert list(r1) == list(r2) and r1[0] is True
    dump = leader.repl_log_dump()
    assert sum(1 for e in dump["entries"]
               if e.get("op_id") == "op-xyz") == 1


def test_failover_loses_no_acked_write(rset):
    leader = wait_for_leader(rset, timeout=10.0)
    c = CoordClient([r.endpoint for r in rset], root="j",
                    failover_grace=15.0)
    acked = {}
    for i in range(10):
        k = "/j/f/nodes/w%d" % i
        c.put(k, b"v%d" % i)
        acked[k] = b"v%d" % i
    leader.stop()
    survivors = [r for r in rset if r is not leader]
    # writes keep flowing through the client's breaker/redirect path
    for i in range(10, 20):
        k = "/j/f/nodes/w%d" % i
        c.put(k, b"v%d" % i)
        acked[k] = b"v%d" % i
    wait_for_leader(survivors, timeout=10.0)
    for k, v in acked.items():
        got = c.get_key(k)
        assert got is not None and got["value"] == v, k
    ok, common = _survivors_logs_match(survivors)
    assert ok and common >= 20


def test_replica_set_restart_recovers_from_logs(tmp_path):
    reps = start_local_replica_set(3, data_dir=str(tmp_path),
                                   election_timeout=ET)
    eps = [r.endpoint for r in reps]
    try:
        leader = wait_for_leader(reps, timeout=10.0)
        leader.store_put("/j/r/nodes/a", b"sticky")
        leader.store_put("/j/r/nodes/b", b"sticky2")
    finally:
        for r in reps:
            r.stop()
    # cold restart of the whole set on the same endpoints + logs
    reps2 = [ReplicatedStoreServer(
        ep, eps, data_dir=os.path.join(str(tmp_path), "r%d" % i),
        election_timeout=ET).start() for i, ep in enumerate(eps)]
    try:
        wait_for_leader(reps2, timeout=10.0)
        c = CoordClient(eps, root="j", failover_grace=10.0)
        assert c.get_value("r", "a") == b"sticky"
        assert c.get_value("r", "b") == b"sticky2"
    finally:
        for r in reps2:
            r.stop()


def test_snapshot_install_catches_up_wiped_replica(tmp_path, monkeypatch):
    # tiny compaction threshold so the leader's log no longer reaches
    # back to index 0 by the time the wiped replica returns
    monkeypatch.setattr(replica_mod, "COMPACT_THRESHOLD", 8)
    reps = start_local_replica_set(3, data_dir=str(tmp_path),
                                   election_timeout=ET)
    eps = [r.endpoint for r in reps]
    try:
        leader = wait_for_leader(reps, timeout=10.0)
        victim = next(r for r in reps if r is not leader)
        victim_ep = victim.endpoint
        victim.stop()
        reps.remove(victim)
        for i in range(24):
            leader.store_put("/j/s/nodes/w%d" % i, b"v%d" % i)
        assert _wait(lambda: leader.repl_status()["base_index"] > 0)
        # the replica returns WIPED (fresh data dir = lost disk)
        wiped_dir = str(tmp_path / "rewipe")
        back = ReplicatedStoreServer(victim_ep, eps, data_dir=wiped_dir,
                                     election_timeout=ET).start()
        reps.append(back)
        assert _wait(lambda: back.repl_status()["applied"]
                     >= leader.repl_status()["commit"] - 1, timeout=15.0)
        assert (back.store.get("/j/s/nodes/w3") or {}).get("value") == b"v3"
        assert back.repl_status()["base_index"] > 0  # came via snapshot
    finally:
        for r in reps:
            try:
                r.stop()
            except Exception:
                pass


# -- watches across failover + retention ------------------------------


def test_watch_longpoll_survives_leader_death(rset):
    """A watch in flight during leader death resumes on the survivors
    without missing or duplicating membership events (the watch is
    served by any replica; the client re-dials transparently)."""
    leader = wait_for_leader(rset, timeout=10.0)
    c = CoordClient([r.endpoint for r in rset], root="j",
                    failover_grace=15.0, timeout=10.0)
    adds = []
    w = c.watch_service("wsvc", lambda a, r, al: adds.extend(a.items()))
    try:
        c.set_server_permanent("wsvc", "pre", b"1")
        assert _wait(lambda: ("pre", b"1") in adds)
        leader.stop()
        survivors = [r for r in rset if r is not leader]
        c.set_server_permanent("wsvc", "post", b"2")
        wait_for_leader(survivors, timeout=10.0)
        assert _wait(lambda: ("post", b"2") in adds, timeout=15.0)
        # no duplicated delivery of either event
        assert adds.count(("pre", b"1")) == 1
        assert adds.count(("post", b"2")) == 1
    finally:
        w.stop()


def test_watch_catchup_past_retention_resets(monkeypatch):
    """A watcher whose since_rev predates Store event retention gets a
    reset event and rebuilds from a snapshot read — never a silent
    miss."""
    from edl_tpu.coordination.embedded import EmbeddedStore
    from edl_tpu.coordination.store import Store

    monkeypatch.setattr(Store, "EVENT_HISTORY", 8)
    with EmbeddedStore() as s:
        c = CoordClient([s.endpoint], root="j")
        c.set_server_permanent("rsvc", "a", b"v")
        stale_rev = c.revision()
        # blow past the retained-event window
        for i in range(20):
            c.set_server_permanent("rsvc", "k%d" % i, b"x")
        evs, rev = c.wait_events(c.service_prefix("rsvc"), stale_rev, 1.0)
        assert [e["type"] for e in evs] == ["reset"]
        # the Watcher turns the reset into a full re-list: it converges
        # to complete membership, missing none of the puts
        snaps = []
        w = c.watch_service("rsvc", lambda a, r, al: snaps.append(al))
        try:
            assert _wait(lambda: snaps and len(snaps[-1]) == 21)
        finally:
            w.stop()


# -- keepalive coalescing ----------------------------------------------


def test_keepalive_hub_single_timer_and_lost_callback():
    from edl_tpu.coordination.embedded import EmbeddedStore

    with EmbeddedStore() as s:
        c = CoordClient([s.endpoint], root="j")
        hub = KeepaliveHub(c)
        lost = []
        l1 = hub.add(c.lease_grant(30.0), 30.0,
                     on_lost=lambda: lost.append("l1"))
        l2 = hub.add(c.lease_grant(30.0), 30.0,
                     on_lost=lambda: lost.append("l2"))
        try:
            res = hub.refresh_now()          # ONE batched RPC
            assert res == {l1: True, l2: True}
            c.lease_revoke(l2)               # dies behind the hub's back
            res = hub.refresh_now()
            assert res[l2] is False and res[l1] is True
            assert lost == ["l2"]
            assert len(hub) == 1             # the lost lease was dropped
            assert hub.refresh_now() == {l1: True}
        finally:
            hub.stop()


def test_legacy_peer_lease_refresh_many_fallback():
    """Against a peer that lacks the batched RPC, the client degrades
    to per-id refreshes via __features__ negotiation."""
    from edl_tpu.coordination.embedded import EmbeddedStore

    with EmbeddedStore() as s:
        # simulate a pre-batching peer: unregister the method + feature
        s._server._rpc.methods.pop("store_lease_refresh_many")
        s._server._rpc.methods["__features__"] = lambda: ["rpc.pipeline"]
        c = CoordClient([s.endpoint], root="j")
        lids = [c.lease_grant(30.0) for _ in range(3)]
        assert c.lease_refresh_many(lids) == {lid: True for lid in lids}


# -- the tier-1 chaos drill --------------------------------------------


def test_chaos_drill_leader_kill_mid_resize(tmp_path):
    """Acceptance drill: a 2-pod elastic job runs against a 3-replica
    store; store.repl.* faults chew on the replication plane and the
    LEADER is killed while the job is mid-flight (the elastic join/
    resize machinery is live on the store: registrations, barriers,
    cluster maps). A new leader must be elected, the job must complete
    SUCCEED, and no acknowledged write may be lost — asserted by a
    linearizability check over the survivors' replicated logs."""
    import signal as signal_mod
    import subprocess
    import sys

    from edl_tpu.controller import cluster as cluster_mod
    from edl_tpu.controller import status
    from edl_tpu.robustness.faults import FaultPlane

    plane = FaultPlane(seed=11).install()
    try:
        # drop a couple of appends + votes: exercises the retry/re-
        # election paths while the job runs
        plane.inject("store.repl.append", "drop", times=2)
        plane.inject("store.repl.vote", "drop", times=1)
        reps = start_local_replica_set(3, data_dir=str(tmp_path / "rs"),
                                       election_timeout=ET)
        eps = [r.endpoint for r in reps]
        endpoints = ",".join(eps)
        job = "chaos_repl"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        trainer = os.path.join(repo, "tests", "fixtures",
                               "dummy_trainer.py")
        env = dict(os.environ)
        env.update({"PYTHONPATH": repo, "EDL_TPU_POD_IP": "127.0.0.1",
                    "EDL_TPU_TTL": "3", "JAX_PLATFORMS": "cpu",
                    "PALLAS_AXON_POOL_IPS": ""})

        def spawn(name):
            lg = open(str(tmp_path / (name + ".log")), "wb")
            p = subprocess.Popen(
                [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
                 "--job_id", job, "--store_endpoints", endpoints,
                 "--nodes_range", "1:2",
                 "--log_dir", str(tmp_path / (name + "_logs")),
                 trainer, "12", "0"],
                env=env, stdout=lg, stderr=subprocess.STDOUT,
                preexec_fn=os.setsid)
            lg.close()
            return p

        pods = [spawn("pod1"), spawn("pod2")]
        c = CoordClient(eps, root=job, failover_grace=25.0, timeout=15.0)
        acked = {}
        try:
            assert _wait(lambda: cluster_mod.load_from_store(c)
                         is not None, timeout=30)
            time.sleep(2)  # the job is mid-flight (post-join, training)
            # acked writes straddling the kill: the loss-check corpus
            for i in range(5):
                k = "/%s/probe/nodes/a%d" % (job, i)
                c.put(k, b"pre%d" % i)
                acked[k] = b"pre%d" % i
            leader = wait_for_leader(reps, timeout=10.0)
            leader.stop()  # the outage, mid-job
            survivors = [r for r in reps if r is not leader]
            for i in range(5):
                k = "/%s/probe/nodes/b%d" % (job, i)
                c.put(k, b"post%d" % i)
                acked[k] = b"post%d" % i
            wait_for_leader(survivors, timeout=15.0)
            for p in pods:
                assert p.wait(timeout=150) == 0, \
                    (tmp_path / "pod1.log").read_text()[-3000:]
            assert status.load_job_status(c) == status.Status.SUCCEED
            # zero acknowledged-write loss, linearizably readable
            for k, v in acked.items():
                got = c.get_key(k)
                assert got is not None and got["value"] == v, k
            ok, common = _survivors_logs_match(survivors)
            assert ok and common > 0
            assert len(json.dumps(
                [e["kind"] for e in survivors[0].repl_log_dump()
                 ["entries"]])) > 0
        finally:
            for p in pods:
                try:
                    os.killpg(os.getpgid(p.pid), signal_mod.SIGKILL)
                except ProcessLookupError:
                    pass
            for r in reps:
                try:
                    r.stop()
                except Exception:
                    pass
    finally:
        plane.uninstall()
