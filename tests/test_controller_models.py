"""Pod/Trainer/Cluster model tests (reference parity: test_pod.py,
test_cluster.py serialization roundtrips)."""

import os

from edl_tpu.controller.cluster import Cluster
from edl_tpu.controller.env import JobEnv, TrainerEnv
from edl_tpu.controller.pod import Pod


def _job_env(**over):
    os.environ.setdefault("EDL_TPU_POD_IP", "127.0.0.1")
    args = type("A", (), dict(
        job_id="job_x", store_endpoints="127.0.0.1:2379", nodes_range="2:4",
        nproc_per_node=over.get("nproc_per_node", 1), pod_ip="127.0.0.1",
        checkpoint_path=None, log_dir=None, log_level=None))()
    return JobEnv(args)


def test_pod_from_env_and_roundtrip():
    os.environ["EDL_TPU_DEVICES"] = "0,1,2,3"
    try:
        pod = Pod.from_env(_job_env())
    finally:
        del os.environ["EDL_TPU_DEVICES"]
    assert len(pod.trainers) == 1
    assert pod.trainers[0].devices == [0, 1, 2, 3]
    clone = Pod().from_json(pod.to_json())
    assert clone == pod
    assert clone.trainers[0].devices == [0, 1, 2, 3]


def test_pod_multi_proc_device_split():
    os.environ["EDL_TPU_DEVICES"] = "0,1,2,3"
    try:
        pod = Pod.from_env(_job_env(nproc_per_node=2))
    finally:
        del os.environ["EDL_TPU_DEVICES"]
    assert [t.devices for t in pod.trainers] == [[0, 1], [2, 3]]


def test_cluster_ranks_and_roundtrip():
    cluster = Cluster()
    for _ in range(3):
        os.environ["EDL_TPU_DEVICES"] = "0,1"
        pod = Pod.from_env(_job_env(nproc_per_node=2))
        del os.environ["EDL_TPU_DEVICES"]
        cluster.pods.append(pod)
    cluster.assign_ranks()
    assert [p.rank for p in cluster.pods] == [0, 1, 2]
    granks = [t.global_rank for p in cluster.pods for t in p.trainers]
    assert granks == list(range(6))
    assert cluster.world_size() == 6
    assert cluster.total_devices() == 6  # 2 devices / 2 procs × 3 pods

    clone = Cluster().from_json(cluster.to_json())
    assert clone == cluster
    assert clone.stage == cluster.stage
    assert clone.get_leader_endpoint() == cluster.get_leader_endpoint()


def test_trainer_env_contract_roundtrip():
    env = {
        "EDL_TPU_JOB_ID": "j", "EDL_TPU_STORE_ENDPOINTS": "a:1,b:2",
        "EDL_TPU_POD_ID": "p", "EDL_TPU_POD_RANK": "1",
        "EDL_TPU_TRAINER_ID": "t", "EDL_TPU_RANK_IN_POD": "0",
        "EDL_TPU_GLOBAL_RANK": "3", "EDL_TPU_WORLD_SIZE": "8",
        "EDL_TPU_COORDINATOR": "a:5000",
        "EDL_TPU_TRAINER_ENDPOINTS": "a:5000,b:5001",
        "EDL_TPU_LOCAL_DEVICES": "0,1", "EDL_TPU_CLUSTER_STAGE": "s1",
    }
    te = TrainerEnv(env)
    assert te.global_rank == 3 and te.world_size == 8
    assert te.store_endpoints == ["a:1", "b:2"]
    assert te.local_devices == [0, 1]
    assert not te.is_rank0 and te.under_launcher
