"""FlightRecorder black box + job_doctor --postmortem (obs/flight.py).

THE contract under test: a dump NEVER masks the original failure —
including when the ``obs.flight.dump`` chaos point makes the recorder
itself fail. The postmortem path must resolve a chaos-killed pod's
artifact back to the exact seeded fault point.
"""

import json
import os
import sys

import pytest

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import flight as flight_mod
from edl_tpu.obs.flight import FlightRecorder
from edl_tpu.robustness import faults
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.tools import job_doctor


@pytest.fixture()
def plane():
    p = FaultPlane(seed=20260805).install()
    yield p
    p.uninstall()
    assert faults.PLANE is None


@pytest.fixture(autouse=True)
def _clean_events():
    obs_events.EVENTS.clear()
    yield
    obs_events.EVENTS.clear()


def test_dump_writes_parseable_blackbox(tmp_path):
    rec = FlightRecorder("pod-0_r1", out_dir=str(tmp_path))
    rec.register_provider("resize_timing", lambda: {"pause_s": 1.25})
    try:
        raise RuntimeError("boom at step 42")
    except RuntimeError as e:
        path = rec.dump("unhandled_exception", e)
    assert path and os.path.exists(path)
    with open(path) as f:
        box = json.load(f)
    assert box["schema"] == "blackbox/v1"
    assert box["pod"] == "pod-0_r1"
    assert box["reason"] == "unhandled_exception"
    assert box["exception"]["type"] == "RuntimeError"
    assert "boom at step 42" in box["exception"]["message"]
    assert "RuntimeError" in box["exception"]["traceback"]
    assert set(box["ledger"]) == set(
        ("compute", "data_wait", "embed_wait", "ckpt_block",
         "resize_pause",
         "restore", "barrier_wait", "idle"))
    assert box["context"]["resize_timing"] == {"pause_s": 1.25}
    # the thread dump must at least see this (the main) thread
    assert "Current thread" in box["threads"] \
        or "Thread" in box["threads"]


def test_failing_provider_does_not_fail_the_dump(tmp_path):
    rec = FlightRecorder("p", out_dir=str(tmp_path))
    rec.register_provider("bad", lambda: 1 / 0)
    path = rec.dump("trainer_exit")
    with open(path) as f:
        box = json.load(f)
    assert "provider_error" in box["context"]["bad"]


def test_chaos_at_dump_point_never_masks_the_original(plane, tmp_path):
    """Seed obs.flight.dump with an error fault: the recorder fails,
    returns None, raises NOTHING — the original exception path is
    byte-identical. The fault counter proves the point actually
    fired (the hook is first, covering the entire dump path)."""
    f = plane.inject("obs.flight.dump", "error")
    rec = FlightRecorder("p", out_dir=str(tmp_path))
    original = ValueError("the real crash")
    caught = None
    try:
        try:
            raise original
        except ValueError as e:
            assert rec.dump("unhandled_exception", e) is None
            raise
    except ValueError as e:
        caught = e
    assert caught is original
    assert f.fired == 1
    assert os.listdir(str(tmp_path)) == []  # nothing half-written


def test_dump_does_not_reenter(tmp_path):
    rec = FlightRecorder("p", out_dir=str(tmp_path))
    inner = []
    rec.register_provider("evil", lambda: inner.append(
        rec.dump("recursive")) or "ok")
    path = rec.dump("outer")
    assert path is not None
    assert inner == [None]  # the nested dump refused to re-enter


def test_excepthook_chains_to_previous(tmp_path):
    rec = FlightRecorder("p", out_dir=str(tmp_path))
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda t, e, tb: seen.append((t, e))
    try:
        rec.install_excepthook()
        err = RuntimeError("late crash")
        sys.excepthook(RuntimeError, err, None)
    finally:
        rec.uninstall()
        sys.excepthook = prev_hook
    # the previous hook ran with the SAME exception, after the dump
    assert seen == [(RuntimeError, err)]
    assert rec.last_path is not None


def test_postmortem_resolves_seeded_fault_point(plane, tmp_path):
    """The full drill in-process: a seeded fault kills the 'pod', the
    box lands on disk, and --postmortem names the exact injected
    point — not just 'pod died'."""
    plane.inject("ckpt.save.write", "error")
    rec = FlightRecorder("pod-3", out_dir=str(tmp_path))
    try:
        faults.PLANE.fire("ckpt.save.write")  # emits fault.fired, raises
        raise AssertionError("fault should have fired")
    except faults.errors.EdlError as e:
        path = rec.dump("trainer_exit", e)
    boxes = job_doctor._load_local_blackboxes([path])
    assert list(boxes) == ["pod-3"]
    report = job_doctor.postmortem(boxes, now=1000.0)
    assert report["schema"] == "doctor_report/v1"
    assert report["mode"] == "postmortem"
    assert report["verdict"] == "critical"
    head = report["findings"][0]
    assert head["detector"] == "flight_recorder"
    assert head["rank"] == 1
    assert "ckpt.save.write" in head["summary"]
    assert "error" in head["summary"]
    assert "ckpt.save.write" in report["summary"]
    # the rendered text (what the operator reads) names the point too
    assert "ckpt.save.write" in job_doctor.render(report)


def test_postmortem_without_fault_names_the_exception(tmp_path):
    rec = FlightRecorder("pod-9", out_dir=str(tmp_path))
    try:
        raise KeyError("missing shard")
    except KeyError as e:
        path = rec.dump("trainer_exit", e)
    report = job_doctor.postmortem(
        job_doctor._load_local_blackboxes([path]))
    assert "KeyError" in report["findings"][0]["summary"]


def test_load_local_blackboxes_skips_garbage(tmp_path, capsys):
    bad = tmp_path / "junk.json"
    bad.write_text("not json")
    assert job_doctor._load_local_blackboxes([str(bad)]) == {}
    assert "not a readable" in capsys.readouterr().err


def test_module_dump_is_noop_before_install():
    assert flight_mod.RECORDER is None or True  # state-agnostic guard
    prev = flight_mod.RECORDER
    flight_mod.RECORDER = None
    try:
        assert flight_mod.dump("whatever") is None
    finally:
        flight_mod.RECORDER = prev


def test_merge_profiles_remaps_pids_per_pod():
    profiles = {
        "pod-a": {"schema": "profile/v1", "source": "tracer_ring",
                  "trace": {"traceEvents": [
                      {"name": "x", "ph": "X", "pid": 77, "tid": 1,
                       "ts": 0, "dur": 5},
                      {"name": "y", "ph": "X", "pid": 77, "tid": 2,
                       "ts": 5, "dur": 5}]}},
        "pod-b": {"schema": "profile/v1", "source": "jax.profiler",
                  "trace": {"traceEvents": [
                      {"name": "z", "ph": "X", "pid": 77, "tid": 1,
                       "ts": 0, "dur": 1}]}},
    }
    merged = job_doctor.merge_profiles(profiles)
    evs = merged["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert [m["args"]["name"] for m in meta] == [
        "pod-a (tracer_ring)", "pod-b (jax.profiler)"]
    # same original pid on two pods -> two distinct merged pids
    pids = {e["pid"] for e in evs if e["ph"] == "X"}
    assert len(pids) == 2
    assert all(e["pid"] == meta[0]["pid"] for e in evs
               if e.get("name") in ("x", "y"))
