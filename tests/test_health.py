"""The active observability layer: straggler/staleness/flap/saturation
detectors, SLO burn rates, the leader HealthMonitor's verdict doc +
transition events, the generator's victim ordering, and the chaos
drill the PR's acceptance criterion names — a seeded latency fault on
one pod's data plane must be flagged (that pod exactly) within 2
publish intervals, the job doctor must name the fault event in its
causal chain, and a clean run of the same length must stay green."""

import json
import time

import pytest

from edl_tpu.data.data_server import BatchCache, DataPlaneServer
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import health as obs_health
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import slo as obs_slo
from edl_tpu.obs.publisher import MetricsPublisher
from edl_tpu.robustness import faults
from edl_tpu.rpc.client import RpcClient
from edl_tpu.tools import job_doctor


class _FleetCoord(object):
    """The store surface the publisher, monitor and doctor share."""

    def __init__(self):
        self.store = {}
        self.root = "test_job"

    def set_server_permanent(self, service, server, value):
        self.store[(service, server)] = value

    def get_service(self, service):
        return [(server, v) for (s, server), v in sorted(self.store.items())
                if s == service]

    def get_value(self, service, server):
        return self.store.get((service, server))


# -- straggler detector ----------------------------------------------------


def test_straggler_flags_k_mad_above_median_for_n_windows():
    det = obs_health.StragglerDetector("edl_train_step_ms")
    base = {"a": 100.0, "b": 102.0, "c": 98.0}
    for _ in range(3):
        assert det.update(dict(base, d=101.0)) == []
    # d turns slow: first over-threshold window arms the streak only
    assert det.update(dict(base, d=500.0)) == []
    flagged = det.update(dict(base, d=500.0))
    assert [f["pod"] for f in flagged] == ["d"]
    f = flagged[0]
    assert f["severity"] == "critical" and f["detector"] == "straggler"
    assert f["metric"] == "edl_train_step_ms"
    assert f["value"] > f["threshold"] > f["baseline"]
    assert f["windows"] >= 2
    # recovery: the EWMA decays back under threshold within a few good
    # windows and the flag clears (no one-window flap in either direction)
    for _ in range(4):
        det.update(dict(base, d=101.0))
    assert det.update(dict(base, d=101.0)) == []


def test_straggler_single_pod_fleet_never_flags():
    """No peers to compare against -> no verdict, however wild the
    values (edge case #1 from the issue)."""
    det = obs_health.StragglerDetector("edl_train_step_ms")
    for mean in (10.0, 5000.0, 10.0, 9000.0, 8000.0):
        assert det.update({"solo": mean}) == []


def test_straggler_cold_join_spike_is_not_flagged():
    """A pod joining mid-window after a resize pays one-off costs
    (compile, cold cache) in its first window; the warmup re-seed must
    keep that spike out of the EWMA so the joiner is never flagged
    once it converges (edge case #2)."""
    det = obs_health.StragglerDetector("edl_train_step_ms")
    base = {"a": 100.0, "b": 101.0, "c": 99.0}
    for _ in range(4):
        det.update(base)
    assert det.update(dict(base, d=5000.0)) == []  # the compile window
    for _ in range(4):  # converged: stays unflagged forever after
        assert det.update(dict(base, d=103.0)) == []


def test_straggler_cold_fleet_genuinely_slow_pod_flagged_in_2_windows():
    """All pods cold (fresh monitor after an election): a pod slow from
    its FIRST window is still flagged by its second — warmup must not
    add latency on top of the n_windows streak."""
    det = obs_health.StragglerDetector("edl_train_step_ms")
    assert det.update({"a": 100.0, "b": 100.0, "c": 600.0}) == []
    flagged = det.update({"a": 100.0, "b": 100.0, "c": 600.0})
    assert [f["pod"] for f in flagged] == ["c"]


def test_straggler_window_mean_reanchors_on_counter_reset():
    det = obs_health.StragglerDetector("edl_train_step_ms")
    assert det.window_mean("a", 1000.0, 10) is None  # first sight
    assert det.window_mean("a", 2000.0, 20) == pytest.approx(100.0)
    assert det.window_mean("a", 50.0, 1) is None     # restart: re-anchor
    assert det.window_mean("a", 150.0, 2) == pytest.approx(100.0)


def test_straggler_tight_fleet_does_not_flag_jitter():
    """MAD ~ 0 on a homogeneous fleet: the min_delta/min_rel floors keep
    micro-jitter below the flag line."""
    det = obs_health.StragglerDetector("edl_train_step_ms")
    for _ in range(6):
        assert det.update({"a": 100.0, "b": 100.2, "c": 100.4,
                           "d": 101.0}) == []


# -- other detectors -------------------------------------------------------


def test_breaker_flap_detector():
    det = obs_health.BreakerFlapDetector(window_count=4, flap_threshold=2)
    assert det.update({"a": 0.0}) == []   # anchor
    assert det.update({"a": 1.0}) == []   # 1 flap window
    flagged = det.update({"a": 2.0})      # 2 of last 2
    assert [f["pod"] for f in flagged] == ["a"]
    assert flagged[0]["detector"] == "breaker_flap"
    assert flagged[0]["severity"] == "warn"
    # quiet windows age the flaps out of the ring
    for _ in range(4):
        det.update({"a": 2.0})
    assert det.update({"a": 2.0}) == []


def test_queue_saturation_detector():
    det = obs_health.QueueSaturationDetector("edl_teacher_queue_depth",
                                             threshold=10, n_windows=2)
    assert det.update({"a": 12.0}) == []
    flagged = det.update({"a": 15.0})
    assert [f["pod"] for f in flagged] == ["a"]
    assert flagged[0]["detector"] == "queue_saturation"
    assert det.update({"a": 3.0}) == []  # drained: streak resets
    assert det.update({"a": 15.0}) == []


# -- SLO burn rates --------------------------------------------------------


def test_hist_good_bad_snaps_threshold_to_bucket_bound():
    fam = {"bounds": [1.0, 2.0, 5.0],
           "series": [{"labels": {}, "buckets": [1, 2, 1, 1], "sum": 0.0,
                       "count": 5}]}
    assert obs_slo.hist_good_bad(fam, 2.0) == (5, 2)
    # 3.0 snaps UP to the le=5 bound: only +Inf observations are bad
    assert obs_slo.hist_good_bad(fam, 3.0) == (5, 1)
    assert obs_slo.hist_good_bad(fam, 100.0) == (5, 1)


def test_hist_good_bad_label_filter():
    fam = {"bounds": [1.0],
           "series": [
               {"labels": {"method": "predict"}, "buckets": [0, 4],
                "sum": 0.0, "count": 4},
               {"labels": {"method": "other"}, "buckets": [9, 0],
                "sum": 0.0, "count": 9}]}
    assert obs_slo.hist_good_bad(fam, 1.0,
                                 labels={"method": "predict"}) == (4, 4)


def test_burn_rate_pages_only_when_both_windows_burn():
    slo = obs_slo.Slo.latency("t", "train", "m", threshold_ms=1.0,
                              target=0.999)
    ev = obs_slo.BurnRateEvaluator(slos=(slo,), short_window=60,
                                   long_window=120, clock=lambda: 0)
    # sustained burn: 2% errors against a 0.1% budget in BOTH windows
    ev.observe("t", 0, 0, now=0)
    ev.observe("t", 6000, 120, now=60)
    ev.observe("t", 12000, 240, now=120)
    row = ev.evaluate(now=120)[0]
    assert row["severity"] == "critical"
    assert row["burn_short"] >= 14.4 and row["burn_long"] >= 14.4

    # short-window spike over a long healthy history: page suppressed
    ev2 = obs_slo.BurnRateEvaluator(slos=(slo,), short_window=60,
                                    long_window=120, clock=lambda: 0)
    ev2.observe("t", 0, 0, now=0)
    ev2.observe("t", 60000, 0, now=60)
    ev2.observe("t", 66000, 120, now=120)
    row2 = ev2.evaluate(now=120)[0]
    assert row2["burn_short"] >= 14.4
    assert row2["burn_long"] < 6.0
    assert row2["severity"] is None


def test_burn_rate_no_traffic_is_not_a_violation():
    slo = obs_slo.Slo.latency("t", "train", "m", threshold_ms=1.0,
                              target=0.99)
    ev = obs_slo.BurnRateEvaluator(slos=(slo,))
    row = ev.evaluate(now=100)[0]
    assert row["burn_short"] is None and row["severity"] is None
    # a counter reset (restart) clears instead of going negative
    ev.observe("t", 1000, 10, now=10)
    ev.observe("t", 50, 0, now=20)
    ev.observe("t", 100, 0, now=30)
    row = ev.evaluate(now=30)[0]
    assert row["severity"] is None


def test_pair_event_durations():
    events = [
        {"id": 1, "ts": 10.0, "kind": "resize.coordinated_stop",
         "pod": "a"},
        {"id": 2, "ts": 11.0, "kind": "resize.coordinated_stop",
         "pod": "b"},
        {"id": 3, "ts": 14.0, "kind": "resize.resumed", "pod": "a"},
        # b's resize still in flight; c's end has no observed start
        {"id": 4, "ts": 15.0, "kind": "resize.resumed", "pod": "c"},
    ]
    pairs = obs_slo.pair_event_durations(events, "resize.coordinated_stop",
                                         "resize.resumed")
    assert len(pairs) == 1
    assert pairs[0]["pod"] == "a"
    assert pairs[0]["duration_s"] == pytest.approx(4.0)
    assert (pairs[0]["start_id"], pairs[0]["end_id"]) == (1, 3)


# -- HealthMonitor ---------------------------------------------------------


def _pub(coord, pod, registry, log):
    return MetricsPublisher(coord, pod, interval=999, registry=registry,
                            events=log)


def test_monitor_stale_publisher_then_recovery_event():
    """Publisher death -> stale verdict -> recovery event citing the
    degraded event as its cause (edge case #3)."""
    coord = _FleetCoord()
    reg_a, reg_b = obs_metrics.MetricsRegistry(), \
        obs_metrics.MetricsRegistry()
    log = obs_events.EventLog()
    pub_a = _pub(coord, "a", reg_a, obs_events.EventLog())
    pub_b = _pub(coord, "b", reg_b, obs_events.EventLog())
    monitor = obs_health.HealthMonitor(coord, "mon", interval=10,
                                       stale_after=30.0, events=log)
    pub_a.publish_once()
    pub_b.publish_once()
    r1 = monitor.check_once()
    assert r1["fleet"]["verdict"] == "ok"
    assert json.loads(coord.store[(obs_health.SERVICE_HEALTH,
                                   obs_health.HEALTH_KEY)])[
        "schema"] == "health_report/v1"

    # b's publisher dies: its doc ts freezes while a keeps publishing
    stale = json.loads(coord.store[("metrics", "obs_b")])
    stale["ts"] = time.time() - 120.0
    coord.store[("metrics", "obs_b")] = json.dumps(stale)
    pub_a.publish_once()
    r2 = monitor.check_once()
    assert r2["pods"]["b"]["verdict"] == "critical"
    assert r2["fleet"]["pods_degraded"] == ["b"]
    finding = next(f for f in r2["findings"] if f["pod"] == "b")
    assert finding["detector"] == "stale_publisher"
    degraded = log.last("health.degraded")
    assert degraded is not None and degraded["attrs"]["pod"] == "b"

    # the publisher returns: verdict clears, recovery cites the cause
    pub_b.publish_once()
    r3 = monitor.check_once()
    assert r3["fleet"]["verdict"] == "ok"
    recovered = log.last("health.recovered")
    assert recovered is not None
    assert recovered["attrs"]["pod"] == "b"
    assert recovered["cause"] == degraded["id"]
    # both transitions ride the report for the doctor
    kinds = [e["kind"] for e in r3["events"]]
    assert kinds.count("health.degraded") == 1
    assert kinds.count("health.recovered") == 1


def test_monitor_victims_exclude_self_and_rank_worst_first():
    coord = _FleetCoord()
    monitor = obs_health.HealthMonitor(coord, "self-pod", interval=10,
                                       stale_after=1e9,
                                       events=obs_events.EventLog())
    bounds = [10.0, 100.0, 1000.0]

    def docs(step_by_pod, cum):
        out = {}
        for pod, step in step_by_pod.items():
            st = cum.setdefault(pod, {"sum": 0.0, "count": 0})
            st["sum"] += step * 10
            st["count"] += 10
            out[pod] = {
                "schema": "obs_pub/v1", "key": "obs_" + pod,
                "ts": time.time(),
                "metrics": {"schema": "obs_snapshot/v1",
                            "ts": time.time(), "pid": 1,
                            "series_dropped": 0,
                            "metrics": {"edl_train_step_ms": {
                                "kind": "histogram", "help": "",
                                "labelnames": [], "bounds": bounds,
                                "series": [{"labels": {},
                                            "buckets": [0, 0, 0, 0],
                                            "sum": st["sum"],
                                            "count": st["count"]}]}}},
                "events": []}
        return out

    cum = {}
    steps = {"self-pod": 900.0, "w1": 100.0, "w2": 100.0, "w4": 100.0,
             "w3": 400.0}
    monitor.evaluate(docs(steps, cum))
    for _ in range(3):
        report = monitor.evaluate(docs(steps, cum))
    flagged = {f["pod"] for f in report["findings"]
               if f["detector"] == "straggler"}
    # the monitor's own pod IS flagged (the verdict is honest)...
    assert flagged == {"self-pod", "w3"}
    # ...but never offered as a scale-in victim (advisory contract)
    assert report["preferred_victims"] == ["w3"]
    assert monitor.preferred_victims() == ["w3"]


# -- the chaos drill -------------------------------------------------------


def _run_drill(faulted, windows=3, fetches=4, delay_s=0.04):
    """Anchor window (pre-fault baseline), then ``windows`` rounds of
    fetch -> publish -> check. Returns (coord, flagged_at, reports)."""
    coord = _FleetCoord()
    pods = ["pod-a", "pod-b", "pod-c"]
    victim = "pod-c"
    obs_events.EVENTS.clear()
    servers, pubs, hists, clients = {}, {}, {}, {}
    plane = None
    try:
        for p in pods:
            servers[p] = DataPlaneServer(BatchCache(capacity=8),
                                         pod_id=p).start()
            reg = obs_metrics.MetricsRegistry()
            # the victim publishes the GLOBAL ring so the fault plane's
            # fault.fired emissions ride its doc (they fire in-process
            # on the producer, which in this drill is this process)
            log = (obs_events.EVENTS if p == victim
                   else obs_events.EventLog())
            pubs[p] = _pub(coord, p, reg, log)
            hists[p] = reg.histogram("edl_reader_fetch_ms",
                                     "batch fetch wire ms")
            clients[p] = RpcClient(servers[p].endpoint)

        monitor = obs_health.HealthMonitor(coord, "monitor-pod",
                                           interval=999, stale_after=1e9,
                                           events=obs_events.EventLog())

        def window(w):
            for p in pods:
                for i in range(fetches):
                    with hists[p].time_ms():
                        clients[p].call("get_batches",
                                        ["w%d-%d" % (w, i)])
                pubs[p].publish_once()
            return monitor.check_once()

        reports = [window(0)]  # anchor: establishes cumulative baselines
        if faulted:
            plane = faults.FaultPlane(seed=7)
            plane.inject("data.fetch.delay", "delay", seconds=delay_s,
                         pod=victim)
            plane.install()
        flagged_at = None
        for w in range(1, windows + 1):
            report = window(w)
            reports.append(report)
            stragglers = {f["pod"] for f in report["findings"]
                          if f["detector"] == "straggler"}
            if stragglers and flagged_at is None:
                flagged_at = w
                assert stragglers == {victim}
        return coord, flagged_at, reports
    finally:
        if plane is not None:
            plane.uninstall()
        for c in clients.values():
            c.close()
        for s in servers.values():
            s.stop()


def test_chaos_drill_detects_exactly_the_faulted_pod():
    """The acceptance drill: a seeded data.fetch.delay on one pod's
    data plane is flagged — that pod exactly — within 2 publish
    intervals of the fault arming, and the doctor's causal chain names
    the fault event."""
    coord, flagged_at, reports = _run_drill(faulted=True)
    assert flagged_at is not None and flagged_at <= 2
    final = reports[-1]
    assert final["fleet"]["verdict"] == "critical"
    assert final["fleet"]["pods_degraded"] == ["pod-c"]
    assert final["preferred_victims"] == ["pod-c"]

    doc = job_doctor.diagnose(job_doctor.collect(coord))
    assert doc["schema"] == "doctor_report/v1"
    assert doc["verdict"] == "critical"
    top = doc["findings"][0]
    assert top["pod"] == "pod-c" and top["detector"] == "straggler"
    chain = "\n".join(top["chain"])
    assert "fault.fired" in chain          # the causal evidence...
    assert "data.fetch.delay" in chain     # ...names the fault point
    rendered = job_doctor.render(doc)
    assert "pod-c" in rendered and "fault.fired" in rendered
    assert "preferred scale-in victims: pod-c" in rendered
    json.dumps(doc)  # the machine surface round-trips


def test_chaos_drill_clean_run_has_zero_false_positives():
    """Same drill, no fault: every window's verdict is ok and the
    doctor reports a healthy fleet."""
    coord, flagged_at, reports = _run_drill(faulted=False)
    assert flagged_at is None
    for report in reports:
        assert report["fleet"]["verdict"] == "ok"
        assert report["findings"] == []
    doc = job_doctor.diagnose(job_doctor.collect(coord))
    assert doc["verdict"] == "ok" and doc["findings"] == []
    assert "healthy" in doc["summary"]


def test_data_fetch_delay_fault_point_fires_on_single_get_batch():
    """The producer-side fault point also covers the serial get_batch
    path, and an armed pod filter keeps other producers untouched."""
    cache = BatchCache(capacity=4)
    cache.put("b1", {"records": [1, 2]})
    server = DataPlaneServer(cache, pod_id="slowpod").start()
    other = DataPlaneServer(BatchCache(capacity=4),
                            pod_id="fastpod").start()
    plane = faults.FaultPlane(seed=3)
    fault = plane.inject("data.fetch.delay", "delay", seconds=0.0,
                         pod="slowpod")
    plane.install()
    try:
        c = RpcClient(server.endpoint)
        assert c.call("get_batch", "b1")["records"] == [1, 2]
        c.close()
        c2 = RpcClient(other.endpoint)
        c2.call("get_batches", ["nope"])
        c2.close()
        assert fault.fired == 1  # slowpod only; fastpod filtered out
        assert plane.log == [("data.fetch.delay", "delay")]
    finally:
        plane.uninstall()
        server.stop()
        other.stop()


# -- job_stats integration -------------------------------------------------


def test_job_stats_renders_health_section():
    """Satellite: collect_job_stats picks up the verdict doc and
    --pretty renders a health section next to the fleet metrics."""
    from edl_tpu.tools import job_stats

    coord, _, _ = _run_drill(faulted=True, windows=2)
    doc = job_stats.collect_job_stats(coord)
    assert doc["health"]["schema"] == "health_report/v1"
    assert doc["health"]["fleet"]["verdict"] == "critical"
    pretty = job_stats.format_fleet(doc)
    assert "health: critical" in pretty
    assert "straggler pod-c" in pretty
    assert "preferred scale-in victims: pod-c" in pretty
