"""Data plane tests: splitters, leader balancing/stealing, multi-reader
iteration with remote fetch — many actors in one process (reference shape:
test_data_server.py)."""

import threading
import time

from edl_tpu.data.data_server import (END, BatchCache, DataPlaneServer,
                                      LeaderDataService)
from edl_tpu.data.reader import ElasticReader, lookup_data_leader
from edl_tpu.data.splitter import BytesChunkSplitter, TxtFileSplitter
from edl_tpu.rpc.client import RpcClient


def _write_files(tmp_path, n_files=4, lines_per_file=20):
    paths = []
    for i in range(n_files):
        p = tmp_path / ("part-%02d.txt" % i)
        p.write_text("".join("file%d_rec%d\n" % (i, j)
                             for j in range(lines_per_file)))
        paths.append(str(p))
    return paths


def test_txt_splitter(tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("x\n\ny\nz\n")
    recs = list(TxtFileSplitter().split(str(p)))
    assert recs == [(0, "x"), (1, "y"), (2, "z")]


def test_bytes_splitter(tmp_path):
    p = tmp_path / "a.bin"
    p.write_bytes(b"abcdefgh")
    recs = list(BytesChunkSplitter(3).split(str(p)))
    assert recs == [(0, b"abc"), (1, b"def"), (2, b"gh")]


def test_leader_service_balancing():
    svc = LeaderDataService(["f0", "f1"])
    svc.register_reader("podA", "a:1")
    svc.register_reader("podB", "b:1")
    # incremental file handout
    assert svc.get_file_list("podA") == [(0, "f0")]
    assert svc.get_file_list("podB") == [(1, "f1")]
    assert svc.get_file_list("podA") == []

    svc.report_batches("podA", ["f0_b0", "f0_b1", "f0_b2"], "a:1")
    # B has produced nothing → steals from A
    got = svc.get_assignment("podB", 1)
    assert got[0]["endpoint"] == "a:1"
    # A consumes its own
    got_a = svc.get_assignment("podA", 2)
    assert [g["endpoint"] for g in got_a] == ["a:1", "a:1"]
    # nothing left, producers not done → retry signal
    assert svc.get_assignment("podA", 1) == []
    svc.reach_data_end("podA")
    svc.reach_data_end("podB")
    assert svc.get_assignment("podA", 1) == [END]
    # double-consumption impossible: 3 unique batches were handed out once
    ids = {g["batch_id"] for g in got + got_a}
    assert len(ids) == 3


def test_batch_server_pop_semantics():
    cache = BatchCache(capacity=4)
    server = DataPlaneServer(cache).start()
    try:
        cache.put("b1", {"records": [1, 2]})
        c = RpcClient(server.endpoint)
        assert c.call("get_batch", "b1") == {"records": [1, 2]}
        # consumed exactly once
        try:
            c.call("get_batch", "b1")
            raise AssertionError("expected NotFoundError")
        except Exception as e:
            assert "not in cache" in str(e)
        c.close()
    finally:
        server.stop()


def test_two_readers_consume_everything(tmp_path, coord):
    paths = _write_files(tmp_path, n_files=4, lines_per_file=20)
    r1 = ElasticReader("podA", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord)
    ep = lookup_data_leader(coord, "reader")
    r2 = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)
    got = {"podA": [], "podB": []}

    def consume(name, reader):
        for batch in reader:
            got[name].extend(batch["records"])
            # pace consumption so the other reader always gets a share
            # even when CPU contention delays its start (the balancing
            # POLICY is unit-tested in test_leader_service_balancing;
            # this test is about exactly-once across two consumers)
            time.sleep(0.05)

    t1 = threading.Thread(target=consume, args=("podA", r1))
    t2 = threading.Thread(target=consume, args=("podB", r2))
    t1.start(); t2.start()
    t1.join(timeout=180); t2.join(timeout=180)
    assert not t1.is_alive() and not t2.is_alive()
    try:
        all_records = got["podA"] + got["podB"]
        assert len(all_records) == 80                 # nothing lost
        assert len(set(all_records)) == 80            # nothing duplicated
        assert got["podA"] and got["podB"]            # both participated
    finally:
        r1.stop()
        r2.stop()


def test_data_checkpoint_resume_cycle(tmp_path, coord):
    """The full data-aware resume loop: consume half, record in State,
    'restart', resume with skip_record — every record seen exactly once."""
    from edl_tpu.runtime.state import State

    paths = _write_files(tmp_path, n_files=2, lines_per_file=10)
    state = State()
    r1 = ElasticReader("podA", TxtFileSplitter(), batch_size=5,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rck")
    first_half = []
    for i, batch in enumerate(r1):
        first_half.extend(batch["records"])
        ElasticReader.mark_consumed(state, batch)
        if i == 1:
            break  # "crash" after 2 batches
    r1.stop()

    # restart: a fresh reader resumes behind the consumed ranges
    state2 = State().from_json(state.to_json())  # as if reloaded
    r2 = ElasticReader("podA2", TxtFileSplitter(), batch_size=5,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rck2",
                       skip_record=state2.data_checkpoint.is_processed)
    rest = []
    for batch in r2:
        rest.extend(batch["records"])
    r2.stop()
    assert sorted(first_half + rest) == sorted(
        "file%d_rec%d" % (f, j) for f in range(2) for j in range(10))
    assert not set(first_half) & set(rest)


def test_reader_skip_processed(tmp_path, coord):
    paths = _write_files(tmp_path, n_files=1, lines_per_file=10)
    # resume semantics: records 0..4 already processed
    reader = ElasticReader(
        "podA", TxtFileSplitter(), batch_size=4, file_list=paths,
        is_leader=True, coord=coord, reader_name="r2",
        skip_record=lambda f, idx: idx < 5)
    records = []
    for batch in reader:
        records.extend(batch["records"])
    reader.stop()
    assert records == ["file0_rec%d" % i for i in range(5, 10)]


def test_exactly_once_across_resize(tmp_path, coord):
    """VERDICT r3 item 7: membership changes MID-EPOCH — a pod joins
    late, another leaves after consuming a few batches (its unfetched
    production is lost with its server, the stage-change model) — and
    after the restart completion pass behind the recorded ranges, every
    record is consumed exactly once: none lost, none duplicated.

    Reference design intent: data_server.py:171-224 (balance across a
    changing reader set); the reference impl was never green."""
    from edl_tpu.runtime.state import State

    paths = _write_files(tmp_path, n_files=8, lines_per_file=20)  # 160
    total = ["file%d_rec%d" % (f, j) for f in range(8) for j in range(20)]
    state = State()
    state_lock = threading.Lock()

    rA = ElasticReader("podA", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rz")
    ep = lookup_data_leader(coord, "rz")
    rB = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)
    got = {"podA": [], "podB": [], "podC": []}
    b_left = threading.Event()

    def consume(name, reader, leave_after=None):
        n = 0
        for batch in reader:
            with state_lock:
                ElasticReader.mark_consumed(state, batch)
            got[name].extend(batch["records"])
            n += 1
            time.sleep(0.08)
            if leave_after is not None and n >= leave_after:
                b_left.set()
                return  # leaves mid-epoch; reader.stop() below kills
                # its batch server, losing its unfetched production

    tA = threading.Thread(target=consume, args=("podA", rA))
    tB = threading.Thread(target=consume, args=("podB", rB, 2))
    tA.start(); tB.start()

    # a pod JOINS while the epoch is in flight (early enough that work
    # remains: 20 batches at a 0.08s consumer pace span ~1s)
    time.sleep(0.1)
    rC = ElasticReader("podC", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)
    tC = threading.Thread(target=consume, args=("podC", rC))
    tC.start()

    # the LEAVE: as soon as podB consumed its quota, tear it down (the
    # launcher's SIGTERM arc: trainer loop exits, reader.stop() in its
    # finally). Batches podB produced but nobody fetched die with it.
    assert b_left.wait(timeout=60)
    rB.stop()

    tA.join(timeout=180); tB.join(timeout=180); tC.join(timeout=180)
    assert not tA.is_alive() and not tC.is_alive()
    rA.stop(); rC.stop()

    phase1 = got["podA"] + got["podB"] + got["podC"]
    assert len(phase1) == len(set(phase1)), "duplicate consumption"
    assert got["podB"], "the leaver consumed nothing before leaving"
    assert got["podC"], "the late joiner never participated"

    # the restart/completion pass (new stage): a fresh reader resumes
    # behind the recorded ranges and sweeps up exactly what was lost
    state2 = State().from_json(state.to_json())
    rD = ElasticReader("podD", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rz2",
                       skip_record=state2.data_checkpoint.is_processed)
    phase2 = []
    for batch in rD:
        phase2.extend(batch["records"])
    rD.stop()

    assert sorted(phase1 + phase2) == sorted(total)
    assert not set(phase1) & set(phase2)


def test_dead_reader_evicted_epoch_converges(tmp_path, coord):
    """A reader that dies WITHOUT reach_data_end (SIGKILL model: its
    threads and server vanish, no goodbye) must not wedge the epoch:
    the leader evicts silent readers after reader_ttl and surviving
    consumers still reach END. Its lost records return via the data
    checkpoint on restart (exactly-once overall)."""
    from edl_tpu.runtime.state import State

    paths = _write_files(tmp_path, n_files=4, lines_per_file=20)  # 80
    total = ["file%d_rec%d" % (f, j) for f in range(4) for j in range(20)]
    state = State()

    class SlowSplitter(TxtFileSplitter):
        # throttle the LEADER's production so podB deterministically
        # wins some files — the coalesced-report producer is otherwise
        # fast enough to drain the whole file list before podB joins
        def split(self, path):
            for item in TxtFileSplitter.split(self, path):
                time.sleep(0.005)
                yield item

    rA = ElasticReader("podA", SlowSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="ev", reader_ttl=2.0)
    ep = lookup_data_leader(coord, "ev")
    rB = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)

    # podB produces (grabs files, reports batches), then DIES silently:
    # kill its threads/server without any data-end report
    deadline = time.time() + 20
    while time.time() < deadline:
        with rB._cache._lock:
            if rB._cache._data:
                break
        time.sleep(0.02)
    rB._stop.set()          # stops generator AND heartbeat threads
    rB._server.stop()       # its batches are unreachable now
    # forge the silence: the generator's finally would normally report
    # data-end; simulate a hard kill by marking it NOT done again
    rB._gen_thread.join(timeout=20)
    rB._hb_thread.join(timeout=20)
    # both threads must be dead BEFORE the forged re-registration, or a
    # late reach_data_end/heartbeat would undo it and the test would
    # pass without exercising eviction at all
    assert not rB._gen_thread.is_alive()
    assert not rB._hb_thread.is_alive()
    rA._leader.call("ds_register_reader", "podB", "127.0.0.1:1")

    got = []
    for batch in rA:
        ElasticReader.mark_consumed(state, batch)
        got.extend(batch["records"])
    rA.stop()
    assert len(got) == len(set(got))
    assert len(got) < len(total)  # podB's work was genuinely lost

    # completion pass sweeps the evicted reader's records exactly once
    state2 = State().from_json(state.to_json())
    rD = ElasticReader("podD", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="ev2",
                       skip_record=state2.data_checkpoint.is_processed)
    rest = []
    for batch in rD:
        rest.extend(batch["records"])
    rD.stop()
    assert sorted(got + rest) == sorted(total)
    assert not set(got) & set(rest)


def test_exactly_once_across_data_leader_death(tmp_path, coord):
    """VERDICT r4 weak #4: the pod hosting LeaderDataService dies
    MID-EPOCH (a different failure from a dead non-leader reader: the
    assignment/report/heartbeat server itself vanishes). Surviving
    consumers must fail FAST and loudly (their next assignment RPC
    raises, which in production crashes the trainer and triggers the
    stage change), and the restarted stage's completion pass behind the
    recorded ranges must consume every record exactly once.

    Reference design: edl/utils/data_server.py:171-224 put the leader's
    balance table on one pod too — its death was likewise a stage-level
    restart, not a data-plane repair."""
    from edl_tpu.runtime.state import State
    from edl_tpu.utils import errors as errors_mod

    paths = _write_files(tmp_path, n_files=6, lines_per_file=20)  # 120
    total = ["file%d_rec%d" % (f, j) for f in range(6) for j in range(20)]
    state = State()
    state_lock = threading.Lock()

    rA = ElasticReader("podA", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="ld")
    ep = lookup_data_leader(coord, "ld")
    rB = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)

    got = {"podA": [], "podB": []}
    died = {}
    b_progress = threading.Event()

    def consume(name, reader):
        try:
            for batch in reader:
                with state_lock:
                    ElasticReader.mark_consumed(state, batch)
                got[name].extend(batch["records"])
                if name == "podB" and len(got["podB"]) >= 16:
                    b_progress.set()
                time.sleep(0.08)
        except errors_mod.EdlError as e:
            died[name] = e
        except Exception as e:  # noqa: BLE001
            died[name] = e

    tA = threading.Thread(target=consume, args=("podA", rA))
    tB = threading.Thread(target=consume, args=("podB", rB))
    tA.start(); tB.start()

    # mid-epoch, the LEADER pod dies (SIGKILL model: server and all
    # threads vanish at once, no goodbye)
    assert b_progress.wait(timeout=60)
    rA._stop.set()
    rA._server.stop()

    tA.join(timeout=120); tB.join(timeout=120)
    assert not tA.is_alive() and not tB.is_alive()
    # the survivor did NOT hang: it either raised out of the iterator
    # (the production arc — trainer crashes, launcher restarts the
    # stage) or its in-flight assignment drained to a clean stop
    assert "podB" in died or got["podB"], died
    rB.stop()

    phase1 = got["podA"] + got["podB"]
    assert phase1, "nobody consumed anything before the leader died"
    assert len(phase1) == len(set(phase1)), "duplicate consumption"
    assert len(phase1) < len(total), \
        "leader death lost nothing — the kill happened too late to test"

    # the stage change: a fresh incarnation (new leader, new stage id)
    # resumes behind the recorded ranges
    state2 = State().from_json(state.to_json())
    rE = ElasticReader("podE", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="ld2",
                       skip_record=state2.data_checkpoint.is_processed)
    phase2 = []
    for batch in rE:
        phase2.extend(batch["records"])
    rE.stop()

    assert sorted(phase1 + phase2) == sorted(total)
    assert not set(phase1) & set(phase2)


def test_heartbeat_protects_busy_reader_and_zombie_rejected():
    """Liveness semantics at the unit level (injectable clock): a
    heartbeating reader is never evicted no matter how long its data
    RPCs pause (long train step); once evicted, a zombie's report is
    rejected loudly so it restarts via the data checkpoint."""
    from edl_tpu.utils import errors as errors_mod

    now = [0.0]
    svc = LeaderDataService(["f0", "f1"], reader_ttl=5.0,
                            clock=lambda: now[0])
    svc.register_reader("podA", "a:1")
    svc.register_reader("podB", "b:1")
    svc.get_file_list("podB")
    svc.report_batches("podB", ["f0_b0"], "b:1")

    # podB goes quiet on the data plane but its heartbeat thread lives
    for t in (3.0, 6.0, 9.0):
        now[0] = t
        svc.heartbeat("podB")
    now[0] = 10.0
    assert svc.get_assignment("podA", 1)  # drains b's batch, no evict
    assert svc.get_assignment("podA", 1) == []  # triggers evict check
    assert not svc.stats()["readers"]["podB"]  # alive: not done

    # now the process really dies: no heartbeats past the ttl
    svc.report_batches("podB", ["f0_b1"], "b:1")
    now[0] = 16.1
    # available batches still drain first (the consumer's fetch failure
    # handles a dead producer); the evict check runs on the next empty
    assert svc.get_assignment("podA", 1)
    assert svc.get_assignment("podA", 1) == []  # evicts B
    assert svc.stats()["readers"]["podB"] is True
    try:
        svc.report_batches("podB", ["f0_b2"], "b:1")
        raise AssertionError("zombie report must be rejected")
    except errors_mod.DataAccessError as e:
        assert "evicted" in str(e)


# ---------------------------------------------------------------------------
# pipelined data plane (docs/data_plane.md): columnar codec, byte-bounded
# cache, long-poll assignments, consumer-only steal, eviction mid-pipeline,
# legacy interop
# ---------------------------------------------------------------------------


def test_pack_unpack_columns_roundtrips():
    import numpy as np

    from edl_tpu.rpc import ndarray as nd

    cases = [
        ["alpha", "", "βeta"],                      # str (utf-8, empty)
        [b"ab", b"", b"\x00\xff"],                   # bytes
        [np.arange(6, dtype=np.float32).reshape(2, 3),
         np.ones((2, 3), np.float32)],               # nd: one dtype+shape
        [1, -5, 2 ** 40],                            # i64
        [0.5, -1.25, 3.0],                           # f64
        [(1, "a"), (2, "b")],                        # tuple of columns
        [[1.0, b"x"], [2.0, b"y"]],                  # list rows
    ]
    for records in cases:
        col = nd.pack_columns(records)
        assert col is not None, records
        back = nd.unpack_columns(col, copy=False)
        assert len(back) == len(records)
        for orig, got in zip(records, back):
            if isinstance(orig, np.ndarray):
                assert got.dtype == orig.dtype and got.shape == orig.shape
                assert np.array_equal(got, orig)
            else:
                assert type(got) is type(orig) and got == orig


def test_pack_columns_falls_back_to_row_form():
    import numpy as np

    from edl_tpu.rpc import ndarray as nd

    # anything the codec cannot represent EXACTLY must return None so
    # the producer keeps the row format
    assert nd.pack_columns([]) is None
    assert nd.pack_columns([1, "a"]) is None          # heterogeneous
    assert nd.pack_columns([True, False]) is None     # bool is not i64
    assert nd.pack_columns([1, True]) is None
    assert nd.pack_columns([2 ** 70]) is None         # int64 overflow
    assert nd.pack_columns([{"k": 1}]) is None        # dict records
    assert nd.pack_columns([(1, 2), (3,)]) is None    # ragged tuples
    assert nd.pack_columns(
        [np.zeros((2,), np.float32), np.zeros((3,), np.float32)]) is None
    assert nd.pack_columns(
        [np.array([object()], dtype=object)]) is None


def test_get_batches_columnar_wire_roundtrip():
    """One multi-batch RPC in columnar form must restore the exact
    records on the consumer (ElasticReader._decode is the consumer-side
    half); a missing batch yields None in its slot, and row format
    matches what get_batch would have returned."""
    import numpy as np

    cache = BatchCache(capacity=8)
    server = DataPlaneServer(cache).start()
    try:
        recs = [np.full((3,), i, np.float32) for i in range(4)]
        payload = {"batch_id": "b0", "file": "f", "range": [0, 3],
                   "records": recs}
        cache.put("b0", payload)
        cache.put("b1", {"batch_id": "b1", "file": "f", "range": [4, 5],
                         "records": ["r4", "r5"]})
        c = RpcClient(server.endpoint)
        got = c.call("get_batches", ["b0", "missing", "b1"], fmt="col")
        assert got[1] is None
        d0 = ElasticReader._decode(got[0])
        assert d0["batch_id"] == "b0" and d0["range"] == [0, 3]
        assert "cols" not in d0 and "fmt" not in d0
        assert all(np.array_equal(a, b)
                   for a, b in zip(d0["records"], recs))
        d1 = ElasticReader._decode(got[2])
        assert d1["records"] == ["r4", "r5"]

        # row format: byte-compatible with the single-batch RPC
        cache.put("b2", {"batch_id": "b2", "records": ["x", "y"]})
        row = c.call("get_batches", ["b2"], fmt="row")[0]
        assert row == {"batch_id": "b2", "records": ["x", "y"]}
        c.close()
    finally:
        server.stop()


def test_batch_cache_byte_bound_blocks_until_pop():
    import numpy as np

    big = {"records": [np.zeros(64, np.uint8)]}  # 64 bytes of payload
    cache = BatchCache(capacity=8, capacity_bytes=100)
    assert cache.put("b0", big)
    assert cache.nbytes() >= 64
    done = threading.Event()

    def blocked_put():
        cache.put("b1", big, timeout=30)
        done.set()

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not done.is_set()            # 128 > 100: put is parked
    assert cache.pop("b0") is big       # room appears ...
    assert done.wait(timeout=5)         # ... and the put completes
    t.join(timeout=5)
    assert len(cache) == 1


def test_batch_cache_put_stop_aware_and_oversized_alone():
    import numpy as np

    cache = BatchCache(capacity=8, capacity_bytes=100)
    # a payload larger than the whole budget is admitted when the cache
    # is empty — one oversized batch can never deadlock the producer
    assert cache.put("huge", {"records": [np.zeros(1000, np.uint8)]})
    stop = threading.Event()
    result = {}

    def stopping_put():
        result["v"] = cache.put("b1", {"records": [b"x" * 50]},
                                timeout=600, stop=stop)

    t = threading.Thread(target=stopping_put, daemon=True)
    t.start()
    time.sleep(0.2)
    assert "v" not in result            # blocked on the full cache
    stop.set()
    t.join(timeout=5)
    assert result["v"] is False         # aborted promptly, not 600s


def test_assignment_long_poll_wakes_on_report_and_end():
    """The wait_ms contract: with nothing assignable the call parks
    server-side and returns the moment a production report (or data-end)
    changes the answer — not after a fixed poll interval."""
    svc = LeaderDataService(["f0"])
    svc.register_reader("podA", "a:1")
    svc.register_reader("podB", "b:1")
    svc.get_file_list("podB")

    # wait_ms=0 keeps the legacy contract: immediate [] retry signal
    t0 = time.monotonic()
    assert svc.get_assignment("podA", 1) == []
    assert time.monotonic() - t0 < 0.2

    out = {}

    def poll():
        t0 = time.monotonic()
        out["got"] = svc.get_assignment("podA", 1, wait_ms=2000)
        out["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=poll)
    t.start()
    time.sleep(0.3)
    svc.report_batches("podB", ["f0_b0"], "b:1")
    t.join(timeout=5)
    assert out["got"] == [{"batch_id": "f0_b0", "endpoint": "b:1"}]
    assert 0.25 <= out["elapsed"] < 1.5  # woke on the report, not cap

    def poll_end():
        t0 = time.monotonic()
        out["end"] = svc.get_assignment("podA", 1, wait_ms=2000)
        out["end_elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=poll_end)
    t.start()
    time.sleep(0.2)
    svc.reach_data_end("podA")
    svc.reach_data_end("podB")
    t.join(timeout=5)
    assert out["end"] == [END]
    assert out["end_elapsed"] < 1.5


def test_assignment_long_poll_capped():
    # a consumer cannot park a server thread past MAX_ASSIGN_WAIT_MS
    from edl_tpu.data import data_server

    svc = LeaderDataService(["f0"])
    svc.register_reader("podA", "a:1")
    t0 = time.monotonic()
    assert svc.get_assignment("podA", 1, wait_ms=60_000) == []
    elapsed = time.monotonic() - t0
    assert elapsed <= data_server.MAX_ASSIGN_WAIT_MS / 1e3 + 1.0


def test_consumer_only_pods_steal_everything(tmp_path):
    """The disaggregated-input shape: one producer pod (never consumes),
    two pure consumers (produce=False) — everything is stolen, both
    consumers get a share, exactly-once holds."""
    paths = _write_files(tmp_path, n_files=4, lines_per_file=24)  # 96
    total = ["file%d_rec%d" % (f, j) for f in range(4) for j in range(24)]
    prod = ElasticReader("prod", TxtFileSplitter(), batch_size=8,
                         file_list=paths, is_leader=True)
    c1 = ElasticReader("c1", TxtFileSplitter(), batch_size=8,
                       produce=False, leader_endpoint=prod.endpoint)
    c2 = ElasticReader("c2", TxtFileSplitter(), batch_size=8,
                       produce=False, leader_endpoint=prod.endpoint)
    got = {"c1": [], "c2": []}

    def consume(name, reader):
        for batch in reader:
            got[name].extend(batch["records"])
            time.sleep(0.03)  # pace so the other consumer shares

    t1 = threading.Thread(target=consume, args=("c1", c1))
    t2 = threading.Thread(target=consume, args=("c2", c2))
    t1.start(); t2.start()
    t1.join(timeout=120); t2.join(timeout=120)
    assert not t1.is_alive() and not t2.is_alive()
    try:
        assert sorted(got["c1"] + got["c2"]) == sorted(total)
        assert got["c1"] and got["c2"]      # steal fairness: both fed
        for reader in (c1, c2):
            s = reader.stats()
            assert s["local"] == 0          # pure consumers own nothing
            assert s["remote"] > 0          # steal ratio 1.0
            assert s["lost"] == []
        stats = prod._leader.call("ds_stats")
        assert stats["stolen"] == stats["consumed"]  # every batch stolen
    finally:
        c1.stop(); c2.stop(); prod.stop()


def test_eviction_while_pipelined_fetches_in_flight(tmp_path, coord):
    """Satellite of the pipelining PR: a producer dies silently while a
    pipelined consumer (fetch_ahead deep) is mid-epoch. Fetches against
    the dead endpoint surface as LOST (never wedge, never duplicate),
    the consumer converges to END, the leader's consumed count equals
    delivered+lost exactly, and the completion pass recovers exactly
    the lost records."""
    from edl_tpu.runtime.state import State

    paths = _write_files(tmp_path, n_files=4, lines_per_file=20)  # 80
    total = ["file%d_rec%d" % (f, j) for f in range(4) for j in range(20)]
    state = State()

    class SlowSplitter(TxtFileSplitter):
        # throttle the leader-side producer so podB wins files
        def split(self, path):
            for item in TxtFileSplitter.split(self, path):
                time.sleep(0.005)
                yield item

    rA = ElasticReader("podA", SlowSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="evp", reader_ttl=2.0, fetch_ahead=4)
    ep = lookup_data_leader(coord, "evp")
    rB = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)

    # podB produces and reports, then dies without a goodbye — its
    # reported batches stay assignable until eviction, so the pipelined
    # consumer WILL issue fetches against the dead endpoint
    deadline = time.time() + 20
    while time.time() < deadline:
        with rB._cache._lock:
            if rB._cache._data:
                break
        time.sleep(0.02)
    rB._stop.set()
    rB._server.stop()
    rB._gen_thread.join(timeout=20)
    rB._hb_thread.join(timeout=20)
    assert not rB._gen_thread.is_alive()
    assert not rB._hb_thread.is_alive()
    rA._leader.call("ds_register_reader", "podB", "127.0.0.1:1")

    got_batches = []
    got = []
    for batch in rA:
        ElasticReader.mark_consumed(state, batch)
        got_batches.append(batch)
        got.extend(batch["records"])
    lost = rA.stats()["lost"]
    stats = rA._leader.call("ds_stats")
    rA.stop()

    assert len(got) == len(set(got))
    assert lost, "no fetch was in flight against the dead producer"
    # exact accounting: every assignment the leader handed out was
    # either delivered or logged lost — nothing silently vanished
    assert stats["consumed"] == len(got_batches) + len(lost)

    state2 = State().from_json(state.to_json())
    rD = ElasticReader("podD", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="evp2",
                       skip_record=state2.data_checkpoint.is_processed)
    rest = []
    for batch in rD:
        rest.extend(batch["records"])
    rD.stop()
    assert sorted(got + rest) == sorted(total)
    assert not set(got) & set(rest)


def test_legacy_producer_serial_row_fallback(tmp_path):
    """Interop: a pre-pipelining producer (no rpc.pipeline feature, only
    per-batch get_batch) feeds a pipelined consumer unchanged — the
    consumer negotiates the endpoint down to serial row fetches and the
    payloads come through byte-identical to what the producer stored."""
    cache = BatchCache(capacity=8)
    legacy = DataPlaneServer(cache).start()
    # masquerade as a pre-pipelining generation
    legacy._rpc.register("__features__", lambda: [])

    leader = ElasticReader("podL", TxtFileSplitter(), batch_size=8,
                           file_list=[], is_leader=True)
    payloads = {}
    for i in range(3):
        bid = "leg_b%d" % i
        payloads[bid] = {"batch_id": bid, "file": "legacy.txt",
                         "range": [i * 2, i * 2 + 1],
                         "records": ["legacy_rec%d" % (i * 2),
                                     "legacy_rec%d" % (i * 2 + 1)]}
        cache.put(bid, payloads[bid])
    leader._leader.call("ds_register_reader", "legacy", legacy.endpoint)
    leader._leader.call("ds_report_batches", "legacy",
                        list(payloads), legacy.endpoint)
    leader._leader.call("ds_reach_data_end", "legacy")

    rC = ElasticReader("podC", TxtFileSplitter(), batch_size=8,
                       produce=False, leader_endpoint=leader.endpoint,
                       pipelined_fetch=True, columnar=True)
    try:
        got = list(rC)
        assert {b["batch_id"]: b for b in got} == payloads  # byte-compat
        s = rC.stats()
        assert s["endpoint_modes"][legacy.endpoint] == "serial"
        assert s["lost"] == [] and s["remote"] == 3
    finally:
        rC.stop()
        leader.stop()
        legacy.stop()


def test_legacy_leader_disables_long_poll(tmp_path):
    """A pre-pipelining LEADER would reject the extra wait_ms argument;
    the consumer must detect the missing feature at registration and
    fall back to the plain polled assignment call — and still drain the
    epoch."""
    paths = _write_files(tmp_path, n_files=1, lines_per_file=16)
    leader = ElasticReader("podL", TxtFileSplitter(), batch_size=8,
                           file_list=paths, is_leader=True)
    # downgrade the leader's advertisement BEFORE the consumer probes it
    leader._server._rpc.register("__features__", lambda: [])
    rC = ElasticReader("podC", TxtFileSplitter(), batch_size=8,
                       produce=False, leader_endpoint=leader.endpoint)
    try:
        assert rC._assign_wait_ms is None       # negotiated away
        assert leader._assign_wait_ms is not None  # probed pre-downgrade
        got = []
        for batch in rC:
            got.extend(batch["records"])
        assert sorted(got) == sorted("file0_rec%d" % i for i in range(16))
    finally:
        rC.stop()
        leader.stop()


def test_reader_stop_idempotent_and_prompt(tmp_path):
    paths = _write_files(tmp_path, n_files=2, lines_per_file=20)
    reader = ElasticReader("podA", TxtFileSplitter(), batch_size=8,
                           file_list=paths, is_leader=True)
    it = iter(reader)
    next(it); next(it)  # pipeline warm, fetches in flight
    t0 = time.monotonic()
    reader.stop()
    reader.stop()  # idempotent — second call is a no-op, not an error
    assert time.monotonic() - t0 < 15  # no 30s socket-timeout stall
    assert not reader._hb_thread.is_alive()
    assert reader._gen_thread is None or not reader._gen_thread.is_alive()
    assert (reader._fetch_thread is None
            or not reader._fetch_thread.is_alive())
    assert reader._pool.stats()["open"] == 0  # owned pool closed
