"""Data plane tests: splitters, leader balancing/stealing, multi-reader
iteration with remote fetch — many actors in one process (reference shape:
test_data_server.py)."""

import threading
import time

from edl_tpu.data.data_server import (END, BatchCache, DataPlaneServer,
                                      LeaderDataService)
from edl_tpu.data.reader import ElasticReader, lookup_data_leader
from edl_tpu.data.splitter import BytesChunkSplitter, TxtFileSplitter
from edl_tpu.rpc.client import RpcClient


def _write_files(tmp_path, n_files=4, lines_per_file=20):
    paths = []
    for i in range(n_files):
        p = tmp_path / ("part-%02d.txt" % i)
        p.write_text("".join("file%d_rec%d\n" % (i, j)
                             for j in range(lines_per_file)))
        paths.append(str(p))
    return paths


def test_txt_splitter(tmp_path):
    p = tmp_path / "a.txt"
    p.write_text("x\n\ny\nz\n")
    recs = list(TxtFileSplitter().split(str(p)))
    assert recs == [(0, "x"), (1, "y"), (2, "z")]


def test_bytes_splitter(tmp_path):
    p = tmp_path / "a.bin"
    p.write_bytes(b"abcdefgh")
    recs = list(BytesChunkSplitter(3).split(str(p)))
    assert recs == [(0, b"abc"), (1, b"def"), (2, b"gh")]


def test_leader_service_balancing():
    svc = LeaderDataService(["f0", "f1"])
    svc.register_reader("podA", "a:1")
    svc.register_reader("podB", "b:1")
    # incremental file handout
    assert svc.get_file_list("podA") == [(0, "f0")]
    assert svc.get_file_list("podB") == [(1, "f1")]
    assert svc.get_file_list("podA") == []

    svc.report_batches("podA", ["f0_b0", "f0_b1", "f0_b2"], "a:1")
    # B has produced nothing → steals from A
    got = svc.get_assignment("podB", 1)
    assert got[0]["endpoint"] == "a:1"
    # A consumes its own
    got_a = svc.get_assignment("podA", 2)
    assert [g["endpoint"] for g in got_a] == ["a:1", "a:1"]
    # nothing left, producers not done → retry signal
    assert svc.get_assignment("podA", 1) == []
    svc.reach_data_end("podA")
    svc.reach_data_end("podB")
    assert svc.get_assignment("podA", 1) == [END]
    # double-consumption impossible: 3 unique batches were handed out once
    ids = {g["batch_id"] for g in got + got_a}
    assert len(ids) == 3


def test_batch_server_pop_semantics():
    cache = BatchCache(capacity=4)
    server = DataPlaneServer(cache).start()
    try:
        cache.put("b1", {"records": [1, 2]})
        c = RpcClient(server.endpoint)
        assert c.call("get_batch", "b1") == {"records": [1, 2]}
        # consumed exactly once
        try:
            c.call("get_batch", "b1")
            raise AssertionError("expected NotFoundError")
        except Exception as e:
            assert "not in cache" in str(e)
        c.close()
    finally:
        server.stop()


def test_two_readers_consume_everything(tmp_path, coord):
    paths = _write_files(tmp_path, n_files=4, lines_per_file=20)
    r1 = ElasticReader("podA", TxtFileSplitter(), batch_size=8,
                       file_list=paths, is_leader=True, coord=coord)
    ep = lookup_data_leader(coord, "reader")
    r2 = ElasticReader("podB", TxtFileSplitter(), batch_size=8,
                       leader_endpoint=ep)
    got = {"podA": [], "podB": []}

    def consume(name, reader):
        for batch in reader:
            got[name].extend(batch["records"])
            # pace consumption so the other reader always gets a share
            # even when CPU contention delays its start (the balancing
            # POLICY is unit-tested in test_leader_service_balancing;
            # this test is about exactly-once across two consumers)
            time.sleep(0.05)

    t1 = threading.Thread(target=consume, args=("podA", r1))
    t2 = threading.Thread(target=consume, args=("podB", r2))
    t1.start(); t2.start()
    t1.join(timeout=180); t2.join(timeout=180)
    assert not t1.is_alive() and not t2.is_alive()
    try:
        all_records = got["podA"] + got["podB"]
        assert len(all_records) == 80                 # nothing lost
        assert len(set(all_records)) == 80            # nothing duplicated
        assert got["podA"] and got["podB"]            # both participated
    finally:
        r1.stop()
        r2.stop()


def test_data_checkpoint_resume_cycle(tmp_path, coord):
    """The full data-aware resume loop: consume half, record in State,
    'restart', resume with skip_record — every record seen exactly once."""
    from edl_tpu.runtime.state import State

    paths = _write_files(tmp_path, n_files=2, lines_per_file=10)
    state = State()
    r1 = ElasticReader("podA", TxtFileSplitter(), batch_size=5,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rck")
    first_half = []
    for i, batch in enumerate(r1):
        first_half.extend(batch["records"])
        ElasticReader.mark_consumed(state, batch)
        if i == 1:
            break  # "crash" after 2 batches
    r1.stop()

    # restart: a fresh reader resumes behind the consumed ranges
    state2 = State().from_json(state.to_json())  # as if reloaded
    r2 = ElasticReader("podA2", TxtFileSplitter(), batch_size=5,
                       file_list=paths, is_leader=True, coord=coord,
                       reader_name="rck2",
                       skip_record=state2.data_checkpoint.is_processed)
    rest = []
    for batch in r2:
        rest.extend(batch["records"])
    r2.stop()
    assert sorted(first_half + rest) == sorted(
        "file%d_rec%d" % (f, j) for f in range(2) for j in range(10))
    assert not set(first_half) & set(rest)


def test_reader_skip_processed(tmp_path, coord):
    paths = _write_files(tmp_path, n_files=1, lines_per_file=10)
    # resume semantics: records 0..4 already processed
    reader = ElasticReader(
        "podA", TxtFileSplitter(), batch_size=4, file_list=paths,
        is_leader=True, coord=coord, reader_name="r2",
        skip_record=lambda f, idx: idx < 5)
    records = []
    for batch in reader:
        records.extend(batch["records"])
    reader.stop()
    assert records == ["file0_rec%d" % i for i in range(5, 10)]
