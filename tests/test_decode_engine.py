"""Decode-engine tests (ISSUE 18): slot-based continuous batching must
be token-identical to ``models.gpt.generate`` under ONE fused step
trace, quantized teachers must pass the logits parity gate, a faulted
fused step fails only the sequences in it (typed error, slot freed,
loop alive), drain strands nothing, and the per-phase admission /
balance / scaler surfaces shed and scale deterministically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.distill.balance import Service
from edl_tpu.models import gpt as gpt_mod
from edl_tpu.ops.quant import dequantize_tree, quantize_tree, \
    quantized_bytes
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.serve.admission import DECODE_SHED_REASONS, DecodeAdmission
from edl_tpu.serve.decode_engine import DecodeEngine
from edl_tpu.serve.kv_cache import SlotKvCache
from edl_tpu.serve.scaler import ServeScaler, load_actions
from edl_tpu.utils import errors


@pytest.fixture(scope="module")
def tiny():
    model = gpt_mod.gpt_tiny(num_layers=2, d_model=32, num_heads=2,
                             mlp_dim=64, vocab_size=64, max_len=64,
                             dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def engine(tiny):
    model, params = tiny
    # prefix_cache=False: this module pins the PR18 cold-prefill
    # semantics; the prefix-reuse and chunked-prefill paths have their
    # own parity suite in tests/test_prefix_cache.py
    eng = DecodeEngine(model, params, slots=4, admission=False,
                       prefix_cache=False)
    eng.start()
    yield eng
    eng.stop()


_REF_PROMPTS = ([1, 5, 9], [3, 3, 3], [9, 8, 7], [2, 4, 6],
                [1, 2, 1], [2, 3, 1], [3, 4, 1], [4, 5, 1], [5, 6, 1],
                [6, 7, 1], [2, 4, 6, 8], [7, 1, 7, 1])
_REF_NEW = 6


@pytest.fixture(scope="module")
def refs(tiny):
    """Unbatched-reference tokens for every prompt the engine tests
    decode, computed in ONE ``gpt.generate`` call per prompt length —
    generate re-traces per call, so batching keeps this file fast."""
    model, params = tiny
    out = {}
    by_len = {}
    for p in _REF_PROMPTS:
        by_len.setdefault(len(p), []).append(p)
    for prompts in by_len.values():
        toks = np.asarray(gpt_mod.generate(
            model, params, np.asarray(prompts, np.int32), _REF_NEW))
        for p, row in zip(prompts, toks):
            out[tuple(p)] = [int(t) for t in row]
    return out


# -- the allocator ---------------------------------------------------------


def test_slot_kv_cache_alloc_free():
    kv = SlotKvCache(lambda n: {"k": jnp.zeros((n, 8, 2, 4))}, slots=3)
    assert kv.free_slots == 3 and kv.occupied == 0
    got = [kv.alloc() for _ in range(3)]
    assert sorted(got) == [0, 1, 2]
    assert kv.alloc() is None  # full: a typed None, never an overwrite
    assert kv.occupied == 3 and kv.free_slots == 0
    kv.free(1)
    assert kv.occupied == 2 and kv.alloc() == 1  # the freed row reused
    with pytest.raises(ValueError):
        kv.free(2)  # double free
        kv.free(2)
    assert kv.bytes() == 3 * 8 * 2 * 4 * 4


# -- per-phase admission ---------------------------------------------------


def test_decode_admission_sheds_every_reason_typed():
    """Each DECODE_SHED_REASONS entry is reachable, typed, and counted;
    the same state that sheds one phase admits when the pressure is on
    the other phase."""
    adm = DecodeAdmission(max_waiting=2, ttft_slo_ms=8.0, itl_slo_ms=2.0,
                          slot_slack=1)
    adm.admit(free_slots=1, waiting=0, occupied=0, slots=2)

    with pytest.raises(errors.OverloadedError, match="queue_full"):
        adm.admit(free_slots=1, waiting=2, occupied=0, slots=2)
    with pytest.raises(errors.OverloadedError, match="slots"):
        adm.admit(free_slots=0, waiting=1, occupied=2, slots=2)
    # estimates gate the SLO projections: no estimate, no shed
    adm.admit(free_slots=1, waiting=1, occupied=1, slots=2)
    adm.observe_prefill_ms(5.0)
    adm.observe_itl_ms(5.0)
    # (waiting+1) * prefill = 10ms > 8ms TTFT SLO
    with pytest.raises(errors.OverloadedError, match="ttft"):
        adm.admit(free_slots=1, waiting=1, occupied=0, slots=2)
    adm.admit(free_slots=1, waiting=0, occupied=0, slots=2)  # queue empty
    # measured step 5ms > 2ms ITL SLO while decodes are resident
    with pytest.raises(errors.OverloadedError, match="itl"):
        adm.admit(free_slots=1, waiting=0, occupied=1, slots=2)
    adm.set_draining(True)
    with pytest.raises(errors.OverloadedError, match="draining"):
        adm.admit(free_slots=2, waiting=0, occupied=0, slots=2)
    adm.set_draining(False)
    with pytest.raises(errors.OverloadedError, match="deadline"):
        raise adm.shed_evicted()

    s = adm.stats()
    assert s["admitted"] == 3
    assert sorted(s["shed"]) == sorted(DECODE_SHED_REASONS)
    assert all(s["shed"][r] == 1 for r in DECODE_SHED_REASONS)
    assert s["shed_total"] == len(DECODE_SHED_REASONS)


# -- continuous batching parity --------------------------------------------


def test_engine_token_identical_to_generate_one_step_trace(engine, refs):
    """Sequences batched into one fused step decode the EXACT tokens of
    ``gpt.generate`` — admission order, slot id, and batch mates never
    leak into the logits — and the whole mixed workload retires under a
    single step trace (fixed-shape discipline)."""
    prompts = [[1, 5, 9], [2, 4, 6, 8], [3, 3, 3], [7, 1, 7, 1],
               [9, 8, 7]]
    handles = [engine.submit(p, _REF_NEW) for p in prompts]
    reports = [h.result(timeout=60.0) for h in handles]
    for p, rep in zip(prompts, reports):
        assert rep["tokens"] == refs[tuple(p)]
        assert len(rep["generated"]) == _REF_NEW
        assert rep["ttft_ms"] >= 0.0
    s = engine.stats()
    assert s["decode_step_traces"] == 1
    # prompts pad to power-of-two buckets: every length above hit ONE
    assert s["decode_prefill_traces"] == 1
    assert s["decode_sequences_total"] >= len(prompts)


def test_drain_finishes_every_admitted_sequence(engine, refs):
    handles = [engine.submit([i + 1, i + 2, 1], _REF_NEW)
               for i in range(6)]
    assert engine.drain(deadline_s=30.0) is True
    for i, h in enumerate(handles):
        rep = h.result(timeout=1.0)  # already resolved: zero stranded
        assert rep["tokens"] == refs[(i + 1, i + 2, 1)]
    s = engine.stats()
    assert s["decode_waiting"] == 0 and s["decode_active"] == 0
    assert s["decode_slots_occupied"] == 0
    # draining front door sheds typed, then reopens for the next test
    with pytest.raises(errors.OverloadedError, match="draining"):
        engine.submit([1, 2], 2)
    engine.admission.set_draining(False)


def test_deadline_burned_in_queue_is_a_typed_eviction(engine):
    dead = engine.submit([1, 2, 3], 2, deadline_ms=0.0)
    with pytest.raises(errors.OverloadedError, match="deadline"):
        dead.result(timeout=30.0)


# -- the chaos drill (docs/fault_tolerance.md catalog row) -----------------


def test_faulted_step_fails_only_active_sequences(tiny, refs):
    """``serve.decode.step`` error fault: the sequences in the faulted
    fused step fail with a typed DecodeStepError and their slots free;
    a sequence still WAITING at fault time is untouched — it takes the
    freed slot and decodes to the exact reference tokens (the loop is
    never wedged)."""
    model, params = tiny
    eng = DecodeEngine(model, params, slots=1, admission=False)
    eng.start()
    plane = FaultPlane(seed=3)
    # deterministic schedule: steps 1-3 decode, step 4 raises, once
    plane.inject("serve.decode.step", "error_once", after=3)
    plane.install()
    try:
        active = eng.submit([1, 2, 3], 20)   # takes the only slot
        waiter = eng.submit([2, 4, 6], _REF_NEW)  # queued behind it
        with pytest.raises(errors.DecodeStepError):
            active.result(timeout=60.0)
        rep = waiter.result(timeout=60.0)  # fault consumed: clean run
        assert rep["tokens"] == refs[(2, 4, 6)]
        s = eng.stats()
        assert s["decode_evicted_total"] == 1
        assert s["decode_slots_occupied"] == 0  # faulted slot freed
        assert plane.log == [("serve.decode.step", "error_once")]
    finally:
        plane.uninstall()
        eng.stop()


# -- quantized teachers: the parity gate -----------------------------------


@pytest.mark.parametrize("mode,max_rel", [("int8", 0.05), ("bf16", 0.05)])
def test_quantized_logits_parity_gate(tiny, mode, max_rel):
    """Weight-only quantization is only allowed behind the gate: logits
    within rel-Frobenius tolerance of fp32 and >= 90% greedy top-1
    agreement — and int8 really halves the teacher's weight bytes."""
    model, params = tiny
    qparams = quantize_tree(params, mode)
    ids = jnp.asarray(np.arange(24, dtype=np.int32).reshape(2, 12) % 64)
    ref = np.asarray(model.apply({"params": params}, ids))
    got = np.asarray(model.apply(
        {"params": dequantize_tree(qparams)}, ids))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < max_rel, "rel fro err %.4f" % rel
    agree = np.mean(got.argmax(-1) == ref.argmax(-1))
    assert agree >= 0.9, "top-1 agreement %.3f" % agree
    if mode == "int8":
        q_bytes, f_bytes = quantized_bytes(qparams)
        assert q_bytes < 0.6 * f_bytes


# -- slot pressure as an elasticity signal ---------------------------------


class _Coord(object):
    def __init__(self):
        self.kv = {}

    def get_value(self, service, key):
        return self.kv.get((service, key))

    def set_server_permanent(self, service, key, value):
        self.kv[(service, key)] = value


def test_scaler_reads_decode_slot_frac_as_overload():
    """A fleet that is idle on the predict plane but whose KV slots are
    pinned at 1.0 scales OUT — decode_slot_frac and decode-admission
    sheds are first-class overload signals."""
    coord = _Coord()
    calls = []
    sc = ServeScaler(
        coord, "pod-decode", mode="on", interval=1.0,
        scale_out_fn=lambda: (calls.append("out"), "ep-new")[1],
        scale_in_fn=lambda ep: True, occupancy_high=0.8,
        out_streak=2, in_streak=1 << 20)
    hot = {"occupancy": 0.0, "decode_slot_frac": 1.0,
           "decode_admission": {"shed_total": 2}}
    acts = []
    for t in range(3):
        acts += sc.tick({"t0": hot}, now=float(t))
    assert [a["kind"] for a in acts] == ["scale_out"]
    assert calls == ["out"]
    assert [a["kind"] for a in load_actions(coord)] == ["scale_out"]
    # same fleet with free slots: no action
    sc2 = ServeScaler(
        coord, "pod-decode-2", mode="on", interval=1.0,
        scale_out_fn=lambda: "ep", scale_in_fn=lambda ep: True,
        occupancy_high=0.8, out_streak=2, in_streak=1 << 20)
    cold = {"occupancy": 0.0, "decode_slot_frac": 0.25,
            "decode_admission": {"shed_total": 0}}
    assert [a for t in range(4)
            for a in sc2.tick({"t0": cold}, now=float(t))] == []


def test_balance_phase_capacity_routes_decode_clients():
    """Per-phase balance weights: a teacher advertising zero
    ``capacity_decode`` takes NO decode-phase clients (its prefill
    capacity is irrelevant to them), while prefill-phase clients still
    spread over both."""
    now = [0.0]
    svc = Service("phases", clock=lambda: now[0])
    svc.set_servers({"pre-only": {"capacity_prefill": 8.0,
                                  "capacity_decode": 0.0},
                     "hybrid": {"capacity_prefill": 8.0,
                                "capacity_decode": 4.0}})
    for i in range(4):
        svc.register_client("d%d" % i, 1, phase="decode")
    stats = svc.stats()
    assert stats["servers"]["pre-only"] == 0
    assert stats["servers"]["hybrid"] == 4

    svc2 = Service("phases2", clock=lambda: now[0])
    svc2.set_servers({"pre-only": {"capacity_prefill": 8.0,
                                   "capacity_decode": 0.0},
                      "hybrid": {"capacity_prefill": 8.0,
                                 "capacity_decode": 4.0}})
    for i in range(4):
        svc2.register_client("p%d" % i, 1, phase="prefill")
    assert sorted(svc2.stats()["servers"].values()) == [2, 2]


# -- the doctor's starvation detector --------------------------------------


def test_job_doctor_flags_decode_slot_starvation():
    """Saturated KV slots WITH a prefill queue is a ranked doctor
    finding (arrivals wait on retirements); saturated slots with an
    empty queue is healthy steady-state and stays silent."""
    from edl_tpu.tools import job_doctor

    def gauge(v):
        return {"series": [{"labels": {}, "value": v}]}

    def doc(occupied, queue):
        return {"metrics": {"metrics": {
            "edl_decode_slots_total": gauge(4),
            "edl_decode_slots_occupied": gauge(occupied),
            "edl_decode_prefill_queue": gauge(queue)}}}

    report = job_doctor.diagnose(
        {"job_id": "j", "job_status": None, "health": None,
         "obs": {"pod-0": doc(4, 3), "pod-1": doc(4, 0),
                 "pod-2": doc(2, 0)}})
    found = [f for f in report["findings"]
             if f["detector"] == "decode_slot_starvation"]
    assert len(found) == 1
    assert found[0]["pod"] == "pod-0"
    assert found[0]["metric"] == "edl_decode_prefill_queue"
    assert "4/4" in found[0]["summary"]
    job_doctor.render(report)  # human surface renders the finding
