"""Checkpoint manager tests: versioned commit, corruption fallback, dtype
fidelity (incl. bfloat16), structured restore."""

import json

import jax.numpy as jnp
import numpy as np

from edl_tpu.runtime.checkpoint import CheckpointManager


def _tree(seed):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "dense": {"w": rng.randn(4, 3).astype(np.float32),
                      "b": np.zeros(3, np.float32)},
            "emb": rng.randn(10, 4).astype(np.float32),
        },
        "step": np.int32(seed),
        "bf16": jnp.ones((2, 2), jnp.bfloat16) * seed,
    }


def _assert_trees_equal(a, b):
    assert np.array_equal(np.asarray(a["step"]), np.asarray(b["step"]))
    np.testing.assert_array_equal(a["params"]["dense"]["w"],
                                  b["params"]["dense"]["w"])
    np.testing.assert_array_equal(np.asarray(a["bf16"], np.float32),
                                  np.asarray(b["bf16"], np.float32))
    assert np.asarray(b["bf16"]).dtype == np.asarray(a["bf16"]).dtype


def test_save_restore_roundtrip(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=3)
    tree = _tree(7)
    cm.save(7, tree, meta={"epoch": 1})
    version, restored, meta = cm.restore_latest(target=tree)
    assert version == 7 and meta == {"epoch": 1}
    _assert_trees_equal(tree, restored)


def test_keep_gc_and_latest(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=2)
    for v in (1, 2, 3, 4):
        cm.save(v, _tree(v))
    assert cm.versions() == [3, 4]
    version, restored, _ = cm.restore_latest(target=_tree(0))
    assert version == 4
    assert int(restored["step"]) == 4


def test_corrupt_latest_falls_back(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=3)
    cm.save(1, _tree(1))
    cm.save(2, _tree(2))
    # corrupt v2's payload after commit
    with open(str(tmp_path / "v_00000002" / "arrays.npz"), "wb") as f:
        f.write(b"garbage")
    version, restored, _ = cm.restore_latest(target=_tree(0))
    assert version == 1
    assert int(restored["step"]) == 1


def test_uncommitted_version_invisible(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=3)
    cm.save(1, _tree(1))
    # a half-written version: files but no MANIFEST
    vdir = tmp_path / "v_00000009"
    vdir.mkdir()
    (vdir / "arrays.npz").write_bytes(b"partial")
    assert cm.versions() == [1]
    version, _, _ = cm.restore_latest(target=_tree(0))
    assert version == 1


def test_missing_key_detected(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=3)
    cm.save(1, {"a": np.zeros(2)})
    try:
        cm.restore(1, target={"a": np.zeros(2), "b": np.zeros(2)})
        raise AssertionError("expected IOError")
    except IOError as e:
        assert "missing keys" in str(e)


def test_manifest_contents(tmp_path):
    cm = CheckpointManager(str(tmp_path), keep=3)
    cm.save(5, _tree(5))
    manifest = json.loads((tmp_path / "v_00000005" / "MANIFEST").read_text())
    assert manifest["version"] == 5 and manifest["nbytes"] > 0
