"""Checkpoint manager tests: versioned commit, corruption fallback, dtype
fidelity (incl. bfloat16), structured restore — parametrized over BOTH
storage backends: LocalFS (POSIX rename available) and GCSFS against the
in-tree fake GCS server (flat object namespace, NO rename — exercises the
manifest-last commit design on the store class it was designed for)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.runtime.checkpoint import CheckpointManager
from edl_tpu.runtime.fs import GCSFS, LocalFS


@pytest.fixture(params=["local", "gcs"])
def ckpt_fs(request, tmp_path):
    """(base_path, FileSystem) for each backend."""
    if request.param == "local":
        yield str(tmp_path), LocalFS()
    else:
        from edl_tpu.tools.fake_gcs import FakeGCSServer
        with FakeGCSServer() as srv:
            yield "gs://ckpt-bucket/job1/ckpt", GCSFS(endpoint=srv.endpoint)


def _cm(ckpt_fs, keep=3):
    base, fs = ckpt_fs
    return CheckpointManager(base, keep=keep, fs=fs)


def _tree(seed):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "dense": {"w": rng.randn(4, 3).astype(np.float32),
                      "b": np.zeros(3, np.float32)},
            "emb": rng.randn(10, 4).astype(np.float32),
        },
        "step": np.int32(seed),
        "bf16": jnp.ones((2, 2), jnp.bfloat16) * seed,
    }


def _assert_trees_equal(a, b):
    assert np.array_equal(np.asarray(a["step"]), np.asarray(b["step"]))
    np.testing.assert_array_equal(a["params"]["dense"]["w"],
                                  b["params"]["dense"]["w"])
    np.testing.assert_array_equal(np.asarray(a["bf16"], np.float32),
                                  np.asarray(b["bf16"], np.float32))
    assert np.asarray(b["bf16"]).dtype == np.asarray(a["bf16"]).dtype


def test_save_restore_roundtrip(ckpt_fs):
    cm = _cm(ckpt_fs)
    tree = _tree(7)
    cm.save(7, tree, meta={"epoch": 1})
    version, restored, meta = cm.restore_latest(target=tree)
    assert version == 7 and meta == {"epoch": 1}
    _assert_trees_equal(tree, restored)


def test_keep_gc_and_latest(ckpt_fs):
    cm = _cm(ckpt_fs, keep=2)
    for v in (1, 2, 3, 4):
        cm.save(v, _tree(v))
    assert cm.versions() == [3, 4]
    version, restored, _ = cm.restore_latest(target=_tree(0))
    assert version == 4
    assert int(restored["step"]) == 4


def test_corrupt_latest_falls_back(ckpt_fs):
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    cm.save(1, _tree(1))
    cm.save(2, _tree(2))
    # corrupt v2's payload after commit
    with fs.open(base + "/v_00000002/arrays.npz", "wb") as f:
        f.write(b"garbage")
    version, restored, _ = cm.restore_latest(target=_tree(0))
    assert version == 1
    assert int(restored["step"]) == 1


def test_uncommitted_version_invisible(ckpt_fs):
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    cm.save(1, _tree(1))
    # a half-written version: files but no MANIFEST (on GCS this is the
    # crash-mid-save state the manifest-last protocol exists for)
    fs.makedirs(base + "/v_00000009")
    with fs.open(base + "/v_00000009/arrays.npz", "wb") as f:
        f.write(b"partial")
    assert cm.versions() == [1]
    version, _, _ = cm.restore_latest(target=_tree(0))
    assert version == 1


def test_missing_key_detected(ckpt_fs):
    cm = _cm(ckpt_fs)
    cm.save(1, {"a": np.zeros(2)})
    try:
        cm.restore(1, target={"a": np.zeros(2), "b": np.zeros(2)})
        raise AssertionError("expected IOError")
    except IOError as e:
        assert "missing keys" in str(e)


def test_manifest_contents(ckpt_fs):
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    cm.save(5, _tree(5))
    with fs.open(base + "/v_00000005/MANIFEST", "r") as f:
        manifest = json.load(f)
    assert manifest["version"] == 5 and manifest["nbytes"] > 0


def _sharded_tree(seed):
    """A train-state-shaped tree with dp-sharded, replicated, bf16 and
    host-numpy leaves over the 8-device CPU mesh."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    rng = np.random.RandomState(seed)
    w = rng.randn(16, 4).astype(np.float32)
    moments = rng.randn(16, 4).astype(np.float32)
    bf = (rng.randn(8, 2) * seed).astype(np.float32)
    tree = {
        "params": {"w": jax.device_put(
            w, NamedSharding(mesh, P()))},            # replicated
        "opt": {"mu": jax.device_put(
            moments, NamedSharding(mesh, P("dp")))},  # zero1-style shard
        "bf16": jax.device_put(jnp.asarray(bf, jnp.bfloat16),
                               NamedSharding(mesh, P("dp"))),
        "step": np.int32(seed),                       # host leaf
    }
    host = {"params": {"w": w}, "opt": {"mu": moments},
            "bf16": bf, "step": np.int32(seed)}
    return tree, host


def _struct_target(tree):
    import jax
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       getattr(x, "dtype",
                                               np.asarray(x).dtype)),
        tree)


def test_sharded_save_restore_roundtrip(ckpt_fs):
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    tree, host = _sharded_tree(3)
    cm.save_sharded(3, tree, meta={"epoch": 0})
    with fs.open(base + "/v_00000003/MANIFEST", "r") as f:
        manifest = json.load(f)
    assert manifest["sharded"] is True and manifest["ranks"] == 1
    version, restored, meta = cm.restore_latest(
        target=_struct_target(tree))
    assert version == 3 and meta == {"epoch": 0}
    np.testing.assert_array_equal(restored["params"]["w"],
                                  host["params"]["w"])
    np.testing.assert_array_equal(restored["opt"]["mu"], host["opt"]["mu"])
    np.testing.assert_array_equal(
        np.asarray(restored["bf16"], np.float32),
        np.asarray(jnp.asarray(host["bf16"], jnp.bfloat16), np.float32))
    assert restored["bf16"].dtype == jnp.bfloat16
    assert int(restored["step"]) == 3


def test_sharded_sentinel_protocol_two_ranks(ckpt_fs):
    """The fs-visibility barrier: rank 1 (no coordination channel) must
    wait for rank 0's STARTED sentinel before writing, and rank 0 must
    wait for rank 1's shard file before committing the manifest."""
    import threading
    import time

    base, fs = ckpt_fs
    cm0, cm1 = _cm(ckpt_fs), _cm(ckpt_fs)
    tree, host = _sharded_tree(9)
    errs = []

    def rank1():
        try:
            cm1.save_sharded(9, {}, rank=1, nranks=2)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=rank1)
    t.start()
    time.sleep(0.3)  # rank 1 is polling for STARTED; nothing written yet
    assert not fs.exists(base + "/v_00000009/arrays.r1.npz")
    cm0.save_sharded(9, tree, meta={"k": 1}, rank=0, nranks=2)
    t.join(timeout=30)
    assert not t.is_alive() and not errs, errs
    with fs.open(base + "/v_00000009/MANIFEST", "r") as f:
        manifest = json.load(f)
    assert manifest["ranks"] == 2 and set(manifest["crcs"]) == {"0", "1"}
    version, restored, meta = cm0.restore_latest(
        target=_struct_target(tree))
    assert version == 9 and meta == {"k": 1}
    np.testing.assert_array_equal(restored["opt"]["mu"], host["opt"]["mu"])


def test_sharded_stale_sentinel_nonce_recovery(ckpt_fs):
    """A STARTED sentinel left by a crashed/older attempt at the SAME
    version must not pair the two attempts: rank 1 that joined the stale
    attempt rewrites its files under rank 0's fresh nonce, and the
    commit retires the sentinel + done markers (advisor r3, medium)."""
    import threading
    import time

    base, fs = ckpt_fs
    cm0, cm1 = _cm(ckpt_fs), _cm(ckpt_fs)
    tree, host = _sharded_tree(7)
    vdir = base + "/v_00000007"
    # simulate a crashed attempt: live stale sentinel, no MANIFEST
    fs.makedirs(vdir)
    with fs.open(vdir + "/STARTED", "w") as f:
        f.write("stalestalestale")
    errs = []

    def rank1():
        try:
            cm1.save_sharded(7, {}, rank=1, nranks=2, timeout=30)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=rank1)
    t.start()
    # rank 1 sees the stale sentinel and publishes against it
    deadline = time.time() + 10
    while not fs.exists(vdir + "/done.r1") and time.time() < deadline:
        time.sleep(0.02)
    assert fs.exists(vdir + "/done.r1")
    # now rank 0 starts the REAL attempt: reset + fresh nonce
    cm0.save_sharded(7, tree, meta={"k": 7}, rank=0, nranks=2,
                     timeout=30)
    t.join(timeout=30)
    assert not t.is_alive() and not errs, errs
    with fs.open(vdir + "/MANIFEST", "r") as f:
        manifest = json.load(f)
    assert manifest["ranks"] == 2 and set(manifest["crcs"]) == {"0", "1"}
    # protocol state is retired at commit
    assert not fs.exists(vdir + "/STARTED")
    assert not fs.exists(vdir + "/done.r0")
    assert not fs.exists(vdir + "/done.r1")
    version, restored, meta = cm0.restore_latest(
        target=_struct_target(tree))
    assert version == 7 and meta == {"k": 7}
    np.testing.assert_array_equal(restored["opt"]["mu"], host["opt"]["mu"])


def test_sharded_corrupt_rank_file_falls_back(ckpt_fs):
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    tree1, _ = _sharded_tree(1)
    tree2, _ = _sharded_tree(2)
    cm.save_sharded(1, tree1)
    cm.save_sharded(2, tree2)
    with fs.open(base + "/v_00000002/arrays.r0.npz", "wb") as f:
        f.write(b"garbage")
    version, restored, _ = cm.restore_latest(target=_struct_target(tree1))
    assert version == 1 and int(restored["step"]) == 1


def _shardings_for(mesh_devices, dp_axis=True):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(mesh_devices), ("dp",))
    repl = NamedSharding(mesh, P())
    dp = NamedSharding(mesh, P("dp")) if dp_axis else repl
    return {"params": {"w": repl}, "opt": {"mu": dp}, "bf16": dp,
            "step": repl}


def test_restore_placed_roundtrip_and_reshard(ckpt_fs):
    """Locality-aware restore: sharded files -> jax.Arrays assembled
    directly under the given shardings, including onto a DIFFERENT mesh
    than the one that saved (the stop-resume resize case), and from a
    dense file."""
    import jax

    cm = _cm(ckpt_fs)
    tree, host = _sharded_tree(5)
    cm.save_sharded(5, tree)
    target = _struct_target(tree)

    sh8 = _shardings_for(jax.devices()[:8])
    v, r8, _ = cm.restore_placed(5, target, sh8)
    assert v == 5
    np.testing.assert_array_equal(np.asarray(r8["opt"]["mu"]),
                                  host["opt"]["mu"])
    np.testing.assert_array_equal(np.asarray(r8["params"]["w"]),
                                  host["params"]["w"])
    assert r8["bf16"].dtype == jnp.bfloat16
    assert r8["opt"]["mu"].sharding.is_equivalent_to(
        sh8["opt"]["mu"], r8["opt"]["mu"].ndim)

    # resize: the 8-way-saved checkpoint restores onto a 4-device mesh
    sh4 = _shardings_for(jax.devices()[:4])
    v, r4, _ = cm.restore_placed(5, target, sh4)
    np.testing.assert_array_equal(np.asarray(r4["opt"]["mu"]),
                                  host["opt"]["mu"])
    assert int(r4["step"]) == 5

    # dense layout through the same API
    cm.save(6, host)
    v, r6, _ = cm.restore_placed(6, target, sh8)
    assert v == 6
    np.testing.assert_array_equal(np.asarray(r6["opt"]["mu"]),
                                  host["opt"]["mu"])


def test_restore_placed_rejects_oversized_and_tampered(ckpt_fs):
    """A stored tensor LARGER than the target must raise (silent
    truncation would train on corrupted weights), and a rank file whose
    bytes differ from what the manifest committed must fail the crc."""
    import io as io_mod

    import jax

    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    tree, host = _sharded_tree(2)
    cm.save_sharded(2, tree)
    sh = _shardings_for(jax.devices()[:8])
    small = _struct_target(tree)
    small["opt"]["mu"] = jax.ShapeDtypeStruct((8, 4), np.float32)  # <16
    with pytest.raises(IOError, match="shape mismatch"):
        cm.restore_placed(2, small, sh)

    cm.save(3, host)  # dense layout: same guard
    with pytest.raises(IOError, match="shape mismatch"):
        cm.restore_placed(3, small, sh)

    # valid-zip-but-wrong-bytes rank file: crc vs manifest must fail
    buf = io_mod.BytesIO()
    np.savez(buf, **{"params/w@0:16;0:4": np.ones((16, 4), np.float32)})
    with fs.open(base + "/v_00000002/arrays.r0.npz", "wb") as f:
        f.write(buf.getvalue())
    with pytest.raises(IOError, match="checksum mismatch"):
        cm.restore_placed(2, _struct_target(tree), sh)


def test_restore_placed_axis_changing_reshard(ckpt_fs):
    """Save sharded along dim 0, restore sharded along dim 1: every
    (saved-row-span x needed-col-block) pair PARTIALLY overlaps, so the
    2-D span intersection in paste() is what reassembles the tensor —
    the layout-change case (dp checkpoint onto a tp axis)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cm = _cm(ckpt_fs)
    tree, host = _sharded_tree(6)
    cm.save_sharded(6, tree)  # mu: (16, 4) split along dim 0 over dp=8

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("tp",))
    repl = NamedSharding(mesh4, P())
    col_sharded = NamedSharding(mesh4, P(None, "tp"))  # dim-1 split
    shardings = {"params": {"w": repl}, "opt": {"mu": col_sharded},
                 "bf16": repl, "step": repl}
    v, restored, _ = cm.restore_placed(6, _struct_target(tree), shardings)
    assert v == 6
    np.testing.assert_array_equal(np.asarray(restored["opt"]["mu"]),
                                  host["opt"]["mu"])
    assert restored["opt"]["mu"].sharding.is_equivalent_to(
        col_sharded, restored["opt"]["mu"].ndim)
    np.testing.assert_array_equal(
        np.asarray(restored["bf16"], np.float32),
        np.asarray(jnp.asarray(host["bf16"], jnp.bfloat16), np.float32))


def test_restore_placed_missing_key(ckpt_fs):
    from edl_tpu.runtime.checkpoint import MissingKeysError

    import jax

    cm = _cm(ckpt_fs)
    tree, _ = _sharded_tree(3)
    cm.save_sharded(3, {"params": tree["params"]})
    with pytest.raises(MissingKeysError):
        cm.restore_placed(3, _struct_target(tree),
                          _shardings_for(jax.devices()[:8]))


def test_clean_uncommitted_removes_crashed_attempts(ckpt_fs):
    """A SIGKILLed sharded save leaves an uncommitted dir whose STARTED
    sentinel would mis-order a later same-version save; the janitor
    (called by trainers at process start) removes it and never touches
    committed versions."""
    base, fs = ckpt_fs
    cm = _cm(ckpt_fs)
    tree, _ = _sharded_tree(1)
    cm.save_sharded(1, tree)
    fs.makedirs(base + "/v_00000002")
    with fs.open(base + "/v_00000002/STARTED", "w") as f:
        f.write("2")
    with fs.open(base + "/v_00000002/arrays.r1.npz", "wb") as f:
        f.write(b"partial")
    removed = cm.clean_uncommitted()
    assert removed == ["v_00000002"]
    assert cm.versions() == [1]
    assert not fs.exists(base + "/v_00000002/STARTED")
    assert cm.clean_uncommitted() == []  # idempotent


def test_sharded_missing_coverage_detected(ckpt_fs):
    from edl_tpu.runtime.checkpoint import MissingKeysError

    cm = _cm(ckpt_fs)
    tree, _ = _sharded_tree(4)
    cm.save_sharded(4, {"params": tree["params"]})
    target = _struct_target(tree)
    with pytest.raises(MissingKeysError):
        cm.restore(4, target=target)


def test_gcs_fs_primitives():
    """GCSFS exists/listdir/delete_tree semantics on the flat namespace."""
    from edl_tpu.tools.fake_gcs import FakeGCSServer
    with FakeGCSServer() as srv:
        fs = GCSFS(endpoint=srv.endpoint)
        assert fs.listdir("gs://b/x") == []
        assert not fs.exists("gs://b/x/file")
        with fs.open("gs://b/x/sub/file.txt", "w") as f:
            f.write("hello")
        with fs.open("gs://b/x/top.bin", "wb") as f:
            f.write(b"\x00\x01")
        assert fs.exists("gs://b/x/top.bin")
        assert fs.exists("gs://b/x")          # prefix-exists
        assert fs.exists("gs://b/x/sub")
        assert fs.listdir("gs://b/x") == ["sub", "top.bin"]
        with fs.open("gs://b/x/sub/file.txt", "r") as f:
            assert f.read() == "hello"
        with pytest.raises(FileNotFoundError):
            fs.open("gs://b/x/nope", "rb")
        fs.delete_tree("gs://b/x/sub")
        assert fs.listdir("gs://b/x") == ["top.bin"]
        with pytest.raises(NotImplementedError):
            fs.rename("gs://b/x/top.bin", "gs://b/x/y")


# -- stream range reads + chunk CRCs (the placed/peer restore data path) --


def test_stream_manifest_records_chunk_crcs(ckpt_fs, monkeypatch):
    from edl_tpu.runtime import checkpoint as ckpt_mod
    monkeypatch.setattr(ckpt_mod, "_CHUNK", 256)
    cm = _cm(ckpt_fs)
    arr = np.arange(50 * 16, dtype=np.float32).reshape(50, 16)  # 3200 B
    cm.save_async(1, {"w": arr, "empty": np.zeros((0, 4), np.float32)}
                  ).result(60.0)
    base, fs = ckpt_fs
    with fs.open(base + "/v_00000001/MANIFEST", "r") as f:
        manifest = json.load(f)
    entry = manifest["entries"]["w@0:50;0:16"]
    assert entry["chunk"] == 256
    assert len(entry["chunk_crcs"]) == (3200 + 255) // 256
    assert manifest["entries"]["empty@0:0;0:4"]["chunk_crcs"] == []


def test_read_entry_rows_range_read_and_crc_reject(ckpt_fs, monkeypatch):
    """_read_entry_rows fetches only the chunk-aligned byte range of the
    needed rows, verifies just those chunks' CRCs, and rejects a
    corrupted chunk inside the range."""
    from edl_tpu.runtime import checkpoint as ckpt_mod
    monkeypatch.setattr(ckpt_mod, "_CHUNK", 256)
    cm = _cm(ckpt_fs)
    arr = np.arange(50 * 16, dtype=np.float32).reshape(50, 16)
    cm.save_async(2, {"w": arr}).result(60.0)
    base, fs = ckpt_fs
    vdir = base + "/v_00000002"
    with fs.open(vdir + "/MANIFEST", "r") as f:
        entry = json.load(f)["entries"]["w@0:50;0:16"]
    path = "%s/%s" % (vdir, entry["file"])

    ranges = []
    orig = fs.read_range
    monkeypatch.setattr(
        fs, "read_range",
        lambda p, off, ln: ranges.append((off, ln)) or orig(p, off, ln))
    got = cm._read_entry_rows(path, entry, 7, 23)
    np.testing.assert_array_equal(got, arr[7:23])
    # rows 7..23 = bytes 448..1472 -> chunks 1..5 -> one 1280 B read
    assert ranges == [(256, 1280)]
    # row hull ending exactly on a chunk boundary: rows 4..8 = bytes
    # 256..512 = exactly chunk 1
    np.testing.assert_array_equal(cm._read_entry_rows(path, entry, 4, 8),
                                  arr[4:8])
    assert ranges[-1] == (256, 256)

    # corrupt one byte inside chunk 2 (bytes 512..768): a range read
    # touching it must fail the per-chunk crc, one missing it must not
    with fs.open(path, "rb") as f:
        raw = bytearray(f.read())
    raw[600] ^= 0xFF
    with fs.open(path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(IOError, match="checksum mismatch"):
        cm._read_entry_rows(path, entry, 7, 23)
    np.testing.assert_array_equal(cm._read_entry_rows(path, entry, 0, 4),
                                  arr[0:4])


def test_fill_placed_partial_blocks_uses_range_reads(ckpt_fs,
                                                     monkeypatch):
    """A process needing a strict row subset of a dense stream entry
    (the multi-host placed-restore case) reads only that range."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from edl_tpu.runtime import checkpoint as ckpt_mod
    monkeypatch.setattr(ckpt_mod, "_CHUNK", 256)
    cm = _cm(ckpt_fs)
    arr = np.arange(64 * 16, dtype=np.float32).reshape(64, 16)
    cm.save_async(3, {"w": arr}).result(60.0)

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    pt = ckpt_mod.PlacedTarget({"w": jax.ShapeDtypeStruct(arr.shape,
                                                          arr.dtype)},
                               {"w": sh})
    # keep only devices 2 and 3's blocks (rows 16..32): emulates the
    # remote ranks of a multi-process restore owning the rest
    _, _, _, blocks, dev_spans = pt.need["w"]
    keep = {spans for dev, spans in dev_spans.items()
            if spans[0][0] in (16, 24)}
    pt.need["w"] = (pt.need["w"][0], pt.need["w"][1], pt.need["w"][2],
                    {s: b for s, b in blocks.items() if s in keep},
                    {d: s for d, s in dev_spans.items() if s in keep})

    base, fs = ckpt_fs
    ranges = []
    orig = fs.read_range
    monkeypatch.setattr(
        fs, "read_range",
        lambda p, off, ln: ranges.append((off, ln)) or orig(p, off, ln))
    cm.fill_placed_from_fs(3, pt, keys={"w"})
    assert not pt.missing()
    for spans, blk in pt.need["w"][3].items():
        np.testing.assert_array_equal(blk[0],
                                      arr[spans[0][0]:spans[0][1]])
    # rows 16..32 = bytes 1024..2048: exactly 1 KiB of the 4 KiB file
    assert ranges == [(1024, 1024)]


def test_dense_sharded_stream_cross_restore_bit_identical(ckpt_fs):
    """The same state saved through BOTH stream engines (dense
    save_async and per-rank save_sharded_async) restores bit-identically
    onto resharded placed targets."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cm = _cm(ckpt_fs)
    tree, host = _sharded_tree(11)
    cm.save_async(1, tree, meta={"src": "dense"}).result(60.0)
    h = cm.save_sharded_async(2, tree, meta={"src": "sharded"})
    h.wait(60.0)
    assert h.exception() is None

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("dp",))
    shardings = {"params": {"w": NamedSharding(mesh4, P())},
                 "opt": {"mu": NamedSharding(mesh4, P("dp"))},
                 "bf16": NamedSharding(mesh4, P("dp")),
                 "step": NamedSharding(mesh4, P())}
    target = _struct_target(tree)
    v1, from_dense, m1 = cm.restore_placed(1, target, shardings)
    v2, from_sharded, m2 = cm.restore_placed(2, target, shardings)
    assert m1 == {"src": "dense"} and m2 == {"src": "sharded"}
    for a, b in zip(jax.tree_util.tree_leaves(from_dense),
                    jax.tree_util.tree_leaves(from_sharded)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(from_dense["opt"]["mu"]),
                                  host["opt"]["mu"])
    np.testing.assert_array_equal(
        np.asarray(from_dense["bf16"], np.float32),
        np.asarray(jnp.asarray(host["bf16"], jnp.bfloat16), np.float32))


def test_restore_placed_rejects_corrupted_stream_chunk(ckpt_fs):
    cm = _cm(ckpt_fs)
    arr = np.arange(32 * 8, dtype=np.float32).reshape(32, 8)
    cm.save_async(5, {"w": arr}).result(60.0)
    base, fs = ckpt_fs
    vdir = base + "/v_00000005"
    with fs.open(vdir + "/MANIFEST", "r") as f:
        entry = json.load(f)["entries"]["w@0:32;0:8"]
    path = "%s/%s" % (vdir, entry["file"])
    with fs.open(path, "rb") as f:
        raw = bytearray(f.read())
    raw[10] ^= 0xFF
    with fs.open(path, "wb") as f:
        f.write(bytes(raw))
    import jax
    sh = {"w": jax.sharding.SingleDeviceSharding(jax.devices()[0])}
    with pytest.raises(IOError):
        cm.restore_placed(5, {"w": jax.ShapeDtypeStruct(arr.shape,
                                                        arr.dtype)}, sh)
