"""Static perf-accounting regression pins (VERDICT r4 item 2).

Every perf lever claims something about flops / bytes / live memory.
These tests pin the STATIC side of each claim so a lever cannot
silently regress in a session where the TPU tunnel is dead:

- BN subset statistics: pinned at the jaxpr level (backend-free) — the
  traced loss must actually subsample the stats reads.
- dense-vs-blockwise attention: pinned on compiled memory growth —
  dense temp memory is quadratic in sequence length, blockwise (the
  flash kernel's semantic twin) is linear.
- fused multi-step: pinned on compiled memory — scanning K train steps
  into one executable must not inflate live memory.
- the TPU compiler itself: `tools/perf_accounting.py` AOT-compiles the
  real steps against a deviceless v5e topology (libtpu's own compiler)
  and writes PERF_ACCOUNTING.json; the pin here asserts that path stays
  alive and that the hardware cost model still sees the bn win.

Caveat recorded once: XLA's *CPU* cost model inverts some TPU claims
(it materializes the strided BN subset, so bn4 shows MORE bytes on
CPU), which is why the BN pin reads the jaxpr and the hardware pin
uses the TPU AOT path rather than CPU `cost_analysis()`.
"""

import jax
import jax.numpy as jnp
import pytest

from edl_tpu.tools import perf_accounting as pa


# -- BN subset statistics (jaxpr, backend-free) ---------------------------


def test_bn_subset_stats_are_structural():
    """bn_stats_every=4 must subsample EVERY BatchNorm's statistics
    input by exactly 4x; full-batch mode must subsample nothing."""
    acc4 = pa.bn_structural_account(4, batch=32, image_size=96)
    # one stats gather per BN site; ResNet50_vd has 53 BNs (+2 from the
    # stem path) — losing sites means some BN stopped subsampling
    assert acc4["stat_subset_sites"] >= 50, acc4
    assert acc4["stats_read_bytes_full"] > 0
    # the saving is exactly 1 - 1/k of the stats reads, by construction
    frac = acc4["stats_bytes_saved"] / acc4["stats_read_bytes_full"]
    assert abs(frac - 0.75) < 1e-6, acc4

    acc1 = pa.bn_structural_account(1, batch=32, image_size=96)
    assert acc1["stat_subset_sites"] == 0, \
        "full-batch stats must not emit subset gathers"


def test_bn_subset_full_scale_account_matches_claim():
    """At the bench shape (batch 128 @ 224) the structural account must
    keep finding the full 2.29 GB/step of stats-input bytes removed —
    the UPPER BOUND of the lever if the subset fused (the TPU compiler
    says it does not; see the bn-tradeoff pin below). A drop here means
    some BN stopped subsetting, independent of the fusion question."""
    acc = pa.bn_structural_account(4, batch=128, image_size=224)
    assert acc["stats_bytes_saved"] >= 2.0e9, acc
    assert acc["est_ms_saved_at_hbm"] >= 2.4, acc


# -- attention memory complexity (compiled, CPU) --------------------------


def _attn_temp(seq, impl):
    out = pa.attention_account(jax.devices("cpu"), seq, impl)
    return out["temp_bytes"], out["flops"]


def test_dense_attention_temp_is_quadratic_blockwise_linear():
    d1, f1 = _attn_temp(512, "dense")
    d2, f2 = _attn_temp(1024, "dense")
    d4, f4 = _attn_temp(2048, "dense")
    # doubling seq must ~4x the dense temp (the s x s scores) and flops
    assert 3.0 < d2 / d1 < 5.5, (d1, d2)
    assert 3.0 < d4 / d2 < 5.5, (d2, d4)
    assert 3.4 < f2 / f1 < 4.6, (f1, f2)

    b1, _ = _attn_temp(512, "block")
    b2, _ = _attn_temp(1024, "block")
    b4, _ = _attn_temp(2048, "block")
    # blockwise live memory grows linearly: ~2x per doubling
    assert b2 / b1 < 2.7, (b1, b2)
    assert b4 / b2 < 2.7, (b2, b4)


def test_flash_backward_memory_is_linear():
    """The FA2-style _flash_bwd (r5) must stay O(seq) in live memory —
    the previous backward (vjp of the blockwise forward) was O(seq^2)
    and at 8k cost MORE temp than dense. 4x the sequence must cost
    ~4x the temp (quadratic would be 16x)."""
    import jax.numpy as jnp

    from edl_tpu.ops import flash_attention as fa

    def temp_at(seq):
        s = jax.ShapeDtypeStruct((1, 12, seq, 64), jnp.bfloat16)

        def bwd(q, k, v, out, g):
            return fa._flash_bwd(q, k, v, out, g, True, 64 ** -0.5)
        comp = jax.jit(bwd).lower(s, s, s, s, s).compile()
        return comp.memory_analysis().temp_size_in_bytes

    t2k, t8k = temp_at(2048), temp_at(8192)
    assert t8k / t2k < 5.5, (t2k, t8k)


def test_dense_attention_memory_crossover_at_long_seq():
    """By 8k tokens the s x s scores dominate everything else: the
    dense forward needs several times the blockwise live memory (the
    reason flash/blockwise is the long-context default)."""
    dense = pa.attention_account(jax.devices("cpu"), 8192, "dense",
                                 grad=False)
    block = pa.attention_account(jax.devices("cpu"), 8192, "block",
                                 grad=False)
    assert dense["temp_bytes"] > 2.0 * block["temp_bytes"], \
        (dense["temp_bytes"], block["temp_bytes"])


# -- fused multi-step memory (compiled, CPU) ------------------------------


@pytest.mark.integration
def test_multistep_scan_adds_no_live_memory():
    """lax.scan of 4 train steps in one executable must cost ~no extra
    temp memory over a single step (the lever buys 4x fewer dispatches;
    it must not pay for them in HBM headroom)."""
    devs = jax.devices("cpu")
    one = pa.multistep_account(devs, 1, batch=16, image_size=64)
    four = pa.multistep_account(devs, 4, batch=16, image_size=64)
    assert four["temp_bytes"] <= one["temp_bytes"] * 1.25, (one, four)


# -- the TPU AOT accounting path itself -----------------------------------


def _tpu_topology_or_skip():
    try:
        return pa.v5e_devices()
    except Exception as e:  # noqa: BLE001
        pytest.skip("no local libtpu AOT compiler: %r" % e)


@pytest.mark.integration
def test_tpu_compiler_accounts_bn_tradeoff():
    """The REAL TPU compiler (libtpu AOT against a deviceless v5e
    topology — no tunnel, no chips) accounts the bn subset-stats
    tradeoff. FINDING (r5, PERF_ACCOUNTING.json): the subset slice
    BREAKS the conv->stats reduce fusion, so bn4 costs MORE bytes
    accessed than bn1 (full-batch stats fuse into the conv and read
    nothing extra) — the opposite of the r3 profile-era hypothesis,
    and why bench.py's default stays bn1. This pin keeps the AOT
    accounting path alive and bounds the regime: flops must not grow
    (subsetting adds no compute), bytes must stay within 2.2x (a
    runaway regression in either implementation trips it), and an
    implementation that ever makes bn4 CHEAPER in bytes shows up as a
    ratio < 1 here — re-evaluate the bench default then."""
    devices = _tpu_topology_or_skip()
    bn1 = pa.resnet_bn_account(devices, 1, batch=32, image_size=96)
    bn4 = pa.resnet_bn_account(devices, 4, batch=32, image_size=96)
    assert bn4["flops"] < bn1["flops"] * 1.02, (bn1, bn4)
    ratio = bn4["bytes_accessed"] / bn1["bytes_accessed"]
    assert 0.3 < ratio < 2.2, (bn1, bn4)


# -- bandwidth roofline (pure arithmetic, r5 measured profile) ------------


def test_roofline_account_is_internally_consistent():
    """Pin the roofline artifact (tools/roofline_resnet.py): the
    activation-pass accounting that closes the MFU question (r5:
    measured 52.4 ms step is within ~10% of the v5e bandwidth+MXU
    roofline) must stay arithmetically coherent — 55 BN input maps on
    resnet50_vd, a ~3 GB streaming pass at batch 128, a non-conv
    tail measured in single-digit pass counts, and a roofline the
    measured wall time can never legally undercut. Asserts the tool's
    OWN account() (one derivation, no formula drift between the
    artifact and this pin)."""
    from edl_tpu.tools import roofline_resnet as rl

    a = rl.account()
    assert a["n_bn"] == 55
    assert 2.5 < a["one_pass_gb"] < 3.5, a["one_pass_gb"]
    assert 5.0 < a["nonconv_passes"] < 12.0, a["nonconv_passes"]
    assert a["conv_floor_ms"] < a["conv_ms"]
    assert 50.0 < a["mxu_during_conv_pct"] < 100.0
    assert rl.MEASURED_WALL_MS >= a["roofline_ms"], (
        "wall time undercuts the roofline — re-derive the account")
    assert 0.0 <= a["headroom_pct"] < 25.0, a["headroom_pct"]


def test_bench_best_tpu_pointer_file_is_valid():
    """BENCH_BEST_TPU.json feeds bench.py's dead-tunnel fallback JSON
    (last_tpu_measured) — keep it parseable, keyed by bench model
    names, and shaped like a bench record so the embedded pointer is
    directly comparable with the live metric line."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "BENCH_BEST_TPU.json")
    with open(path) as f:
        best = json.load(f)
    assert best, "pointer file is empty — the fallback embed is dead"
    assert set(best) <= {"resnet", "gpt", "bert"}, set(best)
    for model, rec in best.items():
        for key in ("metric", "value", "unit", "measured", "source"):
            assert key in rec, (model, key)
        assert rec["value"] > 0


@pytest.mark.integration
def test_lm_batch_arithmetic_intensity_rises_with_batch():
    """The static basis of the r5e LM batch sweep: growing the batch
    multiplies activation flops while the adamw state traffic stays
    constant, so flops-per-byte must rise — the compiler-accounted
    reason batch 8 (the measured 59k tok/s config) sits at low MFU.
    Pinned at a small GPT config to keep the AOT compile cheap; the
    full-size account lives in PERF_ACCOUNTING.json."""
    devices = _tpu_topology_or_skip()
    b2 = pa.lm_batch_account(devices, 2, num_layers=4, d_model=256,
                             seq=256, vocab=1024)
    b8 = pa.lm_batch_account(devices, 8, num_layers=4, d_model=256,
                             seq=256, vocab=1024)
    assert 3.0 < b8["flops"] / b2["flops"] < 5.0, (b2, b8)
    assert b8["bytes_accessed"] < b2["bytes_accessed"] * 3.5, (b2, b8)
    assert b8["flops_per_byte"] > b2["flops_per_byte"] * 1.15, (b2, b8)
