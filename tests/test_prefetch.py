"""Device-prefetch tests: order/content fidelity, error surfacing, early
close, and use inside a training loop over the dp mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.data.prefetch import DevicePrefetcher
from edl_tpu.runtime.mesh import data_sharding, make_mesh


def _batches(n, d=4):
    for i in range(n):
        yield {"x": np.full((8, d), i, np.float32),
               "i": np.full((8,), i, np.int32)}


def test_prefetch_order_and_content():
    mesh = make_mesh()
    sh = data_sharding(mesh)
    with DevicePrefetcher(_batches(7), sh, size=3) as it:
        seen = [int(b["i"][0]) for b in it]
    assert seen == list(range(7))


def test_prefetch_transform_and_sharding():
    mesh = make_mesh()
    sh = data_sharding(mesh)
    it = DevicePrefetcher(_batches(3), sh, size=2,
                          transform=lambda b: {"x": b["x"] * 2.0,
                                               "i": b["i"]})
    out = list(it)
    assert float(out[1]["x"][0, 0]) == 2.0
    assert out[0]["x"].sharding.is_equivalent_to(sh, 2)


def test_prefetch_surfaces_producer_error():
    def boom():
        yield {"x": np.zeros((8, 4), np.float32)}
        raise RuntimeError("producer died")

    mesh = make_mesh()
    it = DevicePrefetcher(boom(), data_sharding(mesh))
    next(it)
    with pytest.raises(RuntimeError, match="producer died"):
        next(it)


def test_prefetch_close_unblocks_producer():
    produced = []

    def slow_infinite():
        i = 0
        while True:
            produced.append(i)
            yield {"x": np.zeros((8, 4), np.float32)}
            i += 1

    mesh = make_mesh()
    it = DevicePrefetcher(slow_infinite(), data_sharding(mesh), size=2)
    next(it)
    it.close()  # must not hang; producer parked on a bounded queue
    assert len(produced) < 10


def test_prefetch_iterator_contract_after_exhaustion_and_close():
    mesh = make_mesh()
    sh = data_sharding(mesh)
    it = DevicePrefetcher(_batches(2), sh)
    assert len(list(it)) == 2
    with pytest.raises(StopIteration):
        next(it)          # repeated next() must keep raising, not hang
    with pytest.raises(StopIteration):
        next(it)
    it2 = DevicePrefetcher(_batches(5), sh)
    next(it2)
    it2.close()
    with pytest.raises(StopIteration):
        next(it2)         # closed → StopIteration, not a blocked get()


def test_prefetch_feeds_training_loop():
    mesh = make_mesh()
    sh = data_sharding(mesh)
    w = jnp.zeros((4,), jnp.float32)

    @jax.jit
    def step(w, batch):
        return w + batch["x"].mean(axis=0)

    with DevicePrefetcher(_batches(5), sh, size=2) as it:
        for batch in it:
            w = step(w, batch)
    np.testing.assert_allclose(np.asarray(w), np.full((4,), 10.0))


def test_prefetch_pump_error_chained_with_original_traceback():
    """The re-raised pump exception is a NEW instance of the same type
    whose __cause__ is the ORIGINAL (with the pump thread's traceback)
    — so the consumer sees both its own call site and where in the
    input pipeline things actually blew up."""
    mesh = make_mesh()

    class FeedError(ValueError):
        pass

    def boom():
        yield {"x": np.zeros((8, 2), np.float32)}
        raise FeedError("bad shard spec")

    it = DevicePrefetcher(boom(), data_sharding(mesh))
    next(it)
    with pytest.raises(FeedError) as ei:
        next(it)
    assert ei.value.args == ("bad shard spec",)
    cause = ei.value.__cause__
    assert isinstance(cause, FeedError) and cause is not ei.value
    assert cause.__traceback__ is not None
    frames = []
    tb = cause.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "boom" in frames  # the producer frame survived the hop
    it.close()


def test_prefetch_pump_error_exotic_signature_wrapped():
    """Exception types that cannot be rebuilt from .args (required
    keyword ctor) degrade to a RuntimeError wrapper — still chained to
    the original, never a secondary TypeError."""
    mesh = make_mesh()

    class Picky(Exception):
        def __init__(self, *, code):
            super().__init__("code=%d" % code)
            self.code = code

    def boom():
        if False:
            yield
        raise Picky(code=7)

    it = DevicePrefetcher(boom(), data_sharding(mesh))
    with pytest.raises(RuntimeError, match="device prefetch pump") as ei:
        next(it)
    assert isinstance(ei.value.__cause__, Picky)
    assert ei.value.__cause__.code == 7
    it.close()


def test_prefetch_close_is_idempotent_and_joins():
    mesh = make_mesh()

    def slow_infinite():
        import itertools
        import time
        for i in itertools.count():
            time.sleep(0.01)
            yield {"x": np.full((8, 2), i, np.float32)}

    it = DevicePrefetcher(slow_infinite(), data_sharding(mesh), size=2)
    next(it)
    it.close()
    assert not it._thread.is_alive()
    it.close()  # second close: no-op, no error, thread still dead
    it.close()
    assert not it._thread.is_alive()
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_stats_overlap_accounting():
    """stats() splits the pipeline's blocked time onto the two sides:
    a slow host iterator shows up as pump_wait_s (step-bound input),
    and the batch count matches what the consumer actually saw."""
    import time

    def slow_batches(n):
        for i in range(n):
            time.sleep(0.02)
            yield {"x": np.full((8, 4), i, np.float32)}

    mesh = make_mesh()
    with DevicePrefetcher(slow_batches(5), data_sharding(mesh),
                          size=2) as it:
        assert len(list(it)) == 5
        s = it.stats()
    assert s["batches"] == 5
    assert s["pump_wait_s"] >= 5 * 0.02 * 0.8  # the host iter was slow
    assert s["consumer_wait_s"] >= 0.0
