"""Roofline cost-model tests: factorization enumeration/legality, the
analytic span-overlap reshard bytes, the scorer, and the pin that the
cluster generator's mesh plan IS the roofline top score."""

import pytest

from edl_tpu.parallel import costmodel


def _profile(**kw):
    kw.setdefault("n_layers", 8)
    kw.setdefault("d_model", 1024)
    kw.setdefault("n_heads", 16)
    kw.setdefault("seq_len", 512)
    return costmodel.transformer_profile(**kw)


def test_candidate_factorizations_cover_the_world():
    for f in costmodel.candidate_factorizations(8):
        assert f["dp"] * f["tp"] * f["pp"] * f["ep"] == 8
    caps = costmodel.candidate_factorizations(8, max_tp=2, max_pp=1,
                                              max_ep=1)
    assert all(f["tp"] <= 2 and f["pp"] == 1 and f["ep"] == 1
               for f in caps)
    assert {f["tp"] for f in caps} == {1, 2}


def test_legality_reasons():
    prof = _profile(n_heads=6, n_experts=0)
    ok = {"dp": 2, "tp": 2, "pp": 2, "ep": 1}
    assert costmodel.legality_reason(ok, prof, total_batch=16) is None
    assert "batch" in costmodel.legality_reason(
        dict(ok, dp=3), prof, total_batch=16)
    assert "heads" in costmodel.legality_reason(
        dict(ok, tp=4), prof, total_batch=16)
    assert "layers" in costmodel.legality_reason(
        dict(ok, pp=3), prof, total_batch=18)
    # no experts in the profile -> any ep>1 is illegal
    assert "experts" in costmodel.legality_reason(
        dict(ok, ep=2), prof, total_batch=16)


def test_device_spans_row_major():
    axes = {"dp": 2, "tp": 2}
    spans = costmodel.device_spans((8, 8), ("dp", "tp"), axes)
    # row-major: device = dp_coord * tp + tp_coord
    assert spans[0] == ((0, 4), (0, 4))
    assert spans[1] == ((0, 4), (4, 8))
    assert spans[2] == ((4, 8), (0, 4))
    assert spans[3] == ((4, 8), (4, 8))
    # absent / size-1 axes in a spec are ignored, not an error
    spans = costmodel.device_spans((8,), ("sp",), axes)
    assert all(s == ((0, 8),) for s in spans.values())


def test_tree_reshard_bytes_zero_wire_and_partial():
    src = costmodel.mesh_axes({"dp": 4})
    dst = costmodel.mesh_axes({"dp": 2, "tp": 2})
    # replicated and tp-sharded leaves slice locally on a dp -> dp x tp
    # transition (the source held everything / tp was size 1): zero wire
    moved, needed = costmodel.tree_reshard_bytes(
        [((16, 16), 4, (), ()),
         ((16, 16), 4, (None, "tp"), (None, "tp"))], src, dst)
    assert moved == 0
    assert needed > 0
    # a dp-sharded moment re-rows: each target device owns 8 rows but
    # held 4 under dp=4 -> 4 rows x 16 cols x 4 B x 4 devices move
    moved, needed = costmodel.tree_reshard_bytes(
        [((16, 16), 4, ("dp",), ("dp",))], src, dst)
    assert moved == 4 * 16 * 4 * 4
    assert needed == 8 * 16 * 4 * 4
    assert moved < needed


def test_step_time_penalizes_needless_model_parallelism():
    """With a batch big enough for pure dp, flat dp must outscore a tp
    mesh on a small dense model (the collectives only cost)."""
    prof = _profile()
    ranked = costmodel.score_factorizations(8, prof, total_batch=64)
    assert ranked, "no legal factorization"
    assert ranked[0]["dp"] == 8
    assert ranked[0]["score"] <= ranked[-1]["score"]


def test_small_batch_forces_model_parallelism():
    """total_batch=4 on world 8: dp>4 is illegal, so the top choice
    must spend the rest of the world on model axes."""
    prof = _profile()
    best = costmodel.best_factorization(8, prof, total_batch=4)
    assert best is not None
    assert best["dp"] <= 4
    assert best["tp"] * best["pp"] * best["ep"] == 8 // best["dp"]


def test_score_includes_reshard_cost_from_current():
    """Moving away from the current mesh costs wire seconds: with a
    tiny amortization window, keeping the current factorization must
    beat an equal-step-time move."""
    prof = _profile()
    cur = {"dp": 4, "tp": 2, "pp": 1, "ep": 1}
    ranked = costmodel.score_factorizations(
        8, prof, total_batch=64, current=cur, amortize_steps=1e-6)
    stay = next(r for r in ranked
                if all(r[k] == cur[k] for k in cur))
    assert stay["reshard_bytes"] == 0
    assert ranked[0] is stay


def test_planner_remembers_its_previous_choice():
    prof = _profile()
    plan = costmodel.make_planner(prof, total_batch=64)
    first = plan(8)
    assert first == {k: costmodel.best_factorization(
        8, prof, 64)[k] for k in ("dp", "tp", "pp", "ep")}
    # the second call scores the move FROM the first choice
    second = plan(4)
    want = costmodel.best_factorization(4, prof, 64, current=first)
    assert second == {k: want[k] for k in ("dp", "tp", "pp", "ep")}


def test_generator_mesh_plan_matches_roofline_top_score():
    """The acceptance pin: for two world sizes, the cluster generator's
    committed mesh (Generator._plan_mesh with a costmodel planner) IS
    the roofline top score for that world, reshard cost included."""
    from edl_tpu.controller import cluster as cluster_mod
    from edl_tpu.controller.cluster_generator import Generator

    prof = _profile()
    gen = Generator.__new__(Generator)
    gen._mesh_planner = costmodel.make_planner(prof, total_batch=16)

    def cluster_of(world):
        c = cluster_mod.Cluster()
        pod = type("PodStub", (), {})()
        pod.trainers = []
        pod.devices = list(range(world))
        c.pods = [pod]
        return c

    current = None
    cur_factors = None
    for world in (8, 4):
        new = cluster_of(world)
        gen._plan_mesh(new, current)
        want = costmodel.best_factorization(world, prof, 16,
                                            current=cur_factors)
        assert new.mesh == {k: want[k] for k in ("dp", "tp", "pp", "ep")}
        current, cur_factors = new, new.mesh


def test_generator_mesh_plan_fails_open():
    from edl_tpu.controller import cluster as cluster_mod
    from edl_tpu.controller.cluster_generator import Generator

    gen = Generator.__new__(Generator)
    gen._mesh_planner = lambda world, current=None: 1 / 0
    new = cluster_mod.Cluster()
    pod = type("PodStub", (), {})()
    pod.trainers = []
    pod.devices = [0, 1]
    new.pods = [pod]
    gen._plan_mesh(new, None)  # must not raise
    assert new.mesh is None


def test_reshard_cost_is_zero_when_staying_put():
    prof = _profile()
    f = {"dp": 4, "tp": 2, "pp": 1, "ep": 1}
    assert costmodel.reshard_cost_bytes(prof, f, f) == 0
    assert costmodel.reshard_cost_bytes(prof, None, f) == 0
    moved = costmodel.reshard_cost_bytes(
        prof, {"dp": 8, "tp": 1, "pp": 1, "ep": 1}, f)
    assert moved > 0


def test_step_time_breakdown_fields():
    prof = _profile(n_experts=8)
    t = costmodel.step_time_s({"dp": 2, "tp": 2, "pp": 2, "ep": 1},
                              prof, total_batch=16)
    for k in ("total_s", "compute_s", "hbm_s", "bubble", "dp_s",
              "tp_s", "pp_s", "ep_s"):
        assert k in t
    assert t["total_s"] > 0
    assert t["bubble"] == pytest.approx(
        1.0 + 1.0 / costmodel.PIPELINE_MICROBATCHES)
