"""Sharded embedding plane (edl_tpu.embed): span ownership, dedup'd
coalesced gathers, the hot-key cache tier (hit/evict/version-fence),
write-back vs a single-host reference optimizer step, mid-resize
reshard byte-identity, the chaos drills (a faulted gather degrades
losslessly), the DeepFM sparse/dense parity contract, and the
embed_wait ledger + job_doctor wiring."""

import numpy as np
import pytest

from edl_tpu.embed import (EmbedPlaneClient, EmbedPrefetcher,
                           EmbedShardServer, TableSpec)
from edl_tpu.embed import cache as cache_mod
from edl_tpu.embed import sharding
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.rpc.pool import ClientPool
from edl_tpu.utils import errors

VOCAB, DIM = 120, 4


# ---------------------------------------------------------------------------
# sharding: ownership is a pure function of the member-id SET


def test_row_spans_deterministic_under_shuffle():
    members = ["pod-c", "pod-a", "pod-b", "pod-d"]
    spans = sharding.row_spans(1000, members)
    for shuffled in (members[::-1], sorted(members),
                     ["pod-b", "pod-d", "pod-a", "pod-c"]):
        assert sharding.row_spans(1000, shuffled) == spans
    # contiguous, ordered, tiling [0, vocab)
    ordered = [spans[m] for m in sorted(spans)]
    assert ordered[0][0] == 0 and ordered[-1][1] == 1000
    for (_, hi), (lo, _) in zip(ordered, ordered[1:]):
        assert hi == lo


def test_row_spans_more_members_than_rows():
    spans = sharding.row_spans(3, ["a", "b", "c", "d", "e"])
    held = [m for m, (lo, hi) in spans.items() if hi > lo]
    assert len(held) == 3
    for m in set(spans) - set(held):
        assert spans[m][0] == spans[m][1]  # empty, not invalid


def test_owner_index_matches_span_containment():
    members = ["m0", "m1", "m2"]
    spans = sharding.row_spans(VOCAB, members)
    keys = np.arange(VOCAB)
    idx = sharding.owner_index(keys, VOCAB, len(members))
    for k, i in zip(keys, idx):
        lo, hi = spans[sorted(members)[int(i)]]
        assert lo <= k < hi


def test_partition_by_owner_contiguous_runs():
    members = ["b", "a", "c"]
    keys = np.array([0, 1, 41, 59, 80, 119])
    parts = sharding.partition_by_owner(keys, VOCAB, members)
    rebuilt = np.concatenate([ks for _, ks in parts])
    assert np.array_equal(rebuilt, keys)
    spans = sharding.row_spans(VOCAB, members)
    for owner, ks in parts:
        lo, hi = spans[owner]
        assert ks.min() >= lo and ks.max() < hi


def test_reshard_moves_tiles_new_span():
    old = ["a", "b"]
    new = ["a", "b", "c"]
    for me in new:
        new_span, keep, pulls = sharding.reshard_moves(VOCAB, old, new,
                                                       me)
        covered = []
        if keep is not None:
            covered.append(keep)
        covered += [span for _, span in pulls]
        covered.sort()
        assert covered[0][0] == new_span[0]
        assert covered[-1][1] == new_span[1]
        for (_, hi), (lo, _) in zip(covered, covered[1:]):
            assert hi == lo  # no gaps, no overlaps


# ---------------------------------------------------------------------------
# fixtures: a tiny live fleet


@pytest.fixture
def fleet():
    tables = {"ctr": TableSpec(VOCAB, DIM, seed=11)}
    members = ["a", "b"]
    servers = {m: EmbedShardServer(m, tables, members) for m in members}
    pool = ClientPool(timeout=10.0)
    yield servers, pool, tables
    for s in servers.values():
        s.stop()
    pool.close()


def _endpoints(servers):
    return {m: s.endpoint for m, s in servers.items()}


def _reference_table(spec):
    return spec.materialize(0, spec.vocab)


# ---------------------------------------------------------------------------
# dedup / scatter round-trip


def test_dedup_scatter_roundtrip_exact(fleet):
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"])
    client = EmbedPlaneClient(pool, _endpoints(servers),
                              cache_entries=16)
    keys = np.array([5, 61, 5, 0, 119, 61, 5, 7])  # dups across owners
    rows = client.lookup("ctr", keys)
    assert rows.shape == (len(keys), DIM)
    assert np.array_equal(rows, ref[keys])
    # and again, now largely cache-served — still exact
    assert np.array_equal(client.lookup("ctr", keys), ref[keys])
    assert client.cache().stats()["hits"] > 0


def test_naive_client_same_rows(fleet):
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"])
    naive = EmbedPlaneClient(pool, _endpoints(servers),
                             client_id="naive", dedup=False)
    keys = np.array([3, 3, 77, 118, 0])
    assert np.array_equal(naive.lookup("ctr", keys), ref[keys])
    assert naive.stats()["unique_key_frac"] == 1.0  # no dedup by design


# ---------------------------------------------------------------------------
# cache tier semantics


def test_cache_lru_hit_then_evict():
    c = cache_mod.HotKeyCache(2)
    rows = np.arange(8, dtype=np.float32).reshape(4, 2)
    c.put_many("t", [1, 2], rows[:2], version=1)
    hits, miss = c.get_many("t", np.array([1, 2, 3]))
    assert set(hits) == {1, 2} and list(miss) == [False, False, True]
    # inserting 3 evicts the LRU entry: the get refreshed 1 then 2 in
    # order, so 1 is now least-recent and goes first
    c.put_many("t", [3], rows[2:3], version=1)
    hits, _ = c.get_many("t", np.array([1, 2, 3]))
    assert set(hits) == {2, 3}
    assert c.stats()["evictions"] == 1


def test_cache_version_guard_rejects_stale_put():
    c = cache_mod.HotKeyCache(4)
    new = np.ones((1, 2), np.float32)
    old = np.zeros((1, 2), np.float32)
    c.put_many("t", [7], new, version=5)
    c.put_many("t", [7], old, version=3)  # late prefetch: must lose
    hits, _ = c.get_many("t", np.array([7]))
    assert np.array_equal(hits[7], new[0])


def test_cache_write_through_matches_server_math():
    c = cache_mod.HotKeyCache(4)
    row = np.array([[1.0, 2.0]], np.float32)
    c.put_many("t", [7], row, version=1)
    delta = np.array([[0.25, -0.5]], np.float32)
    c.apply_update("t", [7], delta, version=2)
    hits, _ = c.get_many("t", np.array([7]))
    assert np.array_equal(hits[7], (row - delta)[0])


def test_cache_stale_invalidate_counts():
    c = cache_mod.HotKeyCache(4)
    c.put_many("t", [1, 2], np.zeros((2, 2), np.float32), version=1)
    assert c.invalidate("t", keys=[1], stale=True) == 1
    assert c.stats()["stale_refetches"] == 1
    _, miss = c.get_many("t", np.array([1, 2]))
    assert list(miss) == [True, False]


def test_hot_set_tracker_decays_to_recent_head():
    t = cache_mod.HotSetTracker(decay_every=2)
    for _ in range(6):
        t.observe([1, 1, 1, 2])
    assert t.top(1) == [1]
    for _ in range(12):
        t.observe([9, 9, 9, 9, 2])
    assert t.top(1) == [9]  # the old head decayed out


def test_version_fence_never_serves_stale(fleet):
    """Writer B updates keys client A holds cached; A's next batch
    must refetch them (counted) and return the POST-write rows."""
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"]).copy()
    a = EmbedPlaneClient(pool, _endpoints(servers), client_id="A",
                         cache_entries=32)
    b = EmbedPlaneClient(pool, _endpoints(servers), client_id="B")
    keys = np.array([4, 5, 90])
    assert np.array_equal(a.lookup("ctr", keys), ref[keys])  # A caches
    grads = np.full((3, DIM), 2.0, np.float32)
    b.writeback("ctr", keys, grads, lr=0.5)
    ref[keys] -= np.float32(0.5) * grads
    rows = a.lookup("ctr", keys)  # fence: touched-by-B → refetch
    assert np.array_equal(rows, ref[keys])
    assert a.cache().stats()["stale_refetches"] > 0


def test_writeback_matches_single_host_reference(fleet):
    """Duplicate-slot grads accumulate per unique key; the sharded
    apply must be bit-identical to the single-host step."""
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"]).copy()
    client = EmbedPlaneClient(pool, _endpoints(servers),
                              cache_entries=32)
    rng = np.random.RandomState(0)
    for step in range(3):
        keys = rng.randint(0, VOCAB, 40)
        grads = rng.randn(40, DIM).astype(np.float32)
        client.lookup("ctr", keys)
        client.writeback("ctr", keys, grads, lr=0.1)
        uniq, inv = np.unique(keys, return_inverse=True)
        acc = np.zeros((uniq.size, DIM), np.float32)
        np.add.at(acc, inv, grads)
        ref[uniq] -= np.float32(0.1) * acc
    stitched = np.concatenate(
        [servers[m].table_bytes("ctr")[1] for m in sorted(servers)])
    assert stitched.tobytes() == ref.tobytes()
    # the write-through cache serves the same bytes as the servers
    keys = np.arange(VOCAB)
    assert np.array_equal(client.lookup("ctr", keys), ref)


# ---------------------------------------------------------------------------
# elastic reshard


def test_reshard_byte_identity_grow_and_shrink(fleet):
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"]).copy()
    client = EmbedPlaneClient(pool, _endpoints(servers),
                              cache_entries=32)
    keys = np.array([1, 60, 60, 119, 2])
    grads = np.ones((5, DIM), np.float32)
    client.lookup("ctr", keys)
    client.writeback("ctr", keys, grads, lr=0.2)
    uniq, inv = np.unique(keys, return_inverse=True)
    acc = np.zeros((uniq.size, DIM), np.float32)
    np.add.at(acc, inv, grads)
    ref[uniq] -= np.float32(0.2) * acc

    # grow 2 -> 3: the joiner starts with an empty span and pulls
    grown = ["a", "b", "c"]
    servers["c"] = EmbedShardServer("c", tables, ["a", "b"])
    eps = _endpoints(servers)
    staged = {m: servers[m].reshard(grown, eps, pool) for m in grown}
    for m in grown:
        servers[m].adopt(staged[m])
    client.resize(_endpoints(servers))
    stitched = np.concatenate(
        [servers[m].table_bytes("ctr")[1] for m in sorted(grown)])
    assert stitched.tobytes() == ref.tobytes()
    assert np.array_equal(client.lookup("ctr", keys), ref[keys])

    # shrink 3 -> 2: pulls complete against the OLD spans before adopt
    back = ["a", "b"]
    eps = _endpoints(servers)
    staged = {m: servers[m].reshard(back, eps, pool) for m in back}
    for m in back:
        servers[m].adopt(staged[m])
    servers.pop("c").stop()
    client.resize(_endpoints(servers))
    stitched = np.concatenate(
        [servers[m].table_bytes("ctr")[1] for m in sorted(back)])
    assert stitched.tobytes() == ref.tobytes()
    assert np.array_equal(client.lookup("ctr", keys), ref[keys])


# ---------------------------------------------------------------------------
# chaos drills: faulted gathers degrade losslessly


def test_chaos_lookup_error_once_is_lossless(fleet):
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"])
    plane = FaultPlane(seed=3).install()
    try:
        fault = plane.inject("embed.lookup", "error_once",
                             error="ConnectError")
        client = EmbedPlaneClient(pool, _endpoints(servers))
        keys = np.array([2, 70, 2, 111])
        rows = client.lookup("ctr", keys)
        # retried, requeued, EXACT rows — never silently-zero
        assert np.array_equal(rows, ref[keys])
        assert fault.fired == 1
        assert client.stats()["retries"] >= 1  # exact accounting
    finally:
        plane.uninstall()


def test_chaos_lookup_persistent_error_is_typed(fleet):
    servers, pool, tables = fleet
    plane = FaultPlane(seed=3).install()
    try:
        plane.inject("embed.lookup", "error", error="ConnectError")
        from edl_tpu.robustness.policy import RetryPolicy
        client = EmbedPlaneClient(
            pool, _endpoints(servers),
            retry=RetryPolicy(max_attempts=2, base_delay=0.01, seed=0))
        with pytest.raises(errors.EmbedLookupError):
            client.lookup("ctr", np.array([1, 2, 3]))
    finally:
        plane.uninstall()


def test_chaos_writeback_error_once_and_persistent(fleet):
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"]).copy()
    plane = FaultPlane(seed=3).install()
    try:
        fault = plane.inject("embed.writeback", "error_once",
                             error="ConnectError")
        client = EmbedPlaneClient(pool, _endpoints(servers))
        keys = np.array([8, 100])
        grads = np.ones((2, DIM), np.float32)
        client.writeback("ctr", keys, grads, lr=0.5)
        ref[keys] -= np.float32(0.5) * grads
        assert fault.fired == 1
        assert np.array_equal(client.lookup("ctr", keys), ref[keys])

        from edl_tpu.robustness.policy import RetryPolicy
        plane.inject("embed.writeback", "error", error="ConnectError")
        strict = EmbedPlaneClient(
            pool, _endpoints(servers), client_id="strict",
            retry=RetryPolicy(max_attempts=2, base_delay=0.01, seed=0))
        with pytest.raises(errors.EmbedWritebackError):
            strict.writeback("ctr", keys, grads, lr=0.5)
    finally:
        plane.uninstall()


# ---------------------------------------------------------------------------
# DeepFM sparse/dense parity


def test_deepfm_sparse_parity_bitwise():
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import deepfm
    vocabs = (16, 24, 8)
    model = deepfm.DeepFM(vocabs, embed_dim=4, mlp_dims=(16, 8))
    params = model.init(jax.random.PRNGKey(2),
                        jnp.zeros((1, 3), jnp.int32))["params"]
    batch = deepfm.synthetic_ctr_batch(13, vocabs, seed=5)
    dense = np.asarray(model.apply({"params": params},
                                   batch["fields"]))
    table = deepfm.combined_embedding_table(params, vocabs)
    keys = deepfm.flat_ctr_keys(batch["fields"], vocabs)
    rows = table[keys].reshape(13, 3, 5)
    tail = deepfm.DeepFMTail(num_fields=3, embed_dim=4,
                             mlp_dims=(16, 8))
    sparse = np.asarray(tail.apply(
        {"params": deepfm.dense_tail_params(params)},
        jnp.asarray(rows)))
    assert np.array_equal(dense, sparse)  # bitwise, not allclose


def test_deepfm_sparse_parity_through_plane(fleet_large=None):
    """Same parity with the rows actually served by the sharded plane
    (gather → scatter → device), duplicates and all."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import deepfm
    vocabs = (16, 24, 8)
    model = deepfm.DeepFM(vocabs, embed_dim=4, mlp_dims=(16, 8))
    params = model.init(jax.random.PRNGKey(2),
                        jnp.zeros((1, 3), jnp.int32))["params"]
    table = deepfm.combined_embedding_table(params, vocabs)
    spec = TableSpec(table.shape[0], table.shape[1],
                     init_fn=lambda v, d, lo, hi: table[lo:hi])
    members = ["a", "b"]
    servers = {m: EmbedShardServer(m, {"ctr": spec}, members)
               for m in members}
    pool = ClientPool(timeout=10.0)
    try:
        client = EmbedPlaneClient(pool, _endpoints(servers),
                                  cache_entries=32)
        batch = deepfm.synthetic_ctr_batch(9, vocabs, seed=6)
        keys = deepfm.flat_ctr_keys(batch["fields"], vocabs)
        rows = client.lookup("ctr", keys).reshape(9, 3, 5)
        tail = deepfm.DeepFMTail(num_fields=3, embed_dim=4,
                                 mlp_dims=(16, 8))
        sparse = np.asarray(tail.apply(
            {"params": deepfm.dense_tail_params(params)},
            jnp.asarray(rows)))
        dense = np.asarray(model.apply({"params": params},
                                       batch["fields"]))
        assert np.array_equal(dense, sparse)
    finally:
        for s in servers.values():
            s.stop()
        pool.close()


# ---------------------------------------------------------------------------
# overlap: prefetcher + embed_wait accounting


def test_prefetcher_fifo_and_embed_wait_state(fleet):
    from edl_tpu.obs import ledger as ledger_mod
    assert "embed_wait" in ledger_mod.STATES
    servers, pool, tables = fleet
    ref = _reference_table(tables["ctr"])
    client = EmbedPlaneClient(pool, _endpoints(servers),
                              cache_entries=16)
    pf = EmbedPrefetcher(client, "ctr")
    try:
        before = ledger_mod.LEDGER.totals().get("embed_wait", 0.0)
        pf.submit(np.array([1, 2, 3]))
        pf.submit(np.array([4, 4]))
        assert np.array_equal(pf.wait(), ref[[1, 2, 3]])
        assert np.array_equal(pf.wait(), ref[[4, 4]])
        after = ledger_mod.LEDGER.totals().get("embed_wait", 0.0)
        assert after >= before  # the join was charged to embed_wait
        assert pf.stats()["waits"] == 2
        with pytest.raises(errors.StatusError):
            pf.wait()  # nothing submitted
    finally:
        pf.close()


def test_prefetcher_surfaces_lookup_errors(fleet):
    servers, pool, tables = fleet
    plane = FaultPlane(seed=3).install()
    try:
        plane.inject("embed.lookup", "error", error="ConnectError")
        from edl_tpu.robustness.policy import RetryPolicy
        client = EmbedPlaneClient(
            pool, _endpoints(servers), client_id="pf-err",
            retry=RetryPolicy(max_attempts=2, base_delay=0.01, seed=0))
        pf = EmbedPrefetcher(client, "ctr")
        try:
            pf.submit(np.array([1]))
            with pytest.raises(errors.EmbedLookupError):
                pf.wait()
        finally:
            pf.close()
    finally:
        plane.uninstall()


# ---------------------------------------------------------------------------
# observability: metrics mirrored in stats(), doctor finding


def test_stats_mirrors_metrics(fleet):
    from edl_tpu.obs import metrics as obs_metrics
    servers, pool, tables = fleet
    client = EmbedPlaneClient(pool, _endpoints(servers),
                              cache_entries=8)
    keys = np.array([1, 1, 2, 60])
    client.lookup("ctr", keys)
    client.writeback("ctr", keys, np.ones((4, DIM), np.float32), 0.1)
    stats = client.stats()
    assert stats["lookups"] == 1 and stats["writebacks"] == 1
    assert 0 < stats["unique_key_frac"] <= 1.0
    fams = obs_metrics.REGISTRY.families()
    for name in ("edl_embed_lookup_ms", "edl_embed_writeback_ms",
                 "edl_embed_unique_key_frac",
                 "edl_embed_cache_hits_total",
                 "edl_embed_cache_evictions_total"):
        assert name in fams, name
    # mirror_stats published the numeric stats as gauges
    assert "edl_embed_lookups" in fams


def _obs_doc(states):
    series = [{"labels": {"state": s}, "value": v}
              for s, v in states.items()]
    return {"schema": "obs_pub/v1",
            "metrics": {"metrics": {"edl_time_seconds_total": {
                "kind": "counter", "series": series}}}}


def test_job_doctor_embed_wait_dominant():
    from edl_tpu.tools import job_doctor
    obs = {"pod0": _obs_doc({"compute": 50.0, "embed_wait": 30.0,
                             "data_wait": 5.0}),
           "pod1": _obs_doc({"compute": 60.0, "embed_wait": 40.0})}
    findings = job_doctor._embed_findings(obs)
    assert len(findings) == 1
    f = findings[0]
    assert f["detector"] == "embed_wait_dominant"
    assert f["pod"] == "pod1"  # loses the most time
    assert f["metric"] == "edl_time_seconds_total"
    assert "embed_wait" in f["summary"]
    # ranked: a known detector, not the unknown-rank bucket
    assert "embed_wait_dominant" in job_doctor._DETECTOR_RANK
    # and it rides diagnose() end to end on a monitor-less collect doc
    report = job_doctor.diagnose({"health": None, "obs": obs})
    assert any(x["detector"] == "embed_wait_dominant"
               for x in report["findings"])


def test_job_doctor_embed_wait_quiet_when_minor():
    from edl_tpu.tools import job_doctor
    # embed_wait present but neither dominant nor over the share floor
    obs = {"pod0": _obs_doc({"compute": 95.0, "embed_wait": 2.0,
                             "data_wait": 3.0})}
    assert job_doctor._embed_findings(obs) == []
    # no ledger counters at all → no finding, no crash
    assert job_doctor._embed_findings({"pod0": {"metrics": {}}}) == []
