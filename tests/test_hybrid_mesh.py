"""Hybrid (multi-slice) mesh tests.

Single-process: virtual-slice construction, axis layout, data sharding.
Multi-process: REAL jax.distributed over 2 CPU processes x 4 local
devices, dp-over-DCN x tp-within-slice — a tp-sharded train step whose
gradient reduction crosses the process (DCN) boundary; both processes
must agree bitwise (VERDICT r1 #10; reference hierarchical-allreduce
knob train_with_fleet.py:372)."""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from edl_tpu.runtime import mesh as mesh_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, nprocs, rank = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=nprocs, process_id=rank)
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from edl_tpu.runtime.mesh import make_hybrid_mesh, data_sharding

mesh = make_hybrid_mesh(tp=2)   # slices from process_index
assert mesh.shape["dcn"] == nprocs and mesh.shape["tp"] == 2, mesh.shape
# every dcn row must be process-pure (dp/tp collectives stay inside a
# slice; only the dcn axis crosses processes)
for row_idx in range(mesh.devices.shape[0]):
    procs = {d.process_index for d in mesh.devices[row_idx].flat}
    assert len(procs) == 1, (row_idx, procs)

w = jnp.arange(16 * 8, dtype=jnp.float32).reshape(16, 8) / 100.0
w = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))  # tp-sharded
batch_sh = data_sharding(mesh)
assert batch_sh.spec == P(("dcn", "dp")), batch_sh.spec

# global batch 8: each process contributes its local 4 rows
local = (jnp.arange(4 * 16, dtype=jnp.float32).reshape(4, 16) / 50.0
         + rank * 0.5)
x = jax.make_array_from_process_local_data(batch_sh, local)

def loss_fn(w, x):
    return (jnp.tanh(x @ w) ** 2).mean()

loss, grads = jax.jit(
    jax.value_and_grad(loss_fn),
    out_shardings=(NamedSharding(mesh, P()),
                   NamedSharding(mesh, P(None, "tp"))))(w, x)
gsum = float(jnp.abs(grads).sum())

# cross-process-sharded checkpoint: w's tp shards live on BOTH processes;
# to_host_tree must all-gather before the rank-0 write, and the restored
# tree must match the global array
from edl_tpu.runtime.checkpoint import CheckpointManager, to_host_tree
import numpy as np
host_tree = to_host_tree({"w": grads})
assert host_tree["w"].shape == (16, 8), host_tree["w"].shape
ckpt_dir = sys.argv[4]
if rank == 0:
    cm = CheckpointManager(ckpt_dir)
    cm.save(1, host_tree)
    _, restored, _ = cm.restore(1, target=host_tree)
    assert np.array_equal(restored["w"], host_tree["w"])
    print("CKPT OK", flush=True)

print("RESULT rank=%d loss=%.10f gsum=%.10f" % (rank, float(loss), gsum),
      flush=True)
"""


def test_virtual_slices_single_process():
    mesh = mesh_mod.make_hybrid_mesh(dcn_dp=2, tp=2,
                                     devices=jax.devices()[:8])
    assert mesh.shape["dcn"] == 2 and mesh.shape["dp"] == 2 \
        and mesh.shape["tp"] == 2
    assert mesh_mod.data_sharding(mesh).spec == \
        jax.sharding.PartitionSpec(("dcn", "dp"))
    # contiguous virtual slices
    row0 = [d.id for d in mesh.devices[0].flat]
    row1 = [d.id for d in mesh.devices[1].flat]
    assert sorted(row0) == [0, 1, 2, 3] and sorted(row1) == [4, 5, 6, 7]


def test_hybrid_mesh_rejects_bad_shapes():
    devs = jax.devices()[:8]
    with pytest.raises(ValueError):
        mesh_mod.make_hybrid_mesh(dcn_dp=3, devices=devs)  # 8 % 3
    with pytest.raises(ValueError):
        mesh_mod.make_hybrid_mesh(dcn_dp=2, tp=3, devices=devs)  # 4 % 3


def test_hybrid_train_step_grads_match_flat_mesh():
    """A dp-over-dcn x dp train step must produce the same grads as the
    flat 1-axis dp mesh (the decomposition is a layout, not a semantics,
    change)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()[:8]
    w = jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4) / 100.0
    x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16) / 50.0

    def loss_fn(w, x):
        return (jnp.tanh(x @ w) ** 2).mean()

    flat = mesh_mod.make_mesh(dp=8, devices=devs)
    hyb = mesh_mod.make_hybrid_mesh(dcn_dp=2, devices=devs)
    outs = {}
    for name, mesh in (("flat", flat), ("hybrid", hyb)):
        xs = jax.device_put(x, mesh_mod.data_sharding(mesh))
        ws = jax.device_put(w, NamedSharding(mesh, P()))
        loss, g = jax.jit(jax.value_and_grad(loss_fn))(ws, xs)
        outs[name] = (float(loss), np.asarray(g))
    assert outs["flat"][0] == pytest.approx(outs["hybrid"][0], rel=1e-6)
    np.testing.assert_allclose(outs["flat"][1], outs["hybrid"][1],
                               rtol=1e-6, atol=1e-7)


@pytest.mark.integration
def test_multiprocess_dcn_mesh(tmp_path):
    """2 real processes (jax.distributed over CPU), 4 local devices each:
    tp-sharded step with grad reduction across the DCN axis."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = "127.0.0.1:%d" % port
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    ckpt_dir = str(tmp_path / "ckpt")
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), coordinator, "2", str(rank),
         ckpt_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out.decode("utf-8", "replace"))
            assert p.returncode == 0, "\n".join(outs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = sorted(ln for out in outs for ln in out.splitlines()
                     if ln.startswith("RESULT"))
    assert len(results) == 2, outs
    # identical loss and grad checksum on both processes → the cross-DCN
    # reduction really happened and agreed
    f0, f1 = (r.split(" ", 1)[1] for r in results)
    assert f0.split("loss=")[1] == f1.split("loss=")[1], results
    # the cross-process-sharded checkpoint gathered + round-tripped
    assert any("CKPT OK" in out for out in outs), outs
