"""Example-suite smoke tests + the resize mutation driver end-to-end."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from edl_tpu.controller import status
from edl_tpu.controller.status import Status
from edl_tpu.distill.teacher_server import TeacherServer
from edl_tpu.tools.resize_driver import ResizeDriver

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(path, args, timeout=240, device_count=2):
    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(device_count)
    proc = subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, path)] + args,
        env=env, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    result = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(result)


@pytest.mark.integration
def test_resnet_example_standalone():
    out = _run_example("examples/resnet/train.py", [
        "--depth", "18", "--epochs", "1", "--steps_per_epoch", "4",
        "--total_batch_size", "8", "--image_size", "32",
        "--num_classes", "4"])
    assert out["model"] == "ResNet18_vd"
    assert out["steps"] == 4
    assert out["imgs_per_sec"] > 0


@pytest.mark.integration
def test_fit_a_line_preemption_emergency_checkpoint(tmp_path):
    """SIGTERM mid-epoch: the trainer writes an emergency checkpoint at
    the current step, exits 101 (the restart convention), and a restart
    resumes from that step — not from the last epoch boundary."""
    import signal
    import time

    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(
        2, EDL_TPU_CHECKPOINT_PATH=str(tmp_path / "ckpt"))
    cmd = [sys.executable, "-u",
           os.path.join(REPO, "examples/fit_a_line/train.py"),
           "--epochs", "2", "--steps_per_epoch", "500",
           "--step_sleep", "0.02"]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    # wait for training to actually start (first step done), then preempt
    deadline = time.time() + 120
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "" and proc.poll() is not None:
            break  # child died before starting
        lines.append(line)
        if line.startswith("fit_a_line:"):
            break
    time.sleep(2.0)  # a few 20ms steps into epoch 0
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    lines.append(out)
    assert proc.returncode == 101, "".join(lines)
    assert "preempted" in out, out

    # the emergency checkpoint landed mid-epoch-0 (no epoch-end save
    # exists before step 500)
    from edl_tpu.runtime.checkpoint import CheckpointManager

    versions = CheckpointManager(str(tmp_path / "ckpt")).versions()
    assert versions, out
    emergency_step = versions[-1]
    assert 0 < emergency_step < 500, (versions, out)

    # a restart resumes from it and completes (no sleep: fast finish)
    cmd2 = [sys.executable, "-u",
            os.path.join(REPO, "examples/fit_a_line/train.py"),
            "--epochs", "2", "--steps_per_epoch", "500"]
    proc2 = subprocess.run(cmd2, env=env, capture_output=True, text=True,
                           timeout=240)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "resumed=True" in proc2.stdout, proc2.stdout
    final = json.loads([l for l in proc2.stdout.splitlines()
                        if l.startswith("{")][-1])
    assert final["steps"] > emergency_step


@pytest.mark.integration
def test_bert_pipeline_example_learns():
    out = _run_example("examples/bert_pipeline/train.py", [
        "--pp", "4", "--steps", "60", "--d_model", "32",
        "--num_heads", "2", "--mlp_dim", "64", "--seq_len", "16",
        "--vocab_size", "50", "--lr", "5e-3"],
        timeout=300, device_count=8)
    assert out["model"] == "bert_pipeline_pp4_dp2"
    # the parity task is learnable: loss must drop toward 0 from ~ln(2)
    assert out["final_loss"] < out["first_loss"] - 0.2, out


@pytest.mark.integration
def test_bert_pipeline_example_interleaved_learns():
    """--chunks 2: the interleaved (circular) engine behind the same
    example CLI, on a config where the Megatron-exact schedule wins."""
    out = _run_example("examples/bert_pipeline/train.py", [
        "--pp", "2", "--chunks", "2", "--num_layers", "4",
        "--num_micro", "8", "--steps", "60", "--d_model", "32",
        "--num_heads", "2", "--mlp_dim", "64", "--seq_len", "16",
        "--vocab_size", "50", "--lr", "5e-3"],
        timeout=600, device_count=8)
    assert out["model"] == "bert_pipeline_pp2_dp4_v2"
    assert out["final_loss"] < out["first_loss"] - 0.2, out


@pytest.mark.integration
def test_bert_pipeline_preemption_resume(tmp_path):
    """SIGTERM the PIPELINED trainer mid-run: emergency checkpoint with
    pp-sharded stages, exit 101, and a rerun resumes past the preempted
    step — elasticity composed with pipeline parallelism at the process
    level."""
    import signal
    import time

    from conftest import cpu_subprocess_env

    env = cpu_subprocess_env(
        8, EDL_TPU_CHECKPOINT_PATH=str(tmp_path / "ckpt"))
    cmd = [sys.executable, "-u",
           os.path.join(REPO, "examples/bert_pipeline/train.py"),
           "--pp", "4", "--steps", "400", "--d_model", "32",
           "--num_heads", "2", "--mlp_dim", "64", "--seq_len", "16",
           "--vocab_size", "50"]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 180
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "" and proc.poll() is not None:
            raise AssertionError("died before starting")
        if line.startswith("step 5 "):  # compiled and actually stepping
            break
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=180)
    assert proc.returncode == 101, out
    assert "preempted" in out, out

    from edl_tpu.runtime.checkpoint import CheckpointManager

    versions = CheckpointManager(str(tmp_path / "ckpt")).versions()
    assert versions and 0 < versions[-1] < 400, (versions, out)

    proc2 = subprocess.run(
        cmd[:6] + ["40"] + cmd[7:], env=env, capture_output=True,
        text=True, timeout=400)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "resumed=True step=%d" % versions[-1] in proc2.stdout, \
        proc2.stdout


@pytest.mark.integration
def test_long_context_example_runs_with_remat():
    out = _run_example("examples/long_context/train.py", [
        "--sp", "4", "--seq_len", "256", "--steps", "6", "--d_model",
        "32", "--num_heads", "2", "--mlp_dim", "64", "--remat"],
        timeout=300, device_count=8)
    assert out["model"] == "bert_ring_sp4_dp2"
    assert out["seq_len"] == 256 and out["remat"]
    assert np.isfinite(out["final_loss"])
    assert out["tokens_per_sec"] > 0


@pytest.mark.integration
def test_gpt_example_learns_and_generates():
    out = _run_example("examples/gpt/train.py",
                       ["--steps", "150"], timeout=400)
    assert out["final_loss"] < 0.3 * out["first_loss"]
    assert out["gen_accuracy"] >= 0.75


@pytest.mark.integration
def test_ctr_example_learns():
    out = _run_example("examples/ctr/train.py", [
        "--epochs", "2", "--steps_per_epoch", "30",
        "--total_batch_size", "128", "--num_fields", "6",
        "--vocab_per_field", "50"])
    assert out["final_loss"] < 0.67  # below chance-level BCE (~0.69)


@pytest.mark.integration
def test_resnet_distill_example_with_teacher():
    def teacher_fn(feed):
        # a deterministic "teacher": logits derived from channel means
        img = feed["image"]
        base = img.mean(axis=(1, 2, 3), keepdims=False)
        return {"logits": np.stack([base * (i + 1) for i in range(10)],
                                   axis=1).astype(np.float32)}

    teacher = TeacherServer(
        teacher_fn, {"image": ([32, 32, 3], "<f4")},
        {"logits": ([10], "<f4")}, max_batch=16, host="127.0.0.1").start()
    try:
        out = _run_example("examples/distill/resnet_distill.py", [
            "--epochs", "1", "--steps_per_epoch", "4",
            "--total_batch_size", "8", "--teachers", teacher.endpoint])
        assert out["steps"] == 4
    finally:
        teacher.stop()


@pytest.mark.integration
def test_nlp_distill_example_with_bert_teacher():
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import bert

    model = bert.bert_tiny(dtype=jnp.float32)
    dummy = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), dummy)

    @jax.jit
    def infer(ids):
        return model.apply(variables, ids)

    def teacher_fn(feed):
        return {"logits": np.asarray(infer(jnp.asarray(
            feed["input_ids"].astype(np.int32))))}

    teacher = TeacherServer(
        teacher_fn, {"input_ids": ([32], "<i4")}, {"logits": ([2], "<f4")},
        max_batch=16, host="127.0.0.1").start()
    try:
        out = _run_example("examples/distill/nlp_distill.py", [
            "--epochs", "1", "--steps_per_epoch", "4", "--batch_size", "8",
            "--teachers", teacher.endpoint])
        assert "final_loss" in out
    finally:
        teacher.stop()


def _make_linear_dataset(root, files, per_file, seed):
    """Whitespace 'v1 ... v13 y' record files with a learnable linear
    target; returns (root, total_records)."""
    rng = np.random.RandomState(seed)
    w_true = np.linspace(-1.0, 1.0, 13).astype(np.float32)
    root.mkdir()
    total = 0
    for f in range(files):
        lines = []
        for _ in range(per_file):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ w_true + 0.5)
            lines.append(" ".join("%.6f" % v for v in x) + " %.6f" % y)
            total += 1
        (root / ("part%d.txt" % f)).write_text("\n".join(lines))
    return root, total


@pytest.mark.integration
def test_elastic_data_example_end_to_end(store, tmp_path):
    """The data-server path e2e: launcher → trainer → ElasticReader
    (leader balancer + batch serving) → mark_consumed/State checkpoints;
    records_seen must equal the dataset exactly (no loss, no dupes)."""
    import subprocess as sp

    data_dir, total = _make_linear_dataset(tmp_path / "data", files=8,
                                           per_file=64, seed=0)

    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(8, EDL_TPU_POD_IP="127.0.0.1",
                             EDL_TPU_TTL="3")
    log = open(str(tmp_path / "pod1.log"), "wb")
    p = sp.Popen(
        [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
         "--job_id", "edata", "--store_endpoints", store.endpoint,
         "--nodes_range", "1:1",
         "--checkpoint_path", str(tmp_path / "ckpt"),
         "--log_dir", str(tmp_path / "pod1_logs"),
         os.path.join(REPO, "examples", "elastic_data", "train.py"),
         "--data_dir", str(data_dir), "--batch_size", "16"],
        env=env, stdout=log, stderr=sp.STDOUT, preexec_fn=os.setsid)
    log.close()
    try:
        assert p.wait(timeout=240) == 0, \
            (tmp_path / "pod1.log").read_text()
        worker_log = (tmp_path / "pod1_logs" / "workerlog.0").read_text()
        out = json.loads([l for l in worker_log.splitlines()
                          if l.startswith("{")][-1])
        assert out["records_seen"] == total, out
        assert out["steps"] == total // 16
        assert out["final_loss"] < 0.5, out
        coord = store.client(root="edata")
        assert status.load_job_status(coord) == Status.SUCCEED
    finally:
        try:
            os.killpg(os.getpgid(p.pid), 9)
        except ProcessLookupError:
            pass


@pytest.mark.integration
def test_elastic_data_exactly_once_across_preemption(store, tmp_path):
    """The coherence proof for the data plane + preemption story: a
    SIGTERM mid-consumption writes an emergency checkpoint whose
    consumed-record ranges cover EXACTLY the trained batches (ranges are
    marked before each step), and the restarted run consumes exactly
    the remainder — no record lost, none replayed."""
    import signal as sig
    import subprocess as sp
    import time

    from edl_tpu.runtime.checkpoint import CheckpointManager

    # per_file batch-divisible: a ragged tail is not divisible by the
    # inherited 8-device dp mesh
    data_dir, total = _make_linear_dataset(tmp_path / "data", files=4,
                                           per_file=64, seed=1)

    from conftest import cpu_subprocess_env
    # the launcher env contract, minus the launcher: the coord-backed
    # reader registry needs a trainer identity
    env = cpu_subprocess_env(
        8, EDL_TPU_STORE_ENDPOINTS=store.endpoint,
        EDL_TPU_JOB_ID="eonce", EDL_TPU_POD_ID="pod_eonce",
        EDL_TPU_TRAINER_ID="t0", EDL_TPU_GLOBAL_RANK="0",
        EDL_TPU_WORLD_SIZE="1",
        EDL_TPU_CHECKPOINT_PATH=str(tmp_path / "ckpt"))
    cmd = [sys.executable, "-u",
           os.path.join(REPO, "examples", "elastic_data", "train.py"),
           "--data_dir", str(data_dir), "--batch_size", "8",
           "--step_sleep", "0.15"]
    # unbuffered binary pipe + os.read: select on a TextIOWrapper lies
    # once readline() pulls multiple lines into the user-space buffer,
    # and a bare readline() would block past the deadline on a hang
    import select

    p1 = sp.Popen(cmd, env=env, stdout=sp.PIPE, stderr=sp.STDOUT,
                  bufsize=0)
    fd = p1.stdout.fileno()
    deadline = time.time() + 120
    seen = b""
    while time.time() < deadline and b"elastic_data:" not in seen:
        ready, _, _ = select.select([fd], [], [], 1.0)
        if ready:
            chunk = os.read(fd, 65536)
            if chunk == b"":
                raise AssertionError("run 1 died before starting:\n"
                                     + seen.decode(errors="replace"))
            seen += chunk
        elif p1.poll() is not None:
            raise AssertionError("run 1 died before starting:\n"
                                 + seen.decode(errors="replace"))
    assert b"elastic_data:" in seen, \
        "run 1 never printed its banner within the deadline"
    time.sleep(2.5)  # ~15 batches in
    p1.send_signal(sig.SIGTERM)
    raw1, _ = p1.communicate(timeout=120)
    out1 = (seen + raw1).decode(errors="replace")
    assert p1.returncode == 101, out1
    assert "preempted" in out1, out1

    # the emergency checkpoint's consumed ranges = what run 1 trained
    cm = CheckpointManager(str(tmp_path / "ckpt"))
    _, _, meta = cm.restore(cm.versions()[-1])
    spans = meta["state"]["data_checkpoint"]["processed"]
    consumed_run1 = sum(e - b + 1 for f_spans in spans.values()
                       for b, e in f_spans)
    assert 0 < consumed_run1 < total, (consumed_run1, total)

    p2 = sp.run(cmd[:-2], env=env, stdout=sp.PIPE, stderr=sp.STDOUT,
                text=True, timeout=240)
    assert p2.returncode == 0, p2.stdout
    out = json.loads([l for l in p2.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert out["resumed"] is True, out
    # exactly the remainder: nothing lost, nothing replayed
    assert out["records_seen"] == total - consumed_run1, \
        (out, consumed_run1, total)


def _make_real_dataset(root, classes=4, per_class=48, size=48, seed=0):
    """Real JPEGs on disk with visually-learnable classes (distinct base
    colors + noise) in class-per-subdirectory layout."""
    from PIL import Image
    rng = np.random.RandomState(seed)
    palette = [(220, 40, 40), (40, 220, 40), (40, 40, 220), (220, 220, 40),
               (220, 40, 220), (40, 220, 220), (230, 140, 30),
               (130, 70, 200), (110, 190, 90), (160, 160, 160)]
    assert classes <= len(palette)
    for c in range(classes):
        d = os.path.join(root, "class_%d" % c)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = np.ones((size, size, 3), np.float32) * palette[c]
            img += rng.randn(size, size, 3) * 25.0
            Image.fromarray(np.clip(img, 0, 255).astype(np.uint8)).save(
                os.path.join(d, "img%03d.jpg" % i))
    return root


@pytest.mark.integration
@pytest.mark.parametrize("bn_every,min_acc", [(1, 0.9), (4, 0.9)])
def test_resnet_real_data_accuracy_through_launcher(store, tmp_path,
                                                    bn_every, min_acc):
    """Accuracy-parity-path evidence (VERDICT r1 #7): train ResNet18 on a
    REAL on-disk image-folder dataset through the full stack (launcher →
    trainer → tf.data decode/augment/shard → eval split) and assert the
    benchmark-log JSON reports converged eval accuracy.

    bn_every=4 is the CONVERGENCE GATE for the subset-statistics BN
    throughput lever (NOTES r2 gap #1): the bench may only default to
    --bn_stats_every 4 because this real-data run converges with it.
    Sharpened per VERDICT r3 weak #3: 10 classes (chance 0.1), a
    160-image eval split (accuracy quantum 0.00625, one confused class
    costs 0.1), graph-seeded augmentation, and BOTH parametrizations
    face the same 0.9 bar — if subset statistics hurt convergence,
    bn_every=4 fails while bn_every=1 passes.

    The gate runs at total_batch 128 so bn_every=4 computes statistics
    from 32 samples — the bench default's effective stats batch AND the
    reference's per-GPU stats batch. That floor is load-bearing: the
    r4 sharpening experiment measured bn4 at total_batch 32 (8-sample
    stats) converging to 0.8 while bn1 passed 0.85+ — subset statistics
    below ~16 samples demonstrably cost accuracy, so bench.py refuses
    stats batches under 16 (see bench.py --bn_stats_every)."""
    import json as json_mod
    import subprocess as sp

    from conftest import cpu_subprocess_env

    train_dir = _make_real_dataset(str(tmp_path / "train"), classes=10,
                                   per_class=40)
    eval_dir = _make_real_dataset(str(tmp_path / "eval"), classes=10,
                                  per_class=16, seed=99)
    env = cpu_subprocess_env(2, EDL_TPU_POD_IP="127.0.0.1",
                             EDL_TPU_TTL="3")
    log = open(str(tmp_path / "pod1.log"), "wb")
    p = sp.Popen(
        [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
         "--job_id", "acc_job", "--store_endpoints", store.endpoint,
         "--nodes_range", "1:1",
         "--log_dir", str(tmp_path / "pod1_logs"),
         os.path.join(REPO, "examples", "resnet", "train.py"),
         "--depth", "18", "--epochs", "3", "--steps_per_epoch", "8",
         "--total_batch_size", "128", "--image_size", "32",
         "--num_classes", "10", "--seed", "7",
         "--data_dir", train_dir, "--eval_dir", eval_dir,
         "--base_lr", "0.08", "--warmup_epochs", "1",
         "--bn_stats_every", str(bn_every)],
        env=env, stdout=log, stderr=sp.STDOUT, preexec_fn=os.setsid)
    log.close()
    try:
        assert p.wait(timeout=540) == 0, \
            (tmp_path / "pod1.log").read_text()
        worker_log = (tmp_path / "pod1_logs" / "workerlog.0").read_text()
        result = json_mod.loads([l for l in worker_log.splitlines()
                                 if l.startswith("{")][-1])
        assert result["steps"] == 24
        assert result["eval_acc1"] > min_acc, worker_log
        coord = store.client(root="acc_job")
        assert status.load_job_status(coord) == Status.SUCCEED
    finally:
        try:
            os.killpg(os.getpgid(p.pid), 9)
        except ProcessLookupError:
            pass


@pytest.mark.integration
def test_resize_driver_north_star_8_4_8(tmp_path):
    """The BASELINE north star at full pod count: 8 launcher pods against
    the C++ store, forced resize 8→4→8 (simulated preemption of half the
    fleet, then recovery), per-stage recovery times measured and resize
    metrics recorded on the store (reference: README.md:126-131 job-server
    demo; recovery-time story edl_live_fault_tolerance.md:37)."""
    import json as json_mod

    from edl_tpu.controller import constants
    from edl_tpu.coordination.client import CoordClient
    from edl_tpu.coordination.native import NativeStoreServer, ensure_binary
    try:
        ensure_binary()
    except Exception as e:
        pytest.skip("native store unavailable: %r" % e)

    with NativeStoreServer(data_dir=str(tmp_path / "wal")) as s:
        driver = ResizeDriver(
            s.endpoint, "ns_job", "4:8",
            [os.path.join(REPO, "tests", "fixtures", "dummy_trainer.py"),
             "600", "0"],
            log_dir=str(tmp_path),
            env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                       "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "5",
                       "PALLAS_AXON_POOL_IPS": ""})
        try:
            events = driver.run_schedule([8, 4, 8], interval=3)
            assert [e["target"] for e in events] == [8, 4, 8]
            # three distinct cluster incarnations, all with measured
            # recovery times
            assert len({e["stage"] for e in events}) == 3
            assert all(e["recovery_s"] >= 0 for e in events)
            coord = CoordClient([s.endpoint], root="ns_job")
            assert status.load_job_status(coord) != Status.FAILED
            # per-pod resize-recovery metrics landed on the store
            metrics = dict(coord.get_service(constants.SERVICE_METRICS))
            assert metrics, "no resize metrics recorded"
            history = [h for v in metrics.values()
                       for h in json_mod.loads(v)]
            assert any(h["recovery_s"] >= 0 for h in history)
        finally:
            driver.shutdown(kill=True)


@pytest.mark.integration
def test_resize_driver_schedule(store, tmp_path):
    """The 8→4→8 story in miniature: 2→1→2 with recovery times measured."""
    driver = ResizeDriver(
        store.endpoint, "resize_job", "1:2",
        [os.path.join(REPO, "examples", "fit_a_line", "train.py"),
         "--epochs", "100", "--steps_per_epoch", "5", "--step_sleep",
         "0.3"],
        log_dir=str(tmp_path),
        env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                   "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "3",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                   "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
                   "PALLAS_AXON_POOL_IPS": ""})
    try:
        events = driver.run_schedule([2, 1, 2], interval=3)
        assert [e["target"] for e in events] == [2, 1, 2]
        assert len({e["stage"] for e in events}) == 3
        assert all(e["recovery_s"] < 120 for e in events)
        coord = store.client(root="resize_job")
        assert status.load_job_status(coord) != Status.FAILED
    finally:
        driver.shutdown(kill=True)


@pytest.mark.integration
def test_resize_driver_graceful_preemption(store, tmp_path):
    """--signal term: the graceful-preemption drill. SIGTERM reaches the
    victim pod's whole group; the trainers' coordinated stop writes a
    MID-EPOCH emergency checkpoint across ranks; the surviving launcher
    treats exit-101 as preemption (not failure) and the resized cluster
    resumes — steps survive that a SIGKILL drill would replay."""
    import glob

    from edl_tpu.runtime.checkpoint import CheckpointManager

    driver = ResizeDriver(
        store.endpoint, "graceful_job", "1:2",
        [os.path.join(REPO, "examples", "fit_a_line", "train.py"),
         # 50-step epochs: the stop lead now tracks watcher latency
         # only (~11 steps at this cadence — heartbeat staleness is
         # handled by per-rank projection, r5), so a preemption a dozen
         # steps into an epoch lands mid-epoch, which the discriminator
         # below requires. The r4 lead ballooned to ~30 steps and
         # forced 200-step epochs here.
         "--epochs", "100", "--steps_per_epoch", "50",
         "--step_sleep", "0.1"],
        # grace 30s (k8s-realistic): under full-suite CPU contention the
        # two-rank coordinated stop + aligned save can overrun 15s and
        # the drill then SIGKILLs mid-save (observed as a rare full-
        # suite-only flake; the test passes in isolation in ~15s)
        log_dir=str(tmp_path), stop_signal="term", grace=30.0,
        env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                   "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "3",
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                   "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
                   "PALLAS_AXON_POOL_IPS": ""})
    try:
        import time

        from edl_tpu.controller import train_status as ts_mod

        coord = store.client(root="graceful_job")
        driver.set_target(2)
        c2, _ = driver.wait_cluster(2)
        # preempt only once training is actually RUNNING (the trainers
        # report it at begin_epoch, after the handler is installed) —
        # a SIGTERM during distributed init has nothing to checkpoint
        deadline = time.time() + 90
        while time.time() < deadline:
            sts = [ts_mod.load_train_status(coord, pid)
                   for pid in c2.pod_ids()]
            if any(s is not None for s in sts):
                break
            time.sleep(0.3)
        else:
            raise AssertionError("training never started")
        time.sleep(2.0)  # a dozen 0.1s steps into epoch 0
        driver.set_target(1)
        _, waited = driver.wait_cluster(1, prev_stage=c2.stage)
        events = [{"target": 1, "recovery_s": waited,
                   "resumed_step": driver._store_global_step()}]
        assert status.load_job_status(coord) != Status.FAILED
        versions = CheckpointManager(str(tmp_path / "ckpt")).versions()
        logs = ""
        for p in glob.glob(str(tmp_path / "pod*_trainers") +
                           "/workerlog.*"):
            with open(p, errors="replace") as f:
                logs += f.read()
        # epoch-end saves land at multiples of 50; a mid-epoch version
        # proves the SIGTERM emergency checkpoint fired
        assert versions, \
            "no checkpoint written during the drill\n" + logs[-3000:]
        assert any(v % 50 != 0 for v in versions), (versions,
                                                    logs[-3000:])
        assert events[-1]["resumed_step"], events
        assert "preempted" in logs, logs[-2000:]
    finally:
        driver.shutdown(kill=True)


@pytest.mark.integration
def test_chaos_soak_mixed_preemptions(store, tmp_path):
    """Bounded chaos soak: a deterministic-seed sequence of resize
    mutations with MIXED preemption modes (hard SIGKILL and graceful
    SIGTERM) against one job, then run-to-completion — the job must
    never FAIL, recover after every mutation, and finish SUCCEED."""
    import random
    import time

    rng = random.Random(7)  # jitters the sleeps only — the mutation
    # sequence itself is explicit so BOTH modes provably run
    driver = ResizeDriver(
        store.endpoint, "chaos_job", "1:2",
        [os.path.join(REPO, "examples", "fit_a_line", "train.py"),
         "--epochs", "6", "--steps_per_epoch", "30",
         "--step_sleep", "0.1"],
        log_dir=str(tmp_path), stop_signal="kill", grace=15.0,
        env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                   "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "3",
                   "XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2",
                   "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
                   "PALLAS_AXON_POOL_IPS": ""})
    coord = store.client(root="chaos_job")
    try:
        driver.set_target(2)
        prev_stage = driver.wait_cluster(2)[0].stage
        for step_i, (mode, target) in enumerate(
                [("term", 1), ("kill", 2), ("term", 1)]):
            time.sleep(rng.uniform(2.0, 4.0))
            driver._stop_signal = mode
            driver.set_target(target)
            cluster, waited = driver.wait_cluster(target,
                                                  prev_stage=prev_stage)
            prev_stage = cluster.stage
            assert waited < 120, (step_i, mode, target, waited)
        # let the survivor finish the job
        deadline = time.time() + 300
        while time.time() < deadline:
            if status.load_job_status(coord) == Status.SUCCEED:
                break
            assert status.load_job_status(coord) != Status.FAILED
            time.sleep(1.0)
        assert status.load_job_status(coord) == Status.SUCCEED
    finally:
        driver.shutdown(kill=True)


@pytest.mark.integration
def test_gpt_distill_example_with_lm_teacher():
    """Sequence-level KD end-to-end: gpt teacher backend -> DistillReader
    -> student GPT trained on per-position soft targets."""
    from edl_tpu.distill.teacher_server import gpt_teacher

    teacher = gpt_teacher(vocab_size=64, seq_len=16, max_batch=8,
                          host="127.0.0.1").start()
    try:
        out = _run_example("examples/distill/gpt_distill.py", [
            "--epochs", "1", "--steps_per_epoch", "4",
            "--total_batch_size", "8", "--seq_len", "16",
            "--vocab_size", "64", "--teachers", teacher.endpoint])
        assert out["steps"] == 4
        assert np.isfinite(out["final_loss"])
    finally:
        teacher.stop()


@pytest.mark.integration
def test_chaos_soak_resize_plus_store_failover(tmp_path):
    """The combined reliability drill: elastic resize mutations AND a
    coordination-store primary loss in one arc. Pods run against
    [primary, standby]; a graceful scale-down lands, then the PRIMARY
    is killed mid-job (standby promotes, leases/elections re-form),
    then another resize mutation runs against the promoted store — and
    the job still finishes SUCCEED. Every failure domain the framework
    claims to survive, exercised together."""
    import time as time_mod

    from edl_tpu.coordination.server import StoreServer
    from edl_tpu.coordination.standby import StandbyServer

    primary = StoreServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=1.5,
                       sync_poll=0.5).start()
    endpoints = "%s,%s" % (primary.endpoint, sb.endpoint)
    driver = ResizeDriver(
        endpoints, "chaos_ha_job", "1:2",
        [os.path.join(REPO, "examples", "fit_a_line", "train.py"),
         "--epochs", "6", "--steps_per_epoch", "30",
         "--step_sleep", "0.1"],
        log_dir=str(tmp_path), stop_signal="term", grace=15.0,
        env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                   "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "3",
                   "XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2",
                   "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
                   "PALLAS_AXON_POOL_IPS": ""})
    from edl_tpu.coordination.client import CoordClient
    coord = CoordClient(endpoints.split(","), root="chaos_ha_job",
                        failover_grace=25.0)
    try:
        driver.set_target(2)
        prev_stage = driver.wait_cluster(2)[0].stage
        time_mod.sleep(2.0)
        # mutation 1: graceful scale-down on the healthy primary
        driver.set_target(1)
        cluster, waited = driver.wait_cluster(1, prev_stage=prev_stage)
        prev_stage = cluster.stage
        assert waited < 120

        # the store outage, mid-job
        primary.stop()
        deadline = time_mod.time() + 30
        while time_mod.time() < deadline and not sb.promoted:
            time_mod.sleep(0.2)
        assert sb.promoted

        # mutation 2: scale back out against the PROMOTED store (the
        # driver's own client rides the failover via endpoint rotation;
        # wait_cluster's own timeout enforces the bound)
        time_mod.sleep(2.0)
        driver.set_target(2)
        driver.wait_cluster(2, prev_stage=prev_stage, timeout=180)

        deadline = time_mod.time() + 300
        while time_mod.time() < deadline:
            if status.load_job_status(coord) == Status.SUCCEED:
                break
            assert status.load_job_status(coord) != Status.FAILED
            time_mod.sleep(1.0)
        assert status.load_job_status(coord) == Status.SUCCEED
    finally:
        driver.shutdown(kill=True)
        sb.stop()
        primary.stop()  # idempotent; without it a pre-outage failure
        # leaks the primary's server threads into the pytest process


@pytest.mark.integration
def test_four_host_dp_tp_resize_with_store_failover(tmp_path):
    """VERDICT r4 item 8 — the closest CPU-reachable analogue of a real
    multi-host TPU resize, one rung past the 2-pod drills: FOUR
    launcher pods x 2 virtual devices each, bert with tp=2 INSIDE the
    dp mesh (params sharded across the process boundary), resized
    4 -> 2 -> 4 gracefully while the coordination store's PRIMARY is
    killed mid-arc (standby promotes). Ties together in one arc:
    launcher elasticity at >2 hosts, tp-sharded save + placed restore
    across RESHAPED meshes (4x2 -> 2x2 -> 4x2 devices), coordinated
    preemption, store HA, the prewarm scope guard, and exactly-once
    step-keyed data consumption (FEED accounting below).

    Reference north star: BASELINE.md's 8 -> 4 -> 8 on v5e-16."""
    import glob
    import re
    import time as time_mod

    from edl_tpu.coordination.server import StoreServer
    from edl_tpu.coordination.standby import StandbyServer

    primary = StoreServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=1.5,
                       sync_poll=0.5).start()
    endpoints = "%s,%s" % (primary.endpoint, sb.endpoint)
    driver = ResizeDriver(
        endpoints, "dptp_job", "2:4",
        [os.path.join(REPO, "tests", "fixtures", "dp_tp_trainer.py"),
         "--epochs", "4", "--steps_per_epoch", "20",
         "--total_batch_size", "24", "--tp", "2",
         "--step_sleep", "0.05"],
        log_dir=str(tmp_path), stop_signal="term", grace=60.0,
        # TTL 10 (not the 2-pod drills' 3): FOUR bert compiles + gloo
        # init can starve every launcher's heartbeat thread at once on
        # a loaded CI box; the below-min grace (2xTTL) then rides it out
        env_extra={"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                   "EDL_TPU_POD_IP": "127.0.0.1", "EDL_TPU_TTL": "10",
                   "XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2",
                   "EDL_TPU_CHECKPOINT_PATH": str(tmp_path / "ckpt"),
                   "PALLAS_AXON_POOL_IPS": ""})
    from edl_tpu.coordination.client import CoordClient
    coord = CoordClient(endpoints.split(","), root="dptp_job",
                        failover_grace=25.0)
    try:
        def _logs():
            out = ""
            for p in glob.glob(str(tmp_path) + "/pod*_trainers/"
                               "workerlog.*"):
                with open(p, errors="replace") as f:
                    out += f.read()
            return out

        def _wait_world_trains(world, why, min_steps=5):
            # each stage must actually COMMIT steps (4-process
            # distributed init + bert compile + shard restore takes
            # tens of seconds on CPU) before the next mutation lands:
            # a SIGTERM that catches trainers mid-compile leaves no
            # boundary for the coordinated stop to save at, and the
            # grace-expiry SIGKILL then tears down the whole jax world
            # unsaved. FEED step=N+1 is printed only after step N's
            # train_step returned, which in a lockstep collective world
            # means EVERY rank finished compiling and committed N.
            deadline = time_mod.time() + 300
            pat = r"FEED step=(\d+) rank=0 world=%d" % world
            while time_mod.time() < deadline:
                steps = [int(m.group(1))
                         for m in re.finditer(pat, _logs())]
                if steps and max(steps) > min_steps:
                    return
                assert status.load_job_status(coord) != Status.FAILED
                time_mod.sleep(1.0)
            raise AssertionError("world-%d stage never trained (%s)\n%s"
                                 % (world, why, _logs()[-3000:]))

        driver.set_target(4)
        prev_stage = driver.wait_cluster(4, timeout=300)[0].stage
        _wait_world_trains(4, "initial 4-host stage")

        # graceful scale-down to 2 hosts: coordinated stop + tp-sharded
        # emergency save, then a 2x2-device restore of 4-rank shards
        driver.set_target(2)
        cluster, waited = driver.wait_cluster(2, prev_stage=prev_stage,
                                              timeout=300)
        prev_stage = cluster.stage
        _wait_world_trains(2, "post-scale-down stage")

        # the store outage mid-job
        primary.stop()
        deadline = time_mod.time() + 30
        while time_mod.time() < deadline and not sb.promoted:
            time_mod.sleep(0.2)
        assert sb.promoted

        # scale back OUT against the promoted store
        time_mod.sleep(1.0)
        driver.set_target(4)
        driver.wait_cluster(4, prev_stage=prev_stage, timeout=300)

        deadline = time_mod.time() + 420
        while time_mod.time() < deadline:
            if status.load_job_status(coord) == Status.SUCCEED:
                break
            assert status.load_job_status(coord) != Status.FAILED
            time_mod.sleep(1.0)
        assert status.load_job_status(coord) == Status.SUCCEED

        logs = _logs()

        # exactly-once, step-keyed: rank 0's FEED lines across every
        # incarnation must cover 1..final contiguously; duplicates only
        # at preemption boundaries (a fetched-but-stopped batch), of
        # which this arc has 2 resizes + 1 failover window
        feeds = [int(m.group(1)) for m in
                 re.finditer(r"FEED step=(\d+) rank=0", logs)]
        assert feeds, logs[-2000:]
        final = max(feeds)
        missing = set(range(1, final + 1)) - set(feeds)
        assert not missing, ("steps never fed (data lost): %s"
                             % sorted(missing))
        dups = len(feeds) - len(set(feeds))
        assert dups <= 6, ("replayed windows beyond preemption "
                           "boundaries: %d" % dups)

        # the job really ran at BOTH world sizes with tp inside
        assert re.search(r"FEED step=\d+ rank=0 world=4", logs), \
            logs[-2000:]
        assert re.search(r"FEED step=\d+ rank=0 world=2", logs), \
            logs[-2000:]
        # prewarm was engaged and its multi-process guard refused
        assert "why='multi-process world'" in logs, logs[-2000:]

        # the 4-host stage wrote SHARDED checkpoints (tp state crosses
        # hosts there; whether the 2-host mesh lays tp locally — and
        # saves dense — depends on device order, so it isn't pinned)
        import json as json_mod
        ranks_seen = set()
        for mp in glob.glob(str(tmp_path / "ckpt") + "/v_*/MANIFEST"):
            with open(mp) as f:
                m = json_mod.load(f)
            if m.get("sharded"):
                ranks_seen.add(m.get("ranks"))
        assert 4 in ranks_seen, ranks_seen
        # ...and the reshaped 2-host mesh RESUMED from them (the
        # placed-restore-across-meshes arc this test exists for)
        assert re.search(
            r"dp_tp: rank=0 world=2 start_epoch=\d+ resumed=True",
            logs), logs[-2000:]
    finally:
        driver.shutdown(kill=True)
        sb.stop()
        primary.stop()
