"""ResNet family tests: shapes, vd structure, training step with BN aux
state through ElasticTrainer on the dp mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import resnet
from edl_tpu.runtime.trainer import ElasticTrainer


def test_resnet50_vd_forward_shape():
    model = resnet.ResNet(depth=50, num_classes=10, vd=True,
                          dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # vd deep stem present
    assert "stem1" in variables["params"]
    assert "stem3" in variables["params"]
    # vd downsample shortcut in first stride-2 block
    assert "downsample" in variables["params"]["stage1_block0"]


def test_space_to_depth_stem_exact():
    """The s2d stem is a pure compute-layout change: identical param tree
    and bit-nearly-identical outputs for the SAME parameters."""
    m0 = resnet.ResNet(depth=50, num_classes=10, vd=True, dtype=jnp.float32)
    m1 = resnet.ResNet(depth=50, num_classes=10, vd=True, dtype=jnp.float32,
                       space_to_depth=True)
    x = jnp.asarray(np.random.RandomState(0)
                    .randn(2, 64, 64, 3).astype(np.float32))
    v = m0.init(jax.random.PRNGKey(0), x, train=False)
    v1 = m1.init(jax.random.PRNGKey(0), x, train=False)
    assert (jax.tree_util.tree_structure(v)
            == jax.tree_util.tree_structure(v1))
    y0 = m0.apply(v, x, train=False)
    y1 = m1.apply(v, x, train=False)  # the s2d model with m0's params
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-5)
    # gradients agree too (the scatter is differentiated through)
    def loss(variables, model):
        return (model.apply(variables, x, train=False) ** 2).mean()
    g0 = jax.grad(loss)(v, m0)["params"]["stem1"]["kernel"]
    g1 = jax.grad(loss)(v, m1)["params"]["stem1"]["kernel"]
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("depth", [18, 50])
def test_resnet_depths(depth):
    model = resnet.ResNet(depth=depth, num_classes=7, vd=False,
                          dtype=jnp.float32)
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (1, 7)


@pytest.mark.parametrize("depth", [11, 16])
def test_vgg_forward_and_train(depth, tmp_path):
    from edl_tpu.models import vgg

    model, params, loss_fn = vgg.create_model_and_loss(
        depth=depth, num_classes=4, image_size=32, fc_dim=64,
        dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (2, 4) and logits.dtype == jnp.float32
    # block structure per the reference spec table
    n_convs = sum(vgg.VGG_SPECS[depth])
    conv_names = [k for k in jax.tree_util.tree_flatten_with_path(
        params)[0] for k in [jax.tree_util.keystr(k[0])]
        if "conv" in k and "kernel" in k]
    assert len(conv_names) == n_convs

    trainer = ElasticTrainer(
        loss_fn, params, optax.sgd(0.01, momentum=0.9),
        total_batch_size=16, checkpoint_dir=str(tmp_path / "ckpt"))
    batch = resnet.synthetic_image_batch(16, image_size=32, num_classes=4,
                                         seed=0)
    losses = [float(trainer.train_step(batch)) for _ in range(6)]
    assert losses[-1] < losses[0]


def test_resnet_trains_with_bn_aux(tmp_path):
    model, params, extra, loss_fn = resnet.create_model_and_loss(
        depth=18, num_classes=4, vd=True, image_size=32,
        dtype=jnp.float32)
    trainer = ElasticTrainer(
        loss_fn, params, optax.sgd(0.05, momentum=0.9),
        total_batch_size=16, checkpoint_dir=str(tmp_path / "ckpt"),
        extra_state=extra, has_aux=True)

    def batch(seed):
        b = resnet.synthetic_image_batch(16, image_size=32, num_classes=4,
                                         seed=seed % 3)  # few distinct
        return b

    stats_before = jax.device_get(
        trainer.extra_state["batch_stats"])
    losses = [float(trainer.train_step(batch(i))) for i in range(8)]
    assert losses[-1] < losses[0]
    stats_after = jax.device_get(trainer.extra_state["batch_stats"])
    # BN running stats actually updated through the aux path
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).sum()),
        stats_before, stats_after)
    assert sum(jax.tree_util.tree_leaves(diffs)) > 0

    # checkpoint roundtrip includes BN stats
    trainer.begin_epoch(0)
    trainer.end_epoch(save=True)
    model2, params2, extra2, loss_fn2 = resnet.create_model_and_loss(
        depth=18, num_classes=4, vd=True, image_size=32, dtype=jnp.float32)
    trainer2 = ElasticTrainer(
        loss_fn2, params2, optax.sgd(0.05, momentum=0.9),
        total_batch_size=16, checkpoint_dir=str(tmp_path / "ckpt"),
        extra_state=extra2, has_aux=True)
    assert trainer2.resume()
    restored = jax.device_get(trainer2.extra_state["batch_stats"])
    chex_like = jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b), rtol=1e-5),
        stats_after, restored)
    del chex_like


def test_resnext_grouped_bottleneck():
    """ResNeXt: grouped 3x3 with base_width-scaled inner channels; the
    32x16d config widens conv1/conv2 to 512 channels per stage-0 block
    while the grouped conv keeps params at width^2*9/groups."""
    import jax

    from edl_tpu.models import resnet

    model = resnet.ResNeXt(depth=50, groups=4, base_width=16,
                           num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    # inner width: 64 * 16/64 * 4 = 64 for stage-0 (filters=64)
    k = variables["params"]["stage0_block0"]["conv2"]["kernel"]
    # grouped conv kernel: [3, 3, width/groups, width]
    assert k.shape == (3, 3, 64 // 4, 64)
    # vanilla (non-vd) stem by default
    assert "stem" in variables["params"]
    # trains one step
    _, params, extra, loss_fn = resnet.create_model_and_loss(
        depth=50, num_classes=10, vd=False, image_size=32,
        dtype=jnp.float32, groups=4, base_width=16)
    import optax

    from edl_tpu.runtime.trainer import make_train_state, make_train_step
    tx = optax.sgd(0.01)
    state = make_train_state(params, tx, extra)
    step = jax.jit(make_train_step(loss_fn, tx, has_aux=True))
    batch = {"image": np.zeros((4, 32, 32, 3), np.float32),
             "label": np.zeros((4,), np.int32)}
    state, loss = step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


def test_resnext_rejects_basicblock_groups():
    from edl_tpu.models import resnet

    model = resnet.ResNet(depth=18, groups=2, num_classes=10,
                          dtype=jnp.float32)
    with pytest.raises(ValueError, match="bottleneck"):
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 32, 32, 3)), train=False)
