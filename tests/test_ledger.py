"""TimeLedger state machine + GoodputMerger (edl_tpu/obs/ledger.py).

The exclusive-states invariant is the whole point: every wall-clock
second belongs to exactly one state, so the totals sum to elapsed time
and goodput % is well-defined. The merger side mirrors PR 8's
counter-reset discipline: a restarted pod re-zeroes its counters and
the fold must re-anchor, never subtract.
"""

import json

from edl_tpu.obs import ledger as ledger_mod
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs.ledger import GoodputMerger, TimeLedger


class _Clock(object):
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_states_are_exclusive_and_sum_to_elapsed():
    clk = _Clock()
    led = TimeLedger(clock=clk)
    led.transition("compute")
    clk.advance(2.0)
    led.transition("data_wait")
    clk.advance(1.0)
    led.transition("compute")
    clk.advance(4.0)
    totals = led.totals()
    assert totals["compute"] == 6.0
    assert totals["data_wait"] == 1.0
    # exclusive: everything since the first touch is accounted, once
    assert sum(totals.values()) == 7.0
    assert led.current() == "compute"


def test_scopes_nest_and_restore_the_outer_state():
    clk = _Clock()
    led = TimeLedger(clock=clk)
    led.transition("resize_pause")
    clk.advance(5.0)
    with led.state("ckpt_block"):
        assert led.current() == "ckpt_block"
        clk.advance(3.0)
    # a drain inside a resize returns to resize_pause, not idle
    assert led.current() == "resize_pause"
    clk.advance(2.0)
    totals = led.totals()
    assert totals["resize_pause"] == 7.0
    assert totals["ckpt_block"] == 3.0


def test_scope_exits_on_exception():
    clk = _Clock()
    led = TimeLedger(clock=clk)
    led.transition("compute")
    try:
        with led.state("data_wait"):
            clk.advance(1.0)
            raise KeyError("queue.Empty analog")
    except KeyError:
        pass
    assert led.current() == "compute"
    assert led.totals()["data_wait"] == 1.0


def test_kill_switch_stops_accrual():
    clk = _Clock()
    led = TimeLedger(clock=clk)
    led.transition("compute")
    clk.advance(2.0)
    prev = obs_metrics.set_enabled(False)
    try:
        led.transition("data_wait")  # no-op: state unchanged
        clk.advance(50.0)
        led.flush()
    finally:
        obs_metrics.set_enabled(prev)
    # totals() re-arms on the next enabled touch; the disabled 50s
    # were never accrued anywhere
    led.flush()
    totals = led.totals()
    assert totals["data_wait"] == 0.0
    assert sum(totals.values()) <= 52.0


def test_flush_syncs_registry_counters_incrementally():
    clk = _Clock()
    led = TimeLedger(clock=clk)

    def _registry_value(state):
        fam = obs_metrics.REGISTRY.snapshot()["metrics"][
            "edl_time_seconds_total"]
        for s in fam["series"]:
            if s["labels"]["state"] == state:
                return s["value"]
        return 0.0

    base = _registry_value("barrier_wait")
    led.transition("barrier_wait")
    clk.advance(3.0)
    # hot path has NOT touched the registry yet
    assert _registry_value("barrier_wait") == base
    led.flush()
    assert _registry_value("barrier_wait") == base + 3.0
    clk.advance(1.5)
    led.flush()  # delta-synced: no double count
    assert _registry_value("barrier_wait") == base + 4.5


def test_reset_zeroes_totals_and_returns_to_idle():
    clk = _Clock()
    led = TimeLedger(clock=clk)
    led.transition("compute")
    clk.advance(2.0)
    led.reset()
    assert led.current() == "idle"
    assert all(v == 0.0 for v in led.totals().values())


def test_pod_states_extraction_and_absent_is_none():
    doc = {"metrics": {"metrics": {"edl_time_seconds_total": {
        "kind": "counter",
        "series": [
            {"labels": {"state": "compute"}, "value": 12.5},
            {"labels": {"state": "idle"}, "value": 2.0},
        ]}}}}
    assert ledger_mod.pod_states(doc) == {"compute": 12.5, "idle": 2.0}
    # absent is not zero: pods predating the ledger are skipped
    assert ledger_mod.pod_states({"metrics": {"metrics": {}}}) is None
    assert ledger_mod.pod_states({}) is None


def test_unengaged_ledger_never_manufactures_idle():
    # a supervisor process (the launcher) imports the ledger but no
    # instrumentation point ever touches it; publisher flush ticks
    # must not turn that into accrued idle time
    clk = _Clock()
    led = TimeLedger(clock=clk)
    clk.advance(30.0)
    led.flush()
    clk.advance(30.0)
    led.flush()
    assert all(v == 0.0 for v in led.totals().values())


def test_merger_skips_all_zero_pods():
    # the launcher's doc carries the zero-valued series (children are
    # materialized at import); it has no time to attribute and must
    # not pad pods_reporting
    def _doc(compute, idle):
        return {"metrics": {"metrics": {"edl_time_seconds_total": {
            "kind": "counter",
            "series": [
                {"labels": {"state": "compute"}, "value": compute},
                {"labels": {"state": "idle"}, "value": idle},
            ]}}}}
    m = GoodputMerger()
    m.update_from_docs({"launcher": _doc(0.0, 0.0),
                        "pod_r0": _doc(12.0, 3.0)})
    assert m.pods() == ["pod_r0"]


def test_merger_accumulates_deltas_and_reanchors_on_restart():
    m = GoodputMerger()
    m.update("p0", {"compute": 10.0, "data_wait": 2.0})  # first: whole
    m.update("p0", {"compute": 15.0, "data_wait": 2.0})  # +5 compute
    # restart: counters re-zero; the backwards sum must re-anchor —
    # fold the new incarnation in whole, never subtract
    m.update("p0", {"compute": 3.0, "data_wait": 1.0})
    total, bad = m.fleet_cumulative()
    assert total == 10.0 + 2.0 + 5.0 + 3.0 + 1.0
    assert bad == 2.0 + 1.0


def test_goodput_doc_shape_and_ranked_badput():
    m = GoodputMerger()
    m.update("p0", {"compute": 60.0, "ckpt_block": 30.0,
                    "data_wait": 10.0})
    m.update("p1", {"compute": 90.0, "ckpt_block": 5.0,
                    "data_wait": 5.0})
    doc = m.doc(now=123.0)
    assert doc["schema"] == "goodput/v1"
    assert doc["ts"] == 123.0
    assert doc["pods_reporting"] == ["p0", "p1"]
    fleet = doc["fleet"]
    assert fleet["total_s"] == 200.0
    assert fleet["goodput_s"] == 150.0
    assert fleet["goodput_pct"] == 75.0
    # badput ranked by fleet seconds, largest first
    assert [b["state"] for b in fleet["badput"]] == ["ckpt_block",
                                                     "data_wait"]
    assert fleet["badput"][0]["seconds"] == 35.0
    pods = doc["pods"]
    assert pods["p0"]["top_badput"] == "ckpt_block"
    assert pods["p0"]["goodput_pct"] == 60.0
    assert doc["spread"]["goodput_pct_min"] == 60.0
    assert doc["spread"]["goodput_pct_max"] == 90.0
    assert doc["spread"]["states"]["ckpt_block"] == {"min_s": 5.0,
                                                     "max_s": 30.0}
    # the doc round-trips through the store encoding
    json.loads(json.dumps(doc))


def test_merger_forget_drops_the_pod():
    m = GoodputMerger()
    m.update("p0", {"compute": 1.0})
    m.update("p1", {"compute": 2.0})
    m.forget("p0")
    assert m.pods() == ["p1"]
    total, _ = m.fleet_cumulative()
    assert total == 2.0


def test_service_health_constant_matches_controller():
    # obs is an import leaf: the constant is inlined, guard the drift
    from edl_tpu.controller import constants
    assert ledger_mod.SERVICE_HEALTH == constants.SERVICE_HEALTH
