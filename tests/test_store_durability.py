"""Store durability + registration self-healing across store restarts."""

import time

from edl_tpu.controller.register import Register
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.server import StoreServer
from edl_tpu.utils.network import find_free_port


def test_wal_persists_permanent_keys(tmp_path):
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="jobd")
    c1.set_server_permanent("cluster", "cluster", '{"pods": []}')
    c1.set_server_permanent("job_status", "job_status", "RUNNING")
    c1.set_server_with_lease("resource", "podA", "x", ttl=30)  # ephemeral
    c1.remove_server("job_status", "job_status")
    s1.stop()

    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jobd")
        # permanent keys survive; deleted and leased keys do not
        assert c2.get_value("cluster", "cluster") == '{"pods": []}'
        assert c2.get_value("job_status", "job_status") is None
        assert c2.get_value("resource", "podA") is None
    finally:
        s2.stop()


def test_wal_torn_tail_is_ignored(tmp_path):
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="jobd")
    c1.set_server_permanent("svc", "a", "v1")
    s1.stop()
    with open(wal, "a") as f:
        f.write('{"op": "put", "k": "/jobd/svc/nodes/b", "v": "tr')  # torn
    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jobd")
        assert c2.get_value("svc", "a") == "v1"
        assert c2.get_value("svc", "b") is None
    finally:
        s2.stop()


def test_wal_torn_tail_truncated_before_append(tmp_path):
    """Crash simulation for the full torn-tail contract: the partial
    record must be TRUNCATED from the file (not just skipped) before
    the store appends again — otherwise the next write glues onto the
    torn bytes and a later replay loses everything from the tear on."""
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="jobd")
    c1.set_server_permanent("svc", "a", "v1")
    s1.stop()
    torn = '{"op": "put", "k": "/jobd/svc/nodes/b", "v": "tr'
    with open(wal, "a") as f:
        f.write(torn)  # crash mid-write()

    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c2 = CoordClient([s2.endpoint], root="jobd")
    assert c2.get_value("svc", "a") == "v1"
    c2.set_server_permanent("svc", "c", "v3")  # append AFTER the tear
    s2.stop()
    raw = open(wal, "rb").read()
    assert torn.encode() not in raw  # physically truncated

    # third incarnation replays cleanly: old + new records, no tear
    s3 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        c3 = CoordClient([s3.endpoint], root="jobd")
        assert c3.get_value("svc", "a") == "v1"
        assert c3.get_value("svc", "c") == "v3"
        assert c3.get_value("svc", "b") is None
    finally:
        s3.stop()


def test_revisions_and_watchers_survive_restart(tmp_path):
    """Revisions never regress across a restart, and a watcher from the
    previous incarnation is forced to re-list (reset) so it sees both new
    keys and leased keys that died with the old process."""
    port = find_free_port()
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    coord = CoordClient(["127.0.0.1:%d" % port], root="jw")
    coord.set_server_permanent("svc", "keep", "v")
    coord.set_server_with_lease("svc", "ephemeral", "x", ttl=60)
    rev_before = coord.revision()

    views = []
    watcher = coord.watch_service("svc", lambda a, r, alls: views.append(
        dict(alls)), poll_timeout=0.5)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not views:
        time.sleep(0.1)
    assert views and set(views[-1]) == {"keep", "ephemeral"}

    s1.stop()
    s2 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    try:
        assert coord.revision() >= rev_before  # no regression
        coord.set_server_permanent("svc", "new", "n")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if views and set(views[-1]) == {"keep", "new"}:
                break
            time.sleep(0.2)
        # the watcher re-listed: ephemeral gone, new key visible
        assert set(views[-1]) == {"keep", "new"}, views[-1]
    finally:
        watcher.stop()
        s2.stop()


def test_permanent_value_shadowed_by_lease_not_resurrected(tmp_path):
    """A permanent key later overwritten by a leased registration must NOT
    come back from the WAL after a restart."""
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="js")
    c1.set_server_permanent("svc", "k", "permanent")
    c1.set_server_with_lease("svc", "k", "ephemeral", ttl=60)
    s1.stop()
    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        assert CoordClient([s2.endpoint],
                           root="js").get_value("svc", "k") is None
    finally:
        s2.stop()


def test_non_string_values_rejected(tmp_path):
    s = StoreServer(host="127.0.0.1",
                    wal_path=str(tmp_path / "w.wal")).start()
    try:
        c = CoordClient([s.endpoint], root="jt")
        try:
            c.put("/jt/k", 123)
            raise AssertionError("expected a type error")
        except Exception as e:
            assert "str or bytes" in str(e)
        c.put("/jt/raw", b"\x00\xff")  # bytes are fine and durable
    finally:
        s.stop()
    s2 = StoreServer(host="127.0.0.1",
                     wal_path=str(tmp_path / "w.wal")).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jt")
        assert c2.get_key("/jt/raw")["value"] == b"\x00\xff"
    finally:
        s2.stop()


def test_native_store_wal_durability(tmp_path):
    """The C++ store's WAL: permanent keys survive a SIGKILL restart,
    leased and shadowed values do not (parity with the Python backend)."""
    import signal

    from edl_tpu.coordination.native import NativeStoreServer, ensure_binary
    try:
        ensure_binary()
    except Exception as e:
        import pytest
        pytest.skip("native store unavailable: %r" % e)
    port_dir = str(tmp_path / "data")
    s1 = NativeStoreServer(data_dir=port_dir)
    s1.start()
    c1 = CoordClient([s1.endpoint], root="jn")
    c1.set_server_permanent("cluster", "cluster", '{"stage": "s1"}')
    c1.put("/jn/raw", b"\x00\xff")
    c1.set_server_permanent("svc", "shadow", "perm")
    c1.set_server_with_lease("svc", "shadow", "eph", ttl=60)
    c1.set_server_with_lease("resource", "pod", "x", ttl=60)
    rev1 = c1.revision()
    s1._proc.send_signal(signal.SIGKILL)  # hard crash
    s1._proc.wait()

    s2 = NativeStoreServer(port=s1._port, data_dir=port_dir)
    s2.start()
    try:
        c2 = CoordClient([s2.endpoint], root="jn")
        assert c2.get_value("cluster", "cluster") == '{"stage": "s1"}'
        assert c2.get_key("/jn/raw")["value"] == b"\x00\xff"
        assert c2.get_value("svc", "shadow") is None   # shadowed → gone
        assert c2.get_value("resource", "pod") is None  # leased → gone
        assert c2.revision() > rev1                     # no regression
    finally:
        s2.stop()


def test_register_survives_store_restart(tmp_path):
    """A store crash/restart must not kill registered components: the
    register re-establishes its lease on the new store instance."""
    port = find_free_port()
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    coord = CoordClient(["127.0.0.1:%d" % port], root="jobr")
    reg = Register(coord, "resource", "podA", "payload", ttl=2)
    try:
        assert coord.get_value("resource", "podA") == "payload"
        s1.stop()
        time.sleep(1.0)
        s2 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if coord.get_value("resource", "podA") == "payload":
                break
            time.sleep(0.3)
        assert coord.get_value("resource", "podA") == "payload"
        assert not reg.is_broken()
        s2.stop()
    finally:
        reg.stop()


# -- warm standby / failover (VERDICT r3 missing #2) -----------------------


def _wait(pred, timeout=15.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def test_standby_mirrors_permanent_keys_only():
    from edl_tpu.coordination.standby import StandbyServer

    primary = StoreServer(host="127.0.0.1").start()
    c = CoordClient([primary.endpoint], root="ha")
    c.set_server_permanent("cluster", "cluster", "v1")
    c.set_server_with_lease("resource", "podA", "x", ttl=30)
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=False).start()
    try:
        assert _wait(sb.synced.is_set)
        # live updates replicate
        c.set_server_permanent("job_status", "job_status", "RUNNING")
        c.set_server_permanent("cluster", "cluster", "v2")
        key = c.server_key("cluster", "cluster")

        def mirrored():
            kv = sb.store.get(key)
            return kv is not None and kv["value"] == "v2" and \
                sb.store.get(c.server_key("job_status",
                                          "job_status")) is not None
        assert _wait(mirrored)
        # the leased key is NOT mirrored (restart semantics: owners
        # re-register after failover)
        assert sb.store.get(c.server_key("resource", "podA")) is None
        # deletes replicate
        c.remove_server("job_status", "job_status")
        assert _wait(lambda: sb.store.get(
            c.server_key("job_status", "job_status")) is None)
    finally:
        sb.stop()
        primary.stop()


def test_standby_rejects_ops_until_promoted_and_client_rotates():
    """A client configured with [standby, primary] must transparently
    land every op on the primary while the standby is gated."""
    from edl_tpu.coordination.standby import StandbyServer
    from edl_tpu.utils import errors as errors_mod

    primary = StoreServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=False).start()
    try:
        # direct client pinned to the standby alone: refused
        lone = CoordClient([sb.endpoint], root="ha", failover_grace=0.0)
        try:
            lone.set_server_permanent("svc", "k", "v")
            assert False, "standby accepted a write while gated"
        except errors_mod.ConnectError:
            pass
        # standby listed FIRST: rotation must find the primary
        both = CoordClient([sb.endpoint, primary.endpoint], root="ha")
        both.set_server_permanent("svc", "k", "v")
        assert both.get_value("svc", "k") == "v"
        direct = CoordClient([primary.endpoint], root="ha")
        assert direct.get_value("svc", "k") == "v"
    finally:
        sb.stop()
        primary.stop()


def test_standby_promotes_on_primary_loss_and_control_plane_survives():
    """Kill the primary; the standby auto-promotes within its window;
    a client holding BOTH endpoints keeps working; permanent state is
    intact; a watcher from the primary era gets reset and re-lists;
    ephemeral owners re-register (the Register round-trips)."""
    from edl_tpu.coordination.standby import StandbyServer

    primary = StoreServer(host="127.0.0.1").start()
    c = CoordClient([primary.endpoint], root="ha")
    c.set_server_permanent("cluster", "cluster", "mapv1")
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=1.0,
                       sync_poll=0.5).start()
    ha_client = CoordClient([primary.endpoint, sb.endpoint], root="ha",
                            failover_grace=20.0)

    seen = []
    watcher = ha_client.watch_service(
        "cluster", lambda a, r, al: seen.append(dict(al)),
        poll_timeout=1.0)
    reg = None
    try:
        assert _wait(sb.synced.is_set)
        assert _wait(lambda: any("cluster" in s for s in seen))

        primary.stop()  # the outage
        assert _wait(lambda: sb.promoted, timeout=30)

        # control-plane calls keep working through the SAME client
        assert ha_client.get_value("cluster", "cluster") == "mapv1"
        ha_client.set_server_permanent("job_status", "job_status",
                                       "RUNNING")
        assert ha_client.get_value("job_status", "job_status") \
            == "RUNNING"

        # ephemeral re-registration against the promoted standby
        reg = Register(ha_client, "resource", "podZ", "zv", ttl=3)
        assert _wait(lambda: ha_client.get_value("resource", "podZ")
                     == "zv")

        # the watcher survived: an update through the promoted server
        # reaches it (reset -> re-list path)
        ha_client.set_server_permanent("cluster", "cluster", "mapv2")
        assert _wait(lambda: any(s.get("cluster") == "mapv2"
                                 for s in seen), timeout=20)
    finally:
        watcher.stop()
        if reg is not None:
            reg.stop()
        sb.stop()


def test_primary_loss_mid_job_chaos(tmp_path):
    """The north-star HA drill: a 2-pod launcher job running against
    [primary, standby]; the primary is killed MID-JOB; the standby
    promotes; leases, elections, barriers, and the job verdict all
    continue on the survivor and the job completes SUCCEED."""
    import os
    import signal as signal_mod
    import subprocess
    import sys

    from edl_tpu.controller import cluster as cluster_mod
    from edl_tpu.controller import status
    from edl_tpu.coordination.standby import StandbyServer

    primary = StoreServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=1.5,
                       sync_poll=0.5).start()
    endpoints = "%s,%s" % (primary.endpoint, sb.endpoint)
    job = "chaos_ha"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trainer = os.path.join(repo, "tests", "fixtures", "dummy_trainer.py")
    env = dict(os.environ)
    env.update({"PYTHONPATH": repo, "EDL_TPU_POD_IP": "127.0.0.1",
                "EDL_TPU_TTL": "3", "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": ""})

    def spawn(name):
        log = open(str(tmp_path / (name + ".log")), "wb")
        p = subprocess.Popen(
            [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
             "--job_id", job, "--store_endpoints", endpoints,
             "--nodes_range", "1:2",
             "--log_dir", str(tmp_path / (name + "_logs")),
             trainer, "25", "0"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            preexec_fn=os.setsid)
        log.close()
        return p

    pods = [spawn("pod1"), spawn("pod2")]
    ha_client = CoordClient(endpoints.split(","), root=job,
                            failover_grace=25.0)
    try:
        # job is up: agreed cluster on the store
        assert _wait(lambda: cluster_mod.load_from_store(ha_client)
                     is not None, timeout=30)
        time.sleep(3)  # trainers are mid-run
        primary.stop()  # the outage
        assert _wait(lambda: sb.promoted, timeout=30)
        for p in pods:
            assert p.wait(timeout=150) == 0, \
                (tmp_path / "pod1.log").read_text()[-3000:]
        assert status.load_job_status(ha_client) == status.Status.SUCCEED
    finally:
        for p in pods:
            try:
                os.killpg(os.getpgid(p.pid), signal_mod.SIGKILL)
            except ProcessLookupError:
                pass
        sb.stop()


def test_standby_replicates_from_native_primary(tmp_path):
    """The standby's replication speaks the shared wire protocol, so a
    C++ store can be the primary (the deployment mixes backends)."""
    import pytest as _pytest

    from edl_tpu.coordination.native import NativeStoreServer, ensure_binary
    from edl_tpu.coordination.standby import StandbyServer

    try:
        ensure_binary()
    except Exception as e:  # noqa: BLE001
        _pytest.skip("native store unavailable: %r" % e)
    with NativeStoreServer(data_dir=str(tmp_path / "wal")) as primary:
        c = CoordClient([primary.endpoint], root="hax")
        c.set_server_permanent("cluster", "cluster", "native-v1")
        c.set_server_with_lease("resource", "podN", "x", ttl=30)
        sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                           auto_promote=False).start()
        try:
            assert _wait(sb.synced.is_set)
            key = c.server_key("cluster", "cluster")
            assert _wait(lambda: (sb.store.get(key) or {}).get("value")
                         == "native-v1")
            assert sb.store.get(c.server_key("resource", "podN")) is None
            c.set_server_permanent("cluster", "cluster", "native-v2")
            assert _wait(lambda: (sb.store.get(key) or {}).get("value")
                         == "native-v2")
        finally:
            sb.stop()

# -- failover fencing (ADVICE r4 medium: asymmetric partitions) ------------


def test_witness_blocks_promotion_on_asymmetric_partition():
    """The standby loses its link to a STILL-ALIVE primary. Without
    fencing it would promote and split-brain the control plane (clients
    rotate on any ConnectError). With a witness that still reaches the
    primary, the standby must stay gated indefinitely."""
    from edl_tpu.coordination.standby import StandbyServer, WitnessServer

    primary = StoreServer(host="127.0.0.1").start()
    witness = WitnessServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=0.5,
                       sync_poll=0.3,
                       witness_endpoints=[witness.endpoint]).start()
    try:
        assert _wait(sb.synced.is_set)
        # sever the standby->primary link ONLY: swap the standby's
        # client for one aimed at a dead port; the primary itself (and
        # the witness's view of it) stays healthy
        dead = "127.0.0.1:%d" % find_free_port()
        sb._primary = CoordClient([dead], timeout=1.0,
                                  failover_grace=0.0)
        time.sleep(3.0)  # several promote_after windows
        assert not sb.promoted, \
            "standby promoted despite a witness reaching the primary"
        # the primary is still serving clients
        c = CoordClient([primary.endpoint], root="ha")
        c.set_server_permanent("svc", "k", "still-primary")
        assert c.get_value("svc", "k") == "still-primary"
    finally:
        sb.stop()
        witness.stop()
        primary.stop()


def test_witness_corroborates_real_primary_death():
    """When the primary is genuinely dead the witness agrees, and the
    fenced standby promotes within its window."""
    from edl_tpu.coordination.standby import StandbyServer, WitnessServer

    primary = StoreServer(host="127.0.0.1").start()
    c = CoordClient([primary.endpoint], root="ha")
    c.set_server_permanent("cluster", "cluster", "v1")
    witness = WitnessServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=0.5,
                       sync_poll=0.3,
                       witness_endpoints=[witness.endpoint]).start()
    try:
        assert _wait(sb.synced.is_set)
        primary.stop()
        assert _wait(lambda: sb.promoted, timeout=30)
        surv = CoordClient([sb.endpoint], root="ha")
        assert surv.get_value("cluster", "cluster") == "v1"
    finally:
        sb.stop()
        witness.stop()


def test_unreachable_witness_fails_safe_no_promotion():
    """Witness configured but down + primary down = no evidence either
    way; auto-promotion must NOT fire (operator fallback via the
    standby_promote RPC is the escape hatch)."""
    from edl_tpu.coordination.standby import StandbyServer, WitnessServer

    primary = StoreServer(host="127.0.0.1").start()
    witness = WitnessServer(host="127.0.0.1").start()
    sb = StandbyServer([primary.endpoint], host="127.0.0.1",
                       auto_promote=True, promote_after=0.5,
                       sync_poll=0.3,
                       witness_endpoints=[witness.endpoint]).start()
    try:
        assert _wait(sb.synced.is_set)
        witness.stop()
        primary.stop()
        time.sleep(3.0)
        assert not sb.promoted, \
            "standby auto-promoted with zero witness corroboration"
        # the operator path still works
        sb.promote()
        assert sb.promoted
    finally:
        sb.stop()


def test_chained_failover_rearm(tmp_path):
    """Redundancy AFTER a failover (VERDICT r4 missing #2): the etcd
    the reference ran kept replication after losing one raft member;
    here the re-arm path restores it. Kill the primary, let the standby
    promote, attach a FRESH standby (the wiped old primary) to the
    promoted store, kill the promoted store too — the chained standby
    promotes and the control plane survives a double machine loss."""
    from edl_tpu.coordination.standby import (StandbyServer, WitnessServer,
                                              rejoin_wipe)

    primary = StoreServer(host="127.0.0.1").start()
    c0 = CoordClient([primary.endpoint], root="ha")
    c0.set_server_permanent("cluster", "cluster", "v1")
    witness = WitnessServer(host="127.0.0.1").start()
    sb1 = StandbyServer([primary.endpoint], host="127.0.0.1",
                        auto_promote=True, promote_after=0.5,
                        sync_poll=0.3,
                        witness_endpoints=[witness.endpoint]).start()
    sb2 = None
    try:
        assert _wait(sb1.synced.is_set)
        primary.stop()  # first machine loss
        assert _wait(lambda: sb1.promoted, timeout=30)
        surv = CoordClient([sb1.endpoint], root="ha")
        assert surv.get_value("cluster", "cluster") == "v1"
        surv.set_server_permanent("cluster", "cluster", "v2")

        # re-arm: the old primary machine returns; its WAL is wiped and
        # it rejoins as a fresh standby of the PROMOTED store
        old_dir = str(tmp_path / "old_primary")
        import os
        os.makedirs(old_dir)
        (tmp_path / "old_primary" / "store.wal").write_text(
            '{"op": "put", "k": "/ha/cluster/nodes/cluster", "v": "v0-stale"}\n')
        rejoin_wipe(old_dir)
        assert os.listdir(old_dir) == []  # stale identity shed
        sb2 = StandbyServer([sb1.endpoint], host="127.0.0.1",
                            wal_path=os.path.join(old_dir, "standby.wal"),
                            auto_promote=True, promote_after=0.5,
                            sync_poll=0.3,
                            witness_endpoints=[witness.endpoint]).start()
        assert _wait(sb2.synced.is_set)
        key = surv.server_key("cluster", "cluster")
        assert _wait(lambda: (sb2.store.get(key) or {}).get("value")
                     == "v2")

        sb1.stop()  # second machine loss
        assert _wait(lambda: sb2.promoted, timeout=30)
        final = CoordClient([sb2.endpoint], root="ha")
        assert final.get_value("cluster", "cluster") == "v2"
        final.set_server_permanent("job_status", "job_status", "RUNNING")
        assert final.get_value("job_status", "job_status") == "RUNNING"
    finally:
        if sb2 is not None:
            sb2.stop()
        sb1.stop()
        witness.stop()
