"""Store durability + registration self-healing across store restarts."""

import time

from edl_tpu.controller.register import Register
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.server import StoreServer
from edl_tpu.utils.network import find_free_port


def test_wal_persists_permanent_keys(tmp_path):
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="jobd")
    c1.set_server_permanent("cluster", "cluster", '{"pods": []}')
    c1.set_server_permanent("job_status", "job_status", "RUNNING")
    c1.set_server_with_lease("resource", "podA", "x", ttl=30)  # ephemeral
    c1.remove_server("job_status", "job_status")
    s1.stop()

    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jobd")
        # permanent keys survive; deleted and leased keys do not
        assert c2.get_value("cluster", "cluster") == '{"pods": []}'
        assert c2.get_value("job_status", "job_status") is None
        assert c2.get_value("resource", "podA") is None
    finally:
        s2.stop()


def test_wal_torn_tail_is_ignored(tmp_path):
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="jobd")
    c1.set_server_permanent("svc", "a", "v1")
    s1.stop()
    with open(wal, "a") as f:
        f.write('{"op": "put", "k": "/jobd/svc/nodes/b", "v": "tr')  # torn
    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jobd")
        assert c2.get_value("svc", "a") == "v1"
        assert c2.get_value("svc", "b") is None
    finally:
        s2.stop()


def test_revisions_and_watchers_survive_restart(tmp_path):
    """Revisions never regress across a restart, and a watcher from the
    previous incarnation is forced to re-list (reset) so it sees both new
    keys and leased keys that died with the old process."""
    port = find_free_port()
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    coord = CoordClient(["127.0.0.1:%d" % port], root="jw")
    coord.set_server_permanent("svc", "keep", "v")
    coord.set_server_with_lease("svc", "ephemeral", "x", ttl=60)
    rev_before = coord.revision()

    views = []
    watcher = coord.watch_service("svc", lambda a, r, alls: views.append(
        dict(alls)), poll_timeout=0.5)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not views:
        time.sleep(0.1)
    assert views and set(views[-1]) == {"keep", "ephemeral"}

    s1.stop()
    s2 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    try:
        assert coord.revision() >= rev_before  # no regression
        coord.set_server_permanent("svc", "new", "n")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if views and set(views[-1]) == {"keep", "new"}:
                break
            time.sleep(0.2)
        # the watcher re-listed: ephemeral gone, new key visible
        assert set(views[-1]) == {"keep", "new"}, views[-1]
    finally:
        watcher.stop()
        s2.stop()


def test_permanent_value_shadowed_by_lease_not_resurrected(tmp_path):
    """A permanent key later overwritten by a leased registration must NOT
    come back from the WAL after a restart."""
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    c1 = CoordClient([s1.endpoint], root="js")
    c1.set_server_permanent("svc", "k", "permanent")
    c1.set_server_with_lease("svc", "k", "ephemeral", ttl=60)
    s1.stop()
    s2 = StoreServer(host="127.0.0.1", wal_path=wal).start()
    try:
        assert CoordClient([s2.endpoint],
                           root="js").get_value("svc", "k") is None
    finally:
        s2.stop()


def test_non_string_values_rejected(tmp_path):
    s = StoreServer(host="127.0.0.1",
                    wal_path=str(tmp_path / "w.wal")).start()
    try:
        c = CoordClient([s.endpoint], root="jt")
        try:
            c.put("/jt/k", 123)
            raise AssertionError("expected a type error")
        except Exception as e:
            assert "str or bytes" in str(e)
        c.put("/jt/raw", b"\x00\xff")  # bytes are fine and durable
    finally:
        s.stop()
    s2 = StoreServer(host="127.0.0.1",
                     wal_path=str(tmp_path / "w.wal")).start()
    try:
        c2 = CoordClient([s2.endpoint], root="jt")
        assert c2.get_key("/jt/raw")["value"] == b"\x00\xff"
    finally:
        s2.stop()


def test_native_store_wal_durability(tmp_path):
    """The C++ store's WAL: permanent keys survive a SIGKILL restart,
    leased and shadowed values do not (parity with the Python backend)."""
    import signal

    from edl_tpu.coordination.native import NativeStoreServer, ensure_binary
    try:
        ensure_binary()
    except Exception as e:
        import pytest
        pytest.skip("native store unavailable: %r" % e)
    port_dir = str(tmp_path / "data")
    s1 = NativeStoreServer(data_dir=port_dir)
    s1.start()
    c1 = CoordClient([s1.endpoint], root="jn")
    c1.set_server_permanent("cluster", "cluster", '{"stage": "s1"}')
    c1.put("/jn/raw", b"\x00\xff")
    c1.set_server_permanent("svc", "shadow", "perm")
    c1.set_server_with_lease("svc", "shadow", "eph", ttl=60)
    c1.set_server_with_lease("resource", "pod", "x", ttl=60)
    rev1 = c1.revision()
    s1._proc.send_signal(signal.SIGKILL)  # hard crash
    s1._proc.wait()

    s2 = NativeStoreServer(port=s1._port, data_dir=port_dir)
    s2.start()
    try:
        c2 = CoordClient([s2.endpoint], root="jn")
        assert c2.get_value("cluster", "cluster") == '{"stage": "s1"}'
        assert c2.get_key("/jn/raw")["value"] == b"\x00\xff"
        assert c2.get_value("svc", "shadow") is None   # shadowed → gone
        assert c2.get_value("resource", "pod") is None  # leased → gone
        assert c2.revision() > rev1                     # no regression
    finally:
        s2.stop()


def test_register_survives_store_restart(tmp_path):
    """A store crash/restart must not kill registered components: the
    register re-establishes its lease on the new store instance."""
    port = find_free_port()
    wal = str(tmp_path / "store.wal")
    s1 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
    coord = CoordClient(["127.0.0.1:%d" % port], root="jobr")
    reg = Register(coord, "resource", "podA", "payload", ttl=2)
    try:
        assert coord.get_value("resource", "podA") == "payload"
        s1.stop()
        time.sleep(1.0)
        s2 = StoreServer(host="127.0.0.1", port=port, wal_path=wal).start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if coord.get_value("resource", "podA") == "payload":
                break
            time.sleep(0.3)
        assert coord.get_value("resource", "podA") == "payload"
        assert not reg.is_broken()
        s2.stop()
    finally:
        reg.stop()
