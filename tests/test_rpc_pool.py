"""ClientPool tests: shared-client checkout, traffic-class channels,
idle reaping vs leases, retire-on-error invalidation, and lifecycle.
The pool is the data plane's connection substrate (docs/data_plane.md);
these pin the lifecycle behaviors the readers rely on."""

import threading
import time

import pytest

from edl_tpu.rpc.pool import ClientPool
from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors


@pytest.fixture()
def server():
    srv = RpcServer()
    srv.register("echo", lambda x: x)
    srv.register("block", lambda s: time.sleep(s) or "done")
    srv.start()
    yield srv
    srv.stop()


def _wait(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return False


def test_pool_shares_one_client_per_endpoint(server):
    with ClientPool() as pool:
        a = pool.get(server.endpoint)
        b = pool.get(server.endpoint)
        assert a is b                       # one client carries everyone
        assert pool.call(server.endpoint, "echo", 7) == 7
        assert pool.stats() == {"open": 1, "dials": 1}


def test_pool_channels_are_distinct_connections(server):
    with ClientPool() as pool:
        ctl = pool.get(server.endpoint, channel="ctl")
        assign = pool.get(server.endpoint, channel="assign")
        assert ctl is not assign
        # both channels work independently against the same endpoint
        assert pool.call(server.endpoint, "echo", 1, channel="ctl") == 1
        assert pool.call(server.endpoint, "echo", 2,
                         channel="assign") == 2
        assert pool.stats() == {"open": 2, "dials": 2}


def test_pool_call_async_pipelines(server):
    with ClientPool() as pool:
        futs = [pool.call_async(server.endpoint, "echo", i)
                for i in range(8)]
        assert [f.result() for f in futs] == list(range(8))
        assert pool.stats()["dials"] == 1   # all rode one connection


def test_pool_idle_reap_and_redial(server):
    with ClientPool(idle_ttl=0.3, reap_interval=0.05) as pool:
        assert pool.call(server.endpoint, "echo", 1) == 1
        assert pool.stats()["open"] == 1
        # idle past the ttl: the reaper closes and drops the client
        assert _wait(lambda: pool.stats()["open"] == 0)
        # next caller transparently redials
        assert pool.call(server.endpoint, "echo", 2) == 2
        assert pool.stats() == {"open": 1, "dials": 2}


def test_pool_lease_blocks_reaper(server):
    with ClientPool(idle_ttl=0.2, reap_interval=0.05) as pool:
        with pool.lease(server.endpoint) as client:
            time.sleep(0.6)  # well past the ttl while leased
            assert pool.stats()["open"] == 1
            assert client.call("echo", 3) == 3  # never closed under us
        # released: now the reaper may take it
        assert _wait(lambda: pool.stats()["open"] == 0)
        assert pool.stats()["dials"] == 1


def test_pool_features_probed_once_and_cached(server):
    with ClientPool() as pool:
        feats = pool.features(server.endpoint)
        assert "rpc.pipeline" in feats
        assert pool.features(server.endpoint) is feats  # cached object


def test_pool_features_empty_for_legacy_peer(server):
    # a pre-pipelining peer advertises nothing; the probe must come
    # back empty rather than raising (the negotiation fallback signal)
    server.register("__features__", lambda: [])
    with ClientPool() as pool:
        assert pool.features(server.endpoint) == ()


def test_pool_retire_drops_all_channels_and_features(server):
    with ClientPool() as pool:
        pool.call(server.endpoint, "echo", 1, channel="ctl")
        pool.call(server.endpoint, "echo", 1, channel="hb")
        assert pool.features(server.endpoint)  # default-channel probe
        assert pool.stats() == {"open": 3, "dials": 3}
        pool.retire(server.endpoint)
        assert pool.stats()["open"] == 0    # every channel dropped
        assert pool._features == {}         # cache invalidated
        # next checkout redials fresh (peer may be a new generation)
        assert pool.call(server.endpoint, "echo", 2) == 2
        assert pool.stats()["dials"] == 4


def test_pool_close_idempotent_and_rejects_checkout(server):
    pool = ClientPool()
    assert pool.call(server.endpoint, "echo", 1) == 1
    pool.close()
    pool.close()  # idempotent
    with pytest.raises(errors.StatusError, match="closed"):
        pool.get(server.endpoint)
    with pytest.raises(errors.StatusError, match="closed"):
        pool.call(server.endpoint, "echo", 1)


def test_pool_close_fails_inflight_calls(server):
    # an owner's stop() relies on this: closing the pool unblocks any
    # thread parked in a pooled RPC instead of waiting out its timeout
    pool = ClientPool(timeout=30.0)
    result = {}

    def blocked():
        try:
            result["v"] = pool.call(server.endpoint, "block", 5.0)
        except errors.EdlError as e:
            result["v"] = e

    t = threading.Thread(target=blocked)
    t.start()
    _wait(lambda: pool.stats()["open"] == 1)
    time.sleep(0.1)  # let the call get onto the wire
    t0 = time.monotonic()
    pool.close()
    t.join(timeout=4)
    assert not t.is_alive()
    assert time.monotonic() - t0 < 4  # did not sit out the 5s handler
    assert isinstance(result["v"], errors.EdlError)
