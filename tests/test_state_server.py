"""Peer-served restore plane: StateServer snapshot/serve semantics and
PeerRestorer's ladder (peers -> per-span FS fill -> error), including
the bit-identical peer-vs-FS restore guarantee the resize bench rests
on."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.controller import constants
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.server import StoreServer
from edl_tpu.runtime.checkpoint import CheckpointManager
from edl_tpu.runtime.state_server import (PeerRestorer, StateServer,
                                          snapshot_entries)
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors


@pytest.fixture()
def coord():
    srv = StoreServer(host="127.0.0.1", port=0).start()
    try:
        yield CoordClient([srv.endpoint], root="t_peer")
    finally:
        srv.stop()


def _tree(seed):
    """dp-sharded + replicated + bf16 + host-scalar state over the
    8-device CPU mesh, with its host mirror."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    rng = np.random.RandomState(seed)
    w = rng.randn(8, 4).astype(np.float32)
    mu = rng.randn(16, 2).astype(np.float32)
    bf = rng.randn(8, 2).astype(np.float32)
    tree = {
        "params": {"w": jax.device_put(w, NamedSharding(mesh, P()))},
        "opt": {"mu": jax.device_put(mu, NamedSharding(mesh, P("dp")))},
        "bf16": jax.device_put(jnp.asarray(bf, jnp.bfloat16),
                               NamedSharding(mesh, P("dp"))),
        "step": np.int32(seed),
    }
    host = {"params": {"w": w}, "opt": {"mu": mu}, "bf16": bf,
            "step": np.int32(seed)}
    return tree, host


def _target_and_shardings(tree, n=4):
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    shardings = {"params": {"w": NamedSharding(mesh, P())},
                 "opt": {"mu": NamedSharding(mesh, P("dp"))},
                 "bf16": NamedSharding(mesh, P("dp")),
                 "step": NamedSharding(mesh, P())}
    target = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       getattr(x, "dtype",
                                               np.asarray(x).dtype)),
        tree)
    return target, shardings


def _assert_bit_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype
        assert xa.tobytes() == ya.tobytes()


def test_snapshot_entries_spans_wire_dtypes_and_copies():
    tree, host = _tree(1)
    entries, dtypes = snapshot_entries(tree)
    # replicated leaf: ONE full-span entry, not eight
    assert "params/w@0:8;0:4" in entries
    # dp-sharded leaf over 8 devices: 8 disjoint row spans
    mu_keys = [k for k in entries if k.startswith("opt/mu@")]
    assert len(mu_keys) == 8
    np.testing.assert_array_equal(entries["opt/mu@2:4;0:2"],
                                  host["opt"]["mu"][2:4])
    # bf16 rides the wire as uint16 + tag
    assert entries["bf16@0:1;0:2"].dtype == np.uint16
    assert dtypes["bf16"] == "bfloat16"
    assert entries["step@"].shape == ()

    # published buffers are copies: mutating the source afterwards must
    # not change what a peer would be served
    src = np.arange(6, dtype=np.float32)
    entries2, _ = snapshot_entries({"h": src})
    src[:] = -1
    np.testing.assert_array_equal(entries2["h@0:6"],
                                  np.arange(6, dtype=np.float32))


def test_state_server_manifest_read_stale_and_missing():
    tree, host = _tree(2)
    srv = StateServer(rank=3, host="127.0.0.1")
    client = None
    try:
        entries, dtypes = snapshot_entries(tree)
        srv.publish(5, entries, dtypes, meta={"state": {"epoch": 9}})
        client = RpcClient(srv.endpoint)
        man = client.call("state.manifest")
        assert man["version"] == 5 and man["rank"] == 3
        assert man["meta"] == {"state": {"epoch": 9}}
        ent = man["entries"]["opt/mu@2:4;0:2"]
        want = host["opt"]["mu"][2:4]
        assert ent["nbytes"] == want.nbytes
        blob = np.asarray(client.call("state.read", 5,
                                      "opt/mu@2:4;0:2", 0,
                                      want.nbytes))
        np.testing.assert_array_equal(
            blob.view(np.float32).reshape(2, 2), want)
        # offset/length sub-reads slice the same buffer
        part = np.asarray(client.call("state.read", 5,
                                      "opt/mu@2:4;0:2", 4, 8))
        assert part.tobytes() == want.tobytes()[4:12]
        with pytest.raises(errors.StaleStateError):
            client.call("state.read", 4, "opt/mu@2:4;0:2", 0, 8)
        with pytest.raises(errors.NotFoundError):
            client.call("state.read", 5, "nope@0:1", 0, 8)
    finally:
        if client is not None:
            client.close()
        srv.stop()


def test_peer_restore_bit_identical_to_fs(coord, tmp_path):
    """THE resize-bench invariant: a peer-served placed restore yields
    byte-for-byte the state a shared-FS placed restore yields."""
    tree, host = _tree(7)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(7, tree, meta={"state": {"epoch": 1}}).result(60.0)

    srv = StateServer(rank=1, host="127.0.0.1")
    try:
        entries, dtypes = snapshot_entries(tree)
        srv.publish(7, entries, dtypes, meta={"state": {"epoch": 1}})
        srv.advertise(coord)
        # discovery sees the advertised endpoint
        regs = coord.get_service(constants.SERVICE_STATE_SERVER)
        assert [json.loads(v)["endpoint"] for _, v in regs] \
            == [srv.endpoint]

        target, shardings = _target_and_shardings(tree)
        v, peer_tree, meta, stats = PeerRestorer(coord, cm) \
            .restore_placed(7, target, shardings)
        assert v == 7 and meta == {"state": {"epoch": 1}}
        assert stats["source"] == "peer" and stats["fs_keys"] == []
        assert stats["peers"] == 1 and stats["peer_bytes"] > 0

        _, fs_tree, _ = cm.restore_placed(7, target, shardings)
        _assert_bit_identical(peer_tree, fs_tree)
        np.testing.assert_array_equal(
            np.asarray(peer_tree["opt"]["mu"]), host["opt"]["mu"])
    finally:
        srv.stop()
        cm.close()


def test_peer_restore_partial_coverage_fills_rest_from_fs(coord,
                                                          tmp_path):
    tree, host = _tree(9)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(3, tree).result(60.0)
    srv = StateServer(rank=2, host="127.0.0.1")
    try:
        entries, dtypes = snapshot_entries(tree)
        partial = {k: v for k, v in entries.items()
                   if k.startswith(("opt/mu@", "step@"))}
        srv.publish(3, partial, dtypes)
        srv.advertise(coord)
        target, shardings = _target_and_shardings(tree)
        v, peer_tree, _, stats = PeerRestorer(coord, cm) \
            .restore_placed(3, target, shardings)
        assert stats["source"] == "peer+fs"
        assert set(stats["fs_keys"]) == {"params/w", "bf16"}
        _, fs_tree, _ = cm.restore_placed(3, target, shardings)
        _assert_bit_identical(peer_tree, fs_tree)
    finally:
        srv.stop()
        cm.close()


def test_peer_restore_no_peers_and_stale_and_self(coord, tmp_path):
    tree, _ = _tree(4)
    cm = CheckpointManager(str(tmp_path))
    target, shardings = _target_and_shardings(tree)
    with pytest.raises(errors.PeerRestoreError):
        PeerRestorer(coord, cm).restore_placed(1, target, shardings)
    srv = StateServer(rank=0, host="127.0.0.1")
    try:
        entries, dtypes = snapshot_entries(tree)
        srv.publish(6, entries, dtypes)  # older than requested
        srv.advertise(coord)
        with pytest.raises(errors.PeerRestoreError):
            PeerRestorer(coord, cm).restore_placed(7, target, shardings)
        # a process must never "restore" from its own server
        srv.publish(7, entries, dtypes)
        with pytest.raises(errors.PeerRestoreError):
            PeerRestorer(coord, cm, self_endpoint=srv.endpoint) \
                .restore_placed(7, target, shardings)
    finally:
        srv.stop()
        cm.close()


def test_peer_restore_unreachable_endpoint_skipped(coord, tmp_path):
    """A peer that died between advertise and dial (lease not yet
    expired) is skipped, not fatal."""
    tree, _ = _tree(5)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(2, tree).result(60.0)
    dead = StateServer(rank=4, host="127.0.0.1")
    dead.advertise(coord)
    dead_reg, dead._register = dead._register, None  # keep the lease
    dead.stop()
    live = StateServer(rank=5, host="127.0.0.1")
    try:
        entries, dtypes = snapshot_entries(tree)
        live.publish(2, entries, dtypes)
        live.advertise(coord)
        target, shardings = _target_and_shardings(tree)
        v, peer_tree, _, stats = PeerRestorer(
            coord, cm, timeout=3.0).restore_placed(2, target, shardings)
        assert stats["source"] == "peer" and stats["peers"] == 1
        _, fs_tree, _ = cm.restore_placed(2, target, shardings)
        _assert_bit_identical(peer_tree, fs_tree)
    finally:
        dead_reg.stop()
        live.stop()
        cm.close()
