"""Flash attention kernel tests (interpret mode on CPU; real-TPU execution
is covered by bench/ops microbenches)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.flash_attention import (_blockwise_reference,
                                         flash_attention, mha)
from edl_tpu.parallel.ring_attention import dense_attention


def _qkv(b=2, h=2, s=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32)) * 0.4
    return mk(), mk(), mk()


def _dense_bhsd(q, k, v, causal):
    # dense_attention uses [b, s, h, d]
    out = dense_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                          v.transpose(0, 2, 1, 3), causal=causal)
    return out.transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [64, 96])  # 96 → ragged last kv block
def test_flash_matches_dense(causal, s):
    q, k, v = _qkv(s=s)
    want = _dense_bhsd(q, k, v, causal)
    got = flash_attention(q, k, v, causal, None, 32, 32, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_reference_matches_dense():
    q, k, v = _qkv(s=80)
    for causal in (False, True):
        want = _dense_bhsd(q, k, v, causal)
        got = _blockwise_reference(q, k, v, causal, q.shape[-1] ** -0.5,
                                   block_k=32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_flash_gradients_match_dense():
    q, k, v = _qkv(s=48, d=8)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, None, 16, 16, True)
                ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_bhsd(q, k, v, True) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_bert_flash_matches_dense():
    from edl_tpu.models import bert
    kw = dict(num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
              vocab_size=100, max_len=64, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 100, (2, 32)),
                      jnp.int32)
    m_dense = bert.Bert(**kw)
    m_flash = bert.Bert(use_flash=True, **kw)
    params = m_dense.init(jax.random.PRNGKey(0), ids)["params"]
    out_d = m_dense.apply({"params": params}, ids)
    out_f = m_flash.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


def test_mha_layout_wrapper():
    q, k, v = _qkv(s=32)
    qs, ks, vs = (x.transpose(0, 2, 1, 3) for x in (q, k, v))  # [b,s,h,d]
    got = mha(qs, ks, vs, causal=False, interpret=True)
    want = _dense_bhsd(q, k, v, False).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
