"""Diskless fault tolerance (runtime/redundancy.py): the GF(256)
erasure codec, the deterministic partner ring, version fencing on the
shard depot, and the parity rung of the restore ladder — including the
headline guarantee: a dead pod's state decoded purely from partner
shards into a NEW mesh factorization is byte-identical to the FS
restore, and a chaos-faulted rebuild degrades to the FS rung
losslessly."""

import itertools
import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.controller import constants
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.server import StoreServer
from edl_tpu.parallel import costmodel  # noqa: F401 — rebuild_plan dep
from edl_tpu.robustness import faults
from edl_tpu.runtime import redundancy
from edl_tpu.runtime.checkpoint import CheckpointManager, PlacedTarget
from edl_tpu.runtime.state_server import (PeerRestorer, StateServer,
                                          snapshot_entries)
from edl_tpu.rpc.client import RpcClient
from edl_tpu.utils import errors


@pytest.fixture()
def coord():
    srv = StoreServer(host="127.0.0.1", port=0).start()
    try:
        yield CoordClient([srv.endpoint], root="t_red")
    finally:
        srv.stop()


def _tree(seed):
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    rng = np.random.RandomState(seed)
    w = rng.randn(8, 4).astype(np.float32)
    mu = rng.randn(16, 2).astype(np.float32)
    bf = rng.randn(8, 2).astype(np.float32)
    tree = {
        "params": {"w": jax.device_put(w, NamedSharding(mesh, P()))},
        "opt": {"mu": jax.device_put(mu, NamedSharding(mesh, P("dp")))},
        "bf16": jax.device_put(jnp.asarray(bf, jnp.bfloat16),
                               NamedSharding(mesh, P("dp"))),
        "step": np.int32(seed),
    }
    host = {"params": {"w": w}, "opt": {"mu": mu}, "bf16": bf,
            "step": np.int32(seed)}
    return tree, host


def _target_and_shardings(tree, n=4):
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    shardings = {"params": {"w": NamedSharding(mesh, P())},
                 "opt": {"mu": NamedSharding(mesh, P("dp"))},
                 "bf16": NamedSharding(mesh, P("dp")),
                 "step": NamedSharding(mesh, P())}
    target = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                       getattr(x, "dtype",
                                               np.asarray(x).dtype)),
        tree)
    return target, shardings


def _assert_bit_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype
        assert xa.tobytes() == ya.tobytes()


def _holder(coord, key, shard_read_hook=None):
    srv = StateServer(rank=int(key), host="127.0.0.1")
    if shard_read_hook is not None:
        srv.shard_read_hook = shard_read_hook
    srv.advertise_redundancy(coord, key=str(key))
    return srv


# -- codec ------------------------------------------------------------------

def test_codec_round_trip_every_loss_subset():
    """k-of-n MDS: EVERY k-subset of the n shards decodes the blob,
    for blob sizes that do and do not divide by k."""
    rng = np.random.default_rng(0)
    for k in range(1, 5):
        for m in range(0, 3):
            for size in (k * 257, k * 257 + 3, 1):
                blob = rng.integers(0, 256, size=size, dtype=np.uint8)
                shards = redundancy.encode(blob, k, m)
                assert len(shards) == k + m
                assert len({s.size for s in shards}) == 1
                for keep in itertools.combinations(range(k + m), k):
                    out = redundancy.decode(
                        {i: shards[i] for i in keep}, k, m, blob.size)
                    assert np.array_equal(out, blob), (k, m, size, keep)


def test_codec_insufficient_shards_reason():
    blob = np.arange(100, dtype=np.uint8)
    shards = redundancy.encode(blob, 3, 1)
    with pytest.raises(errors.RedundancyError) as ei:
        redundancy.decode({0: shards[0], 2: shards[2]}, 3, 1, blob.size)
    assert ei.value.reason == "insufficient_partners"


def test_pack_unpack_snapshot_round_trip():
    entries = {
        "opt/mu@2:4;0:2": np.arange(4, dtype=np.float32).reshape(2, 2),
        "bf16@0:1;0:2": np.array([[7, 9]], np.uint16),  # tagged wire
        "step@": np.int32(5),
    }
    dtypes = {"bf16": "bfloat16"}
    blob = redundancy.pack_snapshot(entries, dtypes,
                                    meta={"state": {"epoch": 3}})
    out, dt, meta = redundancy.unpack_snapshot(blob)
    assert dt == dtypes and meta == {"state": {"epoch": 3}}
    assert set(out) == set(entries)
    for skey in entries:
        want = np.asarray(entries[skey])
        assert out[skey].dtype == want.dtype
        assert out[skey].shape == want.shape
        assert out[skey].tobytes() == want.tobytes()


# -- partner ring -----------------------------------------------------------

def test_partner_ring_pure_function_of_member_set():
    members = ["p3", "p1", "p7", "p0", "p5"]
    want = redundancy.partner_ring(members, "p3", 3)
    assert want == ["p5", "p7", "p0"]  # cyclic successors of p3
    rng = random.Random(0)
    for _ in range(10):
        shuffled = list(members)
        rng.shuffle(shuffled)
        assert redundancy.partner_ring(shuffled, "p3", 3) == want
    # self never partners itself; n caps at the other members
    assert "p3" not in redundancy.partner_ring(members, "p3", 99)
    assert len(redundancy.partner_ring(members, "p3", 99)) == 4
    assert redundancy.partner_ring(["p0"], "p0", 3) == []
    # a resize recomputes consistently: every pod derives every OTHER
    # pod's ring from the same set with no negotiation
    grown = members + ["p2", "p9"]
    rings = {p: redundancy.partner_ring(grown, p, 2) for p in grown}
    for p, ring in rings.items():
        assert p not in ring and len(ring) == 2
        shuffled = list(grown)
        rng.shuffle(shuffled)
        assert redundancy.partner_ring(shuffled, p, 2) == ring


# -- shard depot version fencing --------------------------------------------

def test_shard_put_version_fencing(coord):
    srv = _holder(coord, 9301)
    client = None
    try:
        client = RpcClient(srv.endpoint)
        header = {"k": 2, "m": 1, "blob_len": 8, "chunk_len": 4}
        payload = np.arange(4, dtype=np.uint8)
        client.call("state.shard_put", "owner", 7, 0, header, payload)
        # an OLDER version is fenced at the server, never stored
        with pytest.raises(errors.StaleStateError):
            client.call("state.shard_put", "owner", 6, 1, header,
                        payload)
        # reads are version-fenced too: a stale reader never decodes
        with pytest.raises(errors.StaleStateError):
            client.call("state.shard", "owner", 6, 0, 0, 4)
        got = np.asarray(client.call("state.shard", "owner", 7, 0,
                                     0, 4))
        assert got.tobytes() == payload.tobytes()
        with pytest.raises(errors.NotFoundError):
            client.call("state.shard", "owner", 7, 1, 0, 4)
        # a NEWER version evicts the old record wholesale
        client.call("state.shard_put", "owner", 8, 2, header, payload)
        man = client.call("state.shard_manifest")
        assert man["shards"]["owner"]["version"] == 8
        assert man["shards"]["owner"]["held"] == [2]
        with pytest.raises(errors.StaleStateError):
            client.call("state.shard", "owner", 7, 0, 0, 4)
    finally:
        if client is not None:
            client.close()
        srv.stop()


# -- push + rebuild ---------------------------------------------------------

def test_push_and_rebuild_into_new_mesh_byte_identical(coord, tmp_path):
    """THE diskless guarantee: state saved on the 8-device mesh,
    erasure-coded to partners, is decoded and placed onto a DIFFERENT
    4-device factorization byte-for-byte equal to the FS restore —
    with the checkpoint directory never touched."""
    tree, host = _tree(11)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(7, tree, meta={"state": {"epoch": 2}}).result(60.0)
    holders = [_holder(coord, k) for k in (9301, 9302, 9303)]
    try:
        entries, dtypes = snapshot_entries(tree)
        push = redundancy.push_shards(coord, "0", 7, entries, dtypes,
                                      meta={"state": {"epoch": 2}},
                                      k=2, m=1)
        assert push["pushed"] == 3 and push["k"] == 2 and push["m"] == 1

        target, shardings = _target_and_shardings(tree, n=4)
        v, par_tree, meta, stats = redundancy.restore_placed(
            coord, 7, target, shardings)
        assert v == 7 and meta == {"state": {"epoch": 2}}
        assert stats["source"] == "parity"
        assert stats["owners"] == ["0"] and stats["parity_bytes"] > 0

        _, fs_tree, _ = cm.restore_placed(7, target, shardings)
        _assert_bit_identical(par_tree, fs_tree)
        np.testing.assert_array_equal(
            np.asarray(par_tree["opt"]["mu"]), host["opt"]["mu"])
    finally:
        for h in holders:
            h.stop()
        cm.close()


def test_rebuild_survives_dead_partner(coord, tmp_path):
    """One of three partners dead (lease not yet expired): the decode
    finishes from the remaining k shards, forced through a parity
    shard."""
    tree, _ = _tree(13)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(4, tree).result(60.0)
    holders = [_holder(coord, k) for k in (9301, 9302, 9303)]
    dead_reg = None
    try:
        entries, dtypes = snapshot_entries(tree)
        push = redundancy.push_shards(coord, "0", 4, entries, dtypes,
                                      k=2, m=1)
        assert push["pushed"] == 3
        # kill the middle holder but keep its lease advertised
        dead = holders[1]
        dead_reg, dead._redundancy_register = \
            dead._redundancy_register, None
        dead.stop()
        target, shardings = _target_and_shardings(tree, n=4)
        _, par_tree, _, stats = redundancy.restore_placed(
            coord, 4, target, shardings)
        assert stats["owners"] == ["0"]
        _, fs_tree, _ = cm.restore_placed(4, target, shardings)
        _assert_bit_identical(par_tree, fs_tree)
    finally:
        if dead_reg is not None:
            dead_reg.stop()
        for h in holders:
            h.stop()
        cm.close()


def test_stale_holders_skipped_then_fenced(coord):
    """A holder stuck at an older version is skipped (its shard is
    never decoded); when EVERY holder is stale the rebuild reports
    stale_version and fills nothing — the FS rung's job."""
    tree, _ = _tree(17)
    entries, dtypes = snapshot_entries(tree)
    holders = [_holder(coord, k) for k in (9301, 9302, 9303)]
    try:
        assert redundancy.push_shards(coord, "0", 6, entries, dtypes,
                                      k=2, m=1)["pushed"] == 3
        # v7 lands on only two partners (the third stays at v6): the
        # rebuild must use exactly the fresh pair
        blob = redundancy.pack_snapshot(entries, dtypes, meta=None)
        shards = redundancy.encode(blob, 2, 1)
        header = {"k": 2, "m": 1, "blob_len": int(blob.size),
                  "chunk_len": int(shards[0].size)}
        for idx, srv in ((0, holders[0]), (1, holders[1])):
            c = RpcClient(srv.endpoint)
            try:
                c.call("state.shard_put", "0", 7, idx, header,
                       shards[idx])
            finally:
                c.close()
        target, shardings = _target_and_shardings(tree, n=4)
        _, par_tree, _, _ = redundancy.restore_placed(
            coord, 7, target, shardings)
        _assert_bit_identical(par_tree, jax.device_put(tree, shardings))
        # v8 exists nowhere: every holder is stale -> reason recorded,
        # nothing pasted, restore_placed refuses
        pt = PlacedTarget(target, shardings)
        stats = redundancy.fill_from_parity(coord, 8, pt)
        assert stats["reason"] == "stale_version"
        assert stats["owners"] == [] and stats["parity_bytes"] == 0
        assert pt.missing()
        with pytest.raises(errors.RedundancyError):
            redundancy.restore_placed(coord, 8, target, shardings)
    finally:
        for h in holders:
            h.stop()


def test_insufficient_survivors_reports_reason(coord):
    """k=2 shards spread over three partners; two die -> one live
    shard < k, the rebuild refuses with insufficient_partners (and
    nothing is half-pasted)."""
    tree, _ = _tree(19)
    entries, dtypes = snapshot_entries(tree)
    holders = [_holder(coord, k) for k in (9301, 9302, 9303)]
    dead_regs = []
    try:
        assert redundancy.push_shards(coord, "0", 3, entries, dtypes,
                                      k=2, m=1)["pushed"] == 3
        for dead in holders[:2]:
            dead_regs.append(dead._redundancy_register)
            dead._redundancy_register = None
            dead.stop()
        target, shardings = _target_and_shardings(tree, n=4)
        pt = PlacedTarget(target, shardings)
        stats = redundancy.fill_from_parity(coord, 3, pt, timeout=3.0)
        assert stats["reason"] == "insufficient_partners"
        assert stats["parity_bytes"] == 0 and pt.missing()
    finally:
        for reg in dead_regs:
            reg.stop()
        for h in holders:
            h.stop()


# -- the restore ladder -----------------------------------------------------

def test_ladder_peer_plus_parity_then_faulted_fs_fallback(coord,
                                                          tmp_path):
    """PeerRestorer's ladder with the parity rung in place: a live
    peer covers part of the snapshot, the parity decode covers the
    dead pod's remainder with ZERO FS keys — and when a chaos fault
    arms ``redundancy.rebuild`` the SAME restore degrades to the FS
    rung byte-identically (the catalog drill)."""
    tree, _ = _tree(23)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(5, tree, meta={"state": {"epoch": 4}}).result(60.0)
    entries, dtypes = snapshot_entries(tree)
    peer = StateServer(rank=1, host="127.0.0.1")
    holders = [_holder(coord, k) for k in (9301, 9302, 9303)]
    plane = None
    try:
        # the survivor serves only part of the state...
        partial = {k: v for k, v in entries.items()
                   if k.startswith(("opt/mu@", "step@"))}
        peer.publish(5, partial, dtypes, meta={"state": {"epoch": 4}})
        peer.advertise(coord)
        # ...the dead pod's parity cover holds all of it
        assert redundancy.push_shards(coord, "0", 5, entries, dtypes,
                                      meta={"state": {"epoch": 4}},
                                      k=2, m=1)["pushed"] == 3

        target, shardings = _target_and_shardings(tree, n=4)
        _, fs_tree, _ = cm.restore_placed(5, target, shardings)

        v, got, meta, stats = PeerRestorer(coord, cm).restore_placed(
            5, target, shardings)
        assert v == 5 and meta == {"state": {"epoch": 4}}
        assert stats["source"] == "peer+parity"
        assert stats["fs_keys"] == []
        assert stats["parity_bytes"] > 0
        _assert_bit_identical(got, fs_tree)

        # chaos drill: fault the rebuild -> the parity rung is skipped
        # (reason=fault) and the FS rung restores losslessly
        plane = faults.FaultPlane(seed=0).install()
        fault = plane.inject("redundancy.rebuild", "error")
        _, got2, _, stats2 = PeerRestorer(coord, cm).restore_placed(
            5, target, shardings)
        assert fault.fired >= 1
        assert stats2["source"] == "peer+fs"
        assert set(stats2["fs_keys"]) == {"params/w", "bf16"}
        _assert_bit_identical(got2, fs_tree)
    finally:
        if plane is not None:
            plane.uninstall()
        peer.stop()
        for h in holders:
            h.stop()
        cm.close()


def test_kill_switch_disables_parity_rung(coord, tmp_path,
                                          monkeypatch):
    """EDL_TPU_REDUNDANCY=0 turns the whole tier off: pushes are not
    attempted and the ladder never dials holders."""
    monkeypatch.setenv("EDL_TPU_REDUNDANCY", "0")
    assert not redundancy.enabled()
    tree, _ = _tree(29)
    cm = CheckpointManager(str(tmp_path))
    cm.save_async(2, tree).result(60.0)
    peer = StateServer(rank=1, host="127.0.0.1")
    try:
        entries, dtypes = snapshot_entries(tree)
        partial = {k: v for k, v in entries.items()
                   if k.startswith(("opt/mu@", "step@"))}
        peer.publish(2, partial, dtypes)
        peer.advertise(coord)
        target, shardings = _target_and_shardings(tree, n=4)
        _, got, _, stats = PeerRestorer(coord, cm).restore_placed(
            2, target, shardings)
        assert stats["source"] == "peer+fs"  # parity rung never tried
        _, fs_tree, _ = cm.restore_placed(2, target, shardings)
        _assert_bit_identical(got, fs_tree)
    finally:
        peer.stop()
        cm.close()


# -- analytic plan ----------------------------------------------------------

def test_rebuild_plan_classifies_parity_vs_survivor_bytes():
    """(8,4) f32 dp-sharded one row block per source device; dst is
    the 4-way factorization. Losing source device 0 makes exactly its
    unique row parity traffic; everything else is peer-readable."""
    leaves = [((8, 4), 4, ("dp",), ("dp",))]
    plan = redundancy.rebuild_plan(leaves, {"dp": 8}, {"dp": 4},
                                   lost_devices=[0])
    assert plan["parity_bytes"] == 1 * 4 * 4  # the lost row
    assert plan["survivor_bytes"] == 7 * 4 * 4
    assert plan["needed_bytes"] > 0
    # no losses -> nothing owes the decode anything
    clean = redundancy.rebuild_plan(leaves, {"dp": 8}, {"dp": 4}, [])
    assert clean["parity_bytes"] == 0
    assert clean["survivor_bytes"] == 8 * 4 * 4
    # replicated leaf: any survivor serves it, even losing 7 of 8
    repl = redundancy.rebuild_plan([((8, 4), 4, (), ())],
                                   {"dp": 8}, {"dp": 4},
                                   lost_devices=list(range(7)))
    assert repl["parity_bytes"] == 0
    assert repl["survivor_bytes"] == 8 * 4 * 4
