"""Exit-code-controlled dummy trainer for launcher tests.

Reference parity: example/demo/collective demo trainer + launch_demo.py
(exit-code-controlled, SURVEY.md §4). Usage:
    dummy_trainer.py [sleep_seconds] [exit_code]
Prints its rank/world/stage so tests can assert the env contract.
"""

import sys
import time

from edl_tpu.controller.env import TrainerEnv


def main():
    sleep_s = float(sys.argv[1]) if len(sys.argv) > 1 else 0.5
    exit_code = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    env = TrainerEnv()
    print("dummy_trainer rank=%d world=%d stage=%s pod=%s devices=%s"
          % (env.global_rank, env.world_size, env.cluster_stage, env.pod_id,
             env.local_devices), flush=True)
    deadline = time.time() + sleep_s
    while time.time() < deadline:
        time.sleep(0.1)
    print("dummy_trainer rank=%d exiting %d" % (env.global_rank, exit_code),
          flush=True)
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
