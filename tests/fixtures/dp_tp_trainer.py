"""dp x tp elastic trainer fixture — the 4-host resize analogue.

bert_tiny with Megatron tp rules on ``make_mesh(tp=...)`` over the
launcher's multi-process jax world (each pod contributes its virtual
CPU devices; tp shards params, dp spans pods). Batches derive from the
trainer's OWN global_step — step g+1 consumes the deterministic record
window [g*B, (g+1)*B) — so a committed step IS a consumed window, and
the FEED lines rank 0 prints across every incarnation must cover
1..final contiguously (duplicates only at preemption boundaries, where
a fetched batch's step was stopped before executing): the exactly-once
bar across world-size changes.

Also engages the AOT resize prewarm each incarnation; in a
multi-process world its scope guard must refuse cleanly
(PREWARM_SCOPE line, asserted by the driving test) instead of
corrupting anything.
"""

import argparse
import json
import sys

import optax

from edl_tpu.runtime.trainer import ElasticTrainer, maybe_init_distributed


def main(argv=None):
    maybe_init_distributed()
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import bert
    from edl_tpu.runtime.mesh import make_mesh

    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--steps_per_epoch", type=int, default=20)
    p.add_argument("--total_batch_size", type=int, default=24)
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--step_sleep", type=float, default=0.05)
    args = p.parse_args(argv)

    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    mesh = make_mesh(tp=args.tp)
    trainer = ElasticTrainer(loss_fn, params, optax.adamw(1e-3),
                             total_batch_size=args.total_batch_size,
                             mesh=mesh,
                             param_shardings=bert.bert_partition_rules())
    rank = trainer.env.global_rank
    prewarm_checked = []

    def batches(epoch):
        import time
        for _ in range(args.steps_per_epoch):
            g = trainer.global_step
            print("FEED step=%d rank=%d world=%d epoch=%d"
                  % (g + 1, rank, trainer.world_size, epoch), flush=True)
            full = bert.synthetic_text_batch(args.total_batch_size,
                                             seq_len=16, seed=g)
            yield trainer.local_batch_slice(full)
            if not prewarm_checked:
                # engage the resize prewarm once a step has run (it
                # needs the example batch); the multi-process scope
                # guard must refuse with its reason
                prewarm_checked.append(True)
                why = trainer._prewarm_in_scope()
                done = trainer.prewarm_resize_compiles([1, 2])
                print("PREWARM_SCOPE rank=%d why=%r done=%r"
                      % (rank, why, done), flush=True)
            if args.step_sleep:
                time.sleep(args.step_sleep)

    if trainer.world_size > 1:
        # tp/dp really cross the process boundary: no single process
        # holds the full params
        leaf = next(iter(jax.tree_util.tree_leaves(
            trainer.train_state["params"])))
        assert not leaf.is_fully_addressable, "params fully local?!"

    result = trainer.fit(args.epochs, batches,
                         log_fn=lambda m: print(
                             m.replace("fit:", "dp_tp:"), flush=True))
    print(json.dumps({"final_loss": result["final_loss"],
                      "steps": result["steps"],
                      "world": result["world"]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
