"""Shared-prefix KV reuse + chunked prefill tests (ISSUE 19): the
prefix trie's ref-count/LRU mechanics and the slot cache's third
(cached) state; partial-prefix reuse decoding TOKEN-IDENTICAL to cold
prefill with exact ``reuse_tokens`` accounting; chunked offset-prefill
matching monolithic prefill at the logit level and at the engine level
under ONE fused step trace; drain with a half-prefilled chunked
sequence stranding nothing; the ``EDL_TPU_PREFIX_CACHE=0`` kill switch
reverting to cold prefill byte-identically; the
``serve.decode.prefix_lookup`` chaos point degrading LOSSLESSLY to
cold prefill; the per-token prefill EWMA (the long-prompt-poisoning
fix); and the doctor's ``prefix_thrash`` finding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.models import gpt as gpt_mod
from edl_tpu.robustness.faults import FaultPlane
from edl_tpu.serve.admission import DecodeAdmission
from edl_tpu.serve.decode_engine import DecodeEngine, _init_cache
from edl_tpu.serve.kv_cache import PrefixCache, SlotKvCache
from edl_tpu.utils import errors


@pytest.fixture(scope="module")
def tiny():
    model = gpt_mod.gpt_tiny(num_layers=2, d_model=32, num_heads=2,
                             mlp_dim=64, vocab_size=64, max_len=64,
                             dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _refs(model, params, prompts, max_new):
    """Reference tokens per prompt via ONE batched ``gpt.generate``
    call per prompt length (generate re-traces per call)."""
    out, by_len = {}, {}
    for p in prompts:
        by_len.setdefault(len(p), []).append(p)
    for group in by_len.values():
        toks = np.asarray(gpt_mod.generate(
            model, params, np.asarray(group, np.int32), max_new))
        for p, row in zip(group, toks):
            out[tuple(p)] = [int(t) for t in row]
    return out


# -- the trie ---------------------------------------------------------------


def test_prefix_trie_insert_lookup_depth_cap():
    pc = PrefixCache()
    pc.insert([1, 2, 3, 4], 0)
    assert pc.lookup([1, 2, 3, 4, 9]) == (0, 4)
    # at least one suffix token must remain (the first output token
    # comes from the last prompt position), so an IDENTICAL prompt
    # reuses all but its final token
    assert pc.lookup([1, 2, 3, 4]) == (0, 3)
    assert pc.lookup([7, 7]) == (None, 0)
    # peek never counts nor touches LRU
    assert pc.peek_len([1, 2, 9]) == 2
    s = pc.stats()
    assert (s["hits"], s["misses"], s["reuse_tokens"]) == (2, 1, 7)
    assert s["stored_paths"] == 1
    pc.forget(0)
    assert pc.lookup([1, 2, 3, 4, 9]) == (None, 0)
    assert pc.stats()["stored_paths"] == 0


def test_prefix_trie_one_path_per_slot_and_lru_eviction():
    pc = PrefixCache()
    pc.insert([1, 2, 3], 0)
    pc.insert([1, 2, 4], 1)
    pc.lookup([1, 2, 3, 5])          # bumps slot 0: slot 1 is now LRU
    assert pc.evict_lru([0, 1]) == 1
    assert pc.stats()["evictions"] == 1
    # slot 1's branch is pruned; the shared [1, 2] spine survives
    assert pc.lookup([1, 2, 4, 6]) == (0, 2)
    # re-inserting a slot REPLACES its old path (one path per slot)
    pc.insert([9, 9, 9], 0)
    assert pc.lookup([1, 2, 3, 5]) == (None, 0)
    assert pc.lookup([9, 9, 9, 1]) == (0, 3)
    # no eligible candidate -> no victim
    assert pc.evict_lru([5]) is None


def test_slot_kv_cache_retain_release_states():
    kv = SlotKvCache(lambda n: {"k": jnp.zeros((n, 4, 2, 2))}, slots=2)
    a, b = kv.alloc(), kv.alloc()
    kv.retain(a)                     # live -> cached
    assert kv.cached_rows == 1 and kv.occupied == 1
    assert kv.free_slots == 0 and kv.cached() == [a]
    with pytest.raises(ValueError):
        kv.free(a)                   # cached rows are not live
    with pytest.raises(ValueError):
        kv.release(b)                # live rows are not cached
    kv.release(a)                    # cached -> free
    assert kv.free_slots == 1 and kv.cached_rows == 0
    assert kv.alloc() == a           # the released row is allocatable


# -- per-token prefill EWMA (the long-prompt-poisoning fix) -----------------


def test_admission_prefill_ewma_is_per_token():
    adm = DecodeAdmission(max_waiting=1 << 30, slot_slack=1 << 30,
                          ttft_slo_ms=8.0)
    # one 500-token prefill at 1ms/token must NOT poison the estimate
    # to 500ms-per-prompt (the pre-fix behavior)
    adm.observe_prefill_ms(500.0, tokens=500)
    assert adm.stats()["prefill_ms_per_token"] == pytest.approx(1.0)
    # token-accurate projection: 5 suffix tokens against an EMPTY
    # prefill queue admits regardless of the waiting count (liveness:
    # an idle engine serves the head immediately)
    adm.admit(free_slots=1, waiting=3, occupied=0, slots=4,
              suffix_tokens=5, queued_prefill_tokens=0)
    # 12 queued + 5 suffix tokens at 1ms/token = 17ms > the 8ms SLO
    with pytest.raises(errors.OverloadedError, match="ttft"):
        adm.admit(free_slots=1, waiting=1, occupied=0, slots=4,
                  suffix_tokens=5, queued_prefill_tokens=12)


# -- reuse parity + exact accounting ---------------------------------------


def test_prefix_reuse_token_parity_and_exact_accounting(tiny):
    model, params = tiny
    eng = DecodeEngine(model, params, slots=4, admission=False,
                       prefix_cache=True)
    eng.start()
    try:
        shared = [3, 1, 4, 1, 5, 9, 2, 6]
        prompts = [shared + [7, 7], shared + [8, 8], shared + [9, 9]]
        refs = _refs(model, params, prompts, 6)
        reports = [eng.generate(p, 6, timeout=120.0) for p in prompts]
        for p, r in zip(prompts, reports):
            assert r["tokens"] == refs[tuple(p)]
        pfx = eng.stats()["decode_prefix"]
        assert pfx["enabled"] is True
        # prompts 2 and 3 each reused EXACTLY len(shared) tokens
        assert pfx["hits"] == 2
        assert pfx["reuse_tokens"] == 2 * len(shared)
        # an identical resubmission reuses all but the last token and
        # still decodes the exact reference
        again = eng.generate(prompts[0], 6, timeout=120.0)
        assert again["tokens"] == refs[tuple(prompts[0])]
        pfx = eng.stats()["decode_prefix"]
        assert pfx["hits"] == 3
        assert pfx["reuse_tokens"] == 2 * len(shared) + len(prompts[0]) - 1
        assert pfx["reuse_frac"] > 0
        assert eng.drain(deadline_s=30.0)
    finally:
        eng.stop()


# -- chunked prefill: logit parity, engine parity, one step trace ----------


def test_chunked_prefill_logit_parity_vs_monolithic(tiny):
    """Offset chunks recompute the SAME K/V and final-position logits
    as one monolithic prefill — the model-layer contract the engine's
    token parity rides on."""
    model, params = tiny
    prompt = np.array([[5, 3, 8, 1, 9, 2, 7, 4, 6, 1, 2]], np.int32)
    plen = prompt.shape[1]

    row = _init_cache(model, None, 1)
    logits_full, muts_full = model.apply(
        {"params": params, "cache": row}, jnp.asarray(prompt),
        prefill=True, mutable=["cache"])

    row2 = _init_cache(model, None, 1)
    width = 4
    chunk_last = None
    for off in range(0, plen, width):
        span = min(width, plen - off)
        ids = np.zeros((1, width), np.int32)
        ids[0, :span] = prompt[0, off:off + span]
        logits_c, muts = model.apply(
            {"params": params, "cache": row2}, jnp.asarray(ids),
            prefill=True, prefill_offset=off, mutable=["cache"])
        row2 = muts["cache"]
        chunk_last = np.asarray(logits_c[0, span - 1])

    np.testing.assert_allclose(
        chunk_last, np.asarray(logits_full[0, plen - 1]),
        rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(muts_full["cache"]),
                    jax.tree_util.tree_leaves(row2)):
        np.testing.assert_allclose(np.asarray(a)[:, :plen],
                                   np.asarray(b)[:, :plen],
                                   rtol=1e-5, atol=1e-5)


def test_chunked_engine_token_parity_one_step_trace(tiny):
    model, params = tiny
    eng = DecodeEngine(model, params, slots=4, admission=False,
                       prefix_cache=False, prefill_chunk=3)
    eng.start()
    try:
        prompts = [[1, 5, 9, 2, 4], [3, 3, 3, 1, 2], [9, 8, 7, 6, 5]]
        refs = _refs(model, params, prompts, 6)
        handles = [eng.submit(p, 6) for p in prompts]
        for p, h in zip(prompts, handles):
            assert h.result(timeout=120.0)["tokens"] == refs[tuple(p)]
        s = eng.stats()
        # fixed-shape discipline survives chunking: one fused step
        # trace, every prefill routed through the bounded chunk traces
        assert s["decode_step_traces"] == 1
        assert s["decode_prefill_traces"] == 0
        assert s["decode_chunk_traces"] <= 2  # solo + fused variants
        assert s["decode_prefilled_tokens"] == sum(len(p) for p in prompts)
        assert eng.drain(deadline_s=30.0)
    finally:
        eng.stop()


def test_drain_with_half_prefilled_chunk_strands_nothing(tiny):
    """A drain issued while a chunked sequence is mid-prefill must
    finish that sequence (its chunks, then its decode), not strand
    it."""
    model, params = tiny
    prompt = [(i * 7 + 3) % 64 or 1 for i in range(40)]
    refs = _refs(model, params, [prompt], 4)
    eng = DecodeEngine(model, params, slots=2, admission=False,
                       prefix_cache=False, prefill_chunk=2)
    eng.start()
    try:
        h = eng.submit(prompt, 4)  # 20 chunk quanta ahead of it
        assert eng.drain(deadline_s=60.0)
        rep = h.result(timeout=5.0)
        assert rep["tokens"] == refs[tuple(prompt)]
        s = eng.stats()
        assert s["decode_evicted_total"] == 0
        assert s["decode_prefilling"] == 0 and s["decode_active"] == 0
    finally:
        eng.stop()


# -- the kill switch --------------------------------------------------------


def test_prefix_kill_switch_env_reverts_to_cold_prefill(tiny, monkeypatch):
    monkeypatch.setenv("EDL_TPU_PREFIX_CACHE", "0")
    model, params = tiny
    eng = DecodeEngine(model, params, slots=2, admission=False)
    eng.start()
    try:
        assert eng.stats()["decode_prefix"] == {"enabled": False}
        prompt = [2, 7, 1, 8, 2, 8]
        refs = _refs(model, params, [prompt], 5)
        for _ in range(2):
            assert eng.generate(prompt, 5,
                                timeout=120.0)["tokens"] == \
                refs[tuple(prompt)]
        # both runs prefilled the FULL prompt: nothing was reused
        assert eng.stats()["decode_prefilled_tokens"] == 2 * len(prompt)
        assert eng.drain(deadline_s=30.0)
    finally:
        eng.stop()


# -- the chaos point (docs/fault_tolerance.md catalog row) ------------------


def test_prefix_lookup_fault_is_lossless_cold_fallback(tiny):
    """``serve.decode.prefix_lookup`` error fault: the lookup fails,
    the sequence cold-prefills its FULL prompt, and the tokens are
    exactly the reference — reuse is an optimization, never a
    correctness dependency. The skipped lookup is counted a miss."""
    model, params = tiny
    eng = DecodeEngine(model, params, slots=4, admission=False,
                       prefix_cache=True)
    eng.start()
    plane = FaultPlane(seed=5)
    plane.inject("serve.decode.prefix_lookup", "error")
    plane.install()
    try:
        shared = [6, 2, 8, 3, 1, 7]
        prompts = [shared + [4, 4], shared + [5, 5]]
        refs = _refs(model, params, prompts, 6)
        for p in prompts:
            assert eng.generate(p, 6, timeout=120.0)["tokens"] == \
                refs[tuple(p)]
        pfx = eng.stats()["decode_prefix"]
        assert pfx["hits"] == 0 and pfx["misses"] == 2
        assert eng.stats()["decode_evicted_total"] == 0  # lossless
        assert plane.log == [("serve.decode.prefix_lookup", "error")] * 2
        assert eng.drain(deadline_s=30.0)
    finally:
        plane.uninstall()
        eng.stop()


# -- the doctor's thrash detector ------------------------------------------


def test_job_doctor_flags_prefix_thrash():
    """Evictions outpacing hits past the warmup floor is a ranked
    finding; a cache that is evicting but HITTING more is healthy churn
    and stays silent, as does one below the floor."""
    from edl_tpu.tools import job_doctor

    def gauge(v):
        return {"series": [{"labels": {}, "value": v}]}

    def doc(evictions, hits):
        return {"metrics": {"metrics": {
            "edl_decode_prefix_evictions_total": gauge(evictions),
            "edl_decode_prefix_hits_total": gauge(hits)}}}

    report = job_doctor.diagnose(
        {"job_id": "j", "job_status": None, "health": None,
         "obs": {"pod-0": doc(12, 3), "pod-1": doc(12, 40),
                 "pod-2": doc(2, 0)}})
    found = [f for f in report["findings"]
             if f["detector"] == "prefix_thrash"]
    assert len(found) == 1
    assert found[0]["pod"] == "pod-0"
    assert found[0]["metric"] == "edl_decode_prefix_evictions_total"
    assert "12" in found[0]["summary"]
    job_doctor.render(report)  # human surface renders the finding
