"""Evaluation helper tests."""

import numpy as np
import pytest

from edl_tpu.runtime.evaluation import Evaluator, top_k_accuracies


def test_top_k_accuracies():
    logits = np.array([
        [9.0, 1.0, 0.0, 0.0],   # top1 = 0
        [1.0, 9.0, 8.0, 0.0],   # top1 = 1, top2 incl 2
        [0.0, 1.0, 2.0, 3.0],   # top1 = 3
    ], np.float32)
    labels = np.array([0, 2, 0])
    accs = top_k_accuracies(logits, labels, ks=(1, 2, 4))
    assert float(accs[1]) == pytest.approx(1 / 3)   # only row 0
    assert float(accs[2]) == pytest.approx(2 / 3)   # rows 0 and 1
    assert float(accs[4]) == 1.0


def test_evaluator_weighted_average():
    import jax.numpy as jnp

    def apply_fn(params, extra, batch):
        # "model": predicts the label perfectly when params["good"] else 0
        return jnp.eye(4, dtype=jnp.float32)[batch["label"]] * params["good"]

    ev = Evaluator(apply_fn, ks=(1,))
    batches = [
        {"label": np.array([1, 2, 3])},
        {"label": np.array([0])},
    ]
    out = ev.evaluate({"good": np.float32(1.0)}, {}, iter(batches))
    assert out == {"acc1": 1.0}
    # all-zero logits → top-1 picks class 0 → only the [0] batch scores
    out0 = ev.evaluate({"good": np.float32(0.0)}, {}, iter(batches))
    assert out0 == {"acc1": 0.25}
