"""Watch relay tree tests: deterministic topology, lossless failover,
feature-negotiated fall-through, lease coalescing, and obs_agg/v1
round-trip through the health detectors.

The invariants pinned here are the ones the 10k-pod claim rests on:

- every pod derives the SAME B-ary tree from the cluster map alone
  (no negotiation), and the depth stays ⌈log_B N⌉;
- a relay kill (or a seeded ``relay.forward`` fault) can delay events
  but never lose one, because every consumer resumes from its OWN
  ``since_rev`` against the grandparent or the store;
- peers that predate ``coord.relay`` are permanently skipped and the
  client falls through to the direct store path (wire compat);
- the detectors see the identical per-pod picture whether docs arrive
  flat (``obs_pub/v1``) or relay-folded (``obs_agg/v1``).
"""

import json
import random
import time

from edl_tpu.controller import constants
from edl_tpu.coordination import relay as relay_mod
from edl_tpu.coordination.client import CoordClient
from edl_tpu.coordination.relay import (RelayAttachment, WatchRelay,
                                        tree_ancestors, tree_depth,
                                        tree_parent)
from edl_tpu.obs import events as obs_events
from edl_tpu.obs import health as obs_health
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.robustness import faults
from edl_tpu.tools import obs_bench

PREFIX = "/t/fleet/nodes/"


# -- helpers -------------------------------------------------------------


def _client(store, root="t"):
    return CoordClient([store.endpoint], root=root)


def _start_relay(store, pod_id, parents, **kw):
    """A relay with an explicit parent chain (no registry round-trips:
    tests that want the registry path use the default resolver)."""
    r = WatchRelay(_client(store), pod_id, obs_interval=3600.0,
                   parent_resolver=(lambda: list(parents)), **kw)
    return r.start(register=False)


def _drain(att, fallback, since, want, deadline=12.0):
    """Collect ``want`` distinct event keys through ``att``, falling
    through to the direct ``fallback`` client exactly like the wired
    CoordClient does; returns ({key: rev}, cursor)."""
    got = {}
    end = time.monotonic() + deadline
    while len(got) < want and time.monotonic() < end:
        try:
            out = att.wait_events(PREFIX, since, 0.5)
        except Exception:  # noqa: BLE001 — killed relay mid-poll
            continue
        if out is None:
            evs, since = fallback.wait_events(PREFIX, since, 0.5,
                                              relay=False)
        else:
            evs, since = out
        for e in evs or ():
            if e.get("type") != "reset":
                got[e["key"]] = e["rev"]
    return got, since


# -- the deterministic tree ----------------------------------------------


def test_tree_shape_deterministic_across_resizes():
    rng = random.Random(7)
    ids = ["pod-%03d" % i for i in range(97)]
    b = 4
    srt = sorted(ids)
    for _ in range(5):
        shuffled = list(ids)
        rng.shuffle(shuffled)
        # same parent regardless of the order the map arrived in
        for pod in ids:
            assert tree_parent(shuffled, pod, b) \
                == tree_parent(srt, pod, b)
    # root has no parent; everyone else's parent sorts strictly
    # earlier (the heap property — no cycles possible)
    assert tree_parent(srt, srt[0], b) is None
    children = {}
    for pod in srt[1:]:
        parent = tree_parent(srt, pod, b)
        assert parent < pod
        children.setdefault(parent, []).append(pod)
    # fan-out is capped at B and the ancestor chain is the depth bound
    assert max(len(c) for c in children.values()) <= b
    assert tree_depth(len(srt), b) == 4  # ceil(log4 97)
    for pod in srt:
        assert len(tree_ancestors(srt, pod, b)) <= tree_depth(
            len(srt), b)
    # a resize (pods leave AND join) yields the same tree for every
    # observer of the new map — determinism is what makes the relay
    # topology negotiation-free
    resized = sorted(srt[:40] + ["pod-%03d" % i for i in range(200,
                                                               230)])
    for pod in resized:
        again = list(resized)
        rng.shuffle(again)
        assert tree_parent(again, pod, b) == tree_parent(resized, pod,
                                                         b)


def test_service_relay_constant_matches_inlined_value():
    # relay.py inlines the registry name to stay below controller in
    # the layering; this is the drift guard the comment points at
    assert relay_mod.SERVICE_RELAY == constants.SERVICE_RELAY


def test_kill_switch_env(monkeypatch):
    monkeypatch.delenv("EDL_TPU_RELAY", raising=False)
    assert relay_mod.enabled()
    monkeypatch.setenv("EDL_TPU_RELAY", "0")
    assert not relay_mod.enabled()


# -- fan-out + failover --------------------------------------------------


def test_depth2_fanout_and_kill_reattach_lossless(store):
    """store -> root -> mid -> child; kill mid mid-stream: the child
    reattaches to the grandparent and replays from its own since_rev —
    zero loss, asserted from the relay metrics as well."""
    pub = _client(store)
    root = _start_relay(store, "p0", [])
    mid = _start_relay(store, "p1", [root.endpoint])
    att = RelayAttachment(lambda: [mid.endpoint, root.endpoint],
                          pod_id="leaf")
    reatt0 = relay_mod._REATTACHES.value
    fwd0 = relay_mod._FORWARDED.value
    try:
        since = pub.revision()
        keys = [PREFIX + "a%d" % i for i in range(4)]
        for k in keys:
            pub.put(k, b"v")
        got, since = _drain(att, pub, since, 4)
        assert sorted(got) == keys
        assert att.current() == mid.endpoint

        mid.stop()  # the kill drill: child is attached through mid
        keys2 = [PREFIX + "b%d" % i for i in range(4)]
        for k in keys2:
            pub.put(k, b"v")
        got2, since = _drain(att, pub, since, 4)
        # lossless: every post-kill event arrives via the grandparent
        assert sorted(got2) == keys2
        assert att.current() == root.endpoint
        # and the drill is provable from metrics alone: at least one
        # reattach, and both batches were served from relay caches
        assert relay_mod._REATTACHES.value >= reatt0 + 1
        assert relay_mod._FORWARDED.value >= fwd0 + 8
    finally:
        att.close()
        root.stop()


def test_forward_fault_forces_lossless_reattach(store):
    """Seeded ``relay.forward`` error: the child's poll fails at the
    mid relay, the attachment walks to the grandparent, and the event
    stream resumes from the child's own cursor with nothing missing."""
    pub = _client(store)
    root = _start_relay(store, "p0", [])
    mid = _start_relay(store, "p1", [root.endpoint])
    att = RelayAttachment(lambda: [mid.endpoint, root.endpoint],
                          pod_id="leaf", retry_bad_after=0.5)
    try:
        since = pub.revision()
        pub.put(PREFIX + "pre", b"v")
        got, since = _drain(att, pub, since, 1)
        assert att.current() == mid.endpoint

        plane = faults.FaultPlane(seed=11)
        # child="leaf" scopes the fault to OUR poll; error (not drop)
        # is the kind that drives the reattach path. times is
        # unbounded: mid must stay poisoned until the walk lands on
        # the grandparent.
        plane.inject("relay.forward", "error", child="leaf")
        plane.install()
        try:
            keys = [PREFIX + "c%d" % i for i in range(3)]
            for k in keys:
                pub.put(k, b"v")
            got, since = _drain(att, pub, since, 3)
            assert sorted(got) == keys  # nothing lost crossing relays
            # the grandparent also fires relay.forward for child
            # "leaf", so the attachment ends on the DIRECT store path
            # — fall-through is part of the lossless contract
        finally:
            plane.uninstall()
        # with the fault gone (and the bad marks expired) the next
        # adoption walk lands on a relay again
        time.sleep(0.6)
        pub.put(PREFIX + "post", b"v")
        got, since = _drain(att, pub, since, 1)
        assert list(got) == [PREFIX + "post"]
        assert att.current() in (mid.endpoint, root.endpoint)
    finally:
        att.close()
        mid.stop()
        root.stop()


def test_attach_fault_skips_candidate(store):
    """Seeded ``relay.attach`` error at the mid endpoint: adoption
    skips it and lands on the next ancestor without ever dialing."""
    pub = _client(store)
    root = _start_relay(store, "p0", [])
    mid = _start_relay(store, "p1", [root.endpoint])
    plane = faults.FaultPlane(seed=5)
    plane.inject("relay.attach", "error", endpoint=mid.endpoint)
    plane.install()
    att = RelayAttachment(lambda: [mid.endpoint, root.endpoint],
                          pod_id="leaf")
    try:
        since = pub.revision()
        pub.put(PREFIX + "x", b"v")
        got, _ = _drain(att, pub, since, 1)
        assert list(got) == [PREFIX + "x"]
        assert att.current() == root.endpoint
    finally:
        plane.uninstall()
        att.close()
        mid.stop()
        root.stop()


def test_legacy_peer_without_feature_goes_direct(store):
    """A registered endpoint that does not advertise ``coord.relay``
    (here: the store itself, standing in for a pre-relay peer) is
    permanently skipped — the client falls through to the direct
    store path and keeps working."""
    c = _client(store)
    att = c.attach_relay(RelayAttachment(lambda: [store.endpoint],
                                         pod_id="leaf"))
    try:
        since = c.revision()
        c.put(PREFIX + "legacy", b"v")
        evs, _ = c.wait_events(PREFIX, since, 2.0)  # relayed entry point
        assert [e["key"] for e in evs] == [PREFIX + "legacy"]
        assert att.current() is None  # never adopted the legacy peer
    finally:
        c.detach_relay()
        att.close()


def test_relay_cache_floor_resets_stale_child(store):
    """The relay mirrors the store's watch contract: a child whose
    cursor predates the cache floor gets a synthetic reset, not a
    silent gap."""
    pub = _client(store)
    root = _start_relay(store, "p0", [])
    att = RelayAttachment(lambda: [root.endpoint], pod_id="leaf")
    try:
        since = pub.revision()
        pub.put(PREFIX + "f", b"v")
        got, _ = _drain(att, pub, since, 1)  # feed floor is `since` now
        out = att.wait_events(PREFIX, since - 10_000, 0.5)
        assert out is not None
        evs, rev = out
        assert [e["type"] for e in evs] == ["reset"]
        assert rev > since - 10_000
    finally:
        att.close()
        root.stop()


def test_registry_based_parent_resolution(store):
    """The default resolver: ancestors come from the cluster map (the
    deterministic tree) joined with the SERVICE_RELAY registry."""
    ids = ["p%02d" % i for i in range(8)]
    root = WatchRelay(_client(store), ids[0], branching=4,
                      obs_interval=3600.0)
    root.update_tree(ids)
    root.start(register=True)
    mid = WatchRelay(_client(store), ids[1], branching=4,
                     obs_interval=3600.0)
    mid.update_tree(ids)
    mid.start(register=True)
    try:
        assert mid._parent_endpoints() == [root.endpoint]
        # a leaf pod's local candidates: its own relay first, then the
        # ancestors the map dictates
        assert mid.attachment_candidates()[0] == mid.endpoint
    finally:
        mid.stop()
        root.stop()


# -- upward: leases + obs ------------------------------------------------


def test_lease_coalescing_through_relay(store):
    c = _client(store)
    root = _start_relay(store, "p0", [])
    att = RelayAttachment(lambda: [root.endpoint], pod_id="leaf")
    try:
        lids = [c.lease_grant(30.0) for _ in range(3)]
        verdicts = att.lease_refresh_many(lids)
        assert verdicts == {lid: True for lid in lids}
        # the relay now carries all three child leases in its batch
        assert root.stats()["child_leases"] == 3
        # a dead lease comes back False once the upstream batch runs
        c.lease_revoke(lids[0])
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            verdicts = att.lease_refresh_many(lids)
            if verdicts and verdicts[lids[0]] is False:
                break
            time.sleep(0.2)
        assert verdicts[lids[0]] is False
        assert verdicts[lids[1]] is True
    finally:
        att.close()
        root.stop()


def test_obs_aggregation_one_store_doc(store):
    """Two leaves publish through mid; mid folds into obs_agg/v1 and
    pushes to root; root writes ONE store doc carrying both per-pod
    cells plus the fleet rollup."""
    c = _client(store)
    root = _start_relay(store, "p0", [])
    mid = _start_relay(store, "p1", [root.endpoint])
    att = RelayAttachment(lambda: [mid.endpoint], pod_id="leaf")
    try:
        for pod in ("p2", "p3"):
            doc = {"schema": "obs_pub/v1", "key": "obs_" + pod,
                   "ts": time.time(), "metrics": {}, "events": []}
            assert att.obs_publish("metrics", "obs_" + pod,
                                   json.dumps(doc))
        assert mid.flush_once() is not None   # -> pushed to root
        agg = root.flush_once()               # -> ONE store write
        assert agg["schema"] == "obs_agg/v1"
        assert set(agg["pods"]) == {"obs_p2", "obs_p3"}
        assert "fleet" in agg  # the root-only rollup
        stored = json.loads(c.get_value("metrics", "obs_agg_p0"))
        assert stored["schema"] == "obs_agg/v1"
        assert set(stored["pods"]) == {"obs_p2", "obs_p3"}
    finally:
        att.close()
        mid.stop()
        root.stop()


class _FakeCoord(object):
    """get_service-only stand-in for the monitor's _read_docs path."""

    def __init__(self):
        self.kvs = {}

    def get_service(self, service):
        return sorted(self.kvs.items())


def _expand_through_monitor(docs):
    """Round-trip {pod: obs_pub doc} through ONE obs_agg/v1 store doc
    and the monitor's _read_docs expansion."""
    fake = _FakeCoord()
    agg = {"schema": "obs_agg/v1", "key": "obs_agg_pod-00",
           "ts": max(d["ts"] for d in docs.values()), "relay": "pod-00",
           "pods": {"obs_" + pod: doc for pod, doc in docs.items()}}
    fake.kvs["obs_agg_pod-00"] = json.dumps(agg)
    reader = obs_health.HealthMonitor(coord=fake, pod_id="reader",
                                      events=obs_events.EventLog(),
                                      clock=lambda: 1_000_000.0)
    return reader._read_docs()


def test_health_monitor_flags_same_straggler_via_agg_docs():
    """The acceptance pin for the upward path: the straggler detector
    reaches the SAME verdict (same pod, same window — well inside the
    <=2-interval bound) whether the docs arrive flat or relay-folded,
    because obs_agg/v1 keeps per-pod cells instead of pre-averaging."""
    steps = {"pod-%02d" % p: (600.0 if p == 3 else 100.0)
             for p in range(4)}

    def flagged_window(fold):
        monitor = obs_health.HealthMonitor(
            coord=None, pod_id="m", interval=10.0,
            events=obs_events.EventLog(), clock=lambda: 1_000_000.0)
        state = {}
        for w in range(4):
            docs = obs_bench._synth_fleet_docs(4, w, steps, state,
                                               1_000_000.0, 10.0)
            if fold:
                expanded = _expand_through_monitor(docs)
                assert expanded == docs  # lossless per-pod round-trip
                docs = expanded
            report = monitor.evaluate(docs, now=1_000_000.0 + w * 10.0)
            if report["fleet"]["pods_degraded"]:
                return w, tuple(report["fleet"]["pods_degraded"])
        return None, ()

    flat_w, flat_pods = flagged_window(fold=False)
    agg_w, agg_pods = flagged_window(fold=True)
    assert flat_pods == agg_pods == ("pod-03",)
    assert flat_w == agg_w  # identical data -> identical window
    assert abs(agg_w - flat_w) <= 2  # the ISSUE's interval bound


def test_store_watch_dropped_counter(store):
    """The store.watch.deliver drop branch is observable: suppressed
    deliveries tick edl_store_watch_dropped_total."""
    from edl_tpu.coordination import store as store_mod

    c = _client(store)
    before = store_mod._WATCH_DROPPED.value
    plane = faults.FaultPlane(seed=3)
    plane.inject("store.watch.deliver", "drop", times=1)
    plane.install()
    try:
        evs, _ = c.wait_events(PREFIX, c.revision(), 0.1, relay=False)
        assert evs == []  # the drop looks like a timed-out poll
    finally:
        plane.uninstall()
    assert store_mod._WATCH_DROPPED.value == before + 1


def test_relay_counters_registered():
    """The zero-loss drill reads these families by name; renaming them
    breaks the bench and the ops docs."""
    fams = obs_metrics.REGISTRY.families()
    for name in ("edl_relay_children_total",
                 "edl_relay_events_forwarded_total",
                 "edl_relay_reattaches_total",
                 "edl_store_watch_dropped_total"):
        assert name in fams, name
