"""Operator decision-logic tests (no cluster needed)."""

from edl_tpu.tools.k8s_operator import launcher_pod_command, plan_allocations


def test_plan_min_then_priority_topup():
    jobs = [
        {"name": "a", "min": 2, "max": 8, "priority": 1},
        {"name": "b", "min": 2, "max": 4, "priority": 5},
    ]
    # mins first (both fit), then top-up by priority: b to max, rest to a
    alloc = plan_allocations(jobs, capacity_nodes=8)
    assert alloc == {"b": 4, "a": 4}


def test_plan_admission_under_pressure():
    jobs = [
        {"name": "low", "min": 4, "max": 8, "priority": 0},
        {"name": "high", "min": 4, "max": 8, "priority": 9},
    ]
    alloc = plan_allocations(jobs, capacity_nodes=6)
    # only the high-priority job is admitted; it gets its min + leftovers
    assert alloc == {"high": 6, "low": 0}


def test_plan_exact_capacity():
    jobs = [{"name": "x", "min": 3, "max": 5, "priority": 0}]
    assert plan_allocations(jobs, 3) == {"x": 3}
    assert plan_allocations(jobs, 10) == {"x": 5}
    assert plan_allocations(jobs, 2) == {"x": 0}


def test_launcher_pod_command():
    cmd = launcher_pod_command({
        "jobId": "j1", "script": "/app/train.py",
        "scriptArgs": ["--epochs", "90"], "minNodes": 4, "maxNodes": 8,
        "checkpointPath": "gs://b/ckpt",
    })
    assert cmd[0] == "edl-tpu-run"
    assert "--nodes_range" in cmd and "4:8" in cmd
    assert "--checkpoint_path" in cmd and "gs://b/ckpt" in cmd
    assert cmd[-3:] == ["/app/train.py", "--epochs", "90"]
