"""Operator tests: decision logic + the reconcile loop against a fake
kubernetes API (tests/fake_k8s.py) — CR → StatefulSet create/scale/
status, owner references, autoscaler re-plan, broken-job isolation."""

from fake_k8s import FakeAppsV1Api, FakeCustomObjectsApi

from edl_tpu.tools.k8s_operator import (Operator, launcher_pod_command,
                                        plan_allocations)


def _job(name, uid="u-%s", image="edl-tpu:latest", min_nodes=2, max_nodes=4,
         priority=0):
    return {
        "metadata": {"name": name, "uid": uid % name},
        "spec": {"jobId": name, "image": image, "script": "/app/train.py",
                 "minNodes": min_nodes, "maxNodes": max_nodes,
                 "priority": priority},
    }


def _operator(jobs, capacity=16):
    crd = FakeCustomObjectsApi(jobs)
    apps = FakeAppsV1Api()
    op = Operator(namespace="ns", capacity_nodes=capacity, interval=1,
                  crd_api=crd, apps_api=apps)
    return op, crd, apps


def test_reconcile_creates_statefulsets_with_owner_refs():
    op, crd, apps = _operator([_job("alpha"), _job("beta", priority=5)],
                              capacity=16)
    op.reconcile_once()
    assert sorted(apps.creates) == ["edl-tpu-alpha", "edl-tpu-beta"]
    sts = apps.sets["edl-tpu-beta"]
    # beta (priority 5) topped up to max; alpha got the rest up to max
    assert sts["spec"]["replicas"] == 4
    owner = sts["metadata"]["ownerReferences"][0]
    assert owner["kind"] == "TrainingJob" and owner["name"] == "beta"
    assert owner["uid"] == "u-beta" and owner["controller"]
    cmd = sts["spec"]["template"]["spec"]["containers"][0]["command"]
    assert cmd[0] == "edl-tpu-run" and "2:4" in cmd
    # statuses patched: no pods ready yet → Starting
    assert dict(crd.status_patches)["beta"]["phase"] == "Starting"


def test_reconcile_is_idempotent_and_tracks_ready():
    op, crd, apps = _operator([_job("alpha")], capacity=8)
    op.reconcile_once()
    assert apps.creates == ["edl-tpu-alpha"]
    op.reconcile_once()
    assert apps.patches == []          # nothing changed → no patch
    apps.set_ready("edl-tpu-alpha", 3)
    op.reconcile_once()
    assert crd.jobs["alpha"]["status"] == {"phase": "Running",
                                           "currentNodes": 3}


def test_reconcile_replans_on_capacity_change():
    op, crd, apps = _operator([_job("alpha", min_nodes=2, max_nodes=8),
                               _job("beta", min_nodes=2, max_nodes=8,
                                    priority=9)], capacity=16)
    op.reconcile_once()
    assert apps.sets["edl-tpu-beta"]["spec"]["replicas"] == 8
    assert apps.sets["edl-tpu-alpha"]["spec"]["replicas"] == 8
    # the TPU reservation shrinks: high-priority keeps max, alpha squeezed
    op.set_capacity(10)
    op.reconcile_once()
    assert apps.sets["edl-tpu-beta"]["spec"]["replicas"] == 8
    assert apps.sets["edl-tpu-alpha"]["spec"]["replicas"] == 2
    assert "edl-tpu-alpha" in apps.patches


def test_reconcile_applies_spec_changes():
    jobs = [_job("alpha")]
    op, crd, apps = _operator(jobs, capacity=8)
    op.reconcile_once()
    crd.jobs["alpha"]["spec"]["image"] = "edl-tpu:v2"
    op.reconcile_once()
    assert apps.patches == ["edl-tpu-alpha"]
    c = apps.sets["edl-tpu-alpha"]["spec"]["template"]["spec"]["containers"]
    assert c[0]["image"] == "edl-tpu:v2"


def test_broken_job_does_not_starve_others():
    bad = {"metadata": {"name": "bad", "uid": "u-bad"},
           "spec": {"jobId": "bad", "script": "/x.py",
                    "minNodes": 1, "maxNodes": 1}}  # no image → KeyError
    op, crd, apps = _operator([bad, _job("good")], capacity=8)
    op.reconcile_once()
    assert "edl-tpu-good" in apps.creates
    assert "edl-tpu-bad" not in apps.sets


def test_plan_min_then_priority_topup():
    jobs = [
        {"name": "a", "min": 2, "max": 8, "priority": 1},
        {"name": "b", "min": 2, "max": 4, "priority": 5},
    ]
    # mins first (both fit), then top-up by priority: b to max, rest to a
    alloc = plan_allocations(jobs, capacity_nodes=8)
    assert alloc == {"b": 4, "a": 4}


def test_plan_admission_under_pressure():
    jobs = [
        {"name": "low", "min": 4, "max": 8, "priority": 0},
        {"name": "high", "min": 4, "max": 8, "priority": 9},
    ]
    alloc = plan_allocations(jobs, capacity_nodes=6)
    # only the high-priority job is admitted; it gets its min + leftovers
    assert alloc == {"high": 6, "low": 0}


def test_plan_exact_capacity():
    jobs = [{"name": "x", "min": 3, "max": 5, "priority": 0}]
    assert plan_allocations(jobs, 3) == {"x": 3}
    assert plan_allocations(jobs, 10) == {"x": 5}
    assert plan_allocations(jobs, 2) == {"x": 0}


def test_launcher_pod_command():
    cmd = launcher_pod_command({
        "jobId": "j1", "script": "/app/train.py",
        "scriptArgs": ["--epochs", "90"], "minNodes": 4, "maxNodes": 8,
        "checkpointPath": "gs://b/ckpt",
    })
    assert cmd[0] == "edl-tpu-run"
    assert "--nodes_range" in cmd and "4:8" in cmd
    assert "--checkpoint_path" in cmd and "gs://b/ckpt" in cmd
    assert cmd[-3:] == ["/app/train.py", "--epochs", "90"]
