"""Full-launch integration: real launcher processes + real store server.

Reference parity: test_launch.sh:40-77 — export job env, start two launch
processes with an exit-code-controlled dummy trainer, assert both exit 0 and
the job status key is set. Plus the elastic cases the reference never had
green: resize-survival after SIGKILL and below-min job failure.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from edl_tpu.controller import cluster as cluster_mod
from edl_tpu.controller import status
from edl_tpu.controller.status import Status

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER = os.path.join(REPO, "tests", "fixtures", "dummy_trainer.py")


def _spawn_launcher(store_endpoint, job_id, nodes_range, tmp_path, name,
                    trainer_args=("0.5", "0"), ttl=3):
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": REPO,
        "EDL_TPU_POD_IP": "127.0.0.1",
        "EDL_TPU_TTL": str(ttl),
        "JAX_PLATFORMS": "cpu",
    })
    log = open(str(tmp_path / ("%s.log" % name)), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "edl_tpu.controller.launch",
         "--job_id", job_id, "--store_endpoints", store_endpoint,
         "--nodes_range", nodes_range,
         "--log_dir", str(tmp_path / ("%s_logs" % name)),
         TRAINER] + list(trainer_args),
        env=env, stdout=log, stderr=subprocess.STDOUT,
        preexec_fn=os.setsid)
    log.close()
    return proc


def _kill_group(proc):
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except ProcessLookupError:
        pass


def _wait_cluster_size(coord, n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        c = cluster_mod.load_from_store(coord)
        if c is not None and len(c.pods) == n:
            return c
        time.sleep(0.2)
    raise AssertionError("cluster never reached %d pods" % n)


def _dump_logs(tmp_path):
    out = []
    for root, _, files in os.walk(str(tmp_path)):
        for f in files:
            if f.endswith(".log") or f.startswith("workerlog"):
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    out.append("==== %s ====\n%s" % (
                        p, fh.read().decode("utf-8", "replace")))
    return "\n".join(out)


@pytest.mark.integration
def test_two_pod_launch_success(store, tmp_path):
    job = "launch_ok"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "2:2", tmp_path, "pod1")
    p2 = _spawn_launcher(store.endpoint, job, "2:2", tmp_path, "pod2")
    try:
        r1 = p1.wait(timeout=90)
        r2 = p2.wait(timeout=90)
        assert (r1, r2) == (0, 0), _dump_logs(tmp_path)
        assert status.load_job_status(coord) == Status.SUCCEED, \
            _dump_logs(tmp_path)
    finally:
        _kill_group(p1)
        _kill_group(p2)


@pytest.mark.integration
def test_elastic_resize_survives_pod_kill(store, tmp_path):
    """8→4→8 in miniature: 1→2 pods (scale out), SIGKILL one (shrink),
    survivor resizes and completes; job SUCCEED."""
    job = "launch_elastic"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "1:2", tmp_path, "pod1",
                         trainer_args=("10", "0"))
    try:
        _wait_cluster_size(coord, 1)
        p2 = _spawn_launcher(store.endpoint, job, "1:2", tmp_path, "pod2",
                             trainer_args=("10", "0"))
        c2 = _wait_cluster_size(coord, 2)
        # pod1 started first → it is the leader (pods[0]); kill the joiner
        _kill_group(p2)
        c1b = _wait_cluster_size(coord, 1, timeout=30)
        assert c1b.stage != c2.stage
        r1 = p1.wait(timeout=120)
        assert r1 == 0, _dump_logs(tmp_path)
        assert status.load_job_status(coord) == Status.SUCCEED, \
            _dump_logs(tmp_path)
        # the survivor's trainer was restarted across cluster incarnations
        # (the middle 2-pod incarnation may be torn down before its trainer
        # prints, so require >= 2 distinct stages)
        worker_log = (tmp_path / "pod1_logs" / "workerlog.0").read_text()
        stages = {line.split("stage=")[1].split()[0]
                  for line in worker_log.splitlines() if "stage=" in line}
        assert len(stages) >= 2, worker_log
        # resize metrics were recorded by the survivor
        from edl_tpu.controller import constants
        metrics = dict(coord.get_service(constants.SERVICE_METRICS))
        assert metrics, "no resize metrics recorded"
        history = json.loads(list(metrics.values())[0])
        assert history and all(h["recovery_s"] >= 0 for h in history)
    finally:
        _kill_group(p1)
        _kill_group(p2)


@pytest.mark.integration
def test_pod_stats_endpoint(store, tmp_path):
    """The pod server's observability endpoint reports cluster + trainer
    state while the job runs."""
    from edl_tpu.controller.resource_pods import load_resource_pods
    from edl_tpu.rpc.client import RpcClient

    job = "launch_stats"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "1:1", tmp_path, "pod1",
                         trainer_args=("15", "0"))
    try:
        c = _wait_cluster_size(coord, 1)
        pods = load_resource_pods(coord)
        pod = pods[c.pods[0].id]
        deadline = time.monotonic() + 30
        stats = None
        while time.monotonic() < deadline:
            client = RpcClient(pod.endpoint, timeout=5)
            try:
                stats = client.call("pod_stats")
            finally:
                client.close()
            if stats.get("trainers"):
                break
            time.sleep(0.5)
        assert stats["pod_id"] == c.pods[0].id
        assert stats["cluster_size"] == 1 and stats["world_size"] == 1
        assert stats["trainers"] and stats["trainers"][0]["alive"]
    finally:
        _kill_group(p1)


@pytest.mark.integration
def test_job_stats_aggregation(store, tmp_path):
    """The job-level observability scrape: store state + live pod_stats
    in one document (net-new; reference had no metrics surface)."""
    from edl_tpu.tools.job_stats import collect_job_stats

    job = "launch_jobstats"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "1:1", tmp_path, "pod1",
                         trainer_args=("20", "0"))
    try:
        c = _wait_cluster_size(coord, 1)
        deadline = time.monotonic() + 30
        stats = None
        while time.monotonic() < deadline:
            stats = collect_job_stats(coord)
            if stats["pods_alive"] >= 1:
                break
            time.sleep(0.5)
        assert stats["job_id"] == job
        assert stats["cluster"]["stage"] == c.stage
        assert stats["cluster"]["world_size"] == 1
        assert stats["pods_alive"] == 1
        pod_stat = list(stats["pods"].values())[0]
        assert pod_stat["cluster_size"] == 1
        # terminal flag unset while running (written at SUCCEED/FAILED)
        assert stats["job_status"] in (None, "RUNNING", "INITIAL",
                                       "PENDING")
    finally:
        _kill_group(p1)


@pytest.mark.integration
def test_below_min_nodes_fails_job(store, tmp_path):
    job = "launch_below_min"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "2:2", tmp_path, "pod1",
                         trainer_args=("120", "0"), ttl=5)
    p2 = _spawn_launcher(store.endpoint, job, "2:2", tmp_path, "pod2",
                         trainer_args=("120", "0"), ttl=5)
    try:
        _wait_cluster_size(coord, 2, timeout=90)
        _kill_group(p2)
        # event-driven: watch the STORE for the FAILED verdict (deadline,
        # not sleep-calibrated), THEN expect the leader process to exit 1 —
        # robust under CPU contention (VERDICT r1 weak #2)
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            if status.load_job_status(coord) == Status.FAILED:
                break
            if p1.poll() is not None:
                break  # exited: status must already be FAILED
            time.sleep(0.2)
        assert status.load_job_status(coord) == Status.FAILED, \
            _dump_logs(tmp_path)
        # generous: under full-suite CPU contention the launcher's
        # teardown (kill tree + store writes) can take tens of seconds
        assert p1.wait(timeout=150) == 1, _dump_logs(tmp_path)
    finally:
        _kill_group(p1)
        _kill_group(p2)


@pytest.mark.integration
def test_join_during_failed_job_exits_nonzero(store, tmp_path):
    """Deterministic form of the below-min race: if the job is FAILED
    while a pod is still waiting at the admission barrier (its peer died
    before the first barrier completed), the launcher must exit 1, not
    take the surplus-pod clean exit."""
    job = "launch_join_failed"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "2:2", tmp_path, "pod1",
                         trainer_args=("120", "0"))
    try:
        # wait until the pod has registered (it is past launch.py's
        # failed-job retry reset and parked at the admission barrier,
        # which can never form alone under 2:2) ...
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if status.load_pods_status(coord):
                break
            time.sleep(0.2)
        assert status.load_pods_status(coord), _dump_logs(tmp_path)
        # ... then fail the job out from under it
        status.save_job_status(coord, Status.FAILED)
        assert p1.wait(timeout=90) == 1, _dump_logs(tmp_path)
    finally:
        _kill_group(p1)


@pytest.mark.integration
def test_two_pod_launch_on_native_store(tmp_path):
    """The full elastic launch flow (election, generator, barrier,
    supervision, flags) against the C++ coordination store binary."""
    from edl_tpu.coordination.client import CoordClient
    from edl_tpu.coordination.native import NativeStoreServer, ensure_binary
    try:
        ensure_binary()
    except Exception as e:
        pytest.skip("native store unavailable: %r" % e)
    job = "launch_native"
    with NativeStoreServer(data_dir=str(tmp_path / "wal")) as s:
        coord = CoordClient([s.endpoint], root=job)
        p1 = _spawn_launcher(s.endpoint, job, "2:2", tmp_path, "pod1")
        p2 = _spawn_launcher(s.endpoint, job, "2:2", tmp_path, "pod2")
        try:
            assert (p1.wait(timeout=120), p2.wait(timeout=120)) == (0, 0), \
                _dump_logs(tmp_path)
            assert status.load_job_status(coord) == Status.SUCCEED
            # and the verdict survived a WAL'd store restart
            s.stop()
            s2 = NativeStoreServer(port=s._port,
                                   data_dir=str(tmp_path / "wal")).start()
            try:
                c2 = CoordClient([s2.endpoint], root=job)
                assert status.load_job_status(c2) == Status.SUCCEED
                assert cluster_mod.load_from_store(c2) is not None
            finally:
                s2.stop()
        finally:
            _kill_group(p1)
            _kill_group(p2)


@pytest.mark.integration
def test_failed_trainer_fails_pod(store, tmp_path):
    job = "launch_trainer_fail"
    coord = store.client(root=job)
    p1 = _spawn_launcher(store.endpoint, job, "1:1", tmp_path, "pod1",
                         trainer_args=("0.5", "7"))  # trainer exits 7
    try:
        r1 = p1.wait(timeout=60)
        assert r1 == 1, _dump_logs(tmp_path)
        flags = status.load_job_flags(coord)
        assert list(flags.values()) == [Status.FAILED]
    finally:
        _kill_group(p1)
