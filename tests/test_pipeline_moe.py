"""Pipeline (pp) and expert (ep) parallelism tests on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.parallel.moe import (init_moe_params, moe_ffn, moe_ffn_dense)
from edl_tpu.parallel.pipeline import (pipeline_apply, sequential_apply)
from edl_tpu.runtime import mesh as mesh_mod


def _stage_params(num_stages, d, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(num_stages, d, d).astype(np.float32)
                         * (d ** -0.5)),
        "b": jnp.asarray(rng.randn(num_stages, d).astype(np.float32) * 0.1),
    }


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


@pytest.mark.parametrize("pp,num_micro", [(4, 4), (4, 8), (2, 2)])
def test_pipeline_matches_sequential(pp, num_micro):
    mesh = mesh_mod.make_mesh(dp=8 // pp, pp=pp)
    # collapse dp for this test: batch replicated, stages over pp
    params = _stage_params(pp, d=16)
    x = jnp.asarray(np.random.RandomState(1).randn(num_micro * 4, 16)
                    .astype(np.float32))
    want = sequential_apply(params, x, _stage_fn)
    got = pipeline_apply(params, x, _stage_fn, mesh, num_micro=num_micro)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential():
    pp = 4
    mesh = mesh_mod.make_mesh(dp=2, pp=pp)
    params = _stage_params(pp, d=8)
    x = jnp.asarray(np.random.RandomState(2).randn(8, 8).astype(np.float32))

    def loss_pipe(p):
        return (pipeline_apply(p, x, _stage_fn, mesh) ** 2).sum()

    def loss_seq(p):
        return (sequential_apply(p, x, _stage_fn) ** 2).sum()

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("pp,dp,num_micro", [(4, 1, 4), (4, 2, 8), (2, 4, 2)])
def test_1f1b_matches_sequential_grads(pp, dp, num_micro):
    """The 1F1B schedule must produce the same loss AND grads as the
    unpipelined composite — including encode/decode ends and dp reduction."""
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    mesh = mesh_mod.make_mesh(dp=dp, pp=pp, devices=jax.devices()[:dp * pp])
    rng = np.random.RandomState(7)
    d = 8
    params = {
        "encode": {"w": jnp.asarray(rng.randn(3, d).astype(np.float32))},
        "stages": _stage_params(pp, d, seed=8),
        "decode": {"w": jnp.asarray(rng.randn(d, 2).astype(np.float32))},
    }
    n = dp * num_micro * 2
    x = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 2, (n,)).astype(np.int32))

    def encode(p, xb):
        return jnp.tanh(xb @ p["w"])

    def decode(p, act, labels):
        logits = act @ p["w"]
        one_hot = jax.nn.one_hot(labels, 2)
        return -(jax.nn.log_softmax(logits) * one_hot).sum(-1).mean()

    def seq_loss(p, xb, labels):
        act = encode(p["encode"], xb)
        for s in range(pp):
            ps = jax.tree_util.tree_map(lambda a: a[s], p["stages"])
            act = _stage_fn(ps, act)
        return decode(p["decode"], act, labels)

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, x, y)
    got_loss, got_g = jax.jit(lambda p, xb, yb: pipeline_value_and_grad(
        p, xb, yb, encode_fn=encode, stage_fn=_stage_fn, decode_fn=decode,
        mesh=mesh, num_micro=num_micro))(params, x, y)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(got_g),
                    jax.tree_util.tree_leaves(want_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_1f1b_bert_stack_matches_sequential():
    """A REAL BertLayer stack through the 1F1B pipeline (dp=2 x pp=4):
    loss and every grad leaf equal the unpipelined model's."""
    from edl_tpu.models.bert import create_bert_pipeline
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp, dp = 4, 2
    mesh = mesh_mod.make_mesh(dp=dp, pp=pp)
    params, encode, stage, decode, seq_loss = create_bert_pipeline(
        pp, num_layers=4, d_model=32, num_heads=2, mlp_dim=64,
        vocab_size=100, max_len=64, seq_len=16, dtype=jnp.float32)
    rng = np.random.RandomState(11)
    n = 16
    ids = jnp.asarray(rng.randint(0, 100, (n, 16)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, 2, (n,)).astype(np.int32))

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, ids, labels)
    got_loss, got_g = jax.jit(lambda p, i, l: pipeline_value_and_grad(
        p, i, l, encode_fn=encode, stage_fn=stage, decode_fn=decode,
        mesh=mesh, num_micro=4))(params, ids, labels)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want_g)
    flat_g = dict(jax.tree_util.tree_flatten_with_path(got_g)[0])
    for path, w in flat_w:
        np.testing.assert_allclose(
            np.asarray(flat_g[path]), np.asarray(w), rtol=5e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.parametrize("pp,mm,vv", [(2, 2, 1), (4, 8, 1), (4, 4, 2),
                                      (2, 6, 3), (4, 8, 2)])
def test_interleaved_schedule_valid(pp, mm, vv):
    from edl_tpu.parallel.pipeline_schedule import (build_schedule,
                                                    validate_schedule)
    sched = build_schedule(pp, mm, vv)
    assert validate_schedule(sched)
    # V=1 must not be worse than the closed-form flush schedule
    if vv == 1:
        assert sched["n_ticks"] <= 2 * (pp + mm) - 1


@pytest.mark.parametrize("pp,mm,vv", [(4, 8, 2), (4, 16, 2), (4, 16, 4),
                                      (8, 24, 3)])
def test_megatron_order_hits_ideal_bubble(pp, mm, vv):
    """On M % P == 0 configs the Megatron-exact order must achieve the
    textbook interleaved bubble (P-1)/(V*M + P-1) exactly under this
    tick model — and build_schedule must therefore pick it over the
    looser greedy schedule."""
    from edl_tpu.parallel.pipeline_schedule import (
        IDLE, build_schedule, validate_schedule)
    sched = build_schedule(pp, mm, vv)
    assert validate_schedule(sched)
    busy = (sched["op"] != IDLE).sum()
    bubble = 1 - busy / (sched["n_ticks"] * pp)
    ideal = (pp - 1) / (vv * mm + pp - 1)
    assert bubble == pytest.approx(ideal, abs=1e-9), (bubble, ideal)


def test_interleaved_cuts_wall_clock_for_same_model():
    """Same 8-chunk model on 4 devices: V=2 (1 chunk/tick) must beat
    V=1 (2 chunks fused per stage → 2 units/tick) in work-units."""
    from edl_tpu.parallel.pipeline_schedule import build_schedule
    P, M = 4, 8
    t_v1 = (2 * (P + M) - 2) * 2       # non-interleaved engine, 2-layer
    sched = build_schedule(P, M, 2)
    t_v2 = sched["n_ticks"]            # 1-layer chunks
    assert t_v2 < t_v1, (t_v2, t_v1)
    # saved-input memory stays O(P*V), NOT O(M*V) (GPipe would need 16)
    assert sched["n_save_slots"] <= 2 * P + (2 - 1) * P + 3


@pytest.mark.parametrize("pp,dp,V,mm", [(2, 1, 2, 4), (4, 2, 2, 8),
                                        (2, 2, 3, 4)])
def test_interleaved_matches_sequential_grads(pp, dp, V, mm):
    """The interleaved engine must produce the same loss and grads as the
    unpipelined composite over S = P*V chunks."""
    from edl_tpu.parallel.pipeline import (
        device_major_stage_params, pipeline_value_and_grad_interleaved,
        virtual_stage_major_stage_params)

    mesh = mesh_mod.make_mesh(dp=dp, pp=pp, devices=jax.devices()[:dp * pp])
    S = pp * V
    rng = np.random.RandomState(21)
    d = 8
    params_vsm = {
        "encode": {"w": jnp.asarray(rng.randn(3, d).astype(np.float32))},
        "stages": _stage_params(S, d, seed=22),
        "decode": {"w": jnp.asarray(rng.randn(d, 2).astype(np.float32))},
    }
    n = dp * mm * 2
    x = jnp.asarray(rng.randn(n, 3).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 2, (n,)).astype(np.int32))

    def encode(p, xb):
        return jnp.tanh(xb @ p["w"])

    def decode(p, act, labels):
        logits = act @ p["w"]
        one_hot = jax.nn.one_hot(labels, 2)
        return -(jax.nn.log_softmax(logits) * one_hot).sum(-1).mean()

    def seq_loss(p, xb, labels):
        act = encode(p["encode"], xb)
        for s in range(S):
            ps = jax.tree_util.tree_map(lambda a: a[s], p["stages"])
            act = _stage_fn(ps, act)
        return decode(p["decode"], act, labels)

    want_loss, want_g = jax.value_and_grad(seq_loss)(params_vsm, x, y)

    params_dm = dict(params_vsm)
    params_dm["stages"] = device_major_stage_params(params_vsm["stages"],
                                                    pp, V)
    got_loss, got_g = jax.jit(
        lambda p, xb, yb: pipeline_value_and_grad_interleaved(
            p, xb, yb, encode_fn=encode, stage_fn=_stage_fn,
            decode_fn=decode, mesh=mesh, num_chunks=V, num_micro=mm))(
                params_dm, x, y)
    got_g_vsm = dict(got_g)
    got_g_vsm["stages"] = virtual_stage_major_stage_params(
        got_g["stages"], pp, V)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(got_g_vsm),
                    jax.tree_util.tree_leaves(want_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_1f1b_composes_with_sequence_parallelism():
    """sp x pp: a BERT stack whose activations are seq-sharded over sp
    INSIDE the pipeline stages (in-shard ring attention, shard-offset
    positions, pmean pooling) — loss and grads must match the unsharded
    dense sequential model."""
    from edl_tpu.models.bert import create_bert_pipeline
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp, sp, dp = 2, 2, 2
    mesh = mesh_mod.make_mesh(dp=dp, pp=pp, sp=sp)
    params, encode, stage, decode, seq_loss = create_bert_pipeline(
        pp, num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
        vocab_size=100, max_len=64, seq_len=16, dtype=jnp.float32,
        seq_parallel_axis="sp")
    rng = np.random.RandomState(31)
    n = 8
    ids = jnp.asarray(rng.randint(0, 100, (n, 16)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, 2, (n,)).astype(np.int32))

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, ids, labels)
    got_loss, got_g = jax.jit(lambda p, i, l: pipeline_value_and_grad(
        p, i, l, encode_fn=encode, stage_fn=stage, decode_fn=decode,
        mesh=mesh, num_micro=2, seq_axes=("sp",)))(params, ids, labels)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    flat_w, _ = jax.tree_util.tree_flatten_with_path(want_g)
    flat_g = dict(jax.tree_util.tree_flatten_with_path(got_g)[0])
    for path, w in flat_w:
        np.testing.assert_allclose(
            np.asarray(flat_g[path]), np.asarray(w), rtol=5e-4,
            atol=1e-5, err_msg=jax.tree_util.keystr(path))


def test_pair_schedule_fewer_microbatches_than_stages():
    """sp x pp with M < P: the pair schedule's ramp masks and skew-2
    buffer windows must stay exact when the pipeline never fills."""
    from edl_tpu.models.bert import create_bert_pipeline
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp, sp = 4, 2
    mesh = mesh_mod.make_mesh(dp=1, pp=pp, sp=sp)
    params, encode, stage, decode, seq_loss = create_bert_pipeline(
        pp, num_layers=4, d_model=32, num_heads=2, mlp_dim=64,
        vocab_size=100, max_len=64, seq_len=16, dtype=jnp.float32,
        seq_parallel_axis="sp")
    rng = np.random.RandomState(5)
    n = 4
    ids = jnp.asarray(rng.randint(0, 100, (n, 16)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, 2, (n,)).astype(np.int32))

    want_loss, want_g = jax.value_and_grad(seq_loss)(params, ids, labels)
    got_loss, got_g = jax.jit(lambda p, i, l: pipeline_value_and_grad(
        p, i, l, encode_fn=encode, stage_fn=stage, decode_fn=decode,
        mesh=mesh, num_micro=2, seq_axes=("sp",)))(params, ids, labels)
    np.testing.assert_allclose(float(got_loss), float(want_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(want_g),
                    jax.tree_util.tree_leaves(got_g)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=1e-5)


def test_1f1b_composes_with_remat():
    """remat'd stages under the 1F1B schedule: same loss/grads (the 1F1B
    backward already recomputes the stage from its saved input, so remat
    inside the stage must be a no-op numerically)."""
    from edl_tpu.models.bert import create_bert_pipeline
    from edl_tpu.parallel.pipeline import pipeline_value_and_grad

    pp = 4
    mesh = mesh_mod.make_mesh(dp=2, pp=pp)
    base = create_bert_pipeline(pp, num_layers=4, d_model=32, num_heads=2,
                                mlp_dim=64, vocab_size=100, max_len=64,
                                seq_len=16, dtype=jnp.float32)
    params, encode, stage, decode, seq_loss = base
    import flax.linen as nn

    from edl_tpu.models.bert import BertStage
    remat_stage_mod = nn.remat(BertStage)(1, 2, 64, jnp.float32)

    def remat_stage(p, x):
        return remat_stage_mod.apply({"params": p}, x)

    rng = np.random.RandomState(13)
    ids = jnp.asarray(rng.randint(0, 100, (16, 16)).astype(np.int32))
    labels = jnp.asarray(rng.randint(0, 2, (16,)).astype(np.int32))
    outs = {}
    for name, stg in (("plain", stage), ("remat", remat_stage)):
        loss, g = jax.jit(lambda p, i, l, s=stg: pipeline_value_and_grad(
            p, i, l, encode_fn=encode, stage_fn=s, decode_fn=decode,
            mesh=mesh, num_micro=4))(params, ids, labels)
        outs[name] = (float(loss), g)
    assert outs["plain"][0] == pytest.approx(outs["remat"][0], rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(outs["plain"][1]),
                    jax.tree_util.tree_leaves(outs["remat"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_moe_matches_dense_with_ample_capacity():
    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    params = init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(3).randn(32, 16)
                    .astype(np.float32))
    want = moe_ffn_dense(params, x)
    got = moe_ffn(params, x, mesh, capacity_factor=8.0)  # no overflow
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_moe_overflow_passthrough():
    """With capacity 1 per slice, overflow tokens come back unchanged."""
    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    params = init_moe_params(jax.random.PRNGKey(0), num_experts=4,
                             d_model=8, d_ff=16)
    x = jnp.asarray(np.random.RandomState(4).randn(64, 8)
                    .astype(np.float32))
    out = moe_ffn(params, x, mesh, capacity_factor=0.1)  # capacity = 1
    # every token is EITHER its dense expert output OR identity
    # passthrough — never zeroed/garbage (overflow must not clobber
    # in-capacity slots)
    dense = np.asarray(moe_ffn_dense(params, x))
    o = np.asarray(out)
    xs = np.asarray(x)
    routed = np.isclose(o, dense, atol=2e-4).all(axis=1)
    passed = np.isclose(o, xs, atol=1e-6).all(axis=1)
    assert (routed | passed).all()
    assert passed.sum() > 0            # capacity 1 forces real overflow
    assert routed.sum() > 0


def test_moe_top2_matches_dense_with_ample_capacity():
    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    params = init_moe_params(jax.random.PRNGKey(2), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(6).randn(32, 16)
                    .astype(np.float32))
    want, aux_d = moe_ffn_dense(params, x, k=2, return_aux=True)
    got, aux_s = moe_ffn(params, x, mesh, capacity_factor=8.0, k=2,
                         return_aux=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # aux loss agrees between paths and sits near 1 for near-uniform
    # routing (it IS >= 1 by Cauchy-Schwarz at uniform p)
    assert float(aux_s) == pytest.approx(float(aux_d), rel=1e-4)
    assert 0.9 < float(aux_s) < 8.0


def test_moe_aux_loss_detects_collapse():
    """A router forced onto one expert must score ~E; uniform ~1."""
    params = init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.abs(np.random.RandomState(7).randn(64, 16))
                    .astype(np.float32))  # positive → x@router collapses
    collapsed = dict(params)
    bias = np.zeros((16, 8), np.float32)
    bias[:, 3] = 10.0  # everything routes to expert 3
    collapsed["router"] = jnp.asarray(bias)
    _, aux_c = moe_ffn_dense(collapsed, x, return_aux=True)
    _, aux_u = moe_ffn_dense(params, x, return_aux=True)
    assert float(aux_c) > 6.0          # ~E = 8 at full collapse
    assert float(aux_u) < float(aux_c) / 3


def test_moe_bert_layer_trains_on_ep_mesh():
    """A BERT layer with the MoE FFN: expert-parallel forward+backward on
    dp x ep, aux loss collected via the losses collection, grads flow to
    router and experts."""
    from edl_tpu.models.bert import BertLayer

    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    layer = BertLayer(num_heads=2, mlp_dim=32, dtype=jnp.float32,
                      mesh=mesh, moe_experts=4, moe_k=2)
    x = jnp.asarray(np.random.RandomState(8).randn(4, 8, 16)
                    .astype(np.float32))  # 32 tokens = dp*ep*4
    variables = layer.init(jax.random.PRNGKey(0), x)

    def loss_fn(params):
        y, muts = layer.apply({"params": params}, x, mutable=["losses"])
        aux = muts["losses"]["moe"]["moe_aux"][0]
        return (y ** 2).mean() + 0.01 * aux

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(variables["params"])
    assert np.isfinite(float(loss))
    for leaf in ("router", "w_in", "w_out"):
        g = grads["moe"][leaf]
        assert float(jnp.abs(g).sum()) > 0, leaf


def test_moe_metrics_zloss_and_drop_fraction():
    """return_metrics: z-loss agrees between dense and sharded paths;
    drop_fraction is 0 with ample capacity and rises when capacity is
    tight (the capacity_factor tuning signal)."""
    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    params = init_moe_params(jax.random.PRNGKey(2), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(6).randn(32, 16)
                    .astype(np.float32))
    _, m_d = moe_ffn_dense(params, x, k=2, return_metrics=True)
    _, m_s = moe_ffn(params, x, mesh, capacity_factor=8.0, k=2,
                     return_metrics=True)
    assert float(m_s["z_loss"]) == pytest.approx(float(m_d["z_loss"]),
                                                 rel=1e-4)
    assert float(m_s["aux_loss"]) == pytest.approx(float(m_d["aux_loss"]),
                                                   rel=1e-4)
    assert float(m_d["drop_fraction"]) == 0.0
    assert float(m_s["drop_fraction"]) == 0.0
    _, m_tight = moe_ffn(params, x, mesh, capacity_factor=0.1, k=2,
                         return_metrics=True)
    assert 0.0 < float(m_tight["drop_fraction"]) <= 1.0


def test_moe_zloss_penalizes_large_logits():
    """Scaling the router up must scale the z-loss up — the signal the
    ST-MoE penalty exists to bound."""
    params = init_moe_params(jax.random.PRNGKey(0), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(7).randn(64, 16)
                    .astype(np.float32))
    _, m_small = moe_ffn_dense(params, x, return_metrics=True)
    big = dict(params, router=params["router"] * 20.0)
    _, m_big = moe_ffn_dense(big, x, return_metrics=True)
    assert float(m_big["z_loss"]) > 4 * float(m_small["z_loss"])


def test_moe_losses_fold_into_training_loss():
    """create_model_and_loss must actually apply the sowed MoE router
    losses — an MoE model's loss_fn sees a different loss than the bare
    cross-entropy, and the router gets a gradient from the penalty."""
    from edl_tpu.models.bert import create_model_and_loss, \
        synthetic_text_batch

    _, params, loss_fn = create_model_and_loss(
        num_layers=1, moe_experts=4, dtype=jnp.float32)
    _, _, loss_plain = create_model_and_loss(
        num_layers=1, moe_experts=4, moe_aux_weight=0.0, moe_z_weight=0.0,
        dtype=jnp.float32)
    batch = synthetic_text_batch(8, seq_len=16)
    rng = jax.random.PRNGKey(0)
    with_moe = float(loss_fn(params, batch, rng))
    without = float(loss_plain(params, batch, rng))
    assert np.isfinite(with_moe) and np.isfinite(without)
    assert with_moe != pytest.approx(without, abs=1e-6)
    grads = jax.grad(loss_fn)(params, batch, rng)
    router_g = grads["layer_0"]["moe"]["router"]
    assert float(jnp.abs(router_g).sum()) > 0


def test_moe_tight_capacity_never_corrupts():
    """capacity_factor=1.0 with skewed routing: in-capacity tokens keep
    their dense outputs (regression for the overflow-clobber bug)."""
    mesh = mesh_mod.make_mesh(dp=2, ep=4)
    params = init_moe_params(jax.random.PRNGKey(1), num_experts=8,
                             d_model=16, d_ff=32)
    x = jnp.asarray(np.random.RandomState(5).randn(16, 16)
                    .astype(np.float32))
    out = moe_ffn(params, x, mesh, capacity_factor=1.0)
    dense = np.asarray(moe_ffn_dense(params, x))
    o = np.asarray(out)
    xs = np.asarray(x)
    routed = np.isclose(o, dense, atol=2e-4).all(axis=1)
    passed = np.isclose(o, xs, atol=1e-6).all(axis=1)
    assert (routed | passed).all(), np.where(~(routed | passed))
