"""Aux subsystem tests: timeline profiler, input pipeline, liveft layer."""

import io
import os
import time

import numpy as np
import pytest
from PIL import Image

from edl_tpu.liveft import elastic
from edl_tpu.utils import timeline


def test_timeline_nop_vs_real(monkeypatch):
    monkeypatch.delenv("EDL_TPU_PROFILE", raising=False)
    assert isinstance(timeline.get_timeline(), timeline._NopTimeLine)
    monkeypatch.setenv("EDL_TPU_PROFILE", "1")
    buf = io.StringIO()
    tl = timeline.get_timeline(out=buf)
    with tl.span("predict"):
        time.sleep(0.01)
    tl.record("fetch")
    out = buf.getvalue()
    assert "op=predict" in out and "op=fetch" in out
    assert "ms=" in out


def _make_image_tree(tmp_path, classes=2, per_class=3, size=40):
    rng = np.random.RandomState(0)
    for c in range(classes):
        d = tmp_path / ("class_%d" % c)
        d.mkdir()
        for i in range(per_class):
            arr = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(str(d / ("img%d.jpg" % i)))
    return str(tmp_path)


def test_image_folder_pipeline(tmp_path):
    root = _make_image_tree(tmp_path)
    batches = list(elastic_free_pipeline(root))
    total = sum(len(b["label"]) for b in batches)
    assert total == 6
    b = batches[0]
    assert b["image"].shape[1:] == (32, 32, 3)
    assert b["image"].dtype == np.float32
    labels = np.concatenate([b["label"] for b in batches])
    assert set(labels.tolist()) == {0, 1}


def elastic_free_pipeline(root):
    from edl_tpu.data.input_pipeline import image_folder_pipeline
    return image_folder_pipeline(root, batch_size=2, image_size=32,
                                 train=False)


def test_image_pipeline_sharding(tmp_path):
    root = _make_image_tree(tmp_path, classes=2, per_class=4)
    from edl_tpu.data.input_pipeline import image_folder_pipeline
    n0 = sum(len(b["label"]) for b in image_folder_pipeline(
        root, 2, image_size=32, train=False, shard_index=0, shard_count=2))
    n1 = sum(len(b["label"]) for b in image_folder_pipeline(
        root, 2, image_size=32, train=False, shard_index=1, shard_count=2))
    assert n0 + n1 == 8 and n0 == n1 == 4


def test_synthetic_pipeline_deterministic():
    from edl_tpu.data.input_pipeline import synthetic_pipeline
    a = list(synthetic_pipeline(4, image_size=8, steps=3, seed=1))
    b = list(synthetic_pipeline(4, image_size=8, steps=3, seed=1))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["image"], y["image"])


def _wait(pred, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError("condition not met")


def test_liveft_protocol(coord):
    m1 = elastic.ElasticManager(coord, "hostA", np_target=2, ttl=2).start()
    m2 = elastic.ElasticManager(coord, "hostB", np_target=2, ttl=2).start()
    try:
        hosts = m1.wait(timeout=20)
        assert hosts == ["hostA", "hostB"]
        assert m1.rank() == 0 and m2.rank() == 1
        assert m1.watch(poll=0.05) == elastic.HOLD

        # scale signal: np 2 -> 1 then hostB leaves -> RESTART for A
        m1.set_np(1)
        m2.stop()
        _wait(lambda: m1.hosts() == ["hostA"])
        _wait(lambda: m1.watch(poll=0.05) == elastic.RESTART, timeout=15)
        assert m1.wait(timeout=10) == ["hostA"]

        m1.complete()
        assert m1.watch(poll=0.05) == elastic.COMPLETED
    finally:
        m1.stop()
        m2.stop()


def test_profile_bench_breakdown_parser(tmp_path):
    """The xplane parser handles an empty logdir (no trace produced) and
    the CLI surface parses; the full trace path needs TPU hardware."""
    from edl_tpu.tools import profile_bench

    assert profile_bench.xplane_op_breakdown(str(tmp_path), 10) is None


@pytest.mark.integration
def test_bench_gpt_mode_oneshot(tmp_path):
    """bench.py --model gpt (tiny, CPU): the LM benchmark surface emits
    a parseable tok/s JSON line through the oneshot path."""
    import json
    import subprocess
    import sys

    from conftest import REPO as repo, cpu_subprocess_env
    env = cpu_subprocess_env(8)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--_oneshot",
         "--model", "gpt", "--gpt_tiny", "--batch_per_chip", "2",
         "--seq_len", "32", "--iters", "2"],
        env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads([l for l in proc.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert out["unit"] == "tok/s/chip" and out["value"] > 0
