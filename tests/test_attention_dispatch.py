"""Dense / flash / auto attention dispatch parity (the flash-by-default
satellite): ``use_flash=None`` auto-dispatches by kernel legality, and
the three paths must agree numerically on the SAME small gpt config —
allclose logits, matching grads — with the legality boundaries pinned
so an illegal shape can never silently take the kernel path."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.attention import attention_context, flash_dispatch_reason


# -- legality boundaries (pure shape math, no tracing) ---------------------


def test_auto_dispatch_legality_on_tpu_shapes():
    """The shapes the Pallas kernel handles exactly take flash; ragged
    q blocks and off-lane head dims stay dense."""
    ok = lambda s, d: flash_dispatch_reason(s, d, platform="tpu")
    assert ok(128, 64) is None
    assert ok(1024, 64) is None  # whole 128-blocks
    assert ok(64, 16) is None    # single (clamped) q block
    assert ok(96, 8) is None     # <= one block, ragged kv is masked
    # odd seq: ragged q blocks are NOT masked by the kernel
    assert "seq_len" in ok(129, 64)
    assert "seq_len" in ok(250, 64)
    # head_dim off the 8-lane tiling
    assert "head_dim" in ok(128, 15)
    assert "head_dim" in ok(1024, 12)


def test_auto_dispatch_never_picks_flash_off_tpu_or_with_mask():
    assert "platform" in flash_dispatch_reason(128, 64, platform="cpu")
    assert "mask" in flash_dispatch_reason(
        128, 64, mask=np.ones((2, 128), bool), platform="tpu")


def test_auto_dispatch_env_kill_switch(monkeypatch):
    monkeypatch.setenv("EDL_TPU_FLASH_AUTO", "0")
    assert "EDL_TPU_FLASH_AUTO" in flash_dispatch_reason(
        128, 64, platform="tpu")
    monkeypatch.delenv("EDL_TPU_FLASH_AUTO")
    assert flash_dispatch_reason(128, 64, platform="tpu") is None


# -- numerics parity on the shared dispatch --------------------------------


def _qkv(b=2, s=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) * 0.4
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_attention_context_flash_matches_dense(causal):
    q, k, v = _qkv()
    kw = dict(causal=causal, mask=None, dtype=jnp.float32)
    dense = attention_context(q, k, v, use_flash=False, **kw)
    flash = attention_context(q, k, v, use_flash=True, **kw)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_attention_context_auto_is_dense_on_cpu():
    """On CPU the auto path must resolve to dense (interpret-mode flash
    is slower), bit-for-bit — the tier-1 default behavior is unchanged
    by the new None default."""
    q, k, v = _qkv(seed=1)
    kw = dict(causal=True, mask=None, dtype=jnp.float32)
    dense = attention_context(q, k, v, use_flash=False, **kw)
    auto = attention_context(q, k, v, use_flash=None, **kw)
    assert np.asarray(auto).tobytes() == np.asarray(dense).tobytes()


# -- the small-gpt parity gate (logits + grads) ----------------------------


def _gpt_logits_and_grads(use_flash, seed=0):
    from edl_tpu.models import gpt

    kw = dict(num_layers=2, d_model=64, num_heads=4, mlp_dim=128,
              vocab_size=128, max_len=64, dtype=jnp.float32,
              use_flash=use_flash)
    model = gpt.Gpt(**kw)
    ids = jnp.asarray(np.random.RandomState(seed).randint(0, 128, (2, 64)),
                      jnp.int32)
    ref = gpt.Gpt(**dict(kw, use_flash=False))
    params = ref.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)

    def loss(p):
        out = model.apply({"params": p}, ids)
        return (out.astype(jnp.float32) ** 2).mean()

    grads = jax.grad(loss)(params)
    return logits, grads


def test_gpt_dense_flash_auto_parity():
    """The acceptance gate: dense vs forced-flash (interpret mode on
    CPU) vs auto on one small gpt config — allclose logits AND matching
    grads through the whole stack; auto == dense exactly on CPU."""
    logits_d, grads_d = _gpt_logits_and_grads(False)
    logits_f, grads_f = _gpt_logits_and_grads(True)
    logits_a, grads_a = _gpt_logits_and_grads(None)

    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree_util.tree_leaves(grads_f),
                    jax.tree_util.tree_leaves(grads_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
    # auto resolves to dense on CPU: identical computation
    assert np.asarray(logits_a).tobytes() == np.asarray(logits_d).tobytes()
    for a, b in zip(jax.tree_util.tree_leaves(grads_a),
                    jax.tree_util.tree_leaves(grads_d)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_bert_auto_default_matches_explicit_dense():
    """Threading None through bert must not change the encoder's output
    vs an explicit use_flash=False (the pre-PR default)."""
    from edl_tpu.models import bert

    kw = dict(num_layers=2, d_model=32, num_heads=2, mlp_dim=64,
              vocab_size=100, max_len=64, dtype=jnp.float32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 100, (2, 32)),
                      jnp.int32)
    m_auto = bert.Bert(**kw)  # default use_flash=None
    m_dense = bert.Bert(use_flash=False, **kw)
    params = m_dense.init(jax.random.PRNGKey(0), ids)["params"]
    out_a = m_auto.apply({"params": params}, ids)
    out_d = m_dense.apply({"params": params}, ids)
    assert np.asarray(out_a).tobytes() == np.asarray(out_d).tobytes()


def test_auto_dispatch_decode_shaped_queries_stay_dense():
    """KV-cache decode queries (seq_q=1 vs a longer cached kv) must
    never take the flash kernel — its causal mask assumes square q/kv —
    and square shapes with seq_kv passed explicitly stay legal."""
    assert "decode-shaped" in flash_dispatch_reason(1, 64, platform="tpu",
                                                    seq_kv=64)
    assert "decode-shaped" in flash_dispatch_reason(4, 64, platform="tpu",
                                                    seq_kv=128)
    assert flash_dispatch_reason(128, 64, platform="tpu",
                                 seq_kv=128) is None


def test_auto_dispatch_chunk_shaped_queries_stay_dense():
    """Chunked/suffix prefill (prefill_offset set) anchors row i's
    causal frontier at offset+i, not i — the flash kernel's diagonal
    starts at 0, so ANY non-None offset must stay dense, even an
    otherwise flash-legal square shape. Offset 0 is still chunk-shaped:
    the chunk attends the full cached row, not a square window."""
    assert "chunk-shaped" in flash_dispatch_reason(128, 64,
                                                   platform="tpu",
                                                   offset=32)
    assert "chunk-shaped" in flash_dispatch_reason(128, 64,
                                                   platform="tpu",
                                                   offset=0)
    assert flash_dispatch_reason(128, 64, platform="tpu",
                                 offset=None) is None


def test_use_flash_true_rejects_decode_shaped_q():
    """Forcing the kernel onto a decode-shaped query is a loud
    ValueError, never a silently mis-masked context."""
    q = jnp.zeros((1, 1, 2, 16), jnp.float32)
    k = v = jnp.zeros((1, 8, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="decode-shaped"):
        attention_context(q, k, v, causal=True, mask=None,
                          dtype=jnp.float32, use_flash=True)
