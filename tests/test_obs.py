"""The observability plane: metrics registry semantics, trace-context
propagation over pipelined RPC (the one-trace_id acceptance path and
the legacy-peer byte-compatible fallback), the elastic-event timeline,
and the fleet publisher/merge pipeline job_stats is built on."""

import json
import threading

import pytest

from edl_tpu.obs import events as obs_events
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import publisher as obs_publisher
from edl_tpu.obs import trace as obs_trace
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts with an empty span ring and sampling off, and
    cannot leak either to its neighbors."""
    obs_trace.TRACER.clear()
    was = obs_trace.TRACER.enabled
    obs_trace.TRACER.disable()
    yield
    obs_trace.TRACER.clear()
    (obs_trace.TRACER.enable if was else obs_trace.TRACER.disable)()


# -- registry --------------------------------------------------------------


def test_counter_concurrent_increments():
    """8 threads hammering one labeled child (and the labels() lookup
    itself) lose no increments."""
    fam = obs_metrics.counter("t_obs_conc_total", "c", labels=("k",))
    n_threads, n_incs = 8, 5000

    def work():
        for _ in range(n_incs):
            fam.labels("x").inc()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fam.labels("x").value == n_threads * n_incs
    obs_metrics.REGISTRY.unregister("t_obs_conc_total")


def test_histogram_bucket_boundaries():
    """le-semantics at the exact boundary: an observation equal to a
    bound lands in that bucket, epsilon above spills to the next."""
    hist = obs_metrics.histogram("t_obs_bounds_ms", "h",
                                 buckets=(1.0, 2.0, 5.0))
    for v in (1.0, 1.0001, 2.0, 5.0, 6.0):
        hist.observe(v)
    cum, total_sum, count = hist._d().read()
    # raw per-bucket: [le=1: 1, le=2: 2, le=5: 1, +Inf: 1]
    assert cum == [1, 3, 4, 5]
    assert count == 5
    assert total_sum == pytest.approx(15.0001)
    assert hist.percentile(0.5) == 2.0
    text = obs_metrics.REGISTRY.prometheus_text()
    assert 't_obs_bounds_ms_bucket{le="1"} 1' in text
    assert 't_obs_bounds_ms_bucket{le="+Inf"} 5' in text
    assert "t_obs_bounds_ms_count 5" in text
    obs_metrics.REGISTRY.unregister("t_obs_bounds_ms")


def test_label_cardinality_cap_collapses_to_overflow():
    """Past max_series new label sets share ONE __overflow__ child and
    the registry counts every drop — bounded memory under label abuse."""
    fam = obs_metrics.Family(obs_metrics.REGISTRY, "counter",
                             "t_obs_cap_total", labelnames=("k",),
                             max_series=4)
    dropped0 = obs_metrics.REGISTRY.series_dropped
    for i in range(4):
        fam.labels("k%d" % i).inc()
    over_a = fam.labels("k_extra_a")
    over_b = fam.labels("k_extra_b")
    assert over_a is over_b  # both collapsed into the overflow child
    over_a.inc()
    over_b.inc()
    series = fam.series()
    assert len(series) == 5  # 4 real + 1 overflow, never more
    assert series[(obs_metrics._OVERFLOW,)].value == 2
    # pre-cap children are untouched and still addressable
    assert fam.labels("k0").value == 1
    assert obs_metrics.REGISTRY.series_dropped == dropped0 + 2


def test_family_redeclaration_rules():
    """Same declaration → same object (module-scope declarations across
    planes may collide on purpose); conflicting kind/labels → error."""
    a = obs_metrics.counter("t_obs_redecl_total", "c", labels=("k",))
    b = obs_metrics.counter("t_obs_redecl_total", "c", labels=("k",))
    assert a is b
    with pytest.raises(ValueError):
        obs_metrics.gauge("t_obs_redecl_total")
    with pytest.raises(ValueError):
        obs_metrics.counter("t_obs_redecl_total", labels=("other",))
    obs_metrics.REGISTRY.unregister("t_obs_redecl_total")


def test_kill_switch_stops_observation():
    ctr = obs_metrics.counter("t_obs_kill_total")
    hist = obs_metrics.histogram("t_obs_kill_ms")
    prev = obs_metrics.set_enabled(False)
    try:
        assert obs_metrics.enabled() is False
        ctr.inc()
        hist.observe(3.0)
    finally:
        obs_metrics.set_enabled(prev)
    assert ctr.value == 0
    assert hist._d().read()[2] == 0
    ctr.inc()
    assert ctr.value == 1  # live again after restore
    obs_metrics.REGISTRY.unregister("t_obs_kill_total")
    obs_metrics.REGISTRY.unregister("t_obs_kill_ms")


def test_mirror_stats_exports_numeric_scalars():
    stats = {"hits": 7, "ratio": 0.5, "alive": True, "name": "x",
             "items": [1, 2]}
    out = obs_metrics.mirror_stats("t_obs_mirror", stats)
    assert out is stats  # passthrough for the legacy caller
    fams = obs_metrics.REGISTRY.families()
    assert fams["t_obs_mirror_hits"].value == 7
    assert fams["t_obs_mirror_ratio"].value == 0.5
    assert fams["t_obs_mirror_alive"].value == 1
    assert "t_obs_mirror_name" not in fams
    assert "t_obs_mirror_items" not in fams
    for k in ("hits", "ratio", "alive"):
        obs_metrics.REGISTRY.unregister("t_obs_mirror_%s" % k)


def test_merge_snapshots_fleet_semantics():
    """Counters and histogram buckets sum elementwise across pods;
    gauges keep per-pod values plus min/max/sum."""
    snaps = {}
    for pod, (c, g, h) in (("p0", (3, 10.0, 1.5)),
                           ("p1", (4, 2.0, 100.0))):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("f_total", labels=("k",)).labels("x").inc(c)
        reg.gauge("f_gauge").set(g)
        reg.histogram("f_ms", buckets=(10.0, 1000.0)).observe(h)
        snaps[pod] = reg.snapshot()
    fleet = obs_metrics.merge_snapshots(snaps)
    assert fleet["schema"] == "obs_fleet/v1"
    assert fleet["pods"] == ["p0", "p1"]
    ctr = fleet["metrics"]["f_total"]["series"][0]
    assert ctr["value"] == 7 and ctr["pods"] == {"p0": 3, "p1": 4}
    gauge = fleet["metrics"]["f_gauge"]["series"][0]
    assert (gauge["min"], gauge["max"], gauge["sum"]) == (2.0, 10.0, 12.0)
    hist = fleet["metrics"]["f_ms"]["series"][0]
    assert hist["buckets"] == [1, 1, 0]  # le=10 + le=1000, elementwise
    assert hist["count"] == 2
    json.dumps(fleet)  # the whole fleet doc must stay JSON-able

    # the --pretty renderer must handle every merged-cell shape:
    # counters carry a summed value, gauges only min/max/sum/pods
    from edl_tpu.tools import job_stats
    text = job_stats.format_fleet({
        "job_id": "j", "job_status": "RUNNING", "pods_alive": 2,
        "train": None, "fleet_metrics": fleet,
        "timeline": [{"pod": "p0", "kind": "resize.resumed",
                      "attrs": {"version": 3}}]})
    assert "f_total{k=x} 7" in text
    assert "f_gauge min=2.0 max=10.0 sum=12.0" in text
    assert "f_ms count=2" in text
    assert "[p0] resize.resumed version=3" in text
    assert "None" not in text.split("status=RUNNING")[1]


# -- trace propagation over pipelined RPC ----------------------------------


@pytest.fixture()
def echo_server():
    srv = RpcServer(host="127.0.0.1", port=0)
    srv.register("echo", lambda x: x)
    srv.start()
    yield srv
    srv.stop()


def test_trace_links_client_and_server_spans_pipelined(echo_server):
    """THE acceptance path: one trace_id links the client span of a
    pipelined call_async to the server dispatch span it caused, with
    parent_id threading client → server."""
    obs_trace.TRACER.enable()
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        fut = client.call_async("echo", "hello")
        assert fut.result(timeout=10) == "hello"
    finally:
        client.close()
    [client_span] = obs_trace.TRACER.find(name="rpc.client/echo",
                                          kind="client")
    [server_span] = obs_trace.TRACER.find(name="rpc/echo", kind="server")
    assert client_span["trace_id"] == server_span["trace_id"]
    assert server_span["parent_id"] == client_span["span_id"]
    assert client_span["dur_ms"] is not None
    assert server_span["dur_ms"] is not None
    assert client_span["tags"]["ok"] is True


def test_trace_context_spans_multiple_pipelined_calls(echo_server):
    """An active root context stamps EVERY overlapping call_async on
    the connection: 3 concurrent calls → 3 client + 3 server spans, all
    six sharing the root's trace_id."""
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        with obs_trace.span("resize/restore", root=True) as root:
            futs = [client.call_async("echo", i) for i in range(3)]
            assert [f.result(timeout=10) for f in futs] == [0, 1, 2]
    finally:
        client.close()
    trace_id = root.trace_id
    clients = obs_trace.TRACER.find(name="rpc.client/echo",
                                    trace_id=trace_id)
    servers = obs_trace.TRACER.find(name="rpc/echo", trace_id=trace_id)
    assert len(clients) == 3 and len(servers) == 3
    # every client span hangs off the root; every server span off one
    # distinct client span
    assert {c["parent_id"] for c in clients} == {root.span_id}
    assert ({s["parent_id"] for s in servers}
            == {c["span_id"] for c in clients})


def test_legacy_peer_fallback_no_header_no_breakage(echo_server):
    """A peer without __features__ (pre-obs build) must see a
    byte-identical request: no ``tr`` key, the call succeeds, the
    client span still records locally, and no server span adopts it."""
    del echo_server.methods["__features__"]  # simulate the legacy peer
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        with obs_trace.span("legacy_root", root=True):
            fut = client.call_async("echo", "old")
            assert fut.result(timeout=10) == "old"
    finally:
        client.close()
    assert client.server_features() == ()  # probe failed → cached empty
    [client_span] = obs_trace.TRACER.find(name="rpc.client/echo",
                                          kind="client")
    assert obs_trace.TRACER.find(kind="server") == []
    assert client_span["tags"]["ok"] is True


def test_malformed_trace_header_served_normally(echo_server):
    """Garbage in the tr slot must never fail the request."""
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        # bypass the negotiated path and hand-craft a bad header
        with obs_trace.server_span("rpc/x", 42) as sp:
            assert sp is None
        assert client.call("echo", "fine") == "fine"
    finally:
        client.close()


def test_metrics_rpc_serves_both_formats(echo_server):
    obs_metrics.counter("t_obs_rpc_total", "c").inc(5)
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        doc = client.call("__metrics__")
        assert doc["metrics"]["schema"] == "obs_snapshot/v1"
        fam = doc["metrics"]["metrics"]["t_obs_rpc_total"]
        assert fam["series"][0]["value"] == 5
        assert isinstance(doc["events"], list)
        text = client.call("__metrics__", fmt="prom")
        assert "# TYPE t_obs_rpc_total counter" in text
        assert "t_obs_rpc_total 5" in text
    finally:
        client.close()
        obs_metrics.REGISTRY.unregister("t_obs_rpc_total")


def test_chrome_trace_export(echo_server):
    obs_trace.TRACER.enable()
    client = RpcClient("127.0.0.1:%d" % echo_server.port, timeout=10)
    try:
        client.call("echo", 1)
    finally:
        client.close()
    doc = obs_trace.TRACER.chrome_trace()
    events = [e for e in doc["traceEvents"] if e["name"] == "rpc/echo"]
    assert events and events[0]["ph"] == "X"
    assert events[0]["args"]["parent_id"] is not None
    json.dumps(doc)


# -- elastic-event timeline ------------------------------------------------


def test_event_causal_chain_and_since_watermark():
    log = obs_events.EventLog(capacity=16)
    stop = log.emit("resize.coordinated_stop", reason="scale_up")
    restore = log.emit("resize.restore", cause=stop, source="peer")
    done = log.emit("resize.resumed", cause=restore)
    chain = log.snapshot()
    assert [e["kind"] for e in chain] == [
        "resize.coordinated_stop", "resize.restore", "resize.resumed"]
    assert chain[1]["cause"] == stop and chain[2]["cause"] == restore
    # incremental read: only events past the watermark come back
    assert [e["id"] for e in log.snapshot(since_id=restore)] == [done]
    assert log.snapshot(since_id=0, kinds=("resize.res",)) == chain[1:]
    assert log.last("resize.restore")["id"] == restore


def test_event_carries_active_trace_id():
    log = obs_events.EventLog()
    obs_trace.TRACER.enable()
    with obs_trace.span("resize/rebuild", root=True) as sp:
        log.emit("store.leader_elected", term=3)
    ev = log.last("store.leader_elected")
    assert ev["trace_id"] == sp.trace_id
    assert ev["attrs"] == {"term": 3}


def test_merge_timelines_orders_across_pods():
    a = [{"id": 1, "ts": 10.0, "kind": "x"},
         {"id": 2, "ts": 30.0, "kind": "y"}]
    b = [{"id": 1, "ts": 20.0, "kind": "z"}]
    merged = obs_events.merge_timelines({"p0": a, "p1": b, "p2": None})
    assert [(e["pod"], e["kind"]) for e in merged] == [
        ("p0", "x"), ("p1", "z"), ("p0", "y")]


# -- fleet publisher -------------------------------------------------------


class _FakeCoord(object):
    """The one store method the publisher needs."""

    def __init__(self):
        self.store = {}

    def set_server_permanent(self, service, server, value):
        self.store[(service, server)] = value


def test_publisher_service_name_matches_controller_constant():
    """publisher.SERVICE_METRICS is inlined (obs is a leaf package);
    this is the drift guard the inline comment promises."""
    from edl_tpu.controller import constants
    assert obs_publisher.SERVICE_METRICS == constants.SERVICE_METRICS


def test_health_service_name_matches_controller_constant():
    """health.SERVICE_HEALTH is inlined (obs is a leaf package); the
    same drift guard as SERVICE_METRICS above."""
    from edl_tpu.controller import constants
    from edl_tpu.obs import health as obs_health
    assert obs_health.SERVICE_HEALTH == constants.SERVICE_HEALTH


def test_publisher_doc_carries_ts():
    """Regression: publish_once once omitted the "ts" field its
    docstring promises — staleness liveness detection (obs/health)
    depends on the doc's own publication timestamp, not the inner
    registry snapshot's."""
    import time as _time

    coord = _FakeCoord()
    before = _time.time()
    pub = obs_publisher.MetricsPublisher(
        coord, "pod_ts", interval=999,
        registry=obs_metrics.MetricsRegistry(),
        events=obs_events.EventLog())
    doc = pub.publish_once()
    stored = json.loads(coord.store[("metrics", "obs_pod_ts")])
    for d in (doc, stored):
        assert before <= d["ts"] <= _time.time()


def test_publisher_publishes_and_watermarks_events():
    coord = _FakeCoord()
    log = obs_events.EventLog()
    reg = obs_metrics.MetricsRegistry()
    reg.counter("pub_total").inc(2)
    pub = obs_publisher.MetricsPublisher(coord, "pod7", interval=999,
                                         registry=reg, events=log)
    log.emit("breaker.open", peer="10.0.0.1:7001")
    doc = pub.publish_once()
    assert doc["schema"] == "obs_pub/v1"
    stored = json.loads(coord.store[("metrics", "obs_pod7")])
    assert stored["metrics"]["metrics"]["pub_total"]["series"][0][
        "value"] == 2
    assert [e["kind"] for e in stored["events"]] == ["breaker.open"]
    # watermark: an unchanged timeline publishes zero events...
    assert pub.publish_once()["events"] == []
    # ...and only the new event rides the next tick
    log.emit("breaker.close", peer="10.0.0.1:7001")
    assert [e["kind"] for e in pub.publish_once()["events"]] == [
        "breaker.close"]


def test_publisher_stop_flushes_final_doc():
    coord = _FakeCoord()
    log = obs_events.EventLog()
    pub = obs_publisher.MetricsPublisher(
        coord, "pod8", interval=999,
        registry=obs_metrics.MetricsRegistry(), events=log)
    pub.start()
    log.emit("fault.injected", fault="rpc.drop")
    pub.stop()  # final_flush=True must land the event despite interval
    stored = json.loads(coord.store[("metrics", "obs_pod8")])
    assert [e["kind"] for e in stored["events"]] == ["fault.injected"]
