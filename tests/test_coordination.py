"""Coordination store tests: KV, leases, election, txn, watch.

Mirrors the reference's etcd_client_test.py / test_leader_pod.py shapes
against the in-tree store instead of a real etcd.
"""

import threading
import time

import pytest

from edl_tpu.utils import errors


def test_kv_roundtrip(coord):
    coord.set_server_permanent("svc", "a", "va")
    coord.set_server_permanent("svc", "b", "vb")
    assert coord.get_service("svc") == [("a", "va"), ("b", "vb")]
    assert coord.get_value("svc", "a") == "va"
    assert coord.get_value("svc", "zz") is None
    coord.remove_server("svc", "a")
    assert coord.get_service("svc") == [("b", "vb")]


def test_lease_expiry_removes_server(coord):
    lease = coord.set_server_with_lease("svc", "x", "v", ttl=1)
    assert coord.get_value("svc", "x") == "v"
    coord.refresh_server("svc", "x", lease)
    time.sleep(1.6)
    assert coord.get_value("svc", "x") is None
    with pytest.raises(errors.LeaseExpiredError):
        coord.refresh_server("svc", "x", lease)


def test_put_if_absent_election(coord):
    l1 = coord.set_server_not_exists("leader", "0", "pod_a", ttl=5)
    assert l1 is not None
    # second contender loses
    assert coord.set_server_not_exists("leader", "0", "pod_b", ttl=5) is None
    assert coord.get_value("leader", "0") == "pod_a"
    # leader revokes → key released → next contender wins
    coord.lease_revoke(l1)
    l2 = coord.set_server_not_exists("leader", "0", "pod_b", ttl=5)
    assert l2 is not None
    assert coord.get_value("leader", "0") == "pod_b"


def test_leadership_expires_on_ttl(coord):
    lease = coord.set_server_not_exists("leader", "0", "pod_a", ttl=1)
    assert lease is not None
    time.sleep(1.6)  # no refresh → lease expires → key deleted
    l2 = coord.set_server_not_exists("leader", "0", "pod_b", ttl=5)
    assert l2 is not None


def test_guarded_txn(coord):
    coord.set_server_permanent("leader", "0", "me")
    assert coord.put_if_leader("leader", "0", "me",
                               [("/test_job/cluster/nodes/c", "v1")])
    assert coord.get_value("cluster", "c") == "v1"
    # wrong leader value → txn rejected
    assert not coord.put_if_leader("leader", "0", "not_me",
                                   [("/test_job/cluster/nodes/c", "v2")])
    assert coord.get_value("cluster", "c") == "v1"


def test_txn_compare_ops(coord):
    key = "/test_job/k"
    ok, _ = coord.txn([(key, "not_exists", None)], [("put", key, "1")])
    assert ok
    ok, _ = coord.txn([(key, "not_exists", None)], [("put", key, "2")])
    assert not ok
    ok, _ = coord.txn([(key, "value_eq", "1")], [("put", key, "3")])
    assert ok
    assert coord.get_key(key)["value"] == "3"


def test_watch_service_diffing(coord):
    events = []
    done = threading.Event()

    def cb(added, removed, all_servers):
        events.append((dict(added), dict(removed)))
        if len(events) >= 3:
            done.set()

    w = coord.watch_service("svc", cb, poll_timeout=0.5)
    try:
        coord.set_server_permanent("svc", "a", "va")
        time.sleep(0.3)
        coord.set_server_permanent("svc", "b", "vb")
        time.sleep(0.3)
        coord.remove_server("svc", "a")
        assert done.wait(5.0)
    finally:
        w.stop()
    flat_added = {}
    flat_removed = {}
    for added, removed in events:
        flat_added.update(added)
        flat_removed.update(removed)
    assert flat_added == {"a": "va", "b": "vb"}
    assert "a" in flat_removed


def test_watch_sees_lease_expiry(coord):
    removed_names = []
    got = threading.Event()

    def cb(added, removed, all_servers):
        removed_names.extend(removed.keys())
        if removed:
            got.set()

    coord.set_server_with_lease("svc", "dying", "v", ttl=1)
    w = coord.watch_service("svc", cb, poll_timeout=0.5)
    try:
        assert got.wait(5.0)
        assert removed_names == ["dying"]
    finally:
        w.stop()


def test_clean_root_isolates_namespaces(store):
    c1 = store.client(root="job1")
    c2 = store.client(root="job2")
    c1.set_server_permanent("svc", "a", "1")
    c2.set_server_permanent("svc", "a", "2")
    c1.clean_root()
    assert c1.get_service("svc") == []
    assert c2.get_service("svc") == [("a", "2")]


def test_store_bench_tool_runs():
    """The store benchmark tool (tools/store_bench.py) must stay
    runnable: one tiny py-backend pass, every metric line present."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "edl_tpu.tools.store_bench",
         "--n", "40", "--backends", "py"],
        capture_output=True, timeout=120)
    assert out.returncode == 0, out.stderr[-800:]
    metrics = {json.loads(l)["metric"]
               for l in out.stdout.decode().splitlines() if l}
    for op in ("put", "get", "put4", "lease"):
        assert "store_%s_ops_per_sec" % op in metrics, metrics
    assert "store_watch_latency_ms" in metrics, metrics
