"""ElasticTrainer across REAL processes with params tp-sharded ACROSS the
process boundary: train → save (collective gather + rank-0 write) →
fresh-trainer resume on both ranks. This is the deadlock scenario of the
multi-host checkpoint path: save() must be called by every rank, gather
collectively, and only rank 0 writes."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, nprocs, rank, ckpt = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])
import os
os.environ["EDL_TPU_GLOBAL_RANK"] = str(rank)
os.environ["EDL_TPU_WORLD_SIZE"] = str(nprocs)
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=nprocs, process_id=rank)
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from edl_tpu.models import bert
from edl_tpu.runtime.trainer import ElasticTrainer

# tp axis SPANS the two processes: column j of the mesh = process j's
# devices, so every tp pair crosses the host boundary and the params are
# NOT fully addressable from either process
devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
mine = [d for d in devs if d.process_index == 0]
theirs = [d for d in devs if d.process_index == 1]
mesh = Mesh(np.stack([mine, theirs], axis=1), ("dp", "tp"))

def make_trainer():
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    return ElasticTrainer(
        loss_fn, params, optax.adamw(1e-3), total_batch_size=16,
        checkpoint_dir=ckpt, mesh=mesh,
        param_shardings=bert.bert_partition_rules())

trainer = make_trainer()
qkv = trainer.train_state["params"]["layer_0"]["attention"]["query"][
    "kernel"]
assert not qkv.is_fully_addressable, "tp must cross the process boundary"

full = bert.synthetic_text_batch(16, seq_len=16)
# tp crosses processes → every process supplies ALL rows
host_batch = trainer.local_batch_slice(full)
assert host_batch["label"].shape[0] == 16, host_batch["label"].shape
for i in range(2):
    loss = float(trainer.train_step(host_batch))
trainer.begin_epoch(0)
trainer.end_epoch(save=True)   # collective gather; rank-0 write
print("SAVED rank=%d loss=%.6f" % (rank, loss), flush=True)

trainer2 = make_trainer()
assert trainer2.resume(), "resume failed"
assert trainer2.global_step == 2
q2 = trainer2.train_state["params"]["layer_0"]["attention"]["query"][
    "kernel"]
assert not q2.is_fully_addressable
l2 = float(trainer2.train_step(host_batch))
print("RESUMED rank=%d loss=%.6f" % (rank, l2), flush=True)
"""


@pytest.mark.integration
def test_multihost_tp_trainer_save_resume(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = "127.0.0.1:%d" % port
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    ckpt = str(tmp_path / "ckpt")

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), coordinator, "2", str(rank),
         ckpt],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode("utf-8", "replace"))
            assert p.returncode == 0, "\n".join(outs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    text = "\n".join(outs)
    assert text.count("SAVED") == 2, text
    assert text.count("RESUMED") == 2, text
    # both ranks agree on the post-resume loss (replicated-consistent)
    resumed = sorted(ln.split("loss=")[1] for ln in text.splitlines()
                     if ln.startswith("RESUMED"))
    assert resumed[0] == resumed[1], resumed
