"""ElasticTrainer across REAL processes with params tp-sharded ACROSS the
process boundary: train → save (per-host SHARDED write: each rank writes
only its own shards, fs-sentinel barriers, rank-0 manifest commit — no
gather collective) → fresh-trainer resume on both ranks."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, nprocs, rank, ckpt = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])
import os
os.environ["EDL_TPU_GLOBAL_RANK"] = str(rank)
os.environ["EDL_TPU_WORLD_SIZE"] = str(nprocs)
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=nprocs, process_id=rank)
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from edl_tpu.models import bert
from edl_tpu.runtime.trainer import ElasticTrainer

# tp axis SPANS the two processes: column j of the mesh = process j's
# devices, so every tp pair crosses the host boundary and the params are
# NOT fully addressable from either process
devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
mine = [d for d in devs if d.process_index == 0]
theirs = [d for d in devs if d.process_index == 1]
mesh = Mesh(np.stack([mine, theirs], axis=1), ("dp", "tp"))

def make_trainer():
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    return ElasticTrainer(
        loss_fn, params, optax.adamw(1e-3), total_batch_size=16,
        checkpoint_dir=ckpt, mesh=mesh,
        param_shardings=bert.bert_partition_rules())

trainer = make_trainer()
qkv = trainer.train_state["params"]["layer_0"]["attention"]["query"][
    "kernel"]
assert not qkv.is_fully_addressable, "tp must cross the process boundary"

full = bert.synthetic_text_batch(16, seq_len=16)
# tp crosses processes → every process supplies ALL rows
host_batch = trainer.local_batch_slice(full)
assert host_batch["label"].shape[0] == 16, host_batch["label"].shape
for i in range(2):
    loss = float(trainer.train_step(host_batch))
trainer.begin_epoch(0)
trainer.end_epoch(save=True)   # per-rank sharded write; rank-0 commit
# every rank wrote its own shard file; rank 0 committed the manifest
# (non-zero ranks return before the commit — only rank 0 may read it)
import glob
import json as _json
vdir = sorted(glob.glob(ckpt + "/v_*"))[-1]
assert os.path.exists("%s/arrays.r%d.npz" % (vdir, rank)), vdir
if rank == 0:
    with open(vdir + "/MANIFEST") as f:
        _m = _json.load(f)
    assert _m.get("sharded") and _m["ranks"] == 2, _m
print("SAVED rank=%d loss=%.6f" % (rank, loss), flush=True)

# rank 0's save_sharded returns only after the MANIFEST commit, so this
# barrier guarantees the commit is visible before any rank resumes
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("ckpt-committed")

trainer2 = make_trainer()
assert trainer2.resume(), "resume failed"
assert trainer2.global_step == 2
q2 = trainer2.train_state["params"]["layer_0"]["attention"]["query"][
    "kernel"]
assert not q2.is_fully_addressable
l2 = float(trainer2.train_step(host_batch))
print("RESUMED rank=%d loss=%.6f" % (rank, l2), flush=True)
"""


WORKER_DP = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
coordinator, nprocs, rank, ckpt = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), sys.argv[4])
import os
os.environ["EDL_TPU_GLOBAL_RANK"] = str(rank)
os.environ["EDL_TPU_WORLD_SIZE"] = str(nprocs)
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=nprocs, process_id=rank)
import optax
from edl_tpu.models import linear
from edl_tpu.runtime.trainer import ElasticTrainer
from edl_tpu.utils.errors import PreemptedError

def make_trainer():
    return ElasticTrainer(linear.loss_fn, linear.init_params(),
                          optax.sgd(0.05), total_batch_size=16,
                          checkpoint_dir=ckpt)

trainer = make_trainer()
# pure dp: params replicated across BOTH processes (not fully
# addressable, but every rank holds a complete local replica)
w = trainer.train_state["params"]["w"]
assert not w.is_fully_addressable and w.is_fully_replicated

full = linear.synthetic_batch(16, seed=0)
for i in range(3):
    trainer.train_step(trainer.local_batch_slice(full))
trainer._preempted = True  # both ranks' SIGTERM flags (simulated)
try:
    trainer.train_step(trainer.local_batch_slice(full))
    raise AssertionError("expected PreemptedError")
except PreemptedError as e:
    msg = str(e)
if rank == 0:
    assert "saved at step 4" in msg, msg
else:
    assert "rank 0" in msg, msg
print("PREEMPTED rank=%d" % rank, flush=True)

# rank 0's dense local save is synchronous; barrier so rank 1 sees it
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("emergency-committed")

trainer2 = make_trainer()
assert trainer2.resume(), "resume failed"
assert trainer2.global_step == 4, trainer2.global_step
trainer2.train_step(trainer2.local_batch_slice(full))
print("RESUMED rank=%d step=%d" % (rank, trainer2.global_step),
      flush=True)
"""


WORKER_TP_COORD = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
(coordinator, nprocs, rank, ckpt,
 store_ep) = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
              sys.argv[4], sys.argv[5])
import os
os.environ["EDL_TPU_GLOBAL_RANK"] = str(rank)
os.environ["EDL_TPU_WORLD_SIZE"] = str(nprocs)
jax.distributed.initialize(coordinator_address=coordinator,
                           num_processes=nprocs, process_id=rank)
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from edl_tpu.coordination.client import CoordClient
from edl_tpu.models import bert
from edl_tpu.runtime.trainer import ElasticTrainer
from edl_tpu.utils.errors import PreemptedError

coord = CoordClient([store_ep], root="coordjob")
devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
mine = [d for d in devs if d.process_index == 0]
theirs = [d for d in devs if d.process_index == 1]
mesh = Mesh(np.stack([mine, theirs], axis=1), ("dp", "tp"))

def make_trainer():
    model, params, loss_fn = bert.create_model_and_loss(
        model=bert.bert_tiny(dtype=jnp.float32))
    t = ElasticTrainer(
        loss_fn, params, optax.adamw(1e-3), total_batch_size=16,
        checkpoint_dir=ckpt, mesh=mesh, coord=coord,
        param_shardings=bert.bert_partition_rules())
    t.install_preemption_handler(coordinated=True)
    t._coord_stop._poll = 0.05
    return t

trainer = make_trainer()
qkv = trainer.train_state["params"]["layer_0"]["attention"]["query"][
    "kernel"]
assert not qkv.is_fully_addressable  # tp crosses the process boundary
assert trainer._coord_stop is not None

import time

full = bert.synthetic_text_batch(16, seq_len=16)
host_batch = trainer.local_batch_slice(full)
stopped_at = None
for i in range(120):
    if i == 2 and rank == 1:
        trainer._preempted = True  # SIGTERM lands on rank 1 ONLY
    try:
        # synced + paced like a real training loop (loss fetch for
        # logging): a loop that never syncs can dispatch past any
        # coordinated stop step before its watcher observes it
        loss = trainer.train_step(host_batch)
        jax.block_until_ready(loss)
        time.sleep(0.05)
    except PreemptedError as e:
        assert "coordinated stop" in str(e), str(e)
        stopped_at = trainer.global_step
        break
assert stopped_at is not None, "never stopped (rank %d)" % rank
print("STOPPED rank=%d step=%d" % (rank, stopped_at), flush=True)

from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("emergency-committed")

trainer2 = make_trainer()
assert trainer2.resume(), "resume failed"
assert trainer2.global_step == stopped_at, trainer2.global_step
trainer2.train_step(host_batch)
print("RESUMED rank=%d step=%d" % (rank, trainer2.global_step),
      flush=True)
"""


@pytest.mark.integration
def test_multihost_tp_coordinated_preemption(tmp_path):
    """The full coordinated-stop arc across 2 REAL processes with
    tp-sharded state: SIGTERM on rank 1 only -> store rendezvous on a
    common stop step -> cooperative SHARDED emergency save at that
    aligned boundary on both ranks -> both resume from it."""
    from edl_tpu.coordination.server import StoreServer

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = "127.0.0.1:%d" % port
    worker_py = tmp_path / "worker_coord.py"
    worker_py.write_text(WORKER_TP_COORD)
    ckpt = str(tmp_path / "ckpt")

    store = StoreServer(host="127.0.0.1").start()
    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(4)
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), coordinator, "2", str(rank),
         ckpt, store.endpoint],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode("utf-8", "replace"))
            assert p.returncode == 0, "\n".join(outs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        store.stop()
    text = "\n".join(outs)
    assert text.count("STOPPED") == 2, text
    assert text.count("RESUMED") == 2, text
    # both ranks stopped at the SAME agreed step
    steps = sorted(ln.split("step=")[1] for ln in text.splitlines()
                   if ln.startswith("STOPPED"))
    assert steps[0] == steps[1], text


@pytest.mark.integration
def test_multihost_dp_emergency_preemption_save(tmp_path):
    """2-process pure-dp job: on preemption rank 0 alone writes a dense
    emergency checkpoint from its local replica (no collective, no
    rendezvous with rank 1), and both ranks resume from it."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = "127.0.0.1:%d" % port
    worker_py = tmp_path / "worker_dp.py"
    worker_py.write_text(WORKER_DP)
    ckpt = str(tmp_path / "ckpt")

    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(4)
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), coordinator, "2", str(rank),
         ckpt],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode("utf-8", "replace"))
            assert p.returncode == 0, "\n".join(outs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    text = "\n".join(outs)
    assert text.count("PREEMPTED") == 2, text
    assert text.count("RESUMED") == 2, text


@pytest.mark.integration
def test_multihost_tp_trainer_save_resume(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = "127.0.0.1:%d" % port
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    ckpt = str(tmp_path / "ckpt")

    from conftest import cpu_subprocess_env
    env = cpu_subprocess_env(4)
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), coordinator, "2", str(rank),
         ckpt],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode("utf-8", "replace"))
            assert p.returncode == 0, "\n".join(outs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    text = "\n".join(outs)
    assert text.count("SAVED") == 2, text
    assert text.count("RESUMED") == 2, text
    # both ranks agree on the post-resume loss (replicated-consistent)
    resumed = sorted(ln.split("loss=")[1] for ln in text.splitlines()
                     if ln.startswith("RESUMED"))
    assert resumed[0] == resumed[1], resumed
