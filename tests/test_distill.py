"""Distill plane tests: hash ring, balancer invariants, teacher server,
discovery, and the full DistillReader pipeline with teacher failure
mid-epoch (reference shape: distill_reader_test.py + NOP backend)."""

import threading
import time

import numpy as np
import pytest

from edl_tpu.distill.balance import Service
from edl_tpu.distill.consistent_hash import ConsistentHash
from edl_tpu.distill.discovery_client import DiscoveryClient
from edl_tpu.distill.discovery_server import DiscoveryServer
from edl_tpu.distill.distill_reader import DistillReader
from edl_tpu.distill.registry import TeacherRegister, list_teachers
from edl_tpu.distill.teacher_server import TeacherServer, nop_teacher
from edl_tpu.rpc import ndarray as nd


def test_ndarray_codec():
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "nested": [np.array([1, 2], np.int64), "text", 7]}
    out = nd.decode_tree(nd.encode_tree(tree))
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["nested"][0], tree["nested"][0])
    assert out["nested"][1:] == ["text", 7]


def test_consistent_hash_stability():
    ring = ConsistentHash(["s1", "s2", "s3"])
    owners = {k: ring.get_node("svc%d" % k)[0] for k in range(50)}
    v0 = ring.version
    ring.remove_node("s2")
    assert ring.version > v0
    moved = sum(1 for k in range(50)
                if owners[k] != ring.get_node("svc%d" % k)[0])
    # only keys owned by the removed node move
    assert moved == sum(1 for k in range(50) if owners[k] == "s2")
    assert all(ring.get_node("svc%d" % k)[0] in ("s1", "s3")
               for k in range(50))


def test_balance_invariants():
    svc = Service("s")
    svc.set_servers(["t1", "t2", "t3"])
    for i in range(6):
        svc.register_client("c%d" % i, require_num=2)
    stats = svc.stats()
    # per-server cap = (6+3-1)//3 = 2; per-client = max(1, 3//6) = 1
    assert all(n <= 3 for n in stats["servers"].values())
    assert all(len(s) >= 1 for s in stats["clients"].values())
    # teacher dies → its clients rebalanced
    v_before = {c: svc.heartbeat(c, -1)["version"]
                for c in list(stats["clients"])}
    svc.set_servers(["t1", "t3"])
    stats2 = svc.stats()
    assert "t2" not in stats2["servers"]
    assert all(len(s) >= 1 for s in stats2["clients"].values())
    # affected clients got a version bump
    changed = [c for c in v_before
               if svc.heartbeat(c, v_before[c]) is not None
               and "servers" in svc.heartbeat(c, v_before[c])]
    assert changed


def test_balance_fairness_metrics():
    svc = Service("s")
    svc.set_servers(["t1", "t2", "t3"])
    for i in range(6):
        svc.register_client("c%d" % i, require_num=2)
    f = svc.stats()["fairness"]
    # 6 clients over 3 teachers, per-client allowance 1 → even spread,
    # everyone fully satisfied
    assert f["load_imbalance"] <= 1
    assert f["satisfaction"] == 1.0
    assert f["rebalances"] > 0 and f["evicted"] == 0
    # teacher loss → imbalance stays bounded after the rebalance
    svc.set_servers(["t1", "t2"])
    f2 = svc.stats()["fairness"]
    assert f2["load_imbalance"] <= 1
    assert f2["satisfaction"] == 1.0


def test_balance_evicts_stale_clients():
    """Crashed students (no heartbeat for > TTL) must be evicted so their
    capacity returns to live clients — elastic resizes restart trainers
    with fresh pids, so ghosts would otherwise accumulate forever."""
    now = [0.0]
    svc = Service("s", client_ttl=10.0, clock=lambda: now[0])
    svc.set_servers(["t1", "t2"])
    svc.register_client("ghost", require_num=2)
    svc.register_client("live", require_num=2)
    assert set(svc.stats()["clients"]) == {"ghost", "live"}

    # only "live" heartbeats; ghost goes silent past the TTL
    for t in (4.0, 8.0, 12.0):
        now[0] = t
        assert svc.heartbeat("live", -1) is not None
    stats = svc.stats()
    assert "ghost" not in stats["clients"]
    assert svc.heartbeat("ghost", -1) is None  # must re-register
    # live client now gets the full fleet (per_client = 2//1 = 2)
    assert len(stats["clients"]["live"]) == 2


def test_teacher_server_pad_and_slice():
    def fn(feed):
        return {"out": feed["x"] * 2.0}
    server = TeacherServer(fn, {"x": ([3], "<f4")}, {"out": ([3], "<f4")},
                           max_batch=8, host="127.0.0.1").start()
    try:
        from edl_tpu.distill.distill_reader import _TeacherConn
        conn = _TeacherConn(server.endpoint)
        assert conn.max_batch == 8
        x = np.arange(30, dtype=np.float32).reshape(10, 3)  # > max_batch
        out = conn.predict({"x": x})
        np.testing.assert_allclose(out["out"], x * 2.0)
        conn.close()
    finally:
        server.stop()


def test_gpt_teacher_serves_lm_soft_labels():
    """The causal-LM teacher: per-position logits/probs over the vocab,
    consistent with a local forward of the same params (sequence-level
    KD contract)."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.distill.teacher_server import gpt_teacher
    from edl_tpu.models import gpt as gpt_mod

    server = gpt_teacher(vocab_size=32, seq_len=8, max_batch=4,
                         host="127.0.0.1").start()
    try:
        from edl_tpu.distill.distill_reader import _TeacherConn
        conn = _TeacherConn(server.endpoint)
        ids = np.arange(16, dtype=np.int32).reshape(2, 8) % 32
        out = conn.predict({"input_ids": ids})
        assert out["logits"].shape == (2, 8, 32)
        assert out["probs"].shape == (2, 8, 32)
        np.testing.assert_allclose(out["probs"].sum(-1),
                                   np.ones((2, 8)), rtol=1e-3)
        # matches a local forward of the same (seed-0) teacher params
        model = gpt_mod.Gpt(num_layers=2, d_model=64, num_heads=4,
                            mlp_dim=128, vocab_size=32, max_len=16,
                            dtype=jnp.bfloat16)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        want = np.asarray(model.apply({"params": params},
                                      jnp.asarray(ids)))
        # bf16 jit-vs-eager reassociation noise bounds the tolerance
        np.testing.assert_allclose(out["logits"], want, atol=5e-2)
        conn.close()
    finally:
        server.stop()


def test_registry_and_discovery(coord):
    teacher = nop_teacher({"logits": ([4], "<f4")}, max_batch=4,
                          host="127.0.0.1").start()
    reg = TeacherRegister(coord, "svc_a", teacher.endpoint, ttl=2).start()
    disc = DiscoveryServer(coord, host="127.0.0.1").start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if list_teachers(coord, "svc_a"):
                break
            time.sleep(0.2)
        client = DiscoveryClient(disc.endpoint, "svc_a",
                                 require_num=1).start()
        servers = client.wait_for_servers(timeout=20)
        assert servers == [teacher.endpoint]
        # teacher dies → TTL expiry → discovery pushes the removal
        teacher.stop()
        reg.stop()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if not client.get_servers():
                break
            time.sleep(0.3)
        assert client.get_servers() == []
        client.stop()
    finally:
        disc.stop()


def test_multi_discovery_sharding_and_redirect(coord):
    """Three discovery servers shard service names over the hash ring;
    clients landing on a non-owner follow REDIRECTs to the owner, and all
    clients of one service agree on the same teacher set."""
    teachers = [nop_teacher({"logits": ([2], "<f4")}, max_batch=4,
                            host="127.0.0.1").start() for _ in range(2)]
    regs = [TeacherRegister(coord, "svc_m", t.endpoint, ttl=5).start()
            for t in teachers]
    servers = [DiscoveryServer(coord, host="127.0.0.1").start()
               for _ in range(3)]
    clients = []
    try:
        # wait until every discovery server sees all three peers
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if all(len(s._hash.nodes()) == 3 for s in servers):
                break
            time.sleep(0.2)
        assert all(len(s._hash.nodes()) == 3 for s in servers)

        # clients register against EVERY server; non-owners must redirect
        for entry in servers:
            c = DiscoveryClient(entry.endpoint, "svc_m",
                                require_num=2).start()
            clients.append(c)
        views = [set(c.wait_for_servers(timeout=30)) for c in clients]
        want = {t.endpoint for t in teachers}
        assert all(v <= want and v for v in views), views
        # exactly one discovery server owns the service
        owners = {s._owner("svc_m") for s in servers}
        assert len(owners) == 1
        owner_ep = owners.pop()
        stats = [s.stats() for s in servers]
        with_clients = [st for st in stats if st.get("svc_m", {})
                        .get("clients")]
        assert len(with_clients) == 1  # only the owner holds the table
        owner_idx = stats.index(with_clients[0])
        assert servers[owner_idx].endpoint == owner_ep
    finally:
        for c in clients:
            c.stop()
        for s in servers:
            s.stop()
        for r in regs:
            r.stop()
        for t in teachers:
            t.stop()


def _echo_teacher(scale, port=0):
    def fn(feed):
        return {"soft_label": feed["img"] * scale}
    return TeacherServer(fn, {"img": ([2], "<f4")},
                         {"soft_label": ([2], "<f4")},
                         max_batch=16, host="127.0.0.1", port=port).start()


def test_distill_reader_fixed_teacher_ordering():
    teacher = _echo_teacher(2.0)

    def gen():
        for i in range(20):
            img = np.full((4, 2), i, np.float32)
            label = np.full((4, 1), i, np.int64)
            yield img, label

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=4)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([teacher.endpoint])
    try:
        seen = []
        for img, label, soft in dr():
            np.testing.assert_allclose(soft, img * 2.0)
            seen.append(int(img[0, 0]))
        assert seen == list(range(20))  # original order preserved
        # second epoch works on the same reader
        assert sum(1 for _ in dr()) == 20
    finally:
        dr.stop()
        teacher.stop()


def test_distill_reader_sample_list_and_teacher_failure():
    t1 = _echo_teacher(3.0)
    t2 = _echo_teacher(3.0)

    def gen():
        for i in range(30):
            yield [(np.full(2, i + j, np.float32),) for j in range(3)]

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=4, teacher_backoff=60)
    dr.set_sample_list_generator(gen)
    dr.set_fixed_teacher([t1.endpoint, t2.endpoint])

    killed = threading.Event()
    out_batches = []
    try:
        for i, samples in enumerate(dr()):
            out_batches.append(samples)
            for img, soft in samples:
                np.testing.assert_allclose(soft, img * 3.0)
            if i == 5 and not killed.is_set():
                t1.stop()  # kill a teacher mid-epoch; tasks must be retried
                killed.set()
        assert len(out_batches) == 30  # nothing lost despite the failure
    finally:
        dr.stop()
        t2.stop()


def test_distill_reader_abandoned_epoch_is_fenced():
    """Breaking out of an epoch mid-iteration must not leak stale batches
    into the next epoch (epoch generation token)."""
    teacher = _echo_teacher(1.0)

    def gen():
        for i in range(20):
            yield (np.full((2, 2), i, np.float32),)

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=4)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([teacher.endpoint])
    try:
        for i, (img, soft) in enumerate(dr()):
            if i == 2:
                break  # abandon the epoch with tasks still in flight
        time.sleep(0.3)
        seen = [int(img[0, 0]) for img, _ in dr()]
        assert seen == list(range(20))  # fresh epoch, correct order
    finally:
        dr.stop()
        teacher.stop()


def test_distill_reader_sample_generator_batching():
    teacher = _echo_teacher(1.0)

    def gen():
        for i in range(10):
            yield (np.full(2, i, np.float32),)

    dr = DistillReader(ins=["img"], predicts=["soft_label"])
    dr.set_sample_generator(gen, batch_size=4)
    dr.set_fixed_teacher([teacher.endpoint])
    try:
        sizes = [len(s) for s in dr()]
        assert sizes == [4, 4, 2]
    finally:
        dr.stop()
        teacher.stop()


def test_resnext_teacher_serves_soft_labels():
    """The ResNeXt teacher config (the reference's distill teacher family,
    BASELINE.md): grouped-conv model behind the teacher RPC, soft labels
    sum to 1."""
    from edl_tpu.distill.distill_reader import _TeacherConn
    from edl_tpu.distill.teacher_server import resnet_teacher

    server = resnet_teacher(depth=50, num_classes=16, image_size=32,
                            max_batch=4, host="127.0.0.1", groups=4,
                            base_width=16, vd=False).start()
    try:
        conn = _TeacherConn(server.endpoint)
        out = conn.predict(
            {"image": np.zeros((2, 32, 32, 3), np.float32)})
        assert out["logits"].shape == (2, 16)
        np.testing.assert_allclose(out["probs"].sum(-1), np.ones(2),
                                   rtol=1e-3)
        conn.close()
    finally:
        server.stop()


def test_distill_reader_feeder_exception_reraised():
    """A generator that raises mid-epoch must surface to the consumer,
    not masquerade as a clean (truncated) epoch."""
    teacher = _echo_teacher(2.0)

    def gen():
        for i in range(5):
            yield np.full((2, 2), i, np.float32),
        raise RuntimeError("source storage went away")

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       max_in_flight=4)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher([teacher.endpoint])
    try:
        seen = 0
        with pytest.raises(RuntimeError, match="source storage"):
            for batch in dr():
                seen += 1
        assert seen == 5  # everything fed before the failure is delivered
    finally:
        dr.stop()
        teacher.stop()


def test_teacher_conn_empty_feed_typed_error():
    """Empty feeds fail client-side with a typed DataAccessError before
    any RPC (used to IndexError joining zero chunks)."""
    from edl_tpu.distill.distill_reader import _TeacherConn
    from edl_tpu.utils import errors

    teacher = _echo_teacher(2.0)
    try:
        conn = _TeacherConn(teacher.endpoint)
        with pytest.raises(errors.DataAccessError):
            conn.predict({})
        with pytest.raises(errors.DataAccessError):
            conn.predict({"img": np.zeros((0, 2), np.float32)})
        conn.close()
    finally:
        teacher.stop()


def test_teacher_conn_pipelines_oversized_batch():
    """A feed bigger than max_batch is split into chunks that are all
    in flight together; the join preserves row order."""
    from edl_tpu.distill.distill_reader import _TeacherConn

    teacher = _echo_teacher(2.0)  # max_batch=16
    try:
        conn = _TeacherConn(teacher.endpoint)
        assert conn.pipelined
        x = np.arange(40 * 2, dtype=np.float32).reshape(40, 2)
        out = conn.predict({"img": x})
        np.testing.assert_allclose(out["soft_label"], x * 2.0)
        conn.close()
    finally:
        teacher.stop()


def test_distill_reader_with_pre_pipelining_teacher():
    """A teacher that advertises no features negotiates down to
    lockstep depth 1 and still serves a full epoch."""
    from edl_tpu.rpc.server import RpcServer

    srv = RpcServer(host="127.0.0.1", port=0, workers=0)
    srv.register("get_feed_fetch",
                 lambda: {"feed": {"img": ([2], "<f4")},
                          "fetch": {"soft_label": ([2], "<f4")},
                          "max_batch": 16})  # no "features" key
    srv.register("predict",
                 lambda feed: {"soft_label":
                               np.asarray(feed["img"]) * 4.0})
    srv.start()

    def gen():
        for i in range(8):
            yield np.full((3, 2), i, np.float32),

    dr = DistillReader(ins=["img"], predicts=["soft_label"],
                       pipeline_depth=4)
    dr.set_batch_generator(gen)
    dr.set_fixed_teacher(["127.0.0.1:%d" % srv.port])
    try:
        n = 0
        for img, soft in dr():
            np.testing.assert_allclose(soft, img * 4.0)
            n += 1
        assert n == 8
    finally:
        dr.stop()
        srv.stop()
