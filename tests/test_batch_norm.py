"""SubsetBatchNorm: equivalence with flax BatchNorm at stats_every=1,
exact strided-subset statistics, and checkpoint compatibility of the
ResNet bn_stats_every flag."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.batch_norm import SubsetBatchNorm


def _random_x(shape=(16, 6, 6, 8), seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)


def test_full_batch_matches_flax_batchnorm():
    import flax.linen as nn

    x = _random_x()
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                       epsilon=1e-5, param_dtype=jnp.float32)
    sub = SubsetBatchNorm(use_running_average=False, stats_every=1)
    vref = ref.init(jax.random.PRNGKey(1), x)
    vsub = sub.init(jax.random.PRNGKey(1), x)
    # identical variable structure (checkpoint compatibility)
    assert jax.tree_util.tree_structure(vref) == \
        jax.tree_util.tree_structure(vsub)
    yref, mref = ref.apply(vref, x, mutable=["batch_stats"])
    ysub, msub = sub.apply(vsub, x, mutable=["batch_stats"])
    np.testing.assert_allclose(ysub, yref, atol=1e-5)
    for k in ("mean", "var"):
        np.testing.assert_allclose(msub["batch_stats"][k],
                                   mref["batch_stats"][k], atol=1e-5)


def test_strided_subset_statistics_exact():
    x = _random_x((16, 4, 4, 3), seed=2)
    bn = SubsetBatchNorm(use_running_average=False, stats_every=4,
                         momentum=0.5)
    v = bn.init(jax.random.PRNGKey(0), x)
    y, mut = bn.apply(v, x, mutable=["batch_stats"])
    s = np.asarray(x)[::4]
    mean = s.mean((0, 1, 2))
    var = (s * s).mean((0, 1, 2)) - mean * mean
    inv = 1.0 / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(y, (np.asarray(x) - mean) * inv,
                               atol=1e-4)
    # running stats blend toward the SUBSET statistics
    np.testing.assert_allclose(mut["batch_stats"]["mean"], 0.5 * mean,
                               atol=1e-5)
    np.testing.assert_allclose(mut["batch_stats"]["var"],
                               0.5 * 1.0 + 0.5 * var, atol=1e-5)


def test_inference_uses_running_stats_and_grads_flow():
    x = _random_x((8, 2, 2, 4), seed=3)
    bn = SubsetBatchNorm(use_running_average=True)
    v = bn.init(jax.random.PRNGKey(0), x)
    v = jax.tree_util.tree_map(lambda a: a, v)
    y = bn.apply(v, x)
    # init stats are mean 0 var 1 => identity up to epsilon
    np.testing.assert_allclose(y, x / np.sqrt(1 + 1e-5), atol=1e-5)

    train_bn = SubsetBatchNorm(use_running_average=False, stats_every=2)

    def loss(params):
        out, _ = train_bn.apply(
            {"params": params, "batch_stats": v["batch_stats"]}, x,
            mutable=["batch_stats"])
        return ((out - 1.0) ** 2).mean()

    g = jax.grad(loss)(v["params"])
    assert float(jnp.abs(g["scale"]).sum()) > 0
    # d/db mean((out-1)^2) = 2*mean(out-1) ~= -2 per channel: nonzero
    assert float(jnp.abs(g["bias"]).sum()) > 0


def test_resnet_bn_stats_every_checkpoint_compatible_and_trains():
    import optax

    from edl_tpu.models import resnet
    from edl_tpu.runtime.trainer import make_train_state, make_train_step

    kw = dict(depth=18, num_classes=10, vd=True, image_size=32,
              dtype=jnp.float32)
    _, p1, e1, _ = resnet.create_model_and_loss(**kw)
    _, p4, e4, loss4 = resnet.create_model_and_loss(bn_stats_every=4, **kw)
    assert (jax.tree_util.tree_structure(p1)
            == jax.tree_util.tree_structure(p4))
    assert (jax.tree_util.tree_structure(e1)
            == jax.tree_util.tree_structure(e4))

    # batch 16 & stats_every=4: 4-image statistics — noisy, so a gentle
    # lr (the subset statistics are a throughput knob for LARGE batches;
    # tiny-batch configs should keep stats_every=1)
    tx = optax.sgd(0.01)
    state = make_train_state(p4, tx, e4)
    step = jax.jit(make_train_step(loss4, tx, has_aux=True))
    batch = {
        "image": np.random.RandomState(0)
                   .randn(16, 32, 32, 3).astype(np.float32),
        "label": np.arange(16, dtype=np.int32) % 10,
    }
    losses = []
    for i in range(5):
        state, loss = step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # memorizing 8 images must make progress


@pytest.mark.parametrize("stats_every", [1, 4])
def test_sharded_batch_matches_single_device(stats_every):
    """The strided subset must give identical results under a dp-sharded
    jit (global-view strided slice; per-shard reads when divisible)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    x = _random_x((16, 4, 4, 8), seed=5)
    bn = SubsetBatchNorm(use_running_average=False,
                         stats_every=stats_every)
    v = bn.init(jax.random.PRNGKey(0), x)
    y_ref, _ = bn.apply(v, x, mutable=["batch_stats"])

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    vs = jax.device_put(v, NamedSharding(mesh, P()))
    y_sh, _ = jax.jit(
        lambda v_, x_: bn.apply(v_, x_, mutable=["batch_stats"]))(vs, xs)
    np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                               atol=1e-5)
