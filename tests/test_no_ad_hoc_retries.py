"""Tier-1 wiring for tools/check_no_ad_hoc_retries.py: a NEW raw
``time.sleep`` retry loop in a control-plane module fails the build —
edl_tpu.robustness.policy (RetryPolicy/Deadline) is the sanctioned way
to wait for anything that can fail."""

import ast
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "check_no_ad_hoc_retries.py")


def test_no_new_ad_hoc_retry_loops():
    out = subprocess.run([sys.executable, TOOL], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def test_lint_actually_detects_retry_loops():
    """The lint must not be a rubber stamp: it flags a synthetic
    hand-rolled retry loop in both spelling variants."""
    sys.path.insert(0, os.path.dirname(TOOL))
    try:
        import check_no_ad_hoc_retries as lint
    finally:
        sys.path.pop(0)
    f = lint._Finder("x.py")
    f.visit(ast.parse(
        "import time\ndef f():\n    while True:\n        time.sleep(1)\n"))
    assert f.hits == [("x.py", "f", 4)]
    g = lint._Finder("y.py")
    g.visit(ast.parse(
        "from time import sleep as zz\nfor i in range(3):\n    zz(1)\n"))
    assert g.hits == [("y.py", "<module>", 3)]
