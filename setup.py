from setuptools import find_packages, setup

setup(
    name="edl_tpu",
    version="0.1.0",
    description=("TPU-native elastic deep learning: elastic collective "
                 "training and a distillation service plane on JAX/XLA"),
    packages=find_packages(include=["edl_tpu", "edl_tpu.*"]),
    python_requires=">=3.10",
    install_requires=[
        "jax", "flax", "optax", "numpy", "msgpack", "psutil",
    ],
    entry_points={
        "console_scripts": [
            # reference parity: `edlrun` (setup.py.in:85)
            "edl-tpu-run=edl_tpu.controller.launch:main",
            "edl-tpu-store=edl_tpu.coordination.server:main",
            "edl-tpu-store-standby=edl_tpu.coordination.standby:main",
            "edl-tpu-teacher=edl_tpu.distill.teacher_server:main",
            "edl-tpu-discovery=edl_tpu.distill.discovery_server:main",
            "edl-tpu-register=edl_tpu.distill.registry:main",
            "edl-tpu-measure-distill=edl_tpu.tools.measure_distill:main",
            "edl-tpu-measure-resize=edl_tpu.tools.measure_resize:main",
            "edl-tpu-job-stats=edl_tpu.tools.job_stats:main",
            "edl-tpu-resize-driver=edl_tpu.tools.resize_driver:main",
            "edl-tpu-liveft=edl_tpu.liveft.launch:main",
            "edl-tpu-store-witness=edl_tpu.coordination.standby:witness_main",
            "edl-tpu-fake-gcs=edl_tpu.tools.fake_gcs:main",
            "edl-tpu-k8s-operator=edl_tpu.tools.k8s_operator:main",
        ],
    },
)
