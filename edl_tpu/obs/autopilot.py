"""Goodput autopilot: the policy engine that closes the observe→act loop.

PRs 7–10 made the fleet observable — straggler verdicts, SLO burn
rates, per-state time ledgers, resize-pause benchmarks, crash
blackboxes — but every actuator except the advisory scale-in victim
ranking was a human reading ``job_doctor``. This module turns verdicts
into bounded, auditable ACTIONS. :class:`Autopilot` is leader-hosted
(it runs on the :class:`~edl_tpu.obs.health.HealthMonitor` tick via the
monitor's ``on_report`` hook, so it acts exactly when and where the
verdicts are produced) and maps each fresh ``health_report/v1`` to at
most a handful of journaled actions:

- ``evict`` — a confirmed straggler (top ``preferred_victims`` entry
  for ``evict_streak`` CONSECUTIVE reports) is evicted through the
  cluster generator's directed-eviction actuator; the generator's
  ordinary scale-out then backfills from standby (surplus PENDING
  launchers re-barriering to be scaled in). Promotes the PR 8 victim
  ranking from advisory to acted-upon.
- ``resize`` — trigger/veto gate for scale-out: the projected resize
  pause (median ``recovery_s`` over the per-pod resize histories the
  launchers journal under ``SERVICE_METRICS``) must be repaid by
  marginal goodput (from the report's embedded ``goodput/v1`` fleet
  section) within ``payback_horizon_s`` — see
  :func:`edl_tpu.obs.ledger.resize_payback_s`. The decision feeds the
  generator's ``scale_out_gate``; only decision CHANGES are journaled.
- ``tune_knobs`` — when ``data_wait`` dominates the fleet ledger
  (top-ranked badput state above ``data_wait_share_pct``), the data
  plane's ``fetch_ahead`` is doubled (bounded) through the injected
  knob actuator (the launcher broadcasts ``set_knobs`` to every
  reader's data-plane server).
- ``postmortem`` — a crash loop (>= ``crash_loop_boxes`` recent
  ``blackbox/v1`` artifacts inside ``crash_window_s``) auto-files a
  postmortem bundle (box summaries + the doctor's evidence chain)
  under ``SERVICE_AUTOPILOT`` so the forensics are captured while the
  boxes are still fresh.

Safety model — the engine must provably never flap:

- Every action is an ``action/v1`` record appended to the bounded
  store journal under ``SERVICE_AUTOPILOT``/``JOURNAL_KEY`` with a
  cause chain (``report_ts`` → detector/finding summary → causal
  ``evidence_ids`` from the health report) linking the action back to
  the evidence that triggered it.
- Per-action-kind rate limits: a cooldown between actions of the same
  kind AND a burst bound (at most ``burst`` per ``burst_window_s``).
- Hysteresis: an evict needs ``evict_streak`` consecutive confirming
  reports, and an evicted pod cannot be re-evicted for
  ``reevict_block_s`` — so evict→backfill→re-flag cannot oscillate.
- Global dry-run: ``EDL_TPU_AUTOPILOT=dry`` journals the identical
  action stream while applying NOTHING (actuators never called, the
  scale-out gate always allows); ``off`` (the default) disables the
  engine entirely; ``on`` applies.
- The apply step fires the ``autopilot.apply`` chaos point BEFORE the
  actuator and runs under the standard
  :class:`~edl_tpu.robustness.policy.RetryPolicy` — a failed apply is
  journaled ``outcome: failed`` and, because the fault point precedes
  the actuator inside the retried callable, a retried apply can never
  double-apply.
- All actions hold while a store-failover settle window is open
  (``hold_fn``, wired to ``coordination.standby.failover_guard_active``
  by the launcher): a failover's mass re-registration must not read as
  a fleet-wide health event.

This package is a LEAF — ``SERVICE_AUTOPILOT`` is inlined here (value
of ``controller.constants.SERVICE_AUTOPILOT``, drift-guarded by a
test), the coordination client and every actuator are injected, and
the robustness imports are lazy (robustness imports obs).
"""

import json
import os
import threading
import time
from collections import deque

from edl_tpu.obs import flight as flight_mod
from edl_tpu.obs import ledger as ledger_mod
from edl_tpu.obs import publisher as publisher_mod
from edl_tpu.utils.logger import logger

#: value of controller.constants.SERVICE_AUTOPILOT, inlined so obs
#: stays a leaf package (guarded by a test against drift)
SERVICE_AUTOPILOT = "autopilot"

#: the single bounded action journal under SERVICE_AUTOPILOT
#: (leader-written, last-writer-wins — there is at most one autopilot,
#: hosted next to the one elected HealthMonitor)
JOURNAL_KEY = "journal"

#: filed postmortem bundles: ``postmortem_<seq>`` under SERVICE_AUTOPILOT
POSTMORTEM_PREFIX = "postmortem_"

ENV_VAR = "EDL_TPU_AUTOPILOT"
MODE_OFF = "off"
MODE_DRY = "dry"
MODE_ON = "on"

ACTION_KINDS = ("evict", "resize", "tune_knobs", "postmortem")


def mode_from_env(value=None):
    """Resolve the global mode from ``EDL_TPU_AUTOPILOT`` (or an
    explicit ``value``): ``on`` applies, ``dry`` journals without
    applying, anything else is ``off`` (the default — the engine adds
    zero behavior unless deliberately enabled)."""
    raw = (os.environ.get(ENV_VAR, MODE_OFF)
           if value is None else value)
    raw = str(raw).strip().lower()
    if raw in (MODE_ON, "1", "true", "enabled"):
        return MODE_ON
    if raw in (MODE_DRY, "dry_run", "dryrun"):
        return MODE_DRY
    return MODE_OFF


class Autopilot(object):
    """The leader-hosted policy engine (see module docstring).

    ``on_report(report)`` is the whole runtime surface: the
    HealthMonitor calls it after each published tick, the policies run
    synchronously (they are dict folds over the report — the
    ``autopilot`` arc of ``obs_bench`` measures the tick cost against
    the <2%-of-interval criterion), and every decision lands in the
    store journal. There is no thread of its own and no store polling
    loop: no leader, no monitor tick, no actions.

    Actuators (all injected, all optional — a policy without its
    actuator journals ``outcome: failed`` rather than silently doing
    nothing):

    - ``evict_fn(pod_id)`` — the generator's ``direct_evict``.
    - ``knobs_fn(knobs_dict)`` — the launcher's ``set_knobs``
      broadcast; returns ``{pod: applied}``.
    - ``hold_fn()`` — True while actions must hold (failover settle).
    """

    def __init__(self, coord, pod_id, mode=None, interval=10.0,
                 evict_fn=None, knobs_fn=None, hold_fn=None,
                 evict_streak=2, reevict_block_s=None,
                 payback_horizon_s=600.0, data_wait_share_pct=30.0,
                 fetch_ahead_base=2, fetch_ahead_max=16,
                 crash_loop_boxes=2, crash_window_s=600.0,
                 cooldowns=None, burst=3, burst_window_s=None,
                 journal_cap=64, retry=None, clock=time.time):
        self._coord = coord
        self._pod_id = pod_id
        self._mode = mode_from_env(mode)
        self._interval = float(interval)
        self._evict_fn = evict_fn
        self._knobs_fn = knobs_fn
        self._hold_fn = hold_fn
        self._clock = clock
        # hysteresis / rate-limit knobs (defaults scale with the
        # monitor interval so one tick can never fire twice)
        self._evict_streak = max(1, int(evict_streak))
        self._reevict_block_s = (float(reevict_block_s)
                                 if reevict_block_s is not None
                                 else 30.0 * self._interval)
        self._payback_horizon_s = float(payback_horizon_s)
        self._data_wait_share_pct = float(data_wait_share_pct)
        self._fetch_ahead_target = max(1, int(fetch_ahead_base))
        self._fetch_ahead_max = max(1, int(fetch_ahead_max))
        self._crash_loop_boxes = max(1, int(crash_loop_boxes))
        self._crash_window_s = float(crash_window_s)
        self._cooldowns = {
            "evict": 6.0 * self._interval,
            "resize": 3.0 * self._interval,
            "tune_knobs": 12.0 * self._interval,
            "postmortem": 30.0 * self._interval,
        }
        self._cooldowns.update(cooldowns or {})
        self._burst = max(1, int(burst))
        self._burst_window_s = (float(burst_window_s)
                                if burst_window_s is not None
                                else 60.0 * self._interval)
        self._journal_cap = max(1, int(journal_cap))
        if retry is None:
            # lazy: robustness imports obs, so obs must not import it
            # at module scope (same idiom as flight.py's fault hook)
            from edl_tpu.robustness.policy import RetryPolicy
            retry = RetryPolicy(max_attempts=3, base_delay=0.05,
                                max_delay=0.5, jitter=0.0)
        self._retry = retry

        self._lock = threading.Lock()
        self._seq = None  # lazily anchored on the stored journal
        self._actions = []  # in-memory mirror of this engine's records
        self._last_action_ts = {}   # kind -> ts of last journaled action
        self._recent = {k: deque() for k in ACTION_KINDS}
        # evict hysteresis state
        self._streak_pod = None
        self._streak_n = 0
        self._no_reevict_until = {}  # pod -> ts
        # resize gate state: None until first decision; True = allow
        self._scale_out_ok = None
        self._last_resize_decision = None
        # postmortem dedup: signature of the last filed crash loop
        self._filed_signature = None

    # -- public surface ----------------------------------------------------

    @property
    def mode(self):
        return self._mode

    def actions(self):
        """Records journaled by THIS engine instance (in order)."""
        with self._lock:
            return list(self._actions)

    def scale_out_allowed(self):
        """The generator's ``scale_out_gate``: False only when the
        engine is ``on`` AND the payback model currently vetoes growth.
        Dry-run and off apply nothing; any error fails open."""
        if self._mode != MODE_ON:
            return True
        with self._lock:
            return self._scale_out_ok is not False

    def on_report(self, report):
        """One policy pass over a fresh ``health_report/v1``; returns
        the ``action/v1`` records journaled this tick. Never raises —
        the monitor tick must survive any policy bug."""
        if self._mode == MODE_OFF or not isinstance(report, dict):
            return []
        now = self._clock()
        if self._held():
            logger.info("autopilot: failover settle window open; "
                        "holding all actions")
            return []
        out = []
        for policy in (self._policy_evict, self._policy_resize,
                       self._policy_knobs, self._policy_postmortem):
            try:
                out.extend(policy(report, now))
            except Exception:  # noqa: BLE001 — one policy must not
                logger.exception("autopilot policy %s failed",
                                 policy.__name__)  # kill the others
        return out

    # -- guards ------------------------------------------------------------

    def _held(self):
        if self._hold_fn is None:
            return False
        try:
            return bool(self._hold_fn())
        except Exception:  # noqa: BLE001 — a hold probe failure must
            return False   # not freeze the engine forever: fail open

    def _gate_ok(self, kind, now):
        """Per-kind rate limit: cooldown since the last action of this
        kind AND at most ``burst`` actions per ``burst_window_s``."""
        last = self._last_action_ts.get(kind)
        if last is not None and now - last < self._cooldowns.get(kind,
                                                                 0.0):
            return False
        ring = self._recent[kind]
        while ring and now - ring[0] > self._burst_window_s:
            ring.popleft()
        return len(ring) < self._burst

    def _gate_record(self, kind, now):
        self._last_action_ts[kind] = now
        self._recent[kind].append(now)

    # -- the apply step ----------------------------------------------------

    def _apply(self, kind, actuator, *args):
        """Apply one action through its actuator. Returns
        ``(outcome, attempts, error, result)``. The ``autopilot.apply``
        chaos point fires INSIDE the retried callable, BEFORE the
        actuator — an injected failure therefore aborts the attempt
        with the actuator untouched, and a retry that then succeeds
        has applied exactly once (the never-double-applied contract).
        Dry-run short-circuits: nothing fires, nothing applies."""
        if self._mode == MODE_DRY:
            return "dry_run", 0, None, None
        if actuator is None:
            return "failed", 0, "no actuator bound for %r" % kind, None
        from edl_tpu.robustness import faults
        attempts = [0]

        def once():
            attempts[0] += 1
            if faults.PLANE is not None:
                # ctx key is ``action`` (not ``kind``): inject()'s own
                # ``kind`` parameter would shadow the filter otherwise
                faults.PLANE.fire("autopilot.apply", action=kind,
                                  pod=self._pod_id)
            return actuator(*args)

        try:
            result = self._retry.call(once)
            return "applied", attempts[0], None, result
        except Exception as e:  # noqa: BLE001 — journaled, not raised
            return "failed", attempts[0], repr(e), None

    # -- journaling --------------------------------------------------------

    def _next_seq(self):
        # caller holds self._lock; anchor once on the stored journal so
        # a re-elected leader's engine continues the sequence
        if self._seq is None:
            self._seq = 0
            try:
                for a in load_actions(self._coord):
                    self._seq = max(self._seq, int(a.get("seq", 0)))
            except Exception:  # noqa: BLE001 — fresh store: start at 0
                pass
        self._seq += 1
        return self._seq

    def _record(self, kind, target, reason, cause, outcome, attempts,
                error, result, now, extra=None):
        with self._lock:
            seq = self._next_seq()
            action = {
                "schema": "action/v1",
                "id": "act-%d" % seq,
                "seq": seq,
                "ts": now,
                "kind": kind,
                "mode": ("dry_run" if self._mode == MODE_DRY
                         else "applied"),
                "actor": self._pod_id,
                "target": target,
                "reason": reason,
                "cause": cause,
                "outcome": outcome,
                "attempts": attempts,
                "error": error,
                "result": result,
            }
            if extra:
                action.update(extra)
            self._actions.append(action)
            self._gate_record(kind, now)
        try:
            raw = self._coord.get_value(SERVICE_AUTOPILOT, JOURNAL_KEY) \
                or "[]"
            journal = json.loads(raw)
            if not isinstance(journal, list):
                journal = []
        except Exception:  # noqa: BLE001 — corrupt/absent: restart it
            journal = []
        journal = journal[-(self._journal_cap - 1):]
        journal.append(action)
        try:
            self._coord.set_server_permanent(SERVICE_AUTOPILOT,
                                             JOURNAL_KEY,
                                             json.dumps(journal))
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            logger.debug("autopilot journal write failed: %r", e)
        logger.warning("autopilot %s: %s %s -> %s%s", self._mode, kind,
                       target, outcome,
                       (" (%s)" % error) if error else "")
        return action

    @staticmethod
    def _cause_from_finding(report, finding):
        cause = {"report_ts": report.get("ts"),
                 "detector": None, "summary": None, "evidence_ids": []}
        if finding:
            cause["detector"] = finding.get("detector")
            cause["summary"] = finding.get("summary")
            cause["evidence_ids"] = [i for i in
                                     (finding.get("event_ids") or ())
                                     if i is not None]
        return cause

    # -- policies ----------------------------------------------------------

    def _policy_evict(self, report, now):
        victims = list(report.get("preferred_victims") or ())
        if not victims:
            self._streak_pod, self._streak_n = None, 0
            return []
        top = victims[0]
        if top == self._pod_id:  # never self-decapitate (belt and
            return []            # braces; the monitor excludes itself)
        if top == self._streak_pod:
            self._streak_n += 1
        else:
            self._streak_pod, self._streak_n = top, 1
        if self._streak_n < self._evict_streak:
            return []
        if now < self._no_reevict_until.get(top, 0.0):
            return []
        if not self._gate_ok("evict", now):
            return []
        finding = next(
            (f for f in report.get("findings") or ()
             if f.get("pod") == top and f.get("severity") == "critical"),
            None)
        cause = self._cause_from_finding(report, finding)
        cause["streak"] = self._streak_n
        outcome, attempts, error, result = self._apply(
            "evict", self._evict_fn, top)
        # the block applies in EVERY mode and on failure too: dry-run
        # must journal the identical stream (one action per episode),
        # and a failing actuator must not hot-loop the same victim
        self._no_reevict_until[top] = now + self._reevict_block_s
        reason = ("confirmed straggler for %d consecutive reports; "
                  "evicting and backfilling from standby"
                  % self._streak_n)
        return [self._record("evict", top, reason, cause, outcome,
                             attempts, error, result, now)]

    def _projected_pause_s(self):
        """Median ``recovery_s`` over the per-pod resize histories the
        launchers journal under SERVICE_METRICS — the store-runtime
        analogue of the ``resize_bench/v1`` pause numbers. None with no
        history (the payback model then fails open)."""
        samples = []
        try:
            pairs = self._coord.get_service(
                publisher_mod.SERVICE_METRICS)
        except Exception:  # noqa: BLE001 — no store view: no estimate
            return None
        for key, raw in pairs:
            if key.startswith(publisher_mod.KEY_PREFIX):
                continue  # obs_pub docs, not resize histories
            try:
                history = json.loads(raw)
            except (TypeError, ValueError):
                continue
            if not isinstance(history, list):
                continue
            for entry in history[-20:]:
                if isinstance(entry, dict) and "recovery_s" in entry:
                    try:
                        samples.append(float(entry["recovery_s"]))
                    except (TypeError, ValueError):
                        pass
        if not samples:
            return None
        samples.sort()
        return samples[len(samples) // 2]

    def _policy_resize(self, report, now):
        """Trigger/veto gate for scale-out, journaled on decision
        CHANGE only (the gate itself is consulted every generator
        pass). Fail-open: without a pause projection or a goodput
        fraction there is no model, so growth stays allowed."""
        goodput = report.get("goodput") or {}
        gp_pct = goodput.get("goodput_pct")
        world = (report.get("fleet") or {}).get("pods_total") or 0
        pause = self._projected_pause_s()
        if pause is None or gp_pct is None or world <= 0:
            allow, why, payback = True, "no pause/goodput history " \
                "(fail open)", None
        else:
            payback = ledger_mod.resize_payback_s(
                pause, world, world + 1, gp_pct / 100.0)
            allow = payback <= self._payback_horizon_s
            why = ("projected pause %.2fs at world %d->%d, goodput "
                   "%.1f%%: payback %.0fs vs horizon %.0fs"
                   % (pause, world, world + 1, gp_pct,
                      payback, self._payback_horizon_s))
        prev = self._last_resize_decision
        self._last_resize_decision = allow
        if prev is None:
            # the initial state is not a decision change; the gate
            # simply starts in the computed position
            with self._lock:
                self._scale_out_ok = allow
            return []
        if allow == prev:
            return []
        if not self._gate_ok("resize", now):
            # rate-limited: keep the PREVIOUS gate position — a
            # decision the journal cannot record must not act either
            self._last_resize_decision = prev
            return []
        cause = {"report_ts": report.get("ts"), "detector": "goodput",
                 "summary": why, "evidence_ids": [],
                 "payback_s": (round(payback, 1)
                               if payback not in (None, float("inf"))
                               else None)}

        def flip():
            with self._lock:
                self._scale_out_ok = allow
            return {"scale_out_allowed": allow}

        outcome, attempts, error, result = self._apply("resize", flip)
        verb = "trigger" if allow else "veto"
        return [self._record(
            "resize", "cluster",
            "%s scale-out: %s" % (verb, why), cause, outcome,
            attempts, error, result, now,
            extra={"decision": "allow" if allow else "veto"})]

    def _policy_knobs(self, report, now):
        goodput = report.get("goodput") or {}
        badput = goodput.get("badput") or []
        if not badput or badput[0].get("state") != "data_wait":
            return []
        share = badput[0].get("share_pct") or 0.0
        if share < self._data_wait_share_pct:
            return []
        if self._fetch_ahead_target >= self._fetch_ahead_max:
            return []  # already at the ceiling: nothing left to tune
        if not self._gate_ok("tune_knobs", now):
            return []
        target = min(self._fetch_ahead_max,
                     self._fetch_ahead_target * 2)
        knobs = {"fetch_ahead": target}
        cause = {"report_ts": report.get("ts"), "detector": "goodput",
                 "summary": "data_wait is %.1f%% of fleet badput "
                            "(threshold %.1f%%)"
                            % (share, self._data_wait_share_pct),
                 "evidence_ids": []}
        outcome, attempts, error, result = self._apply(
            "tune_knobs", self._knobs_fn, knobs)
        if outcome in ("applied", "dry_run"):
            # advance in dry-run too: the journaled stream (each action
            # doubling from the last target) must match the on-mode one
            self._fetch_ahead_target = target
        reason = ("data_wait dominates the fleet ledger (%.1f%%); "
                  "raising fetch_ahead to %d" % (share, target))
        return [self._record("tune_knobs", "data_plane", reason, cause,
                             outcome, attempts, error, result, now,
                             extra={"knobs": knobs})]

    def _policy_postmortem(self, report, now):
        boxes = flight_mod.load_blackboxes(self._coord)
        recent = {k: b for k, b in boxes.items()
                  if isinstance(b, dict)
                  and now - (b.get("ts") or 0.0) <= self._crash_window_s}
        if len(recent) < self._crash_loop_boxes:
            return []
        signature = tuple(sorted(
            (k, round(b.get("ts") or 0.0, 1)) for k, b in recent.items()))
        if signature == self._filed_signature:
            return []  # this crash loop is already filed
        if not self._gate_ok("postmortem", now):
            return []
        findings = list(report.get("findings") or ())[:8]
        bundle = {
            "schema": "postmortem/v1",
            "ts": now,
            "boxes": {k: {"reason": b.get("reason"),
                          "ts": b.get("ts"),
                          "exception": (b.get("exception") or {}).get(
                              "type")}
                      for k, b in sorted(recent.items())},
            "findings": [{"detector": f.get("detector"),
                          "pod": f.get("pod"),
                          "severity": f.get("severity"),
                          "summary": f.get("summary"),
                          "event_ids": f.get("event_ids") or []}
                         for f in findings],
            "hint": "job_doctor --postmortem renders the full boxes",
        }
        evidence = sorted({i for f in findings
                           for i in (f.get("event_ids") or ())
                           if i is not None})
        cause = {"report_ts": report.get("ts"), "detector": "crash_loop",
                 "summary": "%d blackboxes within %.0fs: %s"
                            % (len(recent), self._crash_window_s,
                               ", ".join(sorted(recent))),
                 "evidence_ids": evidence}

        def file_bundle():
            with self._lock:
                seq = (self._seq or 0) + 1
            key = "%s%d" % (POSTMORTEM_PREFIX, seq)
            self._coord.set_server_permanent(SERVICE_AUTOPILOT, key,
                                             json.dumps(bundle))
            return {"key": key}

        outcome, attempts, error, result = self._apply("postmortem",
                                                       file_bundle)
        self._filed_signature = signature
        reason = ("crash loop detected (%d recent blackboxes); filed "
                  "postmortem bundle" % len(recent))
        return [self._record("postmortem", "fleet", reason, cause,
                             outcome, attempts, error, result, now,
                             extra={"bundle": bundle})]


def load_actions(coord, service=SERVICE_AUTOPILOT):
    """The stored ``action/v1`` journal (oldest first), or []."""
    try:
        raw = coord.get_value(service, JOURNAL_KEY)
        if not raw:
            return []
        journal = json.loads(raw)
        if not isinstance(journal, list):
            return []
        return [a for a in journal
                if isinstance(a, dict) and a.get("schema") == "action/v1"]
    except Exception as e:  # noqa: BLE001 — absent store == no journal
        logger.debug("autopilot journal read failed: %r", e)
        return []


def load_postmortems(coord, service=SERVICE_AUTOPILOT):
    """Filed ``postmortem/v1`` bundles: ``{key: doc}``."""
    out = {}
    try:
        for key, raw in coord.get_service(service):
            if not key.startswith(POSTMORTEM_PREFIX):
                continue
            try:
                doc = json.loads(raw)
            except (TypeError, ValueError):
                continue
            if isinstance(doc, dict) \
                    and doc.get("schema") == "postmortem/v1":
                out[key] = doc
    except Exception as e:  # noqa: BLE001 — absent store == no bundles
        logger.debug("postmortem read failed: %r", e)
    return out
