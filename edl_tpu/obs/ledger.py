"""Goodput accounting: where every wall-clock second actually went.

PR 8 can say *that* a pod is slow; this module says *where the time
goes* — the precondition for Pollux-style goodput scheduling (OSDI
'21) and the fair-share arbiter ROADMAP item #2 presupposes. Two
layers:

- :class:`TimeLedger` — a per-process state machine classifying every
  wall-clock second into EXCLUSIVE states (:data:`STATES`): exactly
  one state owns the clock at any instant, so the per-state totals sum
  to elapsed time and "goodput %" is well-defined as
  ``compute / total``. The trainer step loop, checkpoint drain, both
  resize paths, the elastic reader's consumer wait, and the launcher
  barrier each mark their boundaries; everything unclaimed is
  ``idle``. Accrued seconds land in the
  ``edl_time_seconds_total{state}`` counter family, so the totals ride
  the ordinary ``obs_pub/v1`` publication for free.
- :class:`GoodputMerger` — the leader-side streaming merger
  (HealthMonitor-hosted): per-pod cumulative counters from the
  published docs, counter-reset re-anchored exactly like PR 8's
  detectors (a restarted pod's counters re-zero; a negative delta
  must re-anchor, never subtract), folded into one fleet
  ``goodput/v1`` document under ``SERVICE_HEALTH`` with goodput %,
  ranked badput attribution, and per-pod spreads. The SLO burn-rate
  evaluator consumes the same cumulative (total, badput) pair as its
  denominator (the ``goodput`` SLO kind in :mod:`edl_tpu.obs.slo`).

Cost model: one :meth:`TimeLedger.transition` is a clock read + one
short lock + one float add; the ``edl_time_seconds_total`` registry
counters catch up lazily in :meth:`TimeLedger.flush` (publisher tick),
keeping the registry entirely off the hot path. With the
``EDL_TPU_OBS`` kill switch off a transition is one global load +
branch. The ``ledger`` section of ``obs_bench`` measures exactly this
on/off delta on a synthetic step loop (<1% criterion).

Threading: the ledger models the TRAINING thread's wall clock. Scopes
(:meth:`TimeLedger.state`) nest via a stack — a drain inside a live
resize accrues ``ckpt_block`` and returns to ``resize_pause`` — but
background threads (async checkpoint writers, publishers) must NOT
push states; their concurrency is not this thread's lost time.
"""

import json
import threading
import time

from edl_tpu.obs import metrics
from edl_tpu.utils.logger import logger

#: value of controller.constants.SERVICE_HEALTH, inlined so obs stays
#: a leaf package (guarded by a test against drift)
SERVICE_HEALTH = "health"

#: the fleet goodput doc's key under SERVICE_HEALTH (leader-written,
#: last-writer-wins — the same contract as health.HEALTH_KEY)
GOODPUT_KEY = "goodput"

#: the exclusive states, in display order. ``compute`` is goodput;
#: everything else is attributed badput; ``idle`` is the default owner
#: of any second no instrumentation point claimed.
STATES = ("compute", "data_wait", "embed_wait", "ckpt_block",
          "resize_pause", "restore", "barrier_wait", "idle")

GOODPUT_STATE = "compute"

_TIME_TOTAL = metrics.counter(
    "edl_time_seconds_total",
    "wall-clock seconds attributed per exclusive ledger state",
    labels=("state",))


class _Scope(object):
    """Context manager returned by :meth:`TimeLedger.state`."""

    __slots__ = ("_ledger", "_name")

    def __init__(self, ledger, name):
        self._ledger = ledger
        self._name = name

    def __enter__(self):
        self._ledger.push(self._name)
        return self

    def __exit__(self, *exc):
        self._ledger.pop()
        return False


class TimeLedger(object):
    """Exclusive wall-clock state machine (see module docstring).

    ``transition(state)`` replaces the CURRENT state (step-boundary
    marks: the step loop flips to ``compute`` once per step);
    ``push``/``pop`` (or the ``state()`` scope) nest a temporary state
    over the current one (waits inside a step). Totals accrue lazily:
    time is charged to the owning state whenever the machine is
    touched, and :meth:`flush` closes the open interval so a snapshot
    (publisher tick, final dump) sees everything up to "now"."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._stack = ["idle"]
        self._mark = None  # clock value at the last accrual, lazy-armed
        self._totals = {s: 0.0 for s in STATES}
        # pre-bound counter children: label resolution off the hot path
        self._children = {s: _TIME_TOTAL.labels(s) for s in STATES}
        # counter seconds already pushed to the registry; the delta is
        # synced in flush() so the hot path pays exactly one lock
        self._synced = {s: 0.0 for s in STATES}

    def _accrue(self, now):
        # caller holds the lock
        if self._mark is not None:
            dt = now - self._mark
            if dt > 0:
                self._totals[self._stack[-1]] += dt
        self._mark = now

    def _sync_counters(self):
        # caller holds the lock; registry counters catch up to _totals
        for state, total in self._totals.items():
            delta = total - self._synced[state]
            if delta > 0:
                self._children[state].inc(delta)
                self._synced[state] = total

    def transition(self, state):
        """Make ``state`` the current owner of the clock (top of the
        stack is replaced, nesting depth unchanged)."""
        if not metrics.enabled():
            return
        with self._lock:
            self._accrue(self._clock())
            self._stack[-1] = state

    def push(self, state):
        """Nest ``state`` over the current one until :meth:`pop`."""
        if not metrics.enabled():
            return
        with self._lock:
            self._accrue(self._clock())
            self._stack.append(state)

    def pop(self):
        """Return to the state active before the matching push."""
        if not metrics.enabled():
            return
        with self._lock:
            self._accrue(self._clock())
            if len(self._stack) > 1:
                self._stack.pop()

    def state(self, name):
        """``with ledger.state("ckpt_block"):`` — push/pop scope."""
        return _Scope(self, name)

    def current(self):
        with self._lock:
            return self._stack[-1]

    def flush(self):
        """Charge the open interval to the current state and push the
        accrued seconds into the ``edl_time_seconds_total`` registry
        counters (publisher tick / final dump hook); the state machine
        itself is unchanged."""
        if not metrics.enabled():
            return
        with self._lock:
            if self._mark is None:
                return  # never engaged: a supervisor process's ledger
                # must not manufacture idle time out of publisher ticks
            self._accrue(self._clock())
            self._sync_counters()

    def totals(self):
        """``{state: seconds}`` including the open interval. Reads do
        not require the kill switch — disabled periods simply never
        accrued."""
        with self._lock:
            if metrics.enabled() and self._mark is not None:
                self._accrue(self._clock())
            return dict(self._totals)

    def reset(self):
        """Zero the per-instance totals and return to ``idle`` (bench
        arcs and tests; the registry counters are monotonic and stay)."""
        with self._lock:
            self._stack = ["idle"]
            self._mark = None
            self._totals = {s: 0.0 for s in STATES}
            self._synced = {s: 0.0 for s in STATES}


#: THE process ledger — every in-tree instrumentation point marks this
#: one instance, keeping the exclusive-states invariant process-wide.
LEDGER = TimeLedger()


def pod_states(obs_doc):
    """Extract ``{state: cumulative_seconds}`` from one ``obs_pub/v1``
    doc (or None when the pod publishes no ledger counters — absent is
    not zero: old pods predate the ledger)."""
    fam = (((obs_doc.get("metrics") or {}).get("metrics") or {})
           .get(_TIME_TOTAL.name))
    if not fam:
        return None
    out = {}
    for s in fam.get("series") or ():
        state = (s.get("labels") or {}).get("state")
        if state:
            out[state] = float(s.get("value") or 0.0)
    return out or None


class GoodputMerger(object):
    """Leader-side streaming accumulation of per-pod ledger counters.

    Counters are cumulative-per-incarnation: they start at zero with
    the process and re-zero on restart. :meth:`update` therefore
    re-anchors on any backwards total (the PR 8 detector idiom) —
    the restarted incarnation's doc is again a delta from zero, so it
    is folded in whole; only the dead incarnation's never-republished
    tail is lost, which is exactly the information that died with it."""

    def __init__(self):
        self._pods = {}  # pod -> {"last": {state: v}|None, "acc": {...}}

    def update(self, pod, states):
        """Fold one pod's cumulative ``{state: seconds}`` sample in."""
        cell = self._pods.setdefault(pod, {"last": None, "acc": {}})
        last = cell["last"]
        if last is not None \
                and sum(states.values()) < sum(last.values()):
            last = None  # counters went backwards: pod restarted
        acc = cell["acc"]
        for state, value in states.items():
            prev = (last or {}).get(state, 0.0)
            delta = value - prev
            if delta > 0:
                acc[state] = acc.get(state, 0.0) + delta
        cell["last"] = dict(states)

    def update_from_docs(self, docs):
        """Fold every pod's ``obs_pub/v1`` doc in. Pods without ledger
        counters are skipped, not zeroed — and so are all-zero ones: a
        process that never engaged its ledger (the launcher supervisor)
        still carries the zero-valued series, but has no time to
        attribute and must not pad the fleet report."""
        for pod, doc in sorted(docs.items()):
            states = pod_states(doc)
            if states and any(states.values()):
                self.update(pod, states)

    def forget(self, pod):
        self._pods.pop(pod, None)

    def pods(self):
        return sorted(self._pods)

    def fleet_cumulative(self):
        """``(total_s, badput_s)`` summed over every pod's accumulated
        history — the cumulative pair the SLO burn-rate evaluator
        consumes as its denominator."""
        total = badput = 0.0
        for cell in self._pods.values():
            for state, sec in cell["acc"].items():
                total += sec
                if state != GOODPUT_STATE:
                    badput += sec
        return total, badput

    def doc(self, now=None):
        """The fleet ``goodput/v1`` document."""
        now = time.time() if now is None else now
        pods_out = {}
        fleet_states = {}
        pcts = []
        for pod, cell in sorted(self._pods.items()):
            acc = cell["acc"]
            total = sum(acc.values())
            good = acc.get(GOODPUT_STATE, 0.0)
            pct = (100.0 * good / total) if total > 0 else None
            badput = {s: v for s, v in acc.items()
                      if s != GOODPUT_STATE and v > 0}
            top = max(badput, key=badput.get) if badput else None
            pods_out[pod] = {
                "total_s": round(total, 3),
                "goodput_s": round(good, 3),
                "goodput_pct": (round(pct, 2) if pct is not None
                                else None),
                "top_badput": top,
                "states": {s: round(v, 3)
                           for s, v in sorted(acc.items())},
            }
            if pct is not None:
                pcts.append(pct)
            for state, sec in acc.items():
                fleet_states[state] = fleet_states.get(state, 0.0) + sec
        total = sum(fleet_states.values())
        good = fleet_states.get(GOODPUT_STATE, 0.0)
        ranked = sorted(((s, v) for s, v in fleet_states.items()
                         if s != GOODPUT_STATE and v > 0),
                        key=lambda kv: -kv[1])
        spread = {}
        for state in sorted(fleet_states):
            vals = [cell["acc"].get(state, 0.0)
                    for cell in self._pods.values()]
            if vals:
                spread[state] = {"min_s": round(min(vals), 3),
                                 "max_s": round(max(vals), 3)}
        return {
            "schema": "goodput/v1",
            "ts": now,
            "pods_reporting": sorted(self._pods),
            "pods": pods_out,
            "fleet": {
                "total_s": round(total, 3),
                "goodput_s": round(good, 3),
                "goodput_pct": (round(100.0 * good / total, 2)
                                if total > 0 else None),
                "badput": [{"state": s, "seconds": round(v, 3),
                            "share_pct": round(100.0 * v / total, 2)}
                           for s, v in ranked],
            },
            "spread": {
                "goodput_pct_min": (round(min(pcts), 2) if pcts
                                    else None),
                "goodput_pct_max": (round(max(pcts), 2) if pcts
                                    else None),
                "states": spread,
            },
        }


def resize_payback_s(pause_s, world_from, world_to, goodput_frac):
    """Seconds until a resize pause is repaid by marginal goodput.

    The pause idles all ``world_from`` pods outright, costing
    ``pause_s * world_from`` compute-seconds. After the resize the
    fleet gains ``(world_to - world_from) * goodput_frac``
    compute-seconds per wall-clock second (the marginal pods convert
    wall time into goodput at the fleet's observed rate). The payback
    horizon is cost / gain-rate; the autopilot triggers a scale-out
    only when this falls inside its configured horizon.

    Returns ``inf`` when the resize gains nothing (``world_to <=
    world_from``), when the fleet converts no time into compute
    (``goodput_frac <= 0``), or on a nonsensical negative pause —
    an infinite horizon is an automatic veto."""
    gain = (float(world_to) - float(world_from)) * float(goodput_frac)
    if gain <= 0.0 or pause_s < 0.0 or goodput_frac <= 0.0:
        return float("inf")
    return float(pause_s) * float(world_from) / gain


def load_goodput(coord, service=SERVICE_HEALTH):
    """Latest ``goodput/v1`` doc from the store, or None."""
    try:
        raw = coord.get_value(service, GOODPUT_KEY)
        if not raw:
            return None
        doc = json.loads(raw)
        if isinstance(doc, dict) and doc.get("schema") == "goodput/v1":
            return doc
    except Exception as e:  # noqa: BLE001 — absent store == no doc
        logger.debug("goodput read failed: %r", e)
    return None
