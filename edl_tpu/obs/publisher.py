"""Periodic fleet publication: registry + event snapshots → the store.

Each process runs (at most) one :class:`MetricsPublisher`; every
``interval`` seconds it writes one JSON document to the coordination
store under ``SERVICE_METRICS`` (service name passed in — this module
stays import-leaf) keyed ``obs_<pod_key>``:

    {"schema": "obs_pub/v1", "key": ..., "ts": ...,
     "metrics": <registry snapshot>, "events": [<new events only>]}

Events are published incrementally (id watermark), so the store holds
the pod's recent timeline without rewriting history on every tick.
``job_stats`` reads every ``obs_*`` key and renders the fleet view
(metrics merged via :func:`edl_tpu.obs.metrics.merge_snapshots`,
events via :func:`edl_tpu.obs.events.merge_timelines`).

Publication is strictly best-effort: a store hiccup is logged at
debug and retried next tick — observability must never take down the
plane it observes.
"""

import json
import threading
import time

from edl_tpu.obs import events as events_mod
from edl_tpu.obs import ledger as ledger_mod
from edl_tpu.obs import metrics as metrics_mod
from edl_tpu.utils.logger import logger

#: value of controller.constants.SERVICE_METRICS, inlined so obs stays
#: a leaf package (guarded by a test against drift)
SERVICE_METRICS = "metrics"

KEY_PREFIX = "obs_"


class MetricsPublisher(object):
    """``coord``: a CoordClient (anything with ``set_server_permanent``).
    ``pod_key``: stable per-process identity (pod id, or pod id +
    rank). ``max_events``: cap on events carried per published doc —
    the store value stays bounded even after an event storm."""

    def __init__(self, coord, pod_key, interval=10.0,
                 registry=None, events=None, max_events=512,
                 service=SERVICE_METRICS):
        self._coord = coord
        self._key = KEY_PREFIX + str(pod_key)
        self._interval = float(interval)
        self._registry = registry or metrics_mod.REGISTRY
        self._events = events or events_mod.EVENTS
        self._max_events = int(max_events)
        self._service = service
        self._since = 0
        self._stop = threading.Event()
        self._thread = None

    def publish_once(self):
        """One publication tick; returns the published doc (also used
        directly by tests and by the trainer's final flush)."""
        fresh = self._events.snapshot(since_id=self._since)
        if len(fresh) > self._max_events:
            fresh = fresh[-self._max_events:]
        # close the time ledger's open interval so the shipped
        # edl_time_seconds_total counters cover right up to this tick
        ledger_mod.LEDGER.flush()
        # "ts" is the staleness detector's liveness signal (obs/health):
        # a doc whose ts stops advancing means the publisher is dead or
        # partitioned, even though the stale doc itself stays readable
        doc = {"schema": "obs_pub/v1", "key": self._key,
               "ts": time.time(),
               "metrics": self._registry.snapshot(),
               "events": fresh}
        # publish_obs routes through the relay tree when the client has
        # one attached (subtree aggregation into obs_agg/v1 — one store
        # write per subtree per tick); plain clients and the fakes in
        # tests take the permanent-put path unchanged
        sink = getattr(self._coord, "publish_obs", None)
        if sink is not None:
            sink(self._service, self._key, json.dumps(doc))
        else:
            self._coord.set_server_permanent(self._service, self._key,
                                             json.dumps(doc))
        if fresh:
            self._since = fresh[-1]["id"]
        return doc

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.publish_once()
            except Exception as e:  # noqa: BLE001 — best-effort by contract
                logger.debug("obs publish failed (will retry): %r", e)

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="obs-publisher")
            self._thread.start()
        return self

    def stop(self, final_flush=True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 5)
            self._thread = None
        if final_flush:
            try:
                self.publish_once()
            except Exception as e:  # noqa: BLE001 — best-effort by contract
                logger.debug("obs final flush failed: %r", e)
