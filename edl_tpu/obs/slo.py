"""Per-plane SLO declarations + multi-window burn-rate evaluation.

An :class:`Slo` names an objective over one of the fleet's planes and
comes in two shapes:

- **latency**: a compliance target over a histogram family ("95% of
  ``edl_train_step_ms`` observations <= 1000ms"). Good/bad counts come
  straight from the published bucket counts — the threshold is snapped
  to the nearest bucket bound at or above it, so evaluation costs one
  pass over ~18 ints and needs no raw samples.
- **event**: a compliance target over durations derived from the causal
  event timeline ("90% of resizes complete <= 30s"), paired from a
  start/end event kind per pod.
- **goodput**: a compliance target over the time ledger's wall-clock
  attribution ("80% of fleet seconds are compute"). The HealthMonitor
  feeds the evaluator the :class:`~edl_tpu.obs.ledger.GoodputMerger`'s
  cumulative ``(total_s, badput_s)`` pair — the ledger IS the
  denominator, so burning this SLO means the fleet is paying wall
  clock to something other than training.

:class:`BurnRateEvaluator` implements the SRE multi-window burn-rate
alert: it keeps a ring of ``(ts, total, bad)`` samples per SLO (fed
with CUMULATIVE totals each tick by the HealthMonitor) and computes

    burn = (bad_delta / total_delta) / (1 - target)

over a short and a long window. A burn of 1.0 spends the error budget
exactly at the sustainable rate; the evaluator raises ``critical``
when BOTH windows burn >= ``fast_burn`` (default 14.4 — budget gone in
~2 days at a 30-day horizon) and ``warn`` when both >= ``slow_burn``
(default 6.0). Requiring both windows is the standard guard: the long
window alone alerts on stale history, the short window alone on a
transient spike. Counter resets (a pod restart re-zeroes its
histograms) clear the ring instead of producing negative deltas.

This module is stdlib-only — the obs package stays an import LEAF.
"""

import threading
import time
from collections import deque

class Slo(object):
    """One declared objective. Use :meth:`latency` / :meth:`event`."""

    __slots__ = ("name", "plane", "kind", "family", "labels",
                 "threshold_ms", "threshold_s", "start_kind", "end_kind",
                 "target", "description")

    def __init__(self, name, plane, kind, target, family=None, labels=None,
                 threshold_ms=None, threshold_s=None, start_kind=None,
                 end_kind=None, description=""):
        if kind not in ("latency", "event", "goodput"):
            raise ValueError("unknown SLO kind %r" % kind)
        self.name = name
        self.plane = plane
        self.kind = kind
        self.target = float(target)
        self.family = family
        self.labels = dict(labels or {})
        self.threshold_ms = threshold_ms
        self.threshold_s = threshold_s
        self.start_kind = start_kind
        self.end_kind = end_kind
        self.description = description

    @classmethod
    def latency(cls, name, plane, family, threshold_ms, target,
                labels=None, description=""):
        return cls(name, plane, "latency", target, family=family,
                   labels=labels, threshold_ms=float(threshold_ms),
                   description=description)

    @classmethod
    def event(cls, name, plane, start_kind, end_kind, threshold_s, target,
              description=""):
        return cls(name, plane, "event", target, start_kind=start_kind,
                   end_kind=end_kind, threshold_s=float(threshold_s),
                   description=description)

    @classmethod
    def goodput(cls, name, plane, target, description=""):
        """``target`` is the compliant fraction of wall-clock seconds
        (good = ledger ``compute``; bad = every other state)."""
        return cls(name, plane, "goodput", target,
                   description=description)

    def declare(self):
        """JSON-able declaration (embedded in every evaluation row)."""
        out = {"name": self.name, "plane": self.plane, "kind": self.kind,
               "target": self.target, "description": self.description}
        if self.kind == "latency":
            out.update(family=self.family, threshold_ms=self.threshold_ms)
            if self.labels:
                out["labels"] = dict(self.labels)
        elif self.kind == "event":
            out.update(start_kind=self.start_kind, end_kind=self.end_kind,
                       threshold_s=self.threshold_s)
        return out

    def __repr__(self):
        return "Slo(%s/%s %s target=%g)" % (self.plane, self.name,
                                            self.kind, self.target)


#: the default objectives, one per plane the repo ships today. Bounds
#: and targets are tuning knobs (docs/observability.md "Health & SLOs");
#: they are deliberately loose — an SLO that pages on CI noise trains
#: operators to ignore it.
DEFAULT_SLOS = (
    Slo.latency("step_p95", "train", "edl_train_step_ms",
                threshold_ms=2500.0, target=0.95,
                description="95% of train steps <= 2.5s"),
    Slo.latency("predict_p99", "distill", "edl_rpc_client_call_ms",
                threshold_ms=500.0, target=0.99,
                labels={"method": "predict"},
                description="99% of teacher predict RPCs <= 500ms"),
    Slo.event("resize_downtime", "elastic",
              start_kind="resize.coordinated_stop", end_kind="resize.resumed",
              threshold_s=30.0, target=0.90,
              description="90% of elastic resizes resume <= 30s"),
    Slo.event("failover_downtime", "store",
              start_kind="store.stepdown", end_kind="store.leader_elected",
              threshold_s=5.0, target=0.90,
              description="90% of store failovers re-elect <= 5s"),
    Slo.goodput("train_goodput", "train", target=0.80,
                description="80% of fleet wall-clock seconds are "
                            "compute (time-ledger attribution)"),
)


def labels_match(series_labels, want):
    """True when every wanted label is present with a matching value."""
    series_labels = series_labels or {}
    return all(str(series_labels.get(k)) == str(v)
               for k, v in want.items())


def hist_good_bad(fam_entry, threshold_ms, labels=None):
    """(total, bad) observation counts for one histogram family entry
    (snapshot or fleet-merged shape — both carry non-cumulative
    ``buckets`` aligned with ``bounds`` + implicit +Inf). ``bad`` is
    everything ABOVE the effective threshold, which is ``threshold_ms``
    snapped UP to the nearest bucket bound (bucket-resolution is the
    published contract; a threshold past the last bound means only
    +Inf observations are bad)."""
    bounds = list(fam_entry.get("bounds") or ())
    idx = len(bounds) - 1
    for i, b in enumerate(bounds):
        if b >= threshold_ms:
            idx = i
            break
    total = bad = 0
    for s in fam_entry.get("series", ()):
        if labels and not labels_match(s.get("labels"), labels):
            continue
        buckets = s.get("buckets") or ()
        total += s.get("count", 0)
        bad += sum(buckets[idx + 1:])
    return total, bad


def pair_event_durations(events, start_kind, end_kind):
    """Pair start/end event kinds per pod into durations. ``events`` is
    an iterable of merged-timeline records (each may carry a ``pod``
    field; same-pod pairing, chronological). Returns
    ``[{"pod", "duration_s", "start_id", "end_id", "end_ts"}, ...]``;
    an end with no prior unmatched start is dropped (its start happened
    before the observation window), a start with no end is left pending
    (still in flight — the caller sees it next tick)."""
    open_starts = {}
    out = []
    for e in sorted(events, key=lambda e: (e.get("ts") or 0,
                                           e.get("id") or 0)):
        pod = e.get("pod")
        kind = e.get("kind")
        if kind == start_kind:
            open_starts[pod] = e
        elif kind == end_kind:
            start = open_starts.pop(pod, None)
            if start is not None:
                out.append({
                    "pod": pod,
                    "duration_s": max(0.0, (e.get("ts") or 0)
                                      - (start.get("ts") or 0)),
                    "start_id": start.get("id"),
                    "end_id": e.get("id"),
                    "end_ts": e.get("ts"),
                })
    return out


class BurnRateEvaluator(object):
    """Streaming multi-window burn-rate evaluation over cumulative
    (total, bad) counts per SLO. Thread-safe; one instance per
    HealthMonitor."""

    def __init__(self, slos=DEFAULT_SLOS, short_window=300.0,
                 long_window=3600.0, fast_burn=14.4, slow_burn=6.0,
                 clock=time.time):
        self.slos = tuple(slos)
        self._short = float(short_window)
        self._long = float(long_window)
        self._fast = float(fast_burn)
        self._slow = float(slow_burn)
        self._clock = clock
        self._lock = threading.Lock()
        # slo name -> deque of (ts, total, bad); bounded by the long
        # window in observe()
        self._rings = {}

    def by_name(self, name):
        for s in self.slos:
            if s.name == name:
                return s
        return None

    def last_sample(self, name):
        """Most recent (ts, total, bad) cumulative sample for ``name``,
        or None before the first observe()."""
        with self._lock:
            ring = self._rings.get(name)
            return ring[-1] if ring else None

    def observe(self, name, total, bad, now=None):
        """Feed one cumulative sample for ``name``. A total that went
        BACKWARDS (fleet restart re-zeroed the counters) clears the
        ring — a negative delta must not read as negative burn."""
        now = self._clock() if now is None else now
        with self._lock:
            ring = self._rings.setdefault(name, deque())
            if ring and total < ring[-1][1]:
                ring.clear()
            ring.append((now, float(total), float(bad)))
            horizon = now - self._long - 1.0
            while len(ring) > 1 and ring[0][0] < horizon:
                ring.popleft()

    def _window_burn(self, ring, now, window, budget):
        """(burn, total_delta, bad_delta) over [now-window, now]."""
        if len(ring) < 2:
            return None, 0.0, 0.0
        cutoff = now - window
        base = ring[0]
        for sample in ring:
            if sample[0] <= cutoff:
                base = sample
            else:
                break
        head = ring[-1]
        d_total = head[1] - base[1]
        d_bad = head[2] - base[2]
        if d_total <= 0:
            return None, d_total, d_bad
        return (d_bad / d_total) / budget, d_total, d_bad

    def evaluate(self, now=None):
        """One row per declared SLO:
        ``{"slo": <declaration>, "burn_short", "burn_long",
        "short_window_s", "long_window_s", "severity": None|"warn"|
        "critical", "budget": 1-target}`` (burns are None with no
        traffic in the window — no traffic is not an SLO violation)."""
        now = self._clock() if now is None else now
        rows = []
        with self._lock:
            for slo in self.slos:
                budget = max(1e-9, 1.0 - slo.target)
                ring = self._rings.get(slo.name, ())
                b_short, _, _ = self._window_burn(ring, now, self._short,
                                                  budget)
                b_long, d_total, d_bad = self._window_burn(
                    ring, now, self._long, budget)
                severity = None
                if b_short is not None and b_long is not None:
                    if b_short >= self._fast and b_long >= self._fast:
                        severity = "critical"
                    elif b_short >= self._slow and b_long >= self._slow:
                        severity = "warn"
                rows.append({
                    "slo": slo.declare(),
                    "burn_short": (round(b_short, 3)
                                   if b_short is not None else None),
                    "burn_long": (round(b_long, 3)
                                  if b_long is not None else None),
                    "short_window_s": self._short,
                    "long_window_s": self._long,
                    "window_total": d_total,
                    "window_bad": d_bad,
                    "budget": round(budget, 6),
                    "severity": severity,
                })
        return rows
