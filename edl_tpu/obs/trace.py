"""Dapper-style trace-context propagation for the RPC substrate.

A **span** is one timed operation; spans in one causal chain share a
``trace_id`` and link parent→child through ``parent_id``. The RPC
client stamps its active context into the request envelope as
``"tr": [trace_id, span_id]`` (only after the peer advertised the
``obs.trace`` feature — a legacy peer never sees the key and nothing
about the frame changes: byte-compatible fallback). The server adopts
the header as the parent of its dispatch span and re-activates the
context around the handler, so a nested RPC issued inside the handler
carries the SAME trace onward: client → server → nested-RPC across
processes, one ``trace_id`` end to end.

Recording is bounded and pull-based: finished spans land in a ring
buffer (:data:`TRACER`, default 4096 spans) and are exported on demand
— :meth:`Tracer.chrome_trace` emits ``chrome://tracing`` /
Perfetto-loadable JSON. Nothing is written anywhere at runtime.

Cost model: with tracing disabled and no propagated context (the
default), ``begin_span`` is one attr load + two falsy checks →
``None``; every downstream call no-ops on ``span is None``. Tracing
turns on per-process via ``EDL_TPU_TRACE=1`` or ``TRACER.enable()``;
a propagated remote context is honored even when local sampling is
off, so one traced client lights up the whole call tree.
"""

import contextlib
import os
import random
import threading
import time
from collections import deque

_tls = threading.local()

#: env switch for root sampling (child spans of a propagated context
#: are always recorded — the caller already paid for the trace)
TRACE_ENV = "EDL_TPU_TRACE"


def _new_id():
    return "%016x" % random.getrandbits(64)


class Span(object):
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "ts", "_t0", "dur_ms", "tags", "pid")

    def __init__(self, trace_id, span_id, parent_id, name, kind, tags):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind            # "client" | "server" | "local"
        self.ts = time.time()
        self._t0 = time.monotonic()
        self.dur_ms = None
        self.tags = tags
        self.pid = os.getpid()

    def to_dict(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "kind": self.kind, "ts": self.ts, "dur_ms": self.dur_ms,
                "tags": self.tags or {}, "pid": self.pid}


class Tracer(object):
    """Span factory + bounded ring of finished spans."""

    def __init__(self, capacity=4096):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=int(capacity))
        self._enabled = os.environ.get(TRACE_ENV, "") == "1"

    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    @property
    def enabled(self):
        return self._enabled

    def clear(self):
        with self._lock:
            self._ring.clear()

    def spans(self):
        """Finished spans, oldest first (dict copies)."""
        with self._lock:
            return [s.to_dict() for s in self._ring]

    def find(self, **match):
        """Finished spans whose fields equal every ``match`` item."""
        return [s for s in self.spans()
                if all(s.get(k) == v for k, v in match.items())]

    def _record(self, span):
        with self._lock:
            self._ring.append(span)

    def chrome_trace(self):
        """``chrome://tracing`` / Perfetto JSON: complete ("X") events,
        one row per pid, span ids threaded through args for hand-tracing
        a chain across processes."""
        events = []
        for s in self.spans():
            events.append({
                "name": s["name"], "ph": "X", "cat": s["kind"],
                "ts": s["ts"] * 1e6, "dur": (s["dur_ms"] or 0.0) * 1e3,
                "pid": s["pid"], "tid": 0,
                "args": {"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"],
                         **(s["tags"] or {})}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


#: THE process tracer
TRACER = Tracer()


def current():
    """The active ``(trace_id, span_id)`` context of this thread, or
    None. This is exactly what :func:`inject` stamps on the wire."""
    return getattr(_tls, "ctx", None)


def _set_ctx(ctx):
    _tls.ctx = ctx


def inject():
    """Wire header for the active context (``[trace_id, span_id]``) or
    None when this thread isn't inside a trace."""
    ctx = getattr(_tls, "ctx", None)
    return [ctx[0], ctx[1]] if ctx is not None else None


def begin_span(name, kind="local", parent=None, root=False, tags=None):
    """Open a span, or return None when nothing is tracing.

    A span is created iff one of: ``parent`` (a remote ``[trace_id,
    span_id]`` header) is given; this thread has an active context;
    ``root=True``/sampling is enabled (starts a fresh trace). The
    caller must pass the result to :func:`end_span` (None is fine).
    """
    ctx = getattr(_tls, "ctx", None)
    if parent is None and ctx is None and not (root or TRACER._enabled):
        return None
    if parent is not None:
        try:
            trace_id, parent_id = str(parent[0]), str(parent[1])
        except (TypeError, IndexError, KeyError):
            return None  # malformed header: trace nothing, serve normally
    elif ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id, parent_id = _new_id() + _new_id(), None
    return Span(trace_id, _new_id(), parent_id, name, kind, tags)


def end_span(span, **extra_tags):
    """Close + record ``span`` (no-op for None; idempotent — error
    unwinding may race a resolve path that already closed it)."""
    if span is None or span.dur_ms is not None:
        return
    span.dur_ms = (time.monotonic() - span._t0) * 1e3
    if extra_tags:
        span.tags = dict(span.tags or {}, **extra_tags)
    TRACER._record(span)


@contextlib.contextmanager
def span(name, kind="local", root=False, **tags):
    """Span context manager; activates the span as this thread's
    context so nested spans / outbound RPCs chain under it."""
    sp = begin_span(name, kind=kind, root=root, tags=tags or None)
    if sp is None:
        yield None
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (sp.trace_id, sp.span_id)
    try:
        yield sp
    finally:
        _tls.ctx = prev
        end_span(sp)


@contextlib.contextmanager
def server_span(name, header, **tags):
    """Dispatch-side span adopting a remote ``[trace_id, span_id]``
    header as parent (None header → plain :func:`span` semantics, which
    usually means "no span at all"). Activates the context for the
    handler's duration so nested client calls propagate the trace."""
    sp = begin_span(name, kind="server", parent=header, tags=tags or None)
    if sp is None:
        yield None
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (sp.trace_id, sp.span_id)
    try:
        yield sp
    finally:
        _tls.ctx = prev
        end_span(sp)
