"""Fleet-wide observability plane: metrics, traces, events.

One substrate replaces the scattered per-component ``stats()`` dicts,
the env-gated stderr stopwatch, and the ad-hoc JSON blobs under
``SERVICE_METRICS``:

- :mod:`edl_tpu.obs.metrics` — process-local registry of labeled
  counters / gauges / bounded-bucket histograms with Prometheus text
  exposition and a JSON snapshot. Served per-process via the
  auto-registered ``__metrics__`` RPC method on every
  :class:`~edl_tpu.rpc.server.RpcServer`.
- :mod:`edl_tpu.obs.trace` — Dapper-style trace-context propagation:
  a ``[trace_id, span_id]`` header rides the RPC envelope (behind
  ``obs.trace`` feature negotiation), spans land in a bounded ring
  buffer, exportable as Chrome-trace JSON.
- :mod:`edl_tpu.obs.events` — the elastic-event timeline: structured,
  causally-linked records for resize phases, leader elections, breaker
  trips, and fault injections.
- :mod:`edl_tpu.obs.publisher` — periodic snapshot publication into
  the coordination store so ``job_stats`` renders a fleet-wide view.
- :mod:`edl_tpu.obs.health` / :mod:`edl_tpu.obs.slo` — the ACTIVE
  layer: streaming detectors (straggler EWMA/MAD, publisher staleness,
  breaker flap, queue saturation) and multi-window SLO burn rates over
  the published docs, run by the leader-hosted
  :class:`~edl_tpu.obs.health.HealthMonitor`, which writes a
  ``health_report/v1`` verdict under ``SERVICE_HEALTH`` and feeds the
  cluster generator's scale-in victim choice.
- :mod:`edl_tpu.obs.ledger` — goodput accounting: the per-process
  :class:`~edl_tpu.obs.ledger.TimeLedger` classifies every wall-clock
  second into exclusive states (``edl_time_seconds_total{state}``),
  and the leader-side :class:`~edl_tpu.obs.ledger.GoodputMerger`
  folds the fleet into a ``goodput/v1`` doc under ``SERVICE_HEALTH``.
- :mod:`edl_tpu.obs.flight` — the crash flight recorder: on any
  death path a bounded ``blackbox/v1`` artifact (event/trace tails,
  metrics, ledger totals, all-thread tracebacks) survives the
  process, for ``job_doctor --postmortem``.
- :mod:`edl_tpu.obs.autopilot` — the policy engine that closes the
  observe→act loop: leader-hosted on the HealthMonitor tick, it maps
  verdicts to journaled, rate-limited, dry-runnable ``action/v1``
  remediations (straggler eviction + backfill, resize trigger/veto by
  goodput payback, data-plane knob tuning, crash-loop postmortems)
  under ``SERVICE_AUTOPILOT``.

This package is a LEAF: it imports nothing from edl_tpu outside
``utils.logger``, so every plane (rpc, robustness, data, coordination)
can instrument itself without import cycles.
"""

from edl_tpu.obs import (autopilot, events, flight, health, ledger, metrics,
                         slo, trace)
from edl_tpu.obs.autopilot import Autopilot
from edl_tpu.obs.events import EVENTS, emit
from edl_tpu.obs.flight import FlightRecorder
from edl_tpu.obs.health import HealthMonitor
from edl_tpu.obs.ledger import LEDGER, GoodputMerger, TimeLedger
from edl_tpu.obs.metrics import (REGISTRY, counter, gauge, histogram,
                                 mirror_stats, set_enabled)
from edl_tpu.obs.publisher import MetricsPublisher

__all__ = ["metrics", "trace", "events", "health", "slo", "ledger",
           "flight", "autopilot", "REGISTRY", "EVENTS", "LEDGER",
           "counter", "gauge", "histogram", "mirror_stats", "set_enabled",
           "emit", "MetricsPublisher", "HealthMonitor", "TimeLedger",
           "GoodputMerger", "FlightRecorder", "Autopilot"]
