"""Fleet health verdicts: streaming detectors over the published docs.

PR 7 built the passive plane — every process publishes an
``obs_pub/v1`` doc (registry snapshot + fresh events) under
``SERVICE_METRICS``. This module closes the loop: a leader-hosted
:class:`HealthMonitor` re-reads every ``obs_*`` doc on a timer, runs
streaming detectors over consecutive snapshots, and writes ONE
machine-readable ``health_report/v1`` verdict doc under
``SERVICE_HEALTH`` that elastic decisions (and the job doctor) consume.

Detectors (each a small streaming class, unit-testable offline):

- :class:`StragglerDetector` — per-pod EWMA of the windowed mean of a
  latency histogram (``delta_sum / delta_count`` between consecutive
  snapshots), flagged when the EWMA sits more than ``k`` times the
  fleet MAD above the fleet median for ``n_windows`` CONSECUTIVE
  windows. Median/MAD (not mean/stddev) so one straggler cannot drag
  the baseline toward itself; the EWMA warmup keeps a pod that just
  joined (cold cache, first compile) from being flagged on its first
  windows; a floor under the MAD keeps a perfectly homogeneous fleet
  (MAD ~ 0) from flagging micro-jitter.
- :class:`StalenessDetector` — a publisher whose doc ``ts`` stops
  advancing past ``stale_after`` is flagged dead-or-partitioned; its
  return produces a recovery transition.
- :class:`BreakerFlapDetector` — ``edl_breaker_trips_total`` deltas: a
  breaker that trips in >= ``flap_threshold`` of the last
  ``window_count`` windows is flapping (the retry plane is masking a
  recurring fault, not riding out a blip).
- :class:`QueueSaturationDetector` — a depth gauge pinned at/above its
  configured ceiling for ``n_windows`` consecutive windows (the
  consumer is not keeping up; back-pressure has gone steady-state).

SLO burn rates ride along via :mod:`edl_tpu.obs.slo`.

The monitor also exposes ``preferred_victims()`` — an ADVISORY ranked
list of flagged pods that the cluster generator consults when a
scale-in must drop someone: evict the straggler first, not an arbitrary
tail pod. Advisory means: never the monitor's own pod, never a reason
to shrink, only an ordering hint.

This package is a LEAF — ``SERVICE_HEALTH`` is inlined here (value of
``controller.constants.SERVICE_HEALTH``, drift-guarded by a test) and
the coordination client is injected, never imported.
"""

import json
import threading
import time
from collections import deque

from edl_tpu.obs import events as events_mod
from edl_tpu.obs import ledger as ledger_mod
from edl_tpu.obs import slo as slo_mod
from edl_tpu.utils.logger import logger

#: value of controller.constants.SERVICE_HEALTH, inlined so obs stays
#: a leaf package (guarded by a test against drift)
SERVICE_HEALTH = "health"

#: the single verdict key under SERVICE_HEALTH (leader-written,
#: last-writer-wins — there is at most one elected monitor)
HEALTH_KEY = "report"

KEY_PREFIX = "obs_"

SEVERITY_RANK = {"critical": 2, "warn": 1}

#: event kinds worth citing as causal evidence next to a finding
_EVIDENCE_KINDS = ("fault.", "breaker.", "resize.", "store.", "health.",
                  "preempt.")


def _median(values):
    vs = sorted(values)
    n = len(vs)
    if not n:
        return 0.0
    mid = n // 2
    if n % 2:
        return vs[mid]
    return (vs[mid - 1] + vs[mid]) / 2.0


def _mad(values, med):
    return _median([abs(v - med) for v in values])


class Finding(dict):
    """One detector verdict — a plain dict (JSON-able) with helpers."""

    @classmethod
    def make(cls, detector, pod, severity, summary, metric=None,
             value=None, baseline=None, threshold=None, windows=None,
             **extra):
        f = cls(detector=detector, pod=pod, severity=severity,
                summary=summary)
        if metric is not None:
            f["metric"] = metric
        if value is not None:
            f["value"] = round(float(value), 3)
        if baseline is not None:
            f["baseline"] = round(float(baseline), 3)
        if threshold is not None:
            f["threshold"] = round(float(threshold), 3)
        if windows is not None:
            f["windows"] = windows
        f.update(extra)
        return f


class _EwmaState(object):
    __slots__ = ("ewma", "windows", "streak", "last_sum", "last_count")

    def __init__(self):
        self.ewma = None
        self.windows = 0
        self.streak = 0
        self.last_sum = None
        self.last_count = None


class StragglerDetector(object):
    """EWMA/MAD straggler scoring over one histogram family.

    Feed :meth:`update` one ``{pod: window_mean_ms}`` map per tick
    (pods with no new observations this window simply absent). Knobs:
    ``k`` (MADs above the median), ``n_windows`` (consecutive windows
    over threshold before flagging), ``warmup`` (windows of data a pod
    needs before it can be FLAGGED; within warmup the EWMA re-seeds
    from each window instead of blending, so a one-window cold-start
    spike — first compile, cold page cache after a resize join — dies
    with the window instead of living on in the average), ``min_pods``
    (below this many pods there is no fleet to be a straggler OF),
    ``min_delta_ms`` / ``min_rel`` (floors under the MAD term so a
    tight fleet doesn't flag noise).

    The baseline median/MAD comes from warmed-up pods when enough
    exist, else from every pod with data — so a cold fleet (all pods
    started together, one of them genuinely slow from its first
    window) still converges on a verdict within ``n_windows``."""

    def __init__(self, family, k=3.0, n_windows=2, warmup=2, alpha=0.5,
                 min_pods=2, min_delta_ms=5.0, min_rel=0.25):
        self.family = family
        self.k = float(k)
        self.n_windows = int(n_windows)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.min_pods = int(min_pods)
        self.min_delta_ms = float(min_delta_ms)
        self.min_rel = float(min_rel)
        self._pods = {}  # pod -> _EwmaState

    def window_mean(self, pod, hist_sum, hist_count):
        """Cumulative (sum, count) -> this window's mean for ``pod``,
        or None when no new observations landed (or the counters went
        backwards — a restart; the state re-anchors)."""
        st = self._pods.setdefault(pod, _EwmaState())
        if st.last_count is None or hist_count < st.last_count:
            st.last_sum, st.last_count = hist_sum, hist_count
            return None
        d_count = hist_count - st.last_count
        d_sum = hist_sum - st.last_sum
        st.last_sum, st.last_count = hist_sum, hist_count
        if d_count <= 0:
            return None
        return d_sum / d_count

    def forget(self, pod):
        self._pods.pop(pod, None)

    def pods(self):
        return list(self._pods)

    def update(self, samples):
        """One detector window; returns a list of Findings."""
        for pod, mean in samples.items():
            st = self._pods.setdefault(pod, _EwmaState())
            st.windows += 1
            if st.ewma is None or st.windows <= self.warmup:
                st.ewma = float(mean)  # warmup: re-seed, don't blend
            else:
                st.ewma = (self.alpha * float(mean)
                           + (1.0 - self.alpha) * st.ewma)
        have = {pod: st.ewma for pod, st in self._pods.items()
                if st.ewma is not None}
        if len(have) < self.min_pods:
            for st in self._pods.values():
                st.streak = 0
            return []
        warm_vals = [st.ewma for st in self._pods.values()
                     if st.ewma is not None
                     and st.windows >= self.warmup]
        base_vals = (warm_vals if len(warm_vals) >= self.min_pods
                     else list(have.values()))
        med = _median(base_vals)
        mad = _mad(base_vals, med)
        threshold = med + max(self.k * mad, self.min_delta_ms,
                              self.min_rel * med)
        findings = []
        for pod, st in self._pods.items():
            value = have.get(pod)
            if value is None:
                continue
            if value > threshold:
                # only count windows with fresh evidence toward the
                # streak; a silent window holds the streak (a pod so
                # slow it finished nothing is not thereby healthy)
                if pod in samples:
                    st.streak += 1
            else:
                st.streak = 0
            if st.streak >= self.n_windows and st.windows >= self.warmup:
                findings.append(Finding.make(
                    "straggler", pod, "critical",
                    "%s ewma %.1fms vs fleet median %.1fms "
                    "(threshold %.1fms, %d consecutive windows)"
                    % (self.family, value, med, threshold, st.streak),
                    metric=self.family, value=value, baseline=med,
                    threshold=threshold, windows=st.streak, mad=round(mad,
                                                                      3)))
        return findings


class StalenessDetector(object):
    """Publisher-liveness from the doc ``ts`` the publisher stamps."""

    def __init__(self, stale_after):
        self.stale_after = float(stale_after)

    def update(self, now, doc_ts):
        """``doc_ts``: {pod: last published ts}; returns Findings."""
        findings = []
        for pod, ts in doc_ts.items():
            if ts is None:
                continue  # pre-fix publisher: cannot judge liveness
            age = now - ts
            if age > self.stale_after:
                findings.append(Finding.make(
                    "stale_publisher", pod, "critical",
                    "no publication for %.1fs (stale_after %.1fs) — "
                    "process dead or partitioned" % (age,
                                                     self.stale_after),
                    metric="obs_pub.ts", value=age,
                    threshold=self.stale_after))
        return findings


class BreakerFlapDetector(object):
    """A circuit breaker that keeps re-tripping across windows."""

    def __init__(self, family="edl_breaker_trips_total", window_count=6,
                 flap_threshold=3):
        self.family = family
        self.window_count = int(window_count)
        self.flap_threshold = int(flap_threshold)
        self._last = {}     # pod -> cumulative trips
        self._windows = {}  # pod -> deque of 0/1 tripped-this-window

    def update(self, trips):
        """``trips``: {pod: cumulative trip count}; returns Findings."""
        findings = []
        for pod, total in trips.items():
            prev = self._last.get(pod)
            self._last[pod] = total
            if prev is None or total < prev:
                continue  # first sight or restart: re-anchor
            ring = self._windows.setdefault(
                pod, deque(maxlen=self.window_count))
            ring.append(1 if total > prev else 0)
            flaps = sum(ring)
            if flaps >= self.flap_threshold:
                findings.append(Finding.make(
                    "breaker_flap", pod, "warn",
                    "breaker tripped in %d of the last %d windows "
                    "(retries are masking a recurring fault)"
                    % (flaps, len(ring)),
                    metric=self.family, value=flaps,
                    threshold=self.flap_threshold, windows=len(ring)))
        return findings


class QueueSaturationDetector(object):
    """A depth gauge pinned at/above its ceiling: steady back-pressure."""

    def __init__(self, family, threshold, n_windows=3):
        self.family = family
        self.threshold = float(threshold)
        self.n_windows = int(n_windows)
        self._streak = {}

    def update(self, depths):
        """``depths``: {pod: gauge value}; returns Findings."""
        findings = []
        for pod, depth in depths.items():
            if depth >= self.threshold:
                self._streak[pod] = self._streak.get(pod, 0) + 1
            else:
                self._streak[pod] = 0
            if self._streak[pod] >= self.n_windows:
                findings.append(Finding.make(
                    "queue_saturation", pod, "warn",
                    "%s at %.0f >= %.0f for %d consecutive windows "
                    "(consumer not keeping up)"
                    % (self.family, depth, self.threshold,
                       self._streak[pod]),
                    metric=self.family, value=depth,
                    threshold=self.threshold, windows=self._streak[pod]))
        return findings


class HealthMonitor(object):
    """The leader-hosted verdict service.

    ``check_once()`` (called by a timer thread between elections) reads
    every ``obs_*`` doc under ``service_metrics``, runs the streaming
    detectors + SLO burn evaluation, writes a ``health_report/v1`` doc
    under ``service_health``/``HEALTH_KEY``, and emits
    ``health.degraded`` / ``health.recovered`` transitions into the
    causal event ring. ``evaluate(docs)`` is the pure core (no store,
    no wall clock when ``now`` is passed) — tests and the detector
    bench drive it directly.

    The monitor is stateful across ticks (EWMAs, streaks, SLO rings,
    event watermarks) but stateless across ELECTIONS by design: a new
    leader's monitor re-warms within ``warmup`` windows rather than
    inheriting a dead leader's baselines."""

    def __init__(self, coord, pod_id, interval=10.0,
                 service_metrics="metrics", service_health=SERVICE_HEALTH,
                 key_prefix=KEY_PREFIX, stale_after=None,
                 straggler_families=("edl_train_step_ms",
                                     "edl_reader_fetch_ms"),
                 k=3.0, n_windows=2, warmup=2,
                 saturation_gauges=(("edl_reader_out_queue_depth", 16.0),
                                    ("edl_teacher_queue_depth", 64.0)),
                 slos=slo_mod.DEFAULT_SLOS, evaluator=None, events=None,
                 clock=time.time, max_transitions=64, ttl_s=None,
                 on_report=None):
        self._coord = coord
        self._pod_id = pod_id
        self._interval = float(interval)
        # verdict freshness bound: past it, consumers (scale-in victim
        # ranking, the autopilot) must treat the report as expired and
        # fail open — a dead leader's stale verdict must not keep
        # biasing eviction (reports are stamped with this value)
        self._ttl_s = (float(ttl_s) if ttl_s is not None
                       else 3.0 * self._interval)
        # called with each fresh report AFTER it is published — the
        # autopilot's tick (must never raise into the monitor loop)
        self._on_report = on_report
        self._service_metrics = service_metrics
        self._service_health = service_health
        self._key_prefix = key_prefix
        self._clock = clock
        self._events = events or events_mod.EVENTS
        self._stragglers = [
            StragglerDetector(fam, k=k, n_windows=n_windows, warmup=warmup)
            for fam in straggler_families]
        self._staleness = StalenessDetector(
            stale_after if stale_after is not None else 3.0 * interval
            + 5.0)
        self._breaker = BreakerFlapDetector()
        self._saturation = [QueueSaturationDetector(fam, thr)
                            for fam, thr in saturation_gauges]
        self._evaluator = evaluator or slo_mod.BurnRateEvaluator(
            slos=slos, clock=clock)
        # leader-side goodput accumulation over the published ledger
        # counters (counter-reset re-anchored, like the detectors)
        self._goodput = ledger_mod.GoodputMerger()
        self._last_goodput = None
        # pod -> {"verdict", "event_id"} for transition detection
        self._pod_state = {}
        # pod -> event-id watermark + bounded recent-evidence ring
        self._event_marks = {}
        self._evidence = {}  # pod -> deque of recent evidence events
        self._transitions = deque(maxlen=int(max_transitions))
        self._last_report = None
        self._victims = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- doc plumbing ------------------------------------------------------

    def _strip_key(self, key):
        return (key[len(self._key_prefix):]
                if key.startswith(self._key_prefix) else key)

    def _read_docs(self):
        """{pod: obs_pub doc} from the store; best-effort.

        Accepts both publication schemas: flat per-pod ``obs_pub/v1``
        docs AND relay-folded ``obs_agg/v1`` docs, whose per-pod cells
        are expanded back into individual obs_pub docs — the detectors
        keep seeing every pod regardless of the fan-in topology.  A pod
        appearing via both paths (e.g. mid-failover, when its doc
        rides a stale agg AND a fresh direct publish) resolves to the
        freshest ``ts``."""
        docs, doc_ts = {}, {}
        try:
            for key, raw in self._coord.get_service(self._service_metrics):
                if not key.startswith(self._key_prefix):
                    continue
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue
                if not isinstance(doc, dict):
                    continue
                if doc.get("schema") == "obs_pub/v1":
                    cells = [(self._strip_key(key), doc)]
                elif doc.get("schema") == "obs_agg/v1":
                    cells = [(self._strip_key(cell_key), cell)
                             for cell_key, cell
                             in sorted((doc.get("pods") or {}).items())
                             if isinstance(cell, dict)
                             and cell.get("schema") == "obs_pub/v1"]
                else:
                    continue
                for pod, cell in cells:
                    ts = cell.get("ts") or 0
                    if pod not in docs or ts > doc_ts[pod]:
                        docs[pod] = cell
                        doc_ts[pod] = ts
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            logger.debug("health: obs doc read failed: %r", e)
        return docs

    @staticmethod
    def _family(doc, name):
        metrics = (doc.get("metrics") or {}).get("metrics") or {}
        return metrics.get(name)

    @staticmethod
    def _series_total(fam, field="value"):
        """Sum a counter/gauge family's series values."""
        return sum(s.get(field, 0.0) for s in (fam or {}).get("series",
                                                              ()))

    @staticmethod
    def _hist_totals(fam):
        """(sum, count) across all series of a histogram family."""
        total_sum = total_count = 0.0
        for s in (fam or {}).get("series", ()):
            total_sum += s.get("sum", 0.0)
            total_count += s.get("count", 0)
        return total_sum, total_count

    def _ingest_events(self, pod, doc):
        """Accumulate this doc's fresh events into the pod's bounded
        evidence ring (docs carry increments and overwrite in place, so
        the monitor is the one remembering). Returns the new events."""
        mark = self._event_marks.get(pod, 0)
        fresh = []
        max_id = mark
        for e in doc.get("events") or ():
            eid = e.get("id") or 0
            if eid <= mark:
                # ids went backwards across the whole doc -> restart;
                # handled below by re-anchoring on the doc's max id
                continue
            fresh.append(e)
            max_id = max(max_id, eid)
        doc_ids = [e.get("id") or 0 for e in doc.get("events") or ()]
        if doc_ids and max(doc_ids) < mark:
            # publisher restarted and ids re-zeroed: re-anchor
            fresh = list(doc.get("events") or ())
            max_id = max(doc_ids)
        self._event_marks[pod] = max_id
        ring = self._evidence.setdefault(pod, deque(maxlen=256))
        for e in fresh:
            if any(str(e.get("kind", "")).startswith(p)
                   for p in _EVIDENCE_KINDS):
                ring.append(e)
        return fresh

    # -- the pure core -----------------------------------------------------

    def evaluate(self, docs, now=None):
        """One detector window over ``{pod: obs_pub doc}``; returns the
        ``health_report/v1`` dict (does not write the store — that is
        :meth:`check_once`)."""
        now = self._clock() if now is None else now
        known = set(docs)
        # prune state for pods that left the fleet entirely
        for det in self._stragglers:
            for pod in det.pods():
                if pod not in known:
                    det.forget(pod)

        fresh_events = []
        doc_ts = {}
        for pod, doc in sorted(docs.items()):
            doc_ts[pod] = doc.get("ts")
            for e in self._ingest_events(pod, doc):
                e = dict(e)
                e["pod"] = pod
                fresh_events.append(e)

        # fold each pod's edl_time_seconds_total counters into the
        # fleet goodput ledger (restart re-anchor inside the merger)
        self._goodput.update_from_docs(docs)
        for pod in self._goodput.pods():
            if pod not in known:
                self._goodput.forget(pod)

        findings = []
        for det in self._stragglers:
            samples = {}
            for pod, doc in docs.items():
                fam = self._family(doc, det.family)
                if fam is None:
                    continue
                h_sum, h_count = self._hist_totals(fam)
                mean = det.window_mean(pod, h_sum, h_count)
                if mean is not None:
                    samples[pod] = mean
            findings.extend(det.update(samples))

        findings.extend(self._staleness.update(now, doc_ts))
        findings.extend(self._breaker.update({
            pod: self._series_total(self._family(doc,
                                                 self._breaker.family))
            for pod, doc in docs.items()
            if self._family(doc, self._breaker.family) is not None}))
        for det in self._saturation:
            depths = {}
            for pod, doc in docs.items():
                fam = self._family(doc, det.family)
                if fam is None:
                    continue
                vals = [s.get("value", 0.0) for s in fam.get("series", ())]
                if vals:
                    depths[pod] = max(vals)
            findings.extend(det.update(depths))

        # SLOs: latency objectives from the cross-pod histogram sums
        # (cumulative — the evaluator differentiates), event objectives
        # from the freshly ingested timeline increments
        slo_rows = self._eval_slos(docs, fresh_events, now)
        for row in slo_rows:
            if row["severity"]:
                findings.append(Finding.make(
                    "slo_burn", "fleet", row["severity"],
                    "SLO %s burning %.1fx budget (short) / %.1fx (long)"
                    % (row["slo"]["name"], row["burn_short"],
                       row["burn_long"]),
                    metric=row["slo"].get("family") or row["slo"]["name"],
                    value=row["burn_short"], threshold=1.0,
                    slo=row["slo"]["name"]))

        findings.sort(key=lambda f: (-SEVERITY_RANK.get(f["severity"], 0),
                                     f["pod"]))
        report = self._build_report(docs, findings, slo_rows, now)
        gdoc = self._goodput.doc(now=now)
        report["goodput"] = gdoc["fleet"]
        with self._lock:
            self._last_report = report
            self._last_goodput = gdoc
            self._victims = list(report["preferred_victims"])
        return report

    def _eval_slos(self, docs, fresh_events, now):
        for slo in self._evaluator.slos:
            if slo.kind == "latency":
                total = bad = 0
                for doc in docs.values():
                    fam = self._family(doc, slo.family)
                    if fam is None:
                        continue
                    t, b = slo_mod.hist_good_bad(fam, slo.threshold_ms,
                                                 labels=slo.labels)
                    total += t
                    bad += b
                self._evaluator.observe(slo.name, total, bad, now=now)
        for slo in self._evaluator.slos:
            if slo.kind == "goodput":
                # the ledger is the denominator: cumulative fleet
                # seconds, bad = everything that is not compute
                total_s, bad_s = self._goodput.fleet_cumulative()
                if total_s > 0:
                    self._evaluator.observe(slo.name, total_s, bad_s,
                                            now=now)
        for slo in self._evaluator.slos:
            if slo.kind == "event":
                pairs = slo_mod.pair_event_durations(
                    fresh_events, slo.start_kind, slo.end_kind)
                prev = self._evaluator.last_sample(slo.name)
                if not pairs and prev is None:
                    continue  # never seen: keep "no data", not zeros
                base_total = prev[1] if prev else 0.0
                base_bad = prev[2] if prev else 0.0
                bad = sum(1 for p in pairs
                          if p["duration_s"] > slo.threshold_s)
                self._evaluator.observe(slo.name,
                                        base_total + len(pairs),
                                        base_bad + bad, now=now)
        return self._evaluator.evaluate(now=now)

    def _build_report(self, docs, findings, slo_rows, now):
        pods = {}
        for pod in docs:
            pods[pod] = {"verdict": "ok", "findings": 0}
        for f in findings:
            pod = f["pod"]
            if pod == "fleet":
                continue
            cell = pods.setdefault(pod, {"verdict": "ok", "findings": 0})
            cell["findings"] += 1
            if SEVERITY_RANK.get(f["severity"], 0) \
                    > SEVERITY_RANK.get(cell["verdict"], 0):
                cell["verdict"] = f["severity"]

        # transition events: ok -> degraded emits health.degraded (id
        # kept so the recovery can cite its cause)
        for pod, cell in sorted(pods.items()):
            prev = self._pod_state.get(pod, {"verdict": "ok",
                                             "event_id": None})
            if cell["verdict"] != "ok" and prev["verdict"] == "ok":
                worst = next((f for f in findings if f["pod"] == pod),
                             None)
                eid = self._events.emit(
                    "health.degraded", pod=pod,
                    severity=cell["verdict"],
                    detector=worst["detector"] if worst else None,
                    summary=worst["summary"] if worst else None)
                self._pod_state[pod] = {"verdict": cell["verdict"],
                                        "event_id": eid}
                self._transitions.append(
                    {"id": eid, "ts": now, "kind": "health.degraded",
                     "pod": pod, "severity": cell["verdict"]})
            elif cell["verdict"] == "ok" and prev["verdict"] != "ok":
                eid = self._events.emit("health.recovered", pod=pod,
                                        cause=prev["event_id"])
                self._pod_state[pod] = {"verdict": "ok", "event_id": None}
                self._transitions.append(
                    {"id": eid, "ts": now, "kind": "health.recovered",
                     "pod": pod, "cause": prev["event_id"]})
            else:
                self._pod_state[pod] = {"verdict": cell["verdict"],
                                        "event_id": prev["event_id"]}

        # attach causal evidence: the degraded-transition event id plus
        # the pod's recent evidence ring (fault firings, breaker trips,
        # resize phases) and the freshest trace id among them
        for f in findings:
            pod = f["pod"]
            state = self._pod_state.get(pod) or {}
            evidence = list(self._evidence.get(pod, ()))[-8:]
            f["event_ids"] = [e.get("id") for e in evidence]
            if state.get("event_id"):
                f["event_ids"].append(state["event_id"])
            trace = next((e.get("trace_id") for e in reversed(evidence)
                          if e.get("trace_id")), None)
            f["trace_id"] = trace
            f["events"] = [
                {"id": e.get("id"), "kind": e.get("kind"),
                 "ts": e.get("ts"), "attrs": e.get("attrs") or {}}
                for e in evidence]

        degraded = sorted(p for p, c in pods.items()
                          if c["verdict"] != "ok")
        fleet_verdict = "ok"
        for f in findings:
            if SEVERITY_RANK.get(f["severity"], 0) \
                    > SEVERITY_RANK.get(fleet_verdict, 0):
                fleet_verdict = f["severity"]

        # advisory eviction ranking: critical per-pod findings only,
        # worst value/baseline ratio first, never the monitor itself
        scored = {}
        for f in findings:
            pod = f["pod"]
            if pod in ("fleet", self._pod_id) \
                    or f["severity"] != "critical":
                continue
            base = f.get("baseline") or 0.0
            score = (f.get("value", 0.0) / base) if base else 1.0
            scored[pod] = max(scored.get(pod, 0.0), score)
        victims = [p for p, _ in sorted(scored.items(),
                                        key=lambda kv: -kv[1])]

        return {
            "schema": "health_report/v1",
            "ts": now,
            "ttl_s": self._ttl_s,
            "monitor": self._pod_id,
            "interval_s": self._interval,
            "fleet": {"verdict": fleet_verdict,
                      "pods_total": len(pods),
                      "pods_degraded": degraded},
            "pods": pods,
            "findings": findings,
            "slos": slo_rows,
            "preferred_victims": victims,
            "events": list(self._transitions),
        }

    # -- store-facing surface ----------------------------------------------

    def check_once(self):
        """One full tick: read docs, evaluate, publish the verdict.
        Best-effort on the write (the verdict is recomputed next tick);
        returns the report."""
        report = self.evaluate(self._read_docs())
        try:
            self._coord.set_server_permanent(
                self._service_health, HEALTH_KEY, json.dumps(report))
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            logger.debug("health report write failed (will retry): %r", e)
        with self._lock:
            gdoc = self._last_goodput
        if gdoc is not None:
            try:
                self._coord.set_server_permanent(
                    self._service_health, ledger_mod.GOODPUT_KEY,
                    json.dumps(gdoc))
            except Exception as e:  # noqa: BLE001 — best-effort by contract
                logger.debug("goodput write failed (will retry): %r", e)
        if self._on_report is not None:
            try:
                self._on_report(report)
            except Exception:  # noqa: BLE001 — a policy bug must not
                logger.exception("on_report hook failed")  # kill ticks
        return report

    def last_report(self):
        with self._lock:
            return self._last_report

    def preferred_victims(self):
        """Ranked advisory eviction order (worst straggler first) from
        the latest tick; empty when the fleet is healthy OR when the
        latest report has aged past its TTL (fail open: a verdict the
        monitor stopped refreshing must not keep biasing eviction)."""
        with self._lock:
            report = self._last_report
            victims = list(self._victims)
        if report is None:
            return []
        if self._clock() - (report.get("ts") or 0.0) > self._ttl_s:
            return []
        return victims

    # -- lifecycle ---------------------------------------------------------

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 — best-effort by contract
                logger.debug("health check failed (will retry): %r", e)

    def start(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="health-monitor")
                self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=self._interval + 5)
            self._thread = None


def load_report(coord, service=SERVICE_HEALTH, fresh_only=False,
                now=None):
    """Latest ``health_report/v1`` from the store, or None.

    ``fresh_only=True`` additionally returns None when the report has
    aged past its stamped ``ttl_s`` — the mode remediation consumers
    must use (a dead leader's verdict expires; tooling that renders
    history keeps the default and shows staleness instead)."""
    try:
        raw = coord.get_value(service, HEALTH_KEY)
        if not raw:
            return None
        doc = json.loads(raw)
        if not isinstance(doc, dict) \
                or doc.get("schema") != "health_report/v1":
            return None
        if fresh_only:
            ttl = doc.get("ttl_s")
            if ttl is not None:
                now = time.time() if now is None else now
                if now - (doc.get("ts") or 0.0) > float(ttl):
                    return None
        return doc
    except Exception as e:  # noqa: BLE001 — absent store == no report
        logger.debug("health report read failed: %r", e)
    return None
