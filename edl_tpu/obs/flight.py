"""Crash flight recorder: the observability plane's black box.

The trace ring and event ring are in-memory — a pod killed by chaos,
preemption, or a live-resize rollback evaporates exactly the evidence
the doctor needs (the Dapper lesson inverted: the most valuable traces
are the ones from requests that died). :class:`FlightRecorder` fixes
that: on SIGTERM, unhandled exception, live-resize rollback, or
launcher-observed child death, :meth:`FlightRecorder.dump` writes one
bounded ``blackbox/v1`` artifact — tail of the event ring (cause
chains intact), recent trace spans, a final metrics snapshot, the
ledger totals, the last ``_resize_timing`` record, and all-thread
tracebacks via :mod:`faulthandler` — to local disk and, best-effort,
to the coordination store, where ``job_doctor --postmortem`` renders
it into the ordinary causal-evidence-chain format.

THE contract: a dump NEVER masks the original failure. Every byte of
work happens inside one catch-all; the chaos point ``obs.flight.dump``
(fired first thing inside it) exists to prove that a recorder failing
in any way leaves the original exception/exit path byte-identical
(``tests/test_flight.py``).

Artifacts are bounded (event/span tails, truncated thread dump) so a
black box is always shippable through the store's value limits.
"""

import faulthandler
import json
import os
import tempfile
import threading
import time
import traceback

from edl_tpu.obs import events as events_mod
from edl_tpu.obs import ledger as ledger_mod
from edl_tpu.obs import metrics as metrics_mod
from edl_tpu.obs import trace as trace_mod
from edl_tpu.utils.logger import logger

#: value of controller.constants.SERVICE_HEALTH, inlined so obs stays
#: a leaf package (guarded by a test against drift)
SERVICE_HEALTH = "health"

#: store keys: ``blackbox_<pod_key>`` under SERVICE_HEALTH
KEY_PREFIX = "blackbox_"

#: artifact bounds — the box must fit through the store value limit
MAX_EVENTS = 256
MAX_SPANS = 128
MAX_THREAD_DUMP = 32768

#: local artifact directory override
BLACKBOX_DIR_ENV = "EDL_TPU_BLACKBOX_DIR"


def _thread_dump():
    """All-thread tracebacks via faulthandler (needs a real fd, so a
    temp file round-trip), bounded to MAX_THREAD_DUMP chars."""
    with tempfile.TemporaryFile(mode="w+") as f:
        faulthandler.dump_traceback(file=f, all_threads=True)
        f.seek(0)
        text = f.read()
    if len(text) > MAX_THREAD_DUMP:
        text = text[-MAX_THREAD_DUMP:]
    return text


def _exc_record(exc):
    if exc is None:
        return None
    tb = "".join(traceback.format_exception(
        type(exc), exc, getattr(exc, "__traceback__", None)))
    if len(tb) > MAX_THREAD_DUMP:
        tb = tb[-MAX_THREAD_DUMP:]
    return {"type": type(exc).__name__, "message": str(exc),
            "traceback": tb}


class FlightRecorder(object):
    """``pod_key``: stable identity stamped on the artifact (pod id,
    or pod id + rank). ``coord``: optional CoordClient for the
    best-effort store copy. ``out_dir``: local artifact directory
    (default ``$EDL_TPU_BLACKBOX_DIR`` or the system temp dir).
    ``providers``: late-bound context — the trainer registers a
    ``resize_timing`` provider so the box carries the live record
    without the recorder importing the runtime (obs stays a leaf)."""

    def __init__(self, pod_key, coord=None, out_dir=None, registry=None,
                 events=None, tracer=None, ledger=None,
                 clock=time.time):
        self._pod_key = str(pod_key)
        self._coord = coord
        self._out_dir = (out_dir or os.environ.get(BLACKBOX_DIR_ENV)
                         or tempfile.gettempdir())
        self._registry = registry or metrics_mod.REGISTRY
        self._events = events or events_mod.EVENTS
        self._tracer = tracer or trace_mod.TRACER
        self._ledger = ledger or ledger_mod.LEDGER
        self._clock = clock
        self._providers = {}
        self._lock = threading.Lock()
        self._dumping = False
        self._prev_excepthook = None
        self.last_path = None

    def register_provider(self, name, fn):
        """``fn()`` is called at dump time (inside the catch-all) and
        its JSON-able return lands under ``context[name]``."""
        self._providers[str(name)] = fn

    # -- the dump itself ----------------------------------------------------

    def _build(self, reason, exc):
        events = self._events.snapshot()
        if len(events) > MAX_EVENTS:
            events = events[-MAX_EVENTS:]
        spans = self._tracer.spans()
        if len(spans) > MAX_SPANS:
            spans = spans[-MAX_SPANS:]
        self._ledger.flush()
        context = {}
        for name, fn in sorted(self._providers.items()):
            try:
                context[name] = fn()
            except Exception as e:  # noqa: BLE001 — providers best-effort
                context[name] = {"provider_error": repr(e)}
        return {
            "schema": "blackbox/v1",
            "ts": self._clock(),
            "pod": self._pod_key,
            "pid": os.getpid(),
            "reason": reason,
            "exception": _exc_record(exc),
            "events": events,
            "spans": spans,
            "metrics": self._registry.snapshot(),
            "ledger": {s: round(v, 3)
                       for s, v in self._ledger.totals().items()},
            "threads": _thread_dump(),
            "context": context,
        }

    def dump(self, reason, exc=None):
        """Write the black box; returns the local path or None. NEVER
        raises and never re-enters (a failure inside the dump must not
        recurse through the excepthook back into the dump)."""
        with self._lock:
            if self._dumping:
                return None
            self._dumping = True
        try:
            # the chaos hook comes FIRST so an injected failure proves
            # the no-masking contract against the whole dump path; the
            # lazy import keeps obs a leaf (robustness imports obs)
            from edl_tpu.robustness import faults
            if faults.PLANE is not None:
                faults.PLANE.fire("obs.flight.dump", reason=str(reason),
                                  pod=self._pod_key)
            doc = self._build(str(reason), exc)
            payload = json.dumps(doc)
            path = os.path.join(
                self._out_dir, "%s%s_%d.json"
                % (KEY_PREFIX, self._pod_key.replace(os.sep, "_"),
                   int(doc["ts"] * 1000)))
            with open(path, "w") as f:
                f.write(payload)
            self.last_path = path
            logger.error("flight recorder: %s black box for pod %s "
                         "-> %s", reason, self._pod_key, path)
            if self._coord is not None:
                try:
                    self._coord.set_server_permanent(
                        SERVICE_HEALTH, KEY_PREFIX + self._pod_key,
                        payload)
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.warning("black box store copy failed: %r", e)
            return path
        except BaseException as e:  # noqa: BLE001 — NEVER mask the crash
            try:
                logger.exception("flight recorder dump failed "
                                 "(original failure unaffected): %r", e)
            except BaseException:
                pass
            return None
        finally:
            with self._lock:
                self._dumping = False

    # -- process hooks ------------------------------------------------------

    def install_excepthook(self):
        """Chain onto ``sys.excepthook``: dump, then defer to the
        previous hook (the crash still prints and the exit code is
        untouched)."""
        import sys
        if self._prev_excepthook is not None:
            return self
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            self.dump("unhandled_exception", exc)
            prev(exc_type, exc, tb)

        self._prev_excepthook = prev
        sys.excepthook = hook
        return self

    def install_sigterm(self):
        """Chain onto SIGTERM (main thread only, best-effort): dump the
        box, then defer to the previous disposition — a chained Python
        handler runs as-is; SIG_DFL is re-raised so the exit status
        still says "killed by SIGTERM". The TRAINER must not use this:
        its PreemptionGuard owns SIGTERM (flag-only handler) and the
        box is dumped on the PreemptedError path instead."""
        import signal as signal_mod
        try:
            prev = signal_mod.getsignal(signal_mod.SIGTERM)

            def handler(signum, frame):
                self.dump("sigterm")
                if callable(prev):
                    prev(signum, frame)
                elif prev != signal_mod.SIG_IGN:
                    signal_mod.signal(signum, signal_mod.SIG_DFL)
                    os.kill(os.getpid(), signum)

            signal_mod.signal(signal_mod.SIGTERM, handler)
        except (ValueError, OSError) as e:  # not the main thread
            logger.debug("flight SIGTERM hook not installed: %r", e)
        return self

    def uninstall(self):
        import sys
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None


#: THE process recorder (installed once, by the launcher or trainer)
RECORDER = None


def install(pod_key, coord=None, out_dir=None, excepthook=True,
            sigterm=False):
    """Create/replace the process recorder; returns it."""
    global RECORDER
    if RECORDER is not None:
        RECORDER.uninstall()
    RECORDER = FlightRecorder(pod_key, coord=coord, out_dir=out_dir)
    if excepthook:
        RECORDER.install_excepthook()
    if sigterm:
        RECORDER.install_sigterm()
    return RECORDER


def dump(reason, exc=None):
    """Dump through the process recorder; no-op (None) before
    :func:`install`."""
    if RECORDER is None:
        return None
    return RECORDER.dump(reason, exc=exc)


def load_blackboxes(coord, service=SERVICE_HEALTH):
    """Every ``blackbox/v1`` doc in the store: ``{pod_key: doc}``."""
    out = {}
    try:
        for key, raw in coord.get_service(service):
            if not key.startswith(KEY_PREFIX):
                continue
            try:
                doc = json.loads(raw)
            except ValueError:
                continue
            if isinstance(doc, dict) \
                    and doc.get("schema") == "blackbox/v1":
                out[key[len(KEY_PREFIX):]] = doc
    except Exception as e:  # noqa: BLE001 — absent store == no boxes
        logger.debug("black box scan failed: %r", e)
    return out
