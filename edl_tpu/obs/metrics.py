"""Process-local metrics registry: labeled counters, gauges, histograms.

Design constraints, in priority order:

1. **Hot-path cost**: instrumentation sits inside the RPC dispatch
   loop and the data-plane fetch pipeline, so the per-observation path
   is one module-global load (the kill switch), one short lock, one
   float add. Label resolution is the expensive part — hot callers
   pre-bind a child once (``HIST.labels(method)``) and hold the
   handle; ``labels()`` itself is a dict lookup on the common path.
2. **Bounded memory**: every family caps its live label sets
   (``max_series``, default 256). Past the cap, new label sets
   collapse into one ``__overflow__`` series and the registry counts
   the drop — unbounded cardinality (per-batch ids, per-peer
   endpoints on a 10k-pod fleet) degrades, never OOMs.
3. **Two exposition formats** from one store: Prometheus text
   (``prometheus_text()``, for scrapes) and a JSON snapshot
   (``snapshot()``, for the coordination-store fleet publisher and
   ``job_stats`` aggregation).

``EDL_TPU_OBS=0`` (or :func:`set_enabled`\\ (False)) turns every handle
into a near-no-op: one global load + branch, no lock. ``obs_bench``
measures exactly this on/off delta on the data-plane hot loop.
"""

import os
import threading
import time

# THE kill switch. Checked at observation time (not bind time) so
# pre-bound handles in long-lived planes honor a runtime toggle — the
# on/off arcs of obs_bench flip it mid-process.
_ENABLED = os.environ.get("EDL_TPU_OBS", "1") != "0"

#: ms-oriented latency buckets (wire RPCs to checkpoint persists);
#: +Inf is implicit. Bounded at 17 buckets so one histogram series is
#: ~20 floats.
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
                   30000.0)

#: per-family live-series cap (see module docstring, point 2)
MAX_SERIES = 256

_OVERFLOW = "__overflow__"


def set_enabled(flag):
    """Flip the process-wide metrics kill switch; returns the previous
    value (so benches can restore it)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


def enabled():
    return _ENABLED


class _Counter(object):
    """One bound (child) counter series."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1.0):
        if not _ENABLED:
            return
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class _Gauge(object):
    """One bound gauge series (set/add semantics)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value):
        if not _ENABLED:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        if not _ENABLED:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        with self._lock:
            return self._value


class _Histogram(object):
    """One bound histogram series: cumulative-on-read bucket counts,
    sum, count. ``observe`` pays one binary search + two adds."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds):
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        if not _ENABLED:
            return
        bounds = self._bounds
        lo, hi = 0, len(bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self._counts[lo] += 1
            self._sum += value
            self._count += 1

    def time_ms(self):
        """Context manager observing the block's wall time in ms."""
        return _HistTimer(self)

    def read(self):
        """(cumulative bucket counts incl +Inf, sum, count)."""
        with self._lock:
            counts = list(self._counts)
            total_sum, total_count = self._sum, self._count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return cum, total_sum, total_count

    def percentile(self, q):
        """Bucket-resolution quantile estimate (upper bound of the
        bucket holding the q-th observation); None when empty."""
        cum, _, count = self.read()
        if not count:
            return None
        rank = q * count
        for i, c in enumerate(cum):
            if c >= rank:
                if i < len(self._bounds):
                    return self._bounds[i]
                return float("inf")
        return float("inf")


class _HistTimer(object):
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe((time.monotonic() - self._t0) * 1e3)
        return False


_CHILD_FACTORY = {
    "counter": lambda fam: _Counter(),
    "gauge": lambda fam: _Gauge(),
    "histogram": lambda fam: _Histogram(fam.buckets),
}


class Family(object):
    """One named metric with N label sets (children). An unlabeled
    family proxies the single default child, so ``counter("x").inc()``
    works without a ``labels()`` hop."""

    def __init__(self, registry, kind, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS, max_series=MAX_SERIES):
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        self.max_series = max_series
        self._registry = registry
        self._lock = threading.Lock()
        self._children = {}  # tuple(labelvalues) -> child
        self._default = None
        if not self.labelnames:
            self._default = _CHILD_FACTORY[kind](self)
            self._children[()] = self._default

    def labels(self, *values, **kv):
        """The bound child for one label set. Accepts positional values
        (in ``labelnames`` order) or keywords. Past ``max_series`` the
        overflow child absorbs new sets (and the registry counts it)."""
        if kv:
            values = tuple(str(kv[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError("%s expects labels %r, got %r"
                             % (self.name, self.labelnames, values))
        child = self._children.get(values)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(values)
            if child is not None:
                return child
            if len(self._children) >= self.max_series:
                self._registry._series_dropped.inc()
                over = (_OVERFLOW,) * len(self.labelnames)
                child = self._children.get(over)
                if child is None:
                    child = _CHILD_FACTORY[self.kind](self)
                    self._children[over] = child
                return child
            child = _CHILD_FACTORY[self.kind](self)
            self._children[values] = child
            return child

    # unlabeled convenience surface -------------------------------------
    def _d(self):
        if self._default is None:
            raise ValueError("%s is labeled (%r); bind with .labels()"
                             % (self.name, self.labelnames))
        return self._default

    def inc(self, amount=1.0):
        self._d().inc(amount)

    def dec(self, amount=1.0):
        self._d().dec(amount)

    def set(self, value):
        self._d().set(value)

    def observe(self, value):
        self._d().observe(value)

    def time_ms(self):
        return self._d().time_ms()

    @property
    def value(self):
        return self._d().value

    def percentile(self, q):
        return self._d().percentile(q)

    def series(self):
        with self._lock:
            return dict(self._children)


class MetricsRegistry(object):
    """Thread-safe family store + the two exposition formats.

    Families are create-once: re-declaring an existing name returns
    the SAME family (declarations live at module scope in every plane,
    and two planes may share a name), but kind/labels must agree.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}  # name -> Family
        self._series_dropped = _Counter()

    def _family(self, kind, name, help, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind \
                        or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        "metric %s re-declared as %s%r (was %s%r)"
                        % (name, kind, tuple(labelnames), fam.kind,
                           fam.labelnames))
                return fam
            fam = Family(self, kind, name, help=help,
                         labelnames=labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labels=()):
        return self._family("counter", name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._family("gauge", name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=DEFAULT_BUCKETS):
        return self._family("histogram", name, help, labels,
                            buckets=buckets)

    def mirror_stats(self, prefix, stats):
        """Mirror a component's legacy ``stats()`` dict into gauges
        (``<prefix>_<key>``). Numeric scalars only — lists/strings keep
        living in the dict; the point is that ``job_stats`` gets ONE
        uniform snapshot shape instead of special-casing key formats."""
        if not _ENABLED:
            return stats
        for key, val in stats.items():
            if isinstance(val, bool):
                val = int(val)
            if isinstance(val, (int, float)):
                self.gauge("%s_%s" % (prefix, key)).set(val)
        return stats

    def families(self):
        with self._lock:
            return dict(self._families)

    def unregister(self, name):
        with self._lock:
            self._families.pop(name, None)

    @property
    def series_dropped(self):
        return self._series_dropped.value

    # -- exposition -----------------------------------------------------

    def snapshot(self):
        """JSON-able snapshot: the shape the fleet publisher ships and
        job_stats aggregates. Histograms carry non-cumulative bucket
        counts so cross-pod merging is pure elementwise addition."""
        out = {}
        for name, fam in sorted(self.families().items()):
            series = []
            for values, child in sorted(fam.series().items()):
                lbl = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    with child._lock:
                        series.append({
                            "labels": lbl,
                            "buckets": list(child._counts),
                            "sum": child._sum,
                            "count": child._count})
                else:
                    series.append({"labels": lbl, "value": child.value})
            entry = {"kind": fam.kind, "help": fam.help,
                     "labelnames": list(fam.labelnames),
                     "series": series}
            if fam.kind == "histogram":
                entry["bounds"] = list(fam.buckets)
            out[name] = entry
        return {"schema": "obs_snapshot/v1", "ts": time.time(),
                "pid": os.getpid(), "series_dropped": self.series_dropped,
                "metrics": out}

    def prometheus_text(self):
        """Prometheus text exposition format (0.0.4)."""
        lines = []
        for name, fam in sorted(self.families().items()):
            if fam.help:
                lines.append("# HELP %s %s" % (name, fam.help))
            lines.append("# TYPE %s %s" % (name, fam.kind))
            for values, child in sorted(fam.series().items()):
                lbl = ",".join('%s="%s"' % (n, v.replace('"', '\\"'))
                               for n, v in zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    cum, total_sum, count = child.read()
                    for bound, c in zip(fam.buckets, cum):
                        ble = (lbl + "," if lbl else "") \
                            + 'le="%s"' % _fmt(bound)
                        lines.append("%s_bucket{%s} %d"
                                     % (name, ble, c))
                    binf = (lbl + "," if lbl else "") + 'le="+Inf"'
                    lines.append("%s_bucket{%s} %d" % (name, binf, count))
                    lines.append("%s_sum%s %s"
                                 % (name, "{%s}" % lbl if lbl else "",
                                    _fmt(total_sum)))
                    lines.append("%s_count%s %d"
                                 % (name, "{%s}" % lbl if lbl else "",
                                    count))
                else:
                    lines.append("%s%s %s"
                                 % (name, "{%s}" % lbl if lbl else "",
                                    _fmt(child.value)))
        return "\n".join(lines) + "\n"


def _fmt(v):
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def merge_snapshots(snaps):
    """Aggregate per-pod ``snapshot()`` docs into one fleet view:
    counters and histogram buckets sum elementwise across pods, gauges
    keep per-pod values plus min/max/sum. ``snaps`` is
    ``{pod_key: snapshot_doc}``."""
    fleet = {}
    for pod, snap in sorted(snaps.items()):
        for name, fam in (snap.get("metrics") or {}).items():
            agg = fleet.setdefault(name, {
                "kind": fam["kind"], "help": fam.get("help", ""),
                "labelnames": fam.get("labelnames", []),
                "series": {}})
            if fam["kind"] == "histogram" and "bounds" not in agg:
                agg["bounds"] = fam.get("bounds", [])
            for s in fam.get("series", []):
                key = tuple(sorted((s.get("labels") or {}).items()))
                cell = agg["series"].get(key)
                if fam["kind"] == "histogram":
                    if cell is None:
                        cell = agg["series"][key] = {
                            "labels": dict(key),
                            "buckets": list(s["buckets"]),
                            "sum": s["sum"], "count": s["count"]}
                    else:
                        cell["buckets"] = [
                            a + b for a, b in zip(cell["buckets"],
                                                  s["buckets"])]
                        cell["sum"] += s["sum"]
                        cell["count"] += s["count"]
                elif fam["kind"] == "counter":
                    if cell is None:
                        cell = agg["series"][key] = {
                            "labels": dict(key), "value": 0.0,
                            "pods": {}}
                    cell["value"] += s["value"]
                    cell["pods"][pod] = s["value"]
                else:  # gauge: per-pod values + spread
                    if cell is None:
                        cell = agg["series"][key] = {
                            "labels": dict(key), "pods": {},
                            "min": s["value"], "max": s["value"],
                            "sum": 0.0}
                    cell["pods"][pod] = s["value"]
                    cell["min"] = min(cell["min"], s["value"])
                    cell["max"] = max(cell["max"], s["value"])
                    cell["sum"] += s["value"]
    for agg in fleet.values():
        agg["series"] = list(agg["series"].values())
    return {"schema": "obs_fleet/v1", "pods": sorted(snaps),
            "metrics": fleet}


#: THE process registry — every in-tree plane instruments against it.
REGISTRY = MetricsRegistry()


def counter(name, help="", labels=()):
    return REGISTRY.counter(name, help=help, labels=labels)


def gauge(name, help="", labels=()):
    return REGISTRY.gauge(name, help=help, labels=labels)


def histogram(name, help="", labels=(), buckets=DEFAULT_BUCKETS):
    return REGISTRY.histogram(name, help=help, labels=labels,
                              buckets=buckets)


def mirror_stats(prefix, stats):
    return REGISTRY.mirror_stats(prefix, stats)
