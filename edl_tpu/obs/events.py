"""The elastic-event timeline: structured, causally-linked records.

Every state transition an operator asks "what happened?" about —
resize phases, leader elections, store failovers, breaker trips,
fault-plane injections — lands here as one bounded-ring record:

    {"id": 17, "ts": <unix>, "pid": ..., "kind": "resize.restore",
     "cause": 15, "trace_id": <active trace or None>, "attrs": {...}}

``cause`` is the id of the event that triggered this one (same
process), forming explicit causal chains; ``trace_id`` links an event
into an RPC trace when one is active. The ring replaces the one-off
``resize_timing_r<rank>`` JSON blobs as the substrate: the trainer
still derives its per-resize record from these events, and the fleet
publisher ships the ring to the coordination store where ``job_stats``
merges all pods into one chronological timeline.

Emission also feeds ``edl_events_total{kind}`` in the metrics
registry, so event RATES (breaker trips/min, elections/hour) are
queryable without reading the ring.
"""

import itertools
import os
import threading
import time
from collections import deque

from edl_tpu.obs import metrics, trace

_EVENTS_TOTAL = metrics.counter(
    "edl_events_total", "timeline events emitted", labels=("kind",))


class EventLog(object):
    def __init__(self, capacity=2048):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=int(capacity))
        self._ids = itertools.count(1)

    def emit(self, kind, cause=None, **attrs):
        """Record one event; returns its id (pass as ``cause=`` to a
        follow-up event to link them). Near-free when metrics are
        disabled process-wide."""
        if not metrics.enabled():
            return 0
        ctx = trace.current()
        event = {"id": next(self._ids), "ts": time.time(),
                 "pid": os.getpid(), "kind": kind, "cause": cause,
                 "trace_id": ctx[0] if ctx else None,
                 "attrs": attrs}
        with self._lock:
            self._ring.append(event)
        _EVENTS_TOTAL.labels(kind).inc()
        return event["id"]

    def snapshot(self, since_id=0, kinds=None):
        """Events with id > ``since_id`` (oldest first), optionally
        filtered to a kind prefix tuple/set."""
        with self._lock:
            out = [dict(e) for e in self._ring if e["id"] > since_id]
        if kinds:
            kinds = tuple(kinds)
            out = [e for e in out
                   if any(e["kind"].startswith(k) for k in kinds)]
        return out

    def last(self, kind=None):
        """Most recent event (of ``kind``, when given) or None."""
        with self._lock:
            for e in reversed(self._ring):
                if kind is None or e["kind"] == kind:
                    return dict(e)
        return None

    def clear(self):
        with self._lock:
            self._ring.clear()


#: THE process event timeline
EVENTS = EventLog()


def emit(kind, cause=None, **attrs):
    return EVENTS.emit(kind, cause=cause, **attrs)


def merge_timelines(per_pod):
    """Merge per-pod event lists into one chronological fleet timeline;
    each event gains a ``pod`` field. ``per_pod`` is
    ``{pod_key: [event, ...]}``."""
    merged = []
    for pod, events in per_pod.items():
        for e in events or ():
            e = dict(e)
            e["pod"] = pod
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ts") or 0, e.get("id") or 0))
    return merged
