"""Row-span ownership for sharded embedding tables.

A table of ``vocab`` rows is split across the member set exactly the
way the parallel plane splits a leading axis across a mesh axis:
:func:`edl_tpu.parallel.costmodel.device_spans` over a one-axis mesh
whose size is the member count. Members are SORTED before span
assignment, so ownership is a pure function of the member-id set —
any process, given the same membership, derives the same span map with
no coordination round (the relay-tree/partner-ring idiom).

The span layout is contiguous equal blocks of ``ceil(vocab / n)``
rows (the last block clamped), which makes the per-key owner a single
integer divide (:func:`owner_index`) — the client's per-batch
partition is one vectorized ``//`` over the deduped key array, not a
hash-ring walk per key.

:func:`reshard_moves` is the elastic half: the rows a member's NEW
span needs that its OLD span did not hold, attributed to the old
owners that hold them — the same span-overlap math PlacedTarget runs
at restore time, on row intervals.
"""

import numpy as np

from edl_tpu.parallel.costmodel import device_spans


def row_spans(vocab, members):
    """``{member_id: (lo, hi)}`` row spans of a ``vocab``-row table
    over ``members`` (any iterable of ids; sorted internally so the
    map is deterministic under shuffled membership). Members past the
    table (more members than rows) own empty spans ``(vocab, vocab)``."""
    ordered = sorted(members)
    if not ordered:
        return {}
    spans = device_spans((int(vocab),), ("rows",),
                         {"rows": len(ordered)})
    return {m: spans[i][0] for i, m in enumerate(ordered)}


def block_rows(vocab, n_members):
    """Rows per ownership block: ``ceil(vocab / n)``."""
    return -(-int(vocab) // int(n_members))


def owner_index(keys, vocab, n_members):
    """Vectorized owner index (position in the SORTED member list) for
    ``keys`` (int ndarray). ``keys // block`` by construction of
    :func:`row_spans`."""
    return np.asarray(keys) // block_rows(vocab, n_members)


def partition_by_owner(keys, vocab, members):
    """Split a SORTED unique key array into per-owner runs:
    ``[(member_id, keys_slice)]``, empty owners omitted. Sorted input
    means each owner's keys are one contiguous slice (a view, not a
    copy) — the coalesced-gather fast path."""
    ordered = sorted(members)
    keys = np.asarray(keys)
    if keys.size == 0:
        return []
    idx = owner_index(keys, vocab, len(ordered))
    # run boundaries of the (sorted, hence non-decreasing) owner index
    cuts = np.flatnonzero(np.diff(idx)) + 1
    out = []
    for lo, hi in zip(np.r_[0, cuts], np.r_[cuts, keys.size]):
        out.append((ordered[int(idx[lo])], keys[lo:hi]))
    return out


def span_overlap(a, b):
    """Intersection of two row spans, or None when disjoint."""
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo < hi else None


def reshard_moves(vocab, old_members, new_members, me):
    """What member ``me`` must do to hold its NEW span after a
    membership change: ``(new_span, keep, pulls)`` where ``keep`` is
    the sub-span already held locally (new ∩ old, possibly None) and
    ``pulls`` is ``[(src_member, (lo, hi))]`` — the remaining rows
    attributed to the OLD owners that hold them, in row order. The
    union of ``keep`` and the pull spans tiles ``new_span`` exactly."""
    old = row_spans(vocab, old_members)
    new_span = row_spans(vocab, new_members)[me]
    keep = span_overlap(old.get(me, (0, 0)), new_span)
    pulls = []
    for src, src_span in sorted(old.items(), key=lambda kv: kv[1]):
        if src == me:
            continue
        ov = span_overlap(src_span, new_span)
        if ov is None:
            continue
        # rows already held locally never cross the wire
        if keep is not None:
            if ov[0] >= keep[0] and ov[1] <= keep[1]:
                continue
            if ov[0] < keep[0]:
                pulls.append((src, (ov[0], min(ov[1], keep[0]))))
            if ov[1] > keep[1]:
                pulls.append((src, (max(ov[0], keep[1]), ov[1])))
        else:
            pulls.append((src, ov))
    return new_span, keep, pulls
