"""Host-side embedding shard server: the parameter-server plane.

Each member hosts the row span :func:`~edl_tpu.embed.sharding.row_spans`
assigns it, per table, as plain float32 host ndarrays, and serves them
over the v2 tensor-frame RPC plane:

- ``embed.manifest()`` — tables, spans, member view, version.
- ``embed.lookup(table, keys, since)`` — batched gather of owned rows
  plus the version fence: the response carries the server's current
  ``version`` and the keys OTHER writers touched in ``(since, now]``
  (``touched``; None when the dirty log no longer reaches back to
  ``since`` — the client must invalidate wholesale).
- ``embed.writeback(table, keys, grads, lr, since, writer)`` — the
  sparse optimizer step ``rows[keys] -= lr * grads`` on DEDUPED keys
  (the client accumulated duplicate-key gradients; the server applies
  one fused subtract so the arithmetic matches a single-host reference
  exactly). Bumps the version and logs (version, writer, keys).
- ``embed.read_range(table, lo, hi)`` — row-range read for the elastic
  reshard path (the ``state.read`` analogue, on rows).
- ``embed.hot_put`` / ``embed.hot_lookup`` — the replicated hot tier:
  owners push their measured-hottest rows (stamped with their version)
  to replicas chosen by a capacity-weighted consistent hash; replicas
  serve them back only at the exact stamped version (StaleStateError
  otherwise — a replica NEVER serves a row older than the client's
  watermark; the client falls back to the owner).

Elasticity: :meth:`EmbedShardServer.reshard` re-derives this member's
span under a new member set, keeps the overlap in place (span-overlap
paste), range-reads the rest from the OLD owners, and adopts the new
membership — with the dirty-log floor advanced so every client's next
version fence forces a wholesale cache invalidation. Pull-then-adopt
ordering across the fleet (every member pulls against the old spans
before any member adopts) makes the reshard byte-identical to
stop-resume; ``rec_bench`` gates exactly that.
"""

import threading
from collections import deque

import numpy as np

from edl_tpu.rpc.server import RpcServer
from edl_tpu.utils import errors
from edl_tpu.utils.logger import logger

#: bounded dirty log: total keys remembered across writeback records.
#: Past this the floor advances and older watermarks fence wholesale.
DIRTY_LOG_KEYS = 1 << 16


class TableSpec(object):
    """Shape + deterministic initializer of one embedding table.

    ``init_fn(vocab, dim, lo, hi) -> float32 [hi-lo, dim]`` must be a
    pure function of the ABSOLUTE row index so that any span layout
    materializes the same logical table — that is what makes a resized
    fleet's table equal a fresh one's, and the reshard byte-identity
    provable. :func:`seeded_rows` is the default."""

    def __init__(self, vocab, dim, init_fn=None, seed=0):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.seed = int(seed)
        self._init_fn = init_fn

    def materialize(self, lo, hi):
        if self._init_fn is not None:
            rows = self._init_fn(self.vocab, self.dim, lo, hi)
        else:
            rows = seeded_rows(self.vocab, self.dim, lo, hi, self.seed)
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.shape != (hi - lo, self.dim):
            raise ValueError("init_fn returned %s, want %s"
                             % (rows.shape, (hi - lo, self.dim)))
        return rows


def seeded_rows(vocab, dim, lo, hi, seed=0):
    """Default init: N(0, 0.01) rows, each a pure function of its
    absolute row index (one tiny per-row RandomState — init-time only),
    so every span layout slices the same logical table."""
    out = np.empty((hi - lo, dim), np.float32)
    for r in range(lo, hi):
        rng = np.random.RandomState((seed * 1000003 + r) % (1 << 31))
        out[r - lo] = rng.standard_normal(dim) * 0.01
    return out


class EmbedShardServer(object):
    """One member's shard of every table (module docstring)."""

    def __init__(self, member_id, tables, members, host="127.0.0.1",
                 port=0, dirty_log_keys=DIRTY_LOG_KEYS):
        from edl_tpu.embed import sharding
        self.member_id = str(member_id)
        self._tables = dict(tables)  # name -> TableSpec
        self._members = sorted(str(m) for m in members)
        self._lock = threading.Lock()
        self._version = 0
        # dirty log: (version, writer, table, keys ndarray); floor =
        # oldest version the log still covers (since < floor - 1 means
        # the fence can no longer enumerate, answer touched=None)
        self._dirty = deque()
        self._dirty_keys = 0
        self._dirty_budget = int(dirty_log_keys)
        self._log_floor = 0
        self._spans = {}  # table -> (lo, hi)
        self._rows = {}   # table -> float32 [hi-lo, dim]
        for name, spec in self._tables.items():
            # a joiner constructed with the PRE-join membership owns an
            # empty span until reshard()/adopt() pulls its share in
            lo, hi = sharding.row_spans(spec.vocab, self._members).get(
                self.member_id, (spec.vocab, spec.vocab))
            self._spans[name] = (lo, hi)
            self._rows[name] = spec.materialize(lo, hi)
        # replicated hot tier: table -> {key: (row, owner_version)}
        self._hot = {}
        self._server = RpcServer(host=host, port=port)
        self._server.register("embed.manifest", self._rpc_manifest)
        self._server.register("embed.lookup", self._rpc_lookup)
        self._server.register("embed.writeback", self._rpc_writeback)
        self._server.register("embed.read_range", self._rpc_read_range)
        self._server.register("embed.hot_put", self._rpc_hot_put)
        self._server.register("embed.hot_lookup", self._rpc_hot_lookup)
        self._server.start()

    @property
    def endpoint(self):
        return self._server.endpoint

    @property
    def version(self):
        with self._lock:
            return self._version

    def span(self, table):
        with self._lock:
            return self._spans[table]

    def members(self):
        with self._lock:
            return list(self._members)

    def stop(self):
        self._server.stop()

    # -- fencing helpers (call under self._lock) ----------------------------

    def _log_write(self, writer, table, keys):
        self._version += 1
        self._dirty.append((self._version, writer, table,
                            np.array(keys, np.int64)))
        self._dirty_keys += len(keys)
        while self._dirty_keys > self._dirty_budget and self._dirty:
            old = self._dirty.popleft()
            self._dirty_keys -= len(old[3])
            self._log_floor = old[0]
        return self._version

    def _touched_since(self, since, table, exclude_writer=None):
        """Keys of ``table`` written in ``(since, version]`` by anyone
        but ``exclude_writer``; None when the log was truncated past
        ``since`` (the wholesale-invalidate sentinel)."""
        since = int(since)
        if since < self._log_floor:
            return None
        touched = [rec[3] for rec in self._dirty
                   if rec[0] > since and rec[2] == table
                   and rec[1] != exclude_writer]
        if not touched:
            return np.empty(0, np.int64)
        return np.unique(np.concatenate(touched))

    def _owned(self, table, keys):
        lo, hi = self._spans[table]
        keys = np.asarray(keys, np.int64).reshape(-1)
        if keys.size and (keys.min() < lo or keys.max() >= hi):
            raise errors.NotFoundError(
                "embed %s: keys outside span [%d, %d) of member %s"
                % (table, lo, hi, self.member_id))
        return keys, lo

    # -- served methods ----------------------------------------------------

    def _rpc_manifest(self):
        with self._lock:
            return {"member": self.member_id,
                    "members": list(self._members),
                    "version": self._version,
                    "tables": {name: {"vocab": spec.vocab,
                                      "dim": spec.dim,
                                      "span": list(self._spans[name])}
                               for name, spec in self._tables.items()}}

    def _rpc_lookup(self, table, keys, since=0, reader=None):
        with self._lock:
            keys, lo = self._owned(table, keys)
            rows = self._rows[table][keys - lo]
            touched = self._touched_since(since, table,
                                          exclude_writer=reader)
            return {"rows": rows, "version": self._version,
                    "touched": touched}

    def _rpc_writeback(self, table, keys, grads, lr, since=0,
                       writer=None):
        grads = np.asarray(grads, np.float32)
        with self._lock:
            keys, lo = self._owned(table, keys)
            # deduped keys: one fused subtract, bit-identical to the
            # single-host reference step on the same accumulated grads
            self._rows[table][keys - lo] -= np.float32(lr) * grads
            touched = self._touched_since(since, table,
                                          exclude_writer=writer)
            version = self._log_write(writer, table, keys)
            return {"version": version, "touched": touched}

    def _rpc_read_range(self, table, lo, hi):
        with self._lock:
            span_lo, span_hi = self._spans[table]
            lo, hi = int(lo), int(hi)
            if lo < span_lo or hi > span_hi:
                raise errors.NotFoundError(
                    "embed %s: range [%d, %d) outside span [%d, %d)"
                    % (table, lo, hi, span_lo, span_hi))
            return {"rows": self._rows[table][lo - span_lo:hi - span_lo],
                    "version": self._version}

    # -- replicated hot tier -----------------------------------------------

    def _rpc_hot_put(self, table, keys, rows, version):
        """Accept hot rows from an owner, stamped with ITS version.
        Newer stamps win; an older push never rolls a row back."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        version = int(version)
        with self._lock:
            tier = self._hot.setdefault(table, {})
            for k, row in zip(keys, rows):
                old = tier.get(int(k))
                if old is not None and old[1] > version:
                    continue
                tier[int(k)] = (np.array(row, copy=True), version)
            return {"held": len(tier)}

    def _rpc_hot_lookup(self, table, keys, min_version):
        """Serve replicated hot rows at stamp >= ``min_version``.
        Partial by design: ``found`` masks the keys served; the client
        routes the rest to the owner. A key held only at an OLDER stamp
        is a miss, never a stale serve."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        min_version = int(min_version)
        with self._lock:
            tier = self._hot.get(table, {})
            found = np.zeros(len(keys), bool)
            rows = []
            for i, k in enumerate(keys):
                ent = tier.get(int(k))
                if ent is not None and ent[1] >= min_version:
                    found[i] = True
                    rows.append(ent[0])
            return {"found": found,
                    "rows": (np.stack(rows) if rows
                             else np.empty((0,), np.float32))}

    # -- elastic reshard ---------------------------------------------------

    def reshard(self, new_members, endpoints, pool):
        """Phase 1 of the two-phase reshard: pull this member's NEW
        span against the OLD owners' still-live spans. ``endpoints``
        maps OLD member ids to their RPC endpoints; ``pool`` is a
        shared ClientPool. Returns the staged state; nothing is
        swapped until :meth:`adopt` — so every member pulls a
        consistent pre-reshard snapshot before any member mutates.

        Rows already held locally are pasted from the live arrays
        (span overlap); the rest arrive as pipelined ``embed.read_range``
        futures, one per (old owner, table, sub-span)."""
        from edl_tpu.embed import sharding
        new_members = sorted(str(m) for m in new_members)
        staged = {}
        pending = []
        with self._lock:
            for name, spec in self._tables.items():
                new_span, keep, pulls = sharding.reshard_moves(
                    spec.vocab, self._members, new_members,
                    self.member_id)
                lo, hi = new_span
                rows = np.zeros((hi - lo, spec.dim), np.float32)
                filled = np.zeros(hi - lo, bool)
                if keep is not None:
                    old_lo = self._spans[name][0]
                    rows[keep[0] - lo:keep[1] - lo] = \
                        self._rows[name][keep[0] - old_lo:
                                         keep[1] - old_lo]
                    filled[keep[0] - lo:keep[1] - lo] = True
                staged[name] = (new_span, rows, filled)
                for src, (plo, phi) in pulls:
                    fut = pool.call_async(endpoints[src],
                                          "embed.read_range", name,
                                          plo, phi)
                    pending.append((name, plo, phi, src, fut))
        for name, plo, phi, src, fut in pending:
            (new_lo, _), rows, filled = staged[name]
            got = np.asarray(fut.result()["rows"], np.float32)
            if got.shape[0] != phi - plo:
                raise errors.StaleStateError(
                    "reshard pull %s[%d:%d) from %s returned %d rows"
                    % (name, plo, phi, src, got.shape[0]))
            rows[plo - new_lo:phi - new_lo] = got
            filled[plo - new_lo:phi - new_lo] = True
        for name, (span, rows, filled) in staged.items():
            if not filled.all():
                raise errors.StaleStateError(
                    "reshard %s: %d rows uncovered"
                    % (name, int((~filled).sum())))
        return {"members": new_members,
                "tables": {name: (span, rows)
                           for name, (span, rows, _) in staged.items()}}

    def adopt(self, staged):
        """Phase 2: swap in the staged spans/rows and the new member
        view. The dirty-log floor advances to the new version, so any
        client watermark from before the reshard fences wholesale
        (rows moved owners; per-key deltas are meaningless now). The
        hot tier is dropped for the same reason."""
        with self._lock:
            self._members = list(staged["members"])
            for name, (span, rows) in staged["tables"].items():
                self._spans[name] = tuple(span)
                self._rows[name] = rows
            self._version += 1
            self._dirty.clear()
            self._dirty_keys = 0
            self._log_floor = self._version
            self._hot.clear()
        logger.info("embed %s: adopted %d-member layout at v%d",
                    self.member_id, len(self._members), self._version)

    # test/bench surface ---------------------------------------------------

    def table_bytes(self, table):
        """(span, rows copy) — bench/test byte-identity probes."""
        with self._lock:
            return self._spans[table], self._rows[table].copy()
